"""Parameter-server runtime: server, client, async Communicator.

Parity targets (SURVEY §2.6/§3.3): the reference's RPC substrate
(operators/distributed/rpc_client.h:33 AsyncSendVar/AsyncGetVar/
AsyncPrefetchVar/barriers/checkpoint-notify, request handlers
request_handler_impl.cc), the listen_and_serv op
(distributed_ops/listen_and_serv_op.cc:330 — RunSyncLoop fan-in →
optimize blocks → barrier → serve gets; RunAsyncLoop per-var update on
arrival), the async Communicator (distributed/communicator.h:160 —
background send threads with gradient merging), sparse parameter
prefetch (distributed/parameter_prefetch.cc), and checkpoint notify
(distributed_ops/checkpoint_notify_op.cc).

TPU-native shape: dense data-parallelism belongs to SPMD/XLA collectives
(paddle_tpu.parallel); the PS path remains for what genuinely needs a
host-side service — giant/growing sparse tables and asynchronous
trainers. The transport is the fixed-schema framed binary protocol in
wire.py over persistent connections (the role of grpc_client.cc's
bytebuffer serde; NO pickle — socket bytes are never evaluated), with
retry/backoff + per-client request-sequence dedup on the client
(rpc_client.h:33 contract, grpc_client.cc retry path). The "optimize
block" the reference executes per parameter is the same functional
`Optimizer` rule the local executor uses, applied server-side.

Sync semantics (RunSyncLoop parity): each var carries a round counter.
``pull(name, min_round)`` blocks until the server has applied that many
rounds; trainers push grads for round r+1, the server averages the
fan-in of all trainers and steps the optimizer, then wakes pullers.
Round 0 is the server-side initial value, so every trainer starts from
identical parameters (the reference broadcasts startup from pserver the
same way).

Fault tolerance (docs/ELASTIC_TRAINING.md "Pserver failover"): a
pserver's hosted state snapshots to generation-tagged artifact sets
published through ``io_checkpoint``'s integrity machinery (per-array
CRC32 manifest, mkstemp + fsync + atomic ``os.replace``), periodically
on a background thread (``start_snapshots``) off the request path. A
restarted server (``run_pserver`` under ``launch_ps
--ps_snapshot_secs``) warm-boots from the newest generation that
VERIFIES — a torn/bit-rotted one is quarantined (``*.corrupt``) and
the restore walks back. Every server carries a random ``incarnation``
token served via the ``SERVER_INFO`` frame; ``PSClient`` probes it on
every reconnect, so a client that outlives a server restart detects
the new incarnation, counts the optimizer rounds lost since the last
snapshot (``ps_stale_rounds_total``), and re-establishes its sync-mode
round expectations instead of deadlocking on a round the reborn server
will never reach.
"""

import collections
import json
import logging
import os
import re
import socket
import socketserver
import threading
import time

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.flags import define_flag, get_flag
from paddle_tpu.distributed import wire
from paddle_tpu.monitor import goodput as _goodput
from paddle_tpu.monitor.registry import counter as _counter
from paddle_tpu.monitor.registry import gauge as _gauge
from paddle_tpu.monitor.registry import histogram as _histogram

__all__ = ["ParameterServer", "NativeParameterServer", "PSClient",
           "Communicator", "run_pserver", "make_parameter_server"]

_m_snap_saves = _counter(
    "ps_snapshot_saves_total",
    "Pserver snapshot generations made durable (periodic background "
    "snapshots + checkpoint-notify + final flush)")
_m_snap_ms = _histogram(
    "ps_snapshot_ms",
    "Wall ms to make one pserver snapshot generation durable "
    "(state export under the table/var locks + integrity-manifested "
    "atomic publish)")
_m_reconnects = _counter(
    "ps_client_reconnects_total",
    "PSClient calls that survived at least one dropped/refused pserver "
    "connection (retried with backoff; mutating frames stay "
    "exactly-once via the (client_id, seq) dedup)")
_m_stale_rounds = _counter(
    "ps_stale_rounds_total",
    "Optimizer rounds a restarted pserver lost between its last "
    "snapshot and the crash, as observed by reconnecting clients "
    "re-establishing their sync-round expectations")
_m_epoch = _gauge(
    "ps_epoch",
    "Committed fleet-membership epoch this pserver serves (0 = the "
    "implicit static-placement epoch: no resize has ever committed)")
_m_table_bytes = _gauge(
    "ps_sparse_table_bytes",
    "Host-resident bytes of each hosted sparse table's row store "
    "(float32 rows + adagrad accumulators; native and Python stores "
    "count the same payload), refreshed at every snapshot generation",
    labels=("table",))
_m_migrated = _counter(
    "ps_migrated_rows_total",
    "Sparse rows + dense vars this pserver adopted across committed "
    "fleet-resize migrations (counted once, at MIGRATE_COMMIT / the "
    "warm-boot epoch reconcile)")


def _migrate_fault_point(stage, path=None):
    """Migration chaos hook: no-op in production. The stage boundaries
    the elastic protocol crosses — \"plan\" (source, before freezing),
    \"chunk\" (source, before streaming a unit), \"staged\" (target,
    after publishing a unit's durable shadow; ``path`` names it) and
    \"commit\" (any server, at MIGRATE_COMMIT entry) — are exactly the
    points ``testing.faults.install_ps_migrate_faults`` patches to
    crash (PT_FAULT_PS_MIGRATE_CRASH) or tear a staged shadow
    (PT_FAULT_PS_MIGRATE_TORN)."""


define_flag("ps_transport", "auto",
            "PS server transport: auto (C++ when the hosted state is "
            "expressible, else Python), native (require C++), python")


def _stop_grace_seconds():
    """How long a server keeps accepting after a STOP frame before the
    listener closes. The trainer that sends STOP has finished, but
    another trainer's final-barrier reply may still be in flight; a
    client needing a retry in that window must be able to reconnect —
    immediate listener close turns the race into ECONNREFUSED at the
    end of an otherwise-successful run. PT_PS_STOP_GRACE overrides
    (seconds)."""
    try:
        v = float(os.environ.get("PT_PS_STOP_GRACE", "0.5"))
    except ValueError:
        return 0.5
    # clamp: a negative value must mean 'no grace', and inf/nan would
    # turn shutdown into a hang (sleep(-1) raises in the daemon thread
    # on the Python path; a negative cast through c_uint64 wraps to
    # ~forever on the native path)
    import math as _math
    if not _math.isfinite(v):
        return 0.5
    return min(max(v, 0.0), 60.0)


# framing delegates to the single shared implementation in wire.py
_recv_exact = wire.recv_exact
_send_frame = wire.send_frame
_recv_frame = wire.recv_frame

#: the SERVER reply path, separated from the client-side _send_frame so
#: testing/faults' wire chaos (reply drop / delay) can patch exactly
#: the server side of the conversation and nothing else
_reply_frame = wire.send_frame

#: the pserver snapshot filename grammar, in ONE place —
#: testing/faults and tools/fsck_checkpoint parse the same names
#: _ps_checkpoint_save writes, and a format change must break loudly
#: there, not silently no-op the fault injection / fsck verdicts
PS_GEN_META_RE = re.compile(r"^pserver_(.+)\.gen(\d+)\.json$")
PS_GEN_ARTIFACT_RE = re.compile(r"^pserver_(.+)\.gen(\d+)\.npz$")

#: the dense-artifact slot-array key prefix (``__slot__/<var>/<slot>``)
_SLOT_KEY_PREFIX = "__slot__/"


def _ps_log(msg):
    """Loud pserver-lifecycle line: straight to stderr (the launcher's
    serverlog), like the launcher's own ``[launch]`` idiom — warm-boot
    and quarantine evidence must be greppable even when the worker
    never configured logging."""
    import sys
    print(f"[pserver] {msg}", file=sys.stderr, flush=True)


def _ps_tag(host, port):
    return f"{host}_{port}".replace(".", "_")


def _ps_dense_path(dirname, tag, gen):
    return os.path.join(dirname, f"pserver_{tag}.gen{gen}.npz")


def _ps_table_path(dirname, tag, table, gen):
    return os.path.join(dirname, f"pserver_{tag}_{table}.gen{gen}.npz")


def _ps_meta_path(dirname, tag, gen):
    return os.path.join(dirname, f"pserver_{tag}.gen{gen}.json")


def _ps_gen_files(dirname, tag, gen, tables):
    """Every file a complete generation comprises (meta last)."""
    return ([_ps_dense_path(dirname, tag, gen)]
            + [_ps_table_path(dirname, tag, t, gen) for t in tables]
            + [_ps_meta_path(dirname, tag, gen)])


def _ps_listdir(dirname):
    """``os.listdir`` under the blip-is-not-corruption rule: a
    transient OSError is retried and then RE-RAISED — swallowing it
    into an empty listing would make a warm boot silently restore
    nothing (discarding training) and a save reuse a generation
    number it couldn't see. ``FileNotFoundError`` (dir never created:
    no snapshots yet) is genuinely empty."""
    from paddle_tpu import io_checkpoint as ioc
    try:
        return ioc._retry_transient(
            lambda: os.listdir(dirname),
            f"pserver snapshot dir {dirname} list")
    except FileNotFoundError:
        return []


def _ps_complete_gens(dirname, tag):
    """Sorted ``[(gen, meta), ...]`` of generations with a parseable
    meta AND every artifact it promises on disk — the generations a
    warm boot will consider (the PR-5 complete-step rule: the meta is
    published LAST, so a crash mid-snapshot can never yield a
    half-generation that looks whole). A garbage meta CONTENT
    (ValueError/TypeError) makes its generation invisible, like a
    torn ``ckpt_N.json``; a transient I/O error re-raises — dropping
    the newest generation over a blip would silently rewind the warm
    boot (``run_pserver`` crashes into the restart budget instead)."""
    from paddle_tpu import io_checkpoint as ioc
    meta_re = re.compile(rf"^pserver_{re.escape(tag)}\.gen(\d+)\.json$")
    out = []
    for f in _ps_listdir(dirname):
        m = meta_re.match(f)
        if not m:
            continue
        gen = int(m.group(1))

        def read_meta(fname=f):
            with open(os.path.join(dirname, fname)) as fh:
                return json.load(fh)

        try:
            meta = ioc._retry_transient(
                read_meta, f"pserver snapshot meta {f} read")
            tables = list(meta.get("tables", []))
        except FileNotFoundError:
            continue            # pruned under us
        except (ValueError, TypeError):
            continue            # garbage content: never complete
        promised = _ps_gen_files(dirname, tag, gen, tables)[:-1]
        if all(ioc._stat_exists(p) for p in promised):
            out.append((gen, meta))
    return sorted(out)


def _ps_next_gen(dirname, tag):
    """One past the highest generation index ANY matching file (meta,
    artifact, or quarantined ``*.corrupt``) has ever used — a
    quarantined generation's number is never reused, so its evidence
    files can't collide with a later healthy publish. A persistent
    listing error re-raises (via ``_ps_listdir``): guessing 0 would
    silently overwrite whatever the listing failed to show."""
    pat = re.compile(
        rf"^pserver_{re.escape(tag)}(?:_.+)?\.gen(\d+)\.(?:npz|json)$")
    best = -1
    for f in _ps_listdir(dirname):
        if f.endswith(".corrupt"):
            f = f[:-len(".corrupt")]
        m = pat.match(f)
        if m:
            best = max(best, int(m.group(1)))
    return best + 1


def _ps_sweep_tmps(dirname, tag):
    """Remove a killed previous incarnation's publish temps
    (``.pserver_<tag>*.tmp.npz`` / this tag's meta temps). The
    supervisor guarantees the previous incarnation of THIS endpoint is
    dead before a respawn, so same-tag temps are stale by
    construction; other endpoints' in-flight temps are never touched."""
    try:
        names = os.listdir(dirname)
    except OSError:
        return
    for f in names:
        # the tag must end at a '.' (dense/meta artifact) or '_'
        # (table artifact): launch_ps puts EVERY pserver's snapshots
        # in one shared ps_state dir, and a bare prefix match would
        # let tag "..._1234" sweep a live sibling "..._12345"'s
        # in-flight publish temp out from under its writer
        mine = (f.startswith((f".pserver_{tag}.", f".pserver_{tag}_"))
                and f.endswith((".tmp.npz", ".json.tmp")))
        if not mine:
            continue
        try:
            os.remove(os.path.join(dirname, f))
        except OSError:
            pass


def _ps_publish_json(path, obj):
    """fsync'd atomic JSON publish (io_checkpoint's one shared
    idiom; the ``.{basename}.`` temp prefix is what _ps_sweep_tmps
    and fsck recognize)."""
    from paddle_tpu import io_checkpoint as ioc
    ioc._publish_json_atomic(path, obj,
                             prefix=f".{os.path.basename(path)}.")
    ioc._fsync_dir(os.path.dirname(path) or ".")


def _ps_checkpoint_save(dirname, host, port, dense, sparse_tables,
                        incarnation=0, keep=2, epoch=0, shard_map=None):
    """The pserver checkpoint artifact contract, shared by BOTH
    transports (cross-transport restore depends on it): one
    generation-tagged artifact set per save —
    ``pserver_<tag>.gen<G>.npz`` holding {name: value} plus per-var
    optimizer slots (``__slot__/<var>/<slot>`` keys) and round/step
    counters in the manifest body, one ``pserver_<tag>_<table>.gen<G>
    .npz`` per sparse table with ids/rows/accum (kCheckpointBlockId
    parity, listen_and_serv_op.cc:345), and a ``.gen<G>.json`` meta
    marker published LAST — a generation without its meta is invisible
    to restore, so a crash mid-save can never look whole. Every npz
    publishes through ``io_checkpoint.publish_npz`` (per-array CRC32
    manifest, mkstemp + fsync + atomic ``os.replace``); the newest
    ``keep`` complete generations survive pruning — the walk-back
    budget a corrupt newest generation falls back into.

    ``dense`` is the ``_dense_export()`` triple
    ``(values, var_state, slots)``: values {name: array}, var_state
    {name: (round, step_count)}, slots {name: {slot: array}}."""
    from paddle_tpu import io_checkpoint as ioc
    os.makedirs(dirname, exist_ok=True)
    tag = _ps_tag(host, port)
    gen = _ps_next_gen(dirname, tag)
    values, var_state, slots = dense
    arrays = {n: v for n, v in values.items()}
    for n, sl in slots.items():
        for k, a in sl.items():
            arrays[f"{_SLOT_KEY_PREFIX}{n}/{k}"] = a
    body = {
        "kind": "pserver_dense",
        "endpoint": tag,
        "gen": gen,
        "incarnation": int(incarnation),
        "var_state": {n: {"round": int(r), "step": int(s)}
                      for n, (r, s) in var_state.items()},
    }
    ioc.publish_npz(_ps_dense_path(dirname, tag, gen), arrays, body)
    for n, t in sorted(sparse_tables.items()):
        ids, rows, accum = t.snapshot()
        ioc.publish_npz(
            _ps_table_path(dirname, tag, n, gen),
            {"ids": ids, "rows": rows, "accum": accum},
            {"kind": "pserver_table", "endpoint": tag, "table": n,
             "gen": gen})
    meta = {
        "gen": gen, "endpoint": tag, "incarnation": int(incarnation),
        "tables": sorted(sparse_tables), "time": time.time(),
        # fleet-membership record (docs/ELASTIC_TRAINING.md "Resizing
        # the pserver fleet"): a snapshot taken at epoch E restores
        # into epoch E — the warm-boot reconcile and fsck's
        # --num-servers verdict both read these
        "epoch": int(epoch),
    }
    if shard_map is not None:
        meta["shard_map"] = shard_map
    _ps_publish_json(_ps_meta_path(dirname, tag, gen), meta)
    # prune: meta FIRST (a killed prune must leave meta-less artifacts
    # — invisible to restore — never a meta promising missing files)
    complete = _ps_complete_gens(dirname, tag)
    for g, m in (complete[:-keep] if keep else []):
        files = _ps_gen_files(dirname, tag, g,
                              list(m.get("tables", [])))
        for p in [files[-1]] + files[:-1]:
            try:
                os.remove(p)
            except OSError:
                pass
    return gen


def _ps_quarantine_gen(dirname, tag, gen, tables):
    """Rename a generation's meta + artifacts ``*.corrupt`` (the
    restore walk-back's quarantine — evidence preserved, never offered
    for restore again; its generation number is never reused)."""
    renamed = []
    files = _ps_gen_files(dirname, tag, gen, tables)
    # meta first: a crash mid-quarantine leaves meta-less artifacts,
    # which restore already ignores
    for p in [files[-1]] + files[:-1]:
        try:
            os.replace(p, p + ".corrupt")
            renamed.append(os.path.basename(p) + ".corrupt")
        except OSError:
            pass
    return renamed


def _ps_load_legacy(dirname, tag, apply_dense, sparse_tables):
    """The pre-generation artifact layout (plain
    ``pserver_<tag>.npz`` + ``pserver_<tag>_<table>.npz``): verified
    when a manifest is present, accepted structurally otherwise; a
    torn artifact is quarantined and restore proceeds without it
    (there is nothing older to walk back to in the legacy layout)."""
    from paddle_tpu import io_checkpoint as ioc
    restored = False
    path = os.path.join(dirname, f"pserver_{tag}.npz")
    if os.path.exists(path):
        try:
            _, arrays = ioc.verify_npz(path)
        except ioc.CheckpointCorruptError as e:
            _ps_log(f"quarantined corrupt legacy artifact {path}: {e}")
            ioc._m_corrupt.inc()
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
        else:
            for n, v in arrays.items():
                if not n.startswith(_SLOT_KEY_PREFIX):
                    apply_dense(n, v, None, None)
            restored = True
    for n, t in sparse_tables.items():
        p = os.path.join(dirname, f"pserver_{tag}_{n}.npz")
        if not os.path.exists(p):
            continue
        try:
            _, arrays = ioc.verify_npz(p)
        except ioc.CheckpointCorruptError as e:
            _ps_log(f"quarantined corrupt legacy artifact {p}: {e}")
            ioc._m_corrupt.inc()
            try:
                os.replace(p, p + ".corrupt")
            except OSError:
                pass
            continue
        t.restore(arrays["ids"], arrays["rows"],
                  arrays.get("accum"))
        restored = True
    return {"gen": None, "legacy": True} if restored else None


def _ps_checkpoint_load(dirname, host, port, apply_dense,
                        sparse_tables, make_table=None):
    """Counterpart of ``_ps_checkpoint_save``: restore the newest
    complete generation that VERIFIES, walking back past corrupt ones.

    Calls ``apply_dense(name, value, state, slots)`` per hosted dense
    var found in the artifact (``state`` = (round, step_count) or
    None; ``slots`` = {slot: array} or None) and restores each sparse
    table (old artifacts without accum restore with empty accumulators
    so stale G cannot scale the rows). A generation whose any artifact
    fails integrity verification is QUARANTINED (every file renamed
    ``*.corrupt``, ``corrupt_checkpoints_total``++) and the previous
    one restores — one rotted file never bricks the warm boot. A
    transient ``OSError`` persisting through retries re-raises
    unchanged (blip is not corruption: crash into the supervisor's
    restart budget rather than quarantine a healthy snapshot). Falls
    back to the legacy un-generational layout when no generation
    exists. Returns the restored generation's meta, or None when
    nothing restorable was found."""
    from paddle_tpu import io_checkpoint as ioc
    tag = _ps_tag(host, port)
    gens = _ps_complete_gens(dirname, tag)
    if not gens:
        return _ps_load_legacy(dirname, tag, apply_dense,
                               sparse_tables)
    quarantined = 0
    for gen, meta in reversed(gens):
        tables = list(meta.get("tables", []))
        try:
            manifest, arrays = ioc.verify_npz(
                _ps_dense_path(dirname, tag, gen))
            table_blobs = {}
            for t in tables:
                _, tb = ioc.verify_npz(
                    _ps_table_path(dirname, tag, t, gen))
                table_blobs[t] = tb
        except ioc.CheckpointCorruptError as e:
            ioc._m_corrupt.inc()
            renamed = _ps_quarantine_gen(dirname, tag, gen, tables)
            quarantined += 1
            _ps_log(f"quarantined corrupt snapshot generation {gen} "
                    f"({', '.join(renamed) or 'nothing renamed'}): "
                    f"{e}; walking back")
            continue
        var_state = (manifest or {}).get("var_state", {})
        slots = {}
        for key, a in arrays.items():
            if not key.startswith(_SLOT_KEY_PREFIX):
                continue
            name, slot = key[len(_SLOT_KEY_PREFIX):].rsplit("/", 1)
            slots.setdefault(name, {})[slot] = a
        for n, v in arrays.items():
            if n.startswith(_SLOT_KEY_PREFIX):
                continue
            st = var_state.get(n)
            state = ((int(st["round"]), int(st["step"]))
                     if st else None)
            apply_dense(n, v, state, slots.get(n))
        for t, tb in table_blobs.items():
            table = sparse_tables.get(t)
            if table is None and make_table is not None:
                # elastic warm boot: the table was adopted via
                # migration (hosted from a recipe, not the program),
                # so re-create it before restoring its rows
                table = make_table(t)
            if table is None:
                continue        # table not hosted here
            table.restore(tb["ids"], tb["rows"], tb.get("accum"))
        if quarantined:
            _ps_log(f"restored from last-good snapshot generation "
                    f"{gen} after quarantining {quarantined} corrupt "
                    f"newer generation(s)")
        return meta
    _ps_log(f"every snapshot generation in {dirname} for {tag} was "
            f"corrupt ({quarantined} quarantined); starting from "
            f"initial values")
    return None


class _UnitRetired(Exception):
    """The hosted unit was migrated away mid-request (elastic resize
    committed while this request waited): the handler converts this
    into a WRONG_EPOCH reply, and the client re-routes via the new
    shard map — nothing was applied here."""


class _DenseVar:
    """One hosted parameter: value + optimizer state + round counter.

    The update mirrors the local executor's per-param optimize op
    (optimizer.py _apply_optimizer_compute) exactly: per-param
    regularizer then lr * param_lr then the optimizer rule — and NO
    gradient clipping here, because the trainer program keeps its
    clip_grads op and clips before sending (fluid clips trainer-side in
    PS mode too)."""

    def __init__(self, value, optimizer, regularizer=None, param_lr=1.0):
        self.value = np.asarray(value)
        self.optimizer = optimizer
        self.regularizer = regularizer
        self.param_lr = param_lr
        self.slots = None              # lazy: built on first update
        self.step_count = 0
        self.round = 0
        self.accum = None              # sum of grads this round
        self.pushed = set()            # trainer ids seen this round
        self.evicted = False           # migrated away (elastic resize)
        self.cv = threading.Condition()
        self._native = None            # (lib, kind) once probed

    # -- native dense optimize block --------------------------------------
    # The server-side update runs in C++ for the common rules
    # (SGD/Momentum/Adam [+ L1/L2 decay]), like the reference's pserver
    # optimize sub-block (request_handler_impl.cc -> C++ optimizer op
    # kernels). LR schedules still evaluate in Python per step; exotic
    # optimizers/regularizers fall back to the jnp path below.

    def _native_kind(self):
        if self._native is not None:
            return self._native
        self._native = (None, None)
        from paddle_tpu import optimizer as po
        opt = self.optimizer
        # exact type, not isinstance: subclasses (DGC momentum, …)
        # define different updates and must take the jnp path
        kind = None
        if type(opt) is po.SGDOptimizer:
            kind = "sgd"
        elif type(opt) is po.MomentumOptimizer:
            kind = "momentum"
        elif type(opt) is po.AdamOptimizer:
            kind = "adam"
        reg = self.regularizer or (opt.regularization if opt else None)
        if reg is not None:
            from paddle_tpu.regularizer import (L1DecayRegularizer,
                                                L2DecayRegularizer)
            if type(reg) not in (L1DecayRegularizer,
                                 L2DecayRegularizer):
                kind = None
        if (kind is not None and self.value.dtype == np.float32
                and self.value.flags.c_contiguous):
            try:
                from paddle_tpu import native
                self._native = (native.get_lib(), kind)
            except Exception:
                pass
        return self._native

    def _step_native(self, lib, kind, grad):
        import ctypes
        fp = ctypes.POINTER(ctypes.c_float)

        def ptr(a):
            return a.ctypes.data_as(fp)

        opt = self.optimizer
        n = self.value.size
        grad = np.ascontiguousarray(grad, np.float32)
        # the kernels write a fresh buffer from the old one and the
        # reference swaps under the caller-held cv: pull() hands out
        # self.value zero-copy and encodes it outside the lock, so a
        # step must never mutate a buffer a puller may still be
        # reading — the jnp path's swap semantics at in-place traffic.
        # The previous step's retired buffer is recycled when the
        # refcount PROVES no puller still holds it (a fresh 64 MB
        # np.empty costs a full page-fault-zeroing pass per step
        # otherwise); a held buffer is simply dropped to the allocator.
        import sys as _sys
        p_in = self.value
        spare, self._spare = getattr(self, "_spare", None), None
        if (spare is not None and spare.shape == p_in.shape
                and _sys.getrefcount(spare) == 2):  # local ref only
            p_out = spare
        else:
            p_out = np.empty_like(p_in)
        reg = self.regularizer or opt.regularization
        if reg is not None:
            from paddle_tpu.regularizer import L2DecayRegularizer
            if grad.base is not None or not grad.flags.owndata:
                grad = grad.copy()
            fn = (lib.pt_dense_l2_decay
                  if isinstance(reg, L2DecayRegularizer)
                  else lib.pt_dense_l1_decay)
            fn(ptr(grad), ptr(p_in), n, reg.coeff)
        # constant lr stays jax-free (the common PS case); only
        # callable schedules evaluate through _lr_value
        if callable(opt.learning_rate):
            lr = float(opt._lr_value(np.float32(self.step_count)))
        else:
            lr = float(opt.learning_rate)
        lr *= self.param_lr
        if kind == "sgd":
            lib.pt_dense_sgd(ptr(p_out), ptr(p_in), ptr(grad), n, lr)
        else:
            if self.slots is None:
                self.slots = {k: np.zeros_like(p_in)
                              for k in opt._slot_defaults}
            if kind == "momentum":
                lib.pt_dense_momentum(
                    ptr(p_out), ptr(p_in), ptr(self.slots["velocity"]),
                    ptr(grad), n, lr, opt.momentum,
                    int(bool(getattr(opt, "use_nesterov", False))))
            else:
                lib.pt_dense_adam(
                    ptr(p_out), ptr(p_in), ptr(self.slots["moment1"]),
                    ptr(self.slots["moment2"]), ptr(grad), n, lr,
                    opt.beta1, opt.beta2, opt.epsilon, self.step_count)
        self.value = p_out
        self._spare = p_in      # next step reuses it if nobody holds it

    def _step(self, grad):
        opt = self.optimizer
        if opt is None:
            return
        self.step_count += 1
        lib, kind = self._native_kind()
        if lib is not None:
            return self._step_native(lib, kind, grad)
        import jax.numpy as jnp
        p = jnp.asarray(self.value)
        g = jnp.asarray(grad)
        if self.slots is None:
            self.slots = opt._slots(p)
        t = jnp.asarray(self.step_count, jnp.int32)
        reg = self.regularizer or opt.regularization
        if reg is not None:
            g = reg(p, g)
        lr = opt._lr_value(t.astype(jnp.float32)) * self.param_lr
        from paddle_tpu.optimizer import _pallas_fused_update
        fused = _pallas_fused_update(opt, p, g, self.slots, lr, t)
        new_p, self.slots = fused if fused is not None \
            else opt._update(p, g, self.slots, lr, t)
        self.value = np.asarray(new_p)

    def _accumulate(self, grad):
        """Sync fan-in accumulation (listen_and_serv's grad
        aggregation): first push owns a fresh float32 buffer,
        subsequent pushes add in place via the native kernel when
        available (numpy otherwise)."""
        if self.accum is None:
            self.accum = np.array(grad, np.float32, copy=True)
            return
        enforce(np.shape(grad) == self.accum.shape,
                f"grad shape {np.shape(grad)} does not match hosted "
                f"var shape {self.accum.shape}")
        lib, _ = self._native_kind()
        if (lib is not None and self.accum.flags.c_contiguous
                and grad.dtype == np.float32):
            import ctypes
            fp = ctypes.POINTER(ctypes.c_float)
            g = np.ascontiguousarray(grad, np.float32)
            lib.pt_dense_accum(self.accum.ctypes.data_as(fp),
                               g.ctypes.data_as(fp), self.accum.size)
        else:
            self.accum = self.accum + grad

    def push_sync(self, trainer_id, grad, num_trainers, timeout=120.0):
        with self.cv:
            if self.evicted:
                raise _UnitRetired("var migrated away")
            if trainer_id in self.pushed:
                # stale duplicate (e.g. retry) — wait for next round
                ok = self.cv.wait_for(
                    lambda: trainer_id not in self.pushed
                    or self.evicted, timeout=timeout)
                enforce(ok, f"duplicate push from trainer {trainer_id} "
                            f"timed out waiting for round fan-in")
                if self.evicted:
                    # the round this duplicate waited on (including
                    # this trainer's FIRST push) migrated verbatim —
                    # this push re-routes and applies at the new owner
                    raise _UnitRetired("var migrated away mid-fan-in")
            self._accumulate(grad)
            self.pushed.add(trainer_id)
            if len(self.pushed) >= num_trainers:
                self._step(self.accum / max(num_trainers, 1))
                self.accum = None
                self.pushed.clear()
                self.round += 1
                self.cv.notify_all()

    def push_async(self, grad):
        with self.cv:
            self._step(grad)
            self.round += 1
            self.cv.notify_all()

    def pull(self, min_round, timeout=120.0):
        with self.cv:
            ok = self.cv.wait_for(
                lambda: self.round >= min_round or self.evicted,
                timeout=timeout)
            enforce(ok, f"pull timed out waiting for round {min_round}")
            if self.evicted and self.round < min_round:
                # the rounds this pull waits for will complete at the
                # NEW owner (partial fan-in state migrated verbatim)
                raise _UnitRetired("var migrated away mid-round")
            return self.value


class _SparseTable:
    """Hosted sparse table (lookup_sparse_table / pserver sparse block
    parity): rows materialize on first touch; pushes apply the table's
    optimizer rule — "sgd" or "adagrad" (the pserver optimize-block
    choices the reference runs for sparse params).

    With the default initializer and the native library built, the row
    store and updates run in C++ (native/src/ps_table.cc — the sparse
    host path SURVEY §2.6/§7 keeps hand-written C++); a custom Python
    initializer falls back to the Python store."""

    def __init__(self, dim, initializer=None, seed=0, lr=1.0,
                 optimizer="sgd", eps=1e-6):
        enforce(optimizer in ("sgd", "adagrad"),
                f"sparse optimizer must be sgd|adagrad, got {optimizer!r}")
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self.eps = eps
        self._native = None
        if initializer is None:
            try:
                from paddle_tpu import native
                if native.available():
                    self._native = native.NativeSparseTable(
                        dim, optimizer, lr, eps, seed)
            except Exception:
                self._native = None
        self.rows = {}
        self.accum = {}               # adagrad per-row G accumulators
        self._step = 0                # pull/push call counter (shrink)
        self._touch = {}              # row id -> last touching step
        self._rng = np.random.RandomState(seed)
        self._init = initializer or (
            lambda rng, dim: rng.normal(0, 0.01, dim).astype(np.float32))
        self.lock = threading.Lock()

    def __len__(self):
        if self._native is not None:
            return len(self._native)
        with self.lock:
            return len(self.rows)

    def nbytes(self):
        """Host-resident bytes of this table's row store: rows are
        float32[dim], adagrad doubles that with the per-row G
        accumulator. Same arithmetic for the native (C++) and Python
        stores — both hold the same float32 payload (the native store's
        hash-map overhead is not counted, matching how the ledger
        counts array payloads everywhere else)."""
        per_row = self.dim * 4 * (2 if self.optimizer == "adagrad"
                                  else 1)
        return len(self) * per_row

    def pull(self, ids):
        if self._native is not None:
            return self._native.pull(ids)
        with self.lock:
            self._step += 1
            out = np.empty((len(ids), self.dim), np.float32)
            for i, x in enumerate(ids):
                row = self.rows.get(int(x))
                if row is None:
                    row = self._init(self._rng, self.dim)
                    self.rows[int(x)] = row
                self._touch[int(x)] = self._step
                out[i] = row
            return out

    def push(self, ids, grads, lr=None):
        if self._native is not None:
            self._native.push(ids, grads, lr)
            return
        lr = self.lr if lr is None else lr
        with self.lock:
            self._step += 1
            for x, g in zip(ids, grads):
                x = int(x)
                row = self.rows.get(x)
                if row is None:
                    row = self._init(self._rng, self.dim)
                if self.optimizer == "adagrad":
                    acc = self.accum.get(x)
                    acc = (g * g if acc is None else acc + g * g)
                    self.accum[x] = acc
                    row = row - lr * g / (np.sqrt(acc) + self.eps)
                else:
                    row = row - lr * g
                self.rows[x] = row
                self._touch[x] = self._step

    def shrink(self, max_age):
        """Evict rows untouched for more than ``max_age`` pull/push
        calls (FleetWrapper::ShrinkSparseTable parity,
        fleet_wrapper.h:141). Returns evicted count."""
        if self._native is not None:
            return self._native.shrink(max_age)
        with self.lock:
            stale = [x for x in self.rows
                     if self._step - self._touch.get(x, 0) > max_age]
            for x in stale:
                self.rows.pop(x, None)
                self.accum.pop(x, None)
                self._touch.pop(x, None)
            return len(stale)

    def snapshot(self):
        """(ids, rows, accum) arrays for checkpoints."""
        if self._native is not None:
            return self._native.snapshot()
        with self.lock:
            ids = np.fromiter(self.rows, np.int64, len(self.rows))
            rows = (np.stack([self.rows[int(i)] for i in ids])
                    if len(ids) else np.zeros((0, self.dim), np.float32))
            accum = (np.stack([self.accum.get(int(i),
                                              np.zeros(self.dim,
                                                       np.float32))
                               for i in ids])
                     if len(ids) else np.zeros((0, self.dim), np.float32))
            return ids, rows, accum

    def restore(self, ids, rows, accum=None):
        if self._native is not None:
            self._native.restore(ids, rows, accum)
            return
        with self.lock:
            self.rows = {int(i): np.asarray(r, np.float32)
                         for i, r in zip(ids, rows)}
            self.accum = {}
            # mirror the native import (ps_table.cc): restored rows are
            # freshly touched, else the next shrink would evict the
            # whole just-loaded table
            self._step += 1
            self._touch = {int(i): self._step for i in ids}
            if accum is not None and len(accum):
                for i, a in zip(ids, accum):
                    a = np.asarray(a, np.float32)
                    if np.any(a):
                        self.accum[int(i)] = a


def _new_incarnation():
    """A fresh random 63-bit token per server object (nonzero; fits the
    SERVER_INFO int64 reply). Random, not PADDLE_RESTART_COUNT: two
    incarnations must never collide even across supervisor restarts
    that reset the attempt counter."""
    return (int.from_bytes(os.urandom(8), "little") & (2 ** 63 - 1)) or 1


class _SnapshotLoop:
    """Periodic async background snapshot, shared by both transports:
    a daemon thread calls ``self.save(dirname)`` every ``interval``
    seconds OFF the request path (the save itself takes each var/table
    lock only long enough to copy). ``stop_snapshots`` joins the
    thread and (by default) flushes one final generation so a graceful
    STOP never loses the tail of training."""

    _snap_thread = None

    def save(self, dirname):
        """One snapshot generation (see ``_ps_checkpoint_save``).
        Serialized per server: the background thread and a request-path
        CHECKPOINT_NOTIFY racing on the same generation number could
        otherwise publish a set whose dense and table artifacts came
        from different moments."""
        with self._save_lock:
            t0 = time.perf_counter()
            _ps_checkpoint_save(dirname, self.host, self.port,
                                self._dense_export(), self.sparse,
                                incarnation=self.incarnation,
                                epoch=getattr(self, "epoch", 0),
                                shard_map=getattr(self, "shard_map",
                                                  None))
            _m_snap_saves.inc()
            _m_snap_ms.observe((time.perf_counter() - t0) * 1e3)
            # snapshot cadence doubles as the sparse-table memory
            # accounting tick: cheap (len * row bytes), off the
            # request path, and fresh enough for capacity planning
            try:
                for name, tbl in self.sparse.items():
                    _m_table_bytes.set(tbl.nbytes(), table=name)
            except Exception:
                pass

    def start_snapshots(self, dirname, interval=5.0):
        enforce(self._snap_thread is None, "snapshots already started")
        enforce(interval > 0, f"snapshot interval must be > 0 "
                              f"(got {interval})")
        os.makedirs(dirname, exist_ok=True)
        _ps_sweep_tmps(dirname, _ps_tag(self.host, self.port))
        self._snap_dir = dirname
        self._snap_stop = threading.Event()

        def loop():
            while not self._snap_stop.wait(interval):
                try:
                    self.save(dirname)
                except Exception as e:
                    # a snapshot failure must never kill the serving
                    # loop it protects; the next interval retries
                    _ps_log(f"snapshot failed (will retry next "
                            f"interval): {type(e).__name__}: {e}")

        self._snap_thread = threading.Thread(
            target=loop, daemon=True, name="pt-ps-snapshot")
        self._snap_thread.start()
        return self

    def stop_snapshots(self, final_save=True, timeout=30.0):
        if self._snap_thread is None:
            return
        self._snap_stop.set()
        t = self._snap_thread
        t.join(timeout)
        self._snap_thread = None
        if t.is_alive():
            # a save wedged in I/O still HOLDS _save_lock: attempting
            # the final flush would block this (shutdown) path on that
            # lock forever — skip it loudly instead; the wedged save
            # may still land on its own
            _ps_log(f"snapshot thread did not stop within {timeout}s "
                    f"(a save is wedged in I/O); skipping the final "
                    f"flush rather than blocking shutdown on its lock")
            return
        if final_save:
            try:
                self.save(self._snap_dir)
            except Exception as e:
                _ps_log(f"final snapshot failed: "
                        f"{type(e).__name__}: {e}")


def _row_chunks(ids, rows, accum):
    """Split one vshard's rows into wire-sized chunks (ids/rows/accum
    sliced together). Always returns at least one chunk so an empty
    vshard still stages a (valid, empty) shadow at the target."""
    if ids.size == 0:
        return [{"ids": ids, "rows": rows, "accum": accum}]
    per_row = int(rows.itemsize
                  * (rows.shape[1] if rows.ndim > 1 else 1)) * 2 + 8
    cap_bytes = max(1, min(wire.max_message_bytes() // 2, 4 << 20))
    cap = max(1, cap_bytes // max(per_row, 1))
    return [{"ids": ids[i:i + cap], "rows": rows[i:i + cap],
             "accum": accum[i:i + cap]}
            for i in range(0, int(ids.size), cap)]


def _merge_parts(parts):
    if len(parts) == 1:
        return parts[0]
    return {k: np.concatenate([p[k] for p in parts], axis=0)
            for k in parts[0]}


def _unit_owned_by(shard_map, unit, me):
    from paddle_tpu.distributed import membership as mb
    kind, name, vsh = mb.parse_unit(unit)
    if kind == "d":
        return shard_map.get("dense", {}).get(name) == me
    owners = (shard_map.get("sparse") or {}).get(name, {})
    return owners.get(str(vsh)) == me


# Epoch-fenced data kinds → their legacy twins. The _E variants carry
# the client's committed fleet epoch as field 0; the server strips it,
# fences, and dispatches the legacy arm (docs/ELASTIC_TRAINING.md
# "Resizing the pserver fleet").
_EPOCH_KINDS = {
    wire.PUSH_GRAD_E: wire.PUSH_GRAD,
    wire.PULL_PARAM_E: wire.PULL_PARAM,
    wire.PULL_SPARSE_E: wire.PULL_SPARSE,
    wire.PUSH_SPARSE_E: wire.PUSH_SPARSE,
}


class ParameterServer(_SnapshotLoop):
    """listen_and_serv parity: hosts a set of dense vars + sparse tables,
    applies optimizer updates on grad fan-in, serves pulls/barriers/
    checkpoint-notify over TCP."""

    def __init__(self, endpoint, num_trainers=1, sync_mode=True):
        self.host, port = endpoint.rsplit(":", 1)
        self.port = int(port)
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self.incarnation = _new_incarnation()
        self._save_lock = threading.Lock()
        self.dense = {}
        self.sparse = {}
        self._barrier_lock = threading.Condition()
        self._barrier_waiting = {}    # tag -> set(trainer ids)
        self._barrier_gen = {}
        self._server = None
        self._thread = None
        # elastic fleet membership (docs/ELASTIC_TRAINING.md "Resizing
        # the pserver fleet"): the committed epoch + shard map this
        # server fences data frames against (None = the implicit
        # epoch-0 static placement — no fencing, today's behavior),
        # hosting recipes for units migrated IN, the shadow-staging
        # dir, and the freeze gate migration holds over moving units
        self.epoch = 0
        self.shard_map = None
        self.recipes = {}
        self.state_dir = None
        self._mig_cv = threading.Condition()
        self._frozen = set()          # unit keys mid-migration
        self._busy = {}               # unit key -> in-flight op count
        self._staged = {}             # epoch -> {unit: entry}
        # retry dedup for mutating requests (grpc retry-idempotence
        # role): per-client bounded LRU of seq -> cached reply, plus an
        # in-flight set so a retry that races the original request
        # waits for it instead of re-applying. Scoped PER CLIENT — a
        # single global LRU would let one chatty client evict another
        # client's in-retry entry and silently re-apply its mutation.
        # The per-client window must cover a multi-threaded client's
        # worst case: one thread backing off through retries while the
        # Communicator thread streams mutations on the shared seq
        # counter — hence 1024, not a handful.
        self._dedup = collections.OrderedDict()   # client -> LRU
        self._dedup_clients_cap = 256
        self._dedup_per_client_cap = 1024
        self._inflight = set()
        self._dedup_cv = threading.Condition()
        # highest seq handled per client — outlives the reply LRU (own
        # larger cap, FIFO), so a retry whose cached reply was evicted
        # is detectable: its seq is well below last_seen yet absent
        # from the LRU. Such a frame is re-applied (we can't answer
        # from cache) but counted + logged so silent double-apply is at
        # least observable. The tolerance below keeps legitimately
        # out-of-order first-time frames (threads sharing one seq
        # counter over separate connections) from tripping it.
        self._dedup_last_seen = collections.OrderedDict()
        self._dedup_last_seen_cap = 16384
        self._replay_seq_tolerance = 8
        self.possible_replays = 0

    # -- hosting -----------------------------------------------------------
    def host_dense(self, name, value, optimizer=None, regularizer=None,
                   param_lr=1.0):
        self.dense[name] = _DenseVar(value, optimizer, regularizer,
                                     param_lr)

    def host_sparse(self, name, dim, initializer=None, seed=0, lr=1.0,
                    optimizer="sgd"):
        self.sparse[name] = _SparseTable(dim, initializer, seed, lr,
                                         optimizer)

    # -- elastic-membership fencing (docs/ELASTIC_TRAINING.md) --------------
    def _map_json(self):
        return json.dumps(self.shard_map or {})

    def _fence_reply(self):
        """WRONG_EPOCH carrying the committed epoch + map: the client
        adopts the newer map and re-routes without a second probe.
        Nothing was applied when this reply is sent."""
        return (wire.WRONG_EPOCH, (int(self.epoch), self._map_json()))

    def _owns_dense(self, name):
        if self.shard_map is None:
            return True
        return self.shard_map.get("dense", {}).get(
            name, self.endpoint) == self.endpoint

    def _owns_sparse(self, name, ids):
        if self.shard_map is None:
            return True
        owners = (self.shard_map.get("sparse") or {}).get(name)
        if owners is None:
            return True           # table outside the map: static route
        from paddle_tpu.distributed import membership as mb
        me = self.endpoint
        return all(owners.get(str(int(v))) == me
                   for v in np.unique(mb.vshard_of(ids)))

    def _sparse_units(self, name, ids):
        from paddle_tpu.distributed import membership as mb
        return {mb.sparse_unit(name, int(v))
                for v in np.unique(mb.vshard_of(ids))}

    def _admit(self, epoch, units, owned, timeout=150.0):
        """Epoch fence + ownership check + migration-freeze gate for
        one data request. Returns a WRONG_EPOCH reply to send, or None
        after marking the units busy (caller must _release(units)).
        Requests touching a frozen (mid-migration) unit wait here and
        re-evaluate the fence — after a commit they bounce with
        WRONG_EPOCH instead of mutating a retired shard."""
        deadline = time.monotonic() + timeout
        while True:
            if epoch is not None and int(epoch) != self.epoch:
                return self._fence_reply()
            if self.shard_map is not None and not owned():
                return self._fence_reply()
            with self._mig_cv:
                if not (self._frozen & units):
                    for u in units:
                        self._busy[u] = self._busy.get(u, 0) + 1
                    return None
                left = deadline - time.monotonic()
                enforce(left > 0, "request blocked on a migration "
                                  "freeze that never released")
                self._mig_cv.wait(timeout=min(left, 1.0))

    def _release(self, units):
        with self._mig_cv:
            for u in units:
                n = self._busy.get(u, 0) - 1
                if n <= 0:
                    self._busy.pop(u, None)
                else:
                    self._busy[u] = n
            self._mig_cv.notify_all()

    # -- request handling (request_handler_impl.cc parity) -----------------
    def _handle(self, kind, fields):
        """Dispatch one decoded request; returns (resp_kind, fields)."""
        epoch = None
        legacy = _EPOCH_KINDS.get(kind)
        if legacy is not None:
            epoch, fields = int(fields[0]), fields[1:]
            kind = legacy
        if kind == wire.PUSH_GRAD:
            name, trainer_id, grad = fields
            units = {"d/" + name}
            gate = self._admit(epoch, units,
                               lambda: self._owns_dense(name))
            if gate is not None:
                return gate
            try:
                v = self.dense[name]
                if self.sync_mode:
                    v.push_sync(int(trainer_id), grad,
                                self.num_trainers)
                else:
                    v.push_async(grad)
            except _UnitRetired:
                return self._fence_reply()
            finally:
                self._release(units)
            return (wire.OK, ())
        if kind == wire.PULL_PARAM:
            name, min_round = fields
            if not self.sync_mode:
                min_round = 0
            # fence + ownership only — no freeze gate: a sync pull may
            # legitimately wait minutes for a round fan-in, and holding
            # the busy count through that wait would deadlock the
            # migration drain against the pushes it gates
            if epoch is not None and int(epoch) != self.epoch:
                return self._fence_reply()
            if self.shard_map is not None and \
                    not self._owns_dense(name):
                return self._fence_reply()
            try:
                return (wire.OK_ARR,
                        (self.dense[name].pull(int(min_round)),))
            except _UnitRetired:
                return self._fence_reply()
        if kind == wire.PULL_SPARSE:
            # the python-store pull MATERIALIZES missing rows — it
            # mutates, so it takes the freeze gate like a push
            name, ids = fields
            units = self._sparse_units(name, ids)
            gate = self._admit(epoch, units,
                               lambda: self._owns_sparse(name, ids))
            if gate is not None:
                return gate
            try:
                return (wire.OK_ARR, (self.sparse[name].pull(ids),))
            finally:
                self._release(units)
        if kind == wire.PUSH_SPARSE:
            name, ids, grads, lr = fields
            units = self._sparse_units(name, ids)
            gate = self._admit(epoch, units,
                               lambda: self._owns_sparse(name, ids))
            if gate is not None:
                return gate
            try:
                self.sparse[name].push(ids, grads, lr)
            finally:
                self._release(units)
            return (wire.OK, ())
        if kind == wire.BARRIER:
            tag, trainer_id = fields
            trainer_id = int(trainer_id)
            with self._barrier_lock:
                gen = self._barrier_gen.setdefault(tag, 0)
                # set-based fan-in: a retried barrier frame from the
                # same trainer is idempotent
                waiting = self._barrier_waiting.setdefault(tag, set())
                waiting.add(trainer_id)
                if len(waiting) >= self.num_trainers:
                    waiting.clear()
                    self._barrier_gen[tag] = gen + 1
                    self._barrier_lock.notify_all()
                else:
                    ok = self._barrier_lock.wait_for(
                        lambda: self._barrier_gen[tag] > gen, timeout=120.0)
                    enforce(ok, f"barrier {tag!r} timed out")
            return (wire.OK, ())
        if kind == wire.CHECKPOINT_NOTIFY:
            (dirname,) = fields
            self.save(dirname)
            return (wire.OK, ())
        if kind == wire.SHRINK_TABLE:
            name, max_age = fields
            removed = self.sparse[name].shrink(int(max_age))
            return (wire.OK_ARR,
                    (np.asarray([removed], np.int64),))
        if kind == wire.LIST_VARS:
            return (wire.OK_NAMES, ("\n".join(sorted(self.dense)),
                                    "\n".join(sorted(self.sparse))))
        if kind == wire.SERVER_INFO:
            # the failover probe: [incarnation, min dense round] — a
            # reconnecting client compares the token against the one it
            # last saw and, on a change, re-establishes its sync-round
            # expectations at the round the reborn server can serve
            return (wire.OK_ARR,
                    (np.asarray([self.incarnation, self._min_round()],
                                np.int64),))
        if kind == wire.STOP:
            def stop_after_grace():
                # only a multi-trainer job has the in-flight-reply
                # race the grace exists for
                if self.num_trainers > 1:
                    time.sleep(_stop_grace_seconds())
                self.stop()

            threading.Thread(target=stop_after_grace,
                             daemon=True).start()
            return (wire.OK, ())
        if kind == wire.MIGRATE_PLAN:
            return self._migrate_source(json.loads(fields[0]))
        if kind == wire.MIGRATE_BEGIN:
            return self._migrate_begin(json.loads(fields[0]))
        if kind == wire.MIGRATE_CHUNK:
            meta, blob, crc = fields
            return self._migrate_chunk(json.loads(meta), blob,
                                       int(crc))
        if kind == wire.MIGRATE_END:
            return self._migrate_end(json.loads(fields[0]))
        if kind == wire.MIGRATE_COMMIT:
            spec = json.loads(fields[0])
            return self._migrate_commit(int(spec["epoch"]),
                                        spec["map"])
        if kind == wire.MIGRATE_ABORT:
            return self._migrate_abort(
                int(json.loads(fields[0])["epoch"]))
        if kind == wire.EPOCH_MAP:
            return (wire.OK_JSON,
                    (json.dumps({"epoch": int(self.epoch),
                                 "map": self.shard_map}),))
        return (wire.ERR, (f"unhandled request kind {kind}",))

    # -- two-phase migration: source side ----------------------------------
    def _migrate_source(self, plan):
        """MIGRATE_PLAN from the coordinator: freeze the moving units,
        drain in-flight writes, stream every unit to its target, and
        stay frozen until COMMIT or ABORT resolves the epoch. Any
        failure unfreezes and replies ERR — the ERR reply is the
        coordinator's abort trigger, and once unfrozen this (still
        authoritative) server keeps applying writes at the old epoch."""
        _migrate_fault_point("plan")
        epoch = int(plan["epoch"])
        units = [(u["unit"], u["to"]) for u in plan["units"]]
        names = {u for u, _ in units}
        with self._mig_cv:
            self._frozen |= names
            ok = self._mig_cv.wait_for(
                lambda: not any(self._busy.get(u) for u in names),
                timeout=30.0)
            if not ok:
                self._frozen -= names
                self._mig_cv.notify_all()
                return (wire.ERR,
                        ("migration freeze drain timed out",))
        by_target = {}
        for u, to in units:
            by_target.setdefault(to, []).append(u)
        rows = 0
        try:
            for to in sorted(by_target):
                rows += self._stream_units(to, epoch, by_target[to])
        except Exception as e:                      # noqa: BLE001
            with self._mig_cv:
                self._frozen -= names
                self._mig_cv.notify_all()
            return (wire.ERR,
                    (f"migration stream failed: "
                     f"{type(e).__name__}: {e}",))
        return (wire.OK_ARR, (np.asarray([rows], np.int64),))

    def _stream_units(self, to, epoch, units):
        from paddle_tpu.distributed import membership as mb
        mb._rpc(to, wire.MIGRATE_BEGIN,
                (json.dumps({"epoch": epoch, "from": self.endpoint,
                             "units": list(units)}),))
        rows = 0
        for unit in sorted(units):
            _migrate_fault_point("chunk")
            kind, name, vsh = mb.parse_unit(unit)
            if kind == "d":
                chunks = [self._export_dense_unit(name)]
                rows += 1
            else:
                ids, vals, accum = self.sparse[name].snapshot()
                sel = mb.vshard_of(ids) == vsh
                ids, vals = ids[sel], vals[sel]
                accum = accum[sel] if accum is not None else \
                    np.zeros_like(vals)
                rows += int(ids.size)
                chunks = _row_chunks(ids, vals, accum)
            last = len(chunks) - 1
            for i, arrays in enumerate(chunks):
                blob, crc = mb.pack_arrays(arrays)
                mb._rpc(to, wire.MIGRATE_CHUNK,
                        (json.dumps({"unit": unit, "epoch": epoch,
                                     "seq": i,
                                     "last": i == last}),
                         blob, crc))
        mb._rpc(to, wire.MIGRATE_END,
                (json.dumps({"epoch": epoch, "from": self.endpoint,
                             "units": list(units)}),))
        return rows

    def _export_dense_unit(self, name):
        """Copy a dense var for the wire — including the mid-round
        fan-in (accum + pushed set): with multiple trainers a round may
        be half-collected at freeze time, and the target must resume
        the fan-in exactly where the source stopped or the round
        double-counts the already-pushed trainers."""
        v = self.dense[name]
        with v.cv:
            out = {"value": np.array(v.value, copy=True),
                   "round": np.asarray([v.round], np.int64),
                   "step": np.asarray([v.step_count], np.int64),
                   "pushed": np.asarray(sorted(v.pushed), np.int64)}
            if v.accum is not None:
                out["accum"] = np.array(v.accum, copy=True)
            if v.slots:
                for k, s in v.slots.items():
                    out["slot/" + k] = np.array(s, copy=True)
        return out

    # -- two-phase migration: target side ----------------------------------
    def _migrate_begin(self, spec):
        epoch = int(spec["epoch"])
        for unit in spec["units"]:
            from paddle_tpu.distributed import membership as mb
            kind, name, _ = mb.parse_unit(unit)
            hosted = name in (self.dense if kind == "d"
                              else self.sparse)
            if not hosted and name not in self.recipes:
                return (wire.ERR,
                        (f"no hosting recipe for migrated "
                         f"unit {unit!r}",))
        with self._mig_cv:
            stage = self._staged.setdefault(epoch, {})
            for unit in spec["units"]:
                stage[unit] = {"parts": [],
                               "from": spec.get("from")}
        return (wire.OK, ())

    def _migrate_chunk(self, meta, blob, crc):
        from paddle_tpu.distributed import membership as mb
        import zlib
        if zlib.crc32(blob.tobytes()) & 0xFFFFFFFF != crc:
            return (wire.ERR,
                    (f"migration chunk CRC mismatch for "
                     f"{meta.get('unit')!r}",))
        epoch, unit = int(meta["epoch"]), meta["unit"]
        with self._mig_cv:
            ent = self._staged.get(epoch, {}).get(unit)
        if ent is None:
            return (wire.ERR,
                    (f"chunk for unstaged unit {unit!r}",))
        ent["parts"].append(mb.unpack_blob(blob))
        return (wire.OK, ())

    def _migrate_end(self, spec):
        """Source finished streaming: merge the chunks and publish each
        unit as a durable, CRC-manifested shadow file. The shadow is
        what survives a target crash between staging and commit — the
        warm-boot reconcile adopts it if the epoch file says we won."""
        from paddle_tpu.distributed import membership as mb
        from paddle_tpu import io_checkpoint as ioc
        if not self.state_dir:
            return (wire.ERR,
                    ("target has no state_dir for shadow staging",))
        epoch = int(spec["epoch"])
        tag = mb.tag_of_ep(self.endpoint)
        staged_rows = 0
        for unit in spec["units"]:
            with self._mig_cv:
                ent = self._staged.get(epoch, {}).get(unit)
            if ent is None:
                return (wire.ERR,
                        (f"END for unstaged unit {unit!r}",))
            arrays = _merge_parts(ent["parts"])
            ent["arrays"] = arrays
            path = mb.shadow_path(self.state_dir, tag, epoch, unit)
            ioc.publish_npz(path, arrays,
                            {"kind": "pserver_shadow",
                             "endpoint": self.endpoint,
                             "epoch": epoch, "unit": unit})
            _migrate_fault_point("staged", path)
            ids = arrays.get("ids")
            staged_rows += int(ids.size) if ids is not None else 1
        return (wire.OK_ARR,
                (np.asarray([staged_rows], np.int64),))

    # -- two-phase migration: resolution -----------------------------------
    def _migrate_commit(self, epoch, new_map):
        """Coordinator published fleet_epoch.json (the commit point)
        and is now telling everyone. Idempotent: a retried COMMIT after
        we already moved to `epoch` is a no-op ack. Adopt what we
        staged, retire what we lost, serve the new epoch."""
        _migrate_fault_point("commit")
        if self.epoch >= epoch:
            return (wire.OK_ARR,
                    (np.asarray([self.epoch], np.int64),))
        with self._mig_cv:
            staged = self._staged.pop(epoch, {})
        from paddle_tpu.distributed import membership as mb
        adopted = 0
        for unit in sorted(staged):
            if not _unit_owned_by(new_map, unit, self.endpoint):
                continue
            ent = staged[unit]
            arrays = ent.get("arrays")
            if arrays is None:
                # crashed-and-respawned between END and COMMIT: the
                # in-memory parts are gone but the shadow survived
                from paddle_tpu import io_checkpoint as ioc
                tag = mb.tag_of_ep(self.endpoint)
                path = mb.shadow_path(self.state_dir, tag, epoch,
                                      unit)
                try:
                    _, arrays = ioc.verify_npz(path)
                except Exception as e:              # noqa: BLE001
                    return (wire.ERR,
                            (f"staged shadow for {unit!r} "
                             f"unreadable: {e}",))
            adopted += self._adopt_unit(unit, arrays)
        if adopted:
            _m_migrated.inc(adopted)
        self._retire_units(new_map)
        self.epoch = int(epoch)
        self.shard_map = new_map
        _m_epoch.set(self.epoch)
        with self._mig_cv:
            self._frozen.clear()
            for e in [e for e in self._staged if e <= epoch]:
                self._staged.pop(e, None)
            self._mig_cv.notify_all()
        snap_dir = getattr(self, "_snap_dir", None)
        saved = True
        if snap_dir:
            try:
                self.save(snap_dir)
            except Exception as e:                  # noqa: BLE001
                # keep the staged shadows: until a snapshot holding
                # the adopted rows lands, they are the only durable
                # copy — a crash now must find them at respawn
                saved = False
                _ps_log(f"post-commit snapshot failed: {e}")
        if saved:
            self._sweep_my_shadows(max_epoch=epoch)
        _ps_log(f"committed fleet epoch {epoch} "
                f"(adopted {adopted} rows)")
        return (wire.OK_ARR,
                (np.asarray([self.epoch], np.int64),))

    def _migrate_abort(self, epoch):
        """Coordinator gave up on `epoch` before the commit point.
        Stale aborts (epoch already committed) must not act — the
        coordinator only aborts epochs it never published."""
        if epoch <= self.epoch:
            return (wire.OK_ARR,
                    (np.asarray([self.epoch], np.int64),))
        with self._mig_cv:
            self._staged.pop(epoch, None)
            self._frozen.clear()
            self._mig_cv.notify_all()
        self._sweep_my_shadows(min_epoch=epoch)
        _ps_log(f"aborted migration toward epoch {epoch}; "
                f"serving epoch {self.epoch}")
        return (wire.OK_ARR,
                (np.asarray([self.epoch], np.int64),))

    def _sweep_my_shadows(self, min_epoch=None, max_epoch=None):
        if not self.state_dir:
            return
        from paddle_tpu.distributed import membership as mb
        tag = mb.tag_of_ep(self.endpoint)
        for path, _, ep, _ in mb.list_shadows(self.state_dir,
                                              tag=tag):
            if min_epoch is not None and ep < min_epoch:
                continue
            if max_epoch is not None and ep > max_epoch:
                continue
            try:
                os.remove(path)
            except OSError:
                pass

    def _adopt_unit(self, unit, arrays):
        """Install one migrated unit, hosting it from the recipe if it
        is not already resident. Returns the row count adopted."""
        from paddle_tpu.distributed import membership as mb
        kind, name, vsh = mb.parse_unit(unit)
        if kind == "d":
            if name not in self.dense:
                rec = self.recipes.get(name, {})
                self.host_dense(
                    name, np.zeros_like(arrays["value"]),
                    optimizer=rec.get("optimizer"),
                    regularizer=rec.get("regularizer"),
                    param_lr=rec.get("param_lr", 1.0))
            v = self.dense[name]
            with v.cv:
                v.value = np.ascontiguousarray(arrays["value"])
                v.round = int(arrays["round"][0])
                v.step_count = int(arrays["step"][0])
                slots = {k[len("slot/"):]:
                         np.ascontiguousarray(a, dtype=np.float32)
                         for k, a in arrays.items()
                         if k.startswith("slot/")}
                v.slots = slots or None
                v.accum = (np.ascontiguousarray(arrays["accum"])
                           if "accum" in arrays else None)
                v.pushed = set(int(t) for t
                               in arrays.get("pushed", []))
                v.evicted = False
                v.cv.notify_all()
            return 1
        if name not in self.sparse:
            rec = self.recipes.get(name, {})
            self.host_sparse(name, int(rec["dim"]),
                             initializer=rec.get("initializer"),
                             seed=rec.get("seed", 0),
                             lr=rec.get("lr", 1.0),
                             optimizer=rec.get("optimizer", "sgd"))
        tbl = self.sparse[name]
        ids_in = np.asarray(arrays["ids"], np.int64)
        rows_in = np.asarray(arrays["rows"], np.float32)
        acc_in = np.asarray(arrays["accum"], np.float32)
        ids0, rows0, acc0 = tbl.snapshot()
        if acc0 is None:
            acc0 = np.zeros_like(rows0)
        keep = mb.vshard_of(ids0) != vsh if ids0.size else \
            np.zeros(0, bool)
        tbl.restore(np.concatenate([ids0[keep], ids_in]),
                    np.concatenate([rows0[keep], rows_in])
                    if rows0.size or rows_in.size else rows_in,
                    np.concatenate([acc0[keep], acc_in])
                    if acc0.size or acc_in.size else acc_in)
        return int(ids_in.size)

    def _retire_units(self, new_map):
        """Drop everything the new map assigns elsewhere. Dense vars
        are evicted (wakes blocked pullers/pushers into _UnitRetired →
        WRONG_EPOCH); sparse tables stay hosted but shed the vshards
        they lost — a table with zero vshards still answers BEGIN for
        a later grow."""
        from paddle_tpu.distributed import membership as mb
        me = self.endpoint
        dense_map = new_map.get("dense", {})
        for name in list(self.dense):
            if dense_map.get(name, me) != me:
                v = self.dense.pop(name)
                with v.cv:
                    v.evicted = True
                    v.cv.notify_all()
        sparse_map = new_map.get("sparse", {})
        for name, tbl in self.sparse.items():
            owners = sparse_map.get(name)
            if owners is None:
                continue
            mine = {int(v) for v, ep in owners.items() if ep == me}
            ids, rows, acc = tbl.snapshot()
            if not ids.size:
                continue
            keep = np.isin(mb.vshard_of(ids),
                           np.asarray(sorted(mine), np.int64))
            if keep.all():
                continue
            tbl.restore(ids[keep], rows[keep],
                        acc[keep] if acc is not None else None)

    def _handle_frame(self, kind, client_id, seq, fields):
        """Dedup wrapper: retried mutating frames (same client, same
        seq) are answered from the cached reply, never re-applied; a
        retry racing the still-running original waits for it."""
        if kind not in wire.MUTATING or not client_id:
            return self._handle(kind, fields)
        key = (client_id, seq)

        def cached():
            lru = self._dedup.get(client_id)
            if lru is not None and seq in lru:
                lru.move_to_end(seq)
                self._dedup.move_to_end(client_id)
                return lru[seq]
            return None

        with self._dedup_cv:
            while True:
                resp = cached()
                if resp is not None:
                    return resp
                if key not in self._inflight:
                    last = self._dedup_last_seen.get(client_id, -1)
                    if seq <= last - self._replay_seq_tolerance:
                        # known client, seq far behind its high-water
                        # mark, and no cached reply: this apply is a
                        # probable double-apply of a retry whose dedup
                        # entry was LRU-evicted.
                        self.possible_replays += 1
                        logging.getLogger("paddle_tpu.ps").warning(
                            "retry-dedup cache miss for %s seq=%d "
                            "(last_seen=%d): mutating frame will be "
                            "re-applied", client_id, seq, last)
                    self._inflight.add(key)
                    break
                ok = self._dedup_cv.wait_for(
                    lambda: cached() is not None
                    or key not in self._inflight, timeout=150.0)
                enforce(ok, f"duplicate frame {key} timed out waiting "
                            f"for the original")
        try:
            resp = self._handle(kind, fields)
            with self._dedup_cv:
                lru = self._dedup.get(client_id)
                if lru is None:
                    lru = self._dedup[client_id] = \
                        collections.OrderedDict()
                lru[seq] = resp
                if seq > self._dedup_last_seen.get(client_id, -1):
                    self._dedup_last_seen[client_id] = seq
                    self._dedup_last_seen.move_to_end(client_id)
                    while (len(self._dedup_last_seen)
                           > self._dedup_last_seen_cap):
                        self._dedup_last_seen.popitem(last=False)
                self._dedup.move_to_end(client_id)
                while len(lru) > self._dedup_per_client_cap:
                    lru.popitem(last=False)
                while len(self._dedup) > self._dedup_clients_cap:
                    self._dedup.popitem(last=False)
            return resp
        finally:
            with self._dedup_cv:
                self._inflight.discard(key)
                self._dedup_cv.notify_all()

    def _min_round(self):
        rounds = []
        for v in self.dense.values():
            with v.cv:
                rounds.append(int(v.round))
        return min(rounds) if rounds else 0

    # -- checkpoint (kCheckpointBlockId parity) ----------------------------
    def _dense_export(self):
        """(values, var_state, slots) — each var copied under its cv:
        the native step mutates slot buffers in place, and a mid-step
        serialization must not see a half-updated state. Per-var
        atomic; a sync round's partial fan-in (accum/pushed) is NOT
        snapshotted — after a restart the trainers re-push the round."""
        values, state, slots = {}, {}, {}
        for n, v in self.dense.items():
            with v.cv:
                values[n] = np.array(v.value, copy=True)
                state[n] = (int(v.round), int(v.step_count))
                if v.slots:
                    slots[n] = {k: np.array(s, copy=True)
                                for k, s in v.slots.items()}
        return values, state, slots

    def _dense_import(self, name, value, state, slots):
        v = self.dense.get(name)
        if v is None:
            # elastic warm boot: a var this server adopted via
            # migration is in the snapshot but not in the transpiled
            # program — re-host it from the recipe before restoring
            rec = self.recipes.get(name)
            if rec is None or rec.get("kind") != "dense":
                return
            self.host_dense(name, np.zeros_like(np.asarray(value)),
                            optimizer=rec.get("optimizer"),
                            regularizer=rec.get("regularizer"),
                            param_lr=rec.get("param_lr", 1.0))
            v = self.dense[name]
        with v.cv:
            v.value = np.asarray(value)
            if state is not None:
                v.round, v.step_count = state
            if slots:
                # contiguous float32: the native dense kernels hand
                # these buffers to C by pointer
                v.slots = {k: np.ascontiguousarray(a, np.float32)
                           for k, a in slots.items()}
            v.accum = None
            v.pushed.clear()
            v.cv.notify_all()

    def load(self, dirname):
        """Warm boot: restore the newest integrity-verified snapshot
        generation (walking back past corrupt ones). Returns the
        restored generation's meta, or None when nothing restorable
        exists."""
        def make_table(t):
            rec = self.recipes.get(t)
            if rec is None or rec.get("kind") != "sparse":
                return None
            self.host_sparse(t, int(rec["dim"]),
                             initializer=rec.get("initializer"),
                             seed=rec.get("seed", 0),
                             lr=rec.get("lr", 1.0),
                             optimizer=rec.get("optimizer", "sgd"))
            return self.sparse[t]
        return _ps_checkpoint_load(dirname, self.host, self.port,
                                   self._dense_import, self.sparse,
                                   make_table=make_table)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        handle_frame = self._handle_frame

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        # header and payload decode separately so a
                        # payload-malformed reply can still echo
                        # (cid, seq) — the client's stale-reply check
                        # would otherwise reject the typed error
                        try:
                            kind, cid, seq, n = wire.decode_header(
                                _recv_exact(self.request,
                                            wire.HEADER_SIZE))
                        except wire.WireError as e:
                            try:
                                _reply_frame(self.request, wire.ERR,
                                             (f"malformed frame: {e}",))
                            except OSError:
                                pass
                            return
                        try:
                            fields = wire.decode_payload(
                                kind, _recv_exact(self.request, n))
                        except wire.WireError as e:
                            # bytes were never evaluated; typed error,
                            # drop the connection
                            try:
                                _reply_frame(self.request, wire.ERR,
                                             (f"malformed frame: {e}",),
                                             cid, seq)
                            except OSError:
                                pass
                            return
                        try:
                            rk, rf = handle_frame(kind, cid, seq, fields)
                        except Exception as e:
                            rk, rf = wire.ERR, (f"{type(e).__name__}: "
                                                f"{e}",)
                        # echo (client_id, seq): the client rejects a
                        # reply whose seq does not match its request
                        # (a late reply to a timed-out call must never
                        # be consumed as the next call's answer).
                        # _reply_frame, not _send_frame: the module
                        # hook testing/faults' wire chaos patches
                        _reply_frame(self.request, rk, rf, cid, seq)
                except (ConnectionError, EOFError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((self.host, self.port), Handler)
        if self.port == 0:
            self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    def run(self):
        """Blocking serve (the listen_and_serv op's RunImpl): start if
        needed and wait until stop() — used by pserver processes."""
        if self._server is None:
            self.start()
        self._thread.join()

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class NativeUnsupported(Exception):
    """Hosted state not expressible by the C++ server (exotic
    optimizer/regularizer/schedule, non-f32 dtype, custom sparse
    initializer) — callers fall back to the Python ParameterServer."""


class _NativeDenseView:
    """Read-through view of a dense var hosted in the C++ server:
    `.value` and `.round` read the authoritative native state (the
    surface tests and checkpoints use)."""

    def __init__(self, server, name, shape, dtype):
        self._server = server
        self.name = name
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    @property
    def value(self):
        import ctypes
        srv = self._server
        out = np.empty(self.shape, np.float32)
        rc = srv._lib.pt_pss_dense_get(
            srv._h, self.name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        enforce(rc == 0, f"no hosted dense var {self.name!r}")
        return out

    @value.setter
    def value(self, v):
        import ctypes
        srv = self._server
        v = np.ascontiguousarray(v, np.float32)
        rc = srv._lib.pt_pss_dense_set(
            srv._h, self.name.encode(),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), v.size)
        enforce(rc == 0, f"cannot set dense var {self.name!r} "
                         f"(size mismatch?)")

    @property
    def round(self):
        return int(self._server._lib.pt_pss_dense_round(
            self._server._h, self.name.encode()))


class NativeParameterServer(_SnapshotLoop):
    """The C++ control-plane transport (native/src/ps_server.cc):
    listen_and_serv parity with the SAME wire protocol and observable
    semantics as ParameterServer, but the accept loop, frame codec,
    request dispatch, dedup, and optimize kernels all run in C++ — a
    request never touches Python (SURVEY §5.8's hand-written-C++
    commitment; ref: operators/distributed/grpc/grpc_server.cc,
    request_handler_impl.cc). Checkpoint-notify calls back into Python
    to write the same npz artifacts as the Python server.

    Hosting raises NativeUnsupported for state the C++ side cannot
    express (callable LR schedules, exotic optimizers/regularizers,
    non-float32 params, custom sparse initializers); callers
    (make_parameter_server, PServerProgram.build_server) fall back to
    the Python server then."""

    _OPT_KINDS = {"none": 0, "sgd": 1, "momentum": 2, "adam": 3}

    def __init__(self, endpoint, num_trainers=1, sync_mode=True):
        from paddle_tpu import native
        self.host, port = endpoint.rsplit(":", 1)
        self.port = int(port)
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self._lib = native.get_lib()
        self._native_mod = native
        self._h = self._lib.pt_pss_new(
            self.host.encode(), self.port, num_trainers,
            1 if sync_mode else 0, wire.max_message_bytes())
        enforce(bool(self._h), "pt_pss_new failed")
        self._lib.pt_pss_set_stop_grace_ms(
            self._h, int(_stop_grace_seconds() * 1000))
        self.dense = {}            # name -> _NativeDenseView
        self.sparse = {}           # name -> NativeSparseTable view
        self._started = False
        self._stopped = False
        # the ctypes callback object must outlive the server
        self._ckpt_cb = native.PS_CKPT_CB(self._on_checkpoint)
        self._lib.pt_pss_set_checkpoint_cb(self._h, self._ckpt_cb)
        self.incarnation = _new_incarnation()
        self._lib.pt_pss_set_incarnation(self._h, self.incarnation)
        self._save_lock = threading.Lock()

    # -- expressibility ---------------------------------------------------
    @staticmethod
    def _opt_config(optimizer, regularizer):
        """(kind, lr, mu_or_b1, b2, eps, nesterov, decay, coeff) or
        raises NativeUnsupported. (param_lr is NOT folded in here — it
        passes to the C++ side separately and scales lr per step.)"""
        from paddle_tpu import optimizer as po
        if optimizer is None:
            return (0, 0.0, 0.0, 0.0, 0.0, 0, 0, 0.0)
        if callable(optimizer.learning_rate):
            raise NativeUnsupported("callable LR schedule")
        lr = float(optimizer.learning_rate)
        # exact type, not isinstance: subclasses define different rules
        if type(optimizer) is po.SGDOptimizer:
            cfg = (1, lr, 0.0, 0.0, 0.0, 0)
        elif type(optimizer) is po.MomentumOptimizer:
            cfg = (2, lr, float(optimizer.momentum), 0.0, 0.0,
                   int(bool(getattr(optimizer, "use_nesterov", False))))
        elif type(optimizer) is po.AdamOptimizer:
            cfg = (3, lr, float(optimizer.beta1), float(optimizer.beta2),
                   float(optimizer.epsilon), 0)
        else:
            raise NativeUnsupported(
                f"optimizer {type(optimizer).__name__}")
        reg = regularizer or optimizer.regularization
        if reg is None:
            decay = (0, 0.0)
        else:
            from paddle_tpu.regularizer import (L1DecayRegularizer,
                                                L2DecayRegularizer)
            if type(reg) is L2DecayRegularizer:
                decay = (1, float(reg.coeff))
            elif type(reg) is L1DecayRegularizer:
                decay = (2, float(reg.coeff))
            else:
                raise NativeUnsupported(
                    f"regularizer {type(reg).__name__}")
        return cfg + decay

    # -- hosting ----------------------------------------------------------
    def host_dense(self, name, value, optimizer=None, regularizer=None,
                   param_lr=1.0):
        import ctypes
        enforce(not self._started, "host_dense before start()")
        value = np.asarray(value)
        if value.dtype != np.float32:
            raise NativeUnsupported(f"dtype {value.dtype}")
        kind, lr, b1, b2, eps, nesterov, decay, coeff = \
            self._opt_config(optimizer, regularizer)
        v = np.ascontiguousarray(value, np.float32)
        dims = np.asarray(v.shape or (1,), np.uint32)
        rc = self._lib.pt_pss_host_dense(
            self._h, name.encode(),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(dims), kind, lr, b1, b2, eps, nesterov, decay, coeff,
            float(param_lr))
        enforce(rc == 0, "pt_pss_host_dense failed")
        self.dense[name] = _NativeDenseView(self, name,
                                            v.shape or (1,), v.dtype)

    def host_sparse(self, name, dim, initializer=None, seed=0, lr=1.0,
                    optimizer="sgd"):
        if initializer is not None:
            raise NativeUnsupported("custom sparse initializer")
        enforce(not self._started, "host_sparse before start()")
        enforce(optimizer in ("sgd", "adagrad"),
                f"sparse optimizer must be sgd|adagrad, got {optimizer!r}")
        rc = self._lib.pt_pss_host_sparse(
            self._h, name.encode(), int(dim),
            {"sgd": 0, "adagrad": 1}[optimizer], float(lr), 1e-6,
            int(seed) & 0xFFFFFFFFFFFFFFFF)
        enforce(rc == 0, "pt_pss_host_sparse failed")
        handle = self._lib.pt_pss_sparse_table(self._h, name.encode())
        self.sparse[name] = self._native_mod.NativeSparseTable \
            .from_handle(handle, dim, owner=self)

    # -- checkpoint (same artifacts as ParameterServer.save/load) ---------
    #: Python slot name -> native slot selector (ps_server.cc:
    #: pt_pss_dense_set_slot takes it directly; pt_pss_dense_export
    #: reports presence as the bitmask ``1 << which``). The artifact
    #: contract speaks the Python names so cross-transport restore
    #: works either direction.
    _SLOT_WHICH = {"velocity": 0, "moment1": 1, "moment2": 2}

    def _on_checkpoint(self, dirname):
        try:
            self.save(os.fsdecode(dirname))
        except Exception:
            logging.getLogger("paddle_tpu.ps").exception(
                "checkpoint-notify save failed")

    def _dense_export(self):
        import ctypes
        fp = ctypes.POINTER(ctypes.c_float)
        values, state, slots = {}, {}, {}
        for n, view in self.dense.items():
            count = int(np.prod(view.shape or (1,), dtype=np.int64))
            # ONE native lock acquisition per var (pt_pss_dense_export)
            # copies value + round/step + every materialized slot
            # together: reading them through separate getters would let
            # an optimizer step land in between and publish round R+1
            # stamped onto round-R parameters — a torn snapshot whose
            # lost update no staleness accounting would ever see (the
            # Python transport's export holds the var cv the same way)
            val = np.empty(count, np.float32)
            bufs = {k: np.empty(count, np.float32)
                    for k in self._SLOT_WHICH}
            rnd = ctypes.c_uint64()
            stp = ctypes.c_long()
            have = ctypes.c_int()
            rc = self._lib.pt_pss_dense_export(
                self._h, n.encode(), val.ctypes.data_as(fp),
                ctypes.byref(rnd), ctypes.byref(stp),
                bufs["velocity"].ctypes.data_as(fp),
                bufs["moment1"].ctypes.data_as(fp),
                bufs["moment2"].ctypes.data_as(fp),
                ctypes.byref(have))
            enforce(rc == 0, f"no hosted dense var {n!r}")
            values[n] = val.reshape(view.shape)
            state[n] = (int(rnd.value), int(stp.value))
            sl = {k: bufs[k].reshape(view.shape)
                  for k, which in self._SLOT_WHICH.items()
                  if have.value & (1 << which)}
            if sl:
                slots[n] = sl
        return values, state, slots

    def _dense_import(self, name, value, state, slots):
        import ctypes
        fp = ctypes.POINTER(ctypes.c_float)
        view = self.dense.get(name)
        if view is None:
            return
        view.value = value
        if state is not None:
            self._lib.pt_pss_dense_set_state(
                self._h, name.encode(), int(state[0]), int(state[1]))
        for k, a in (slots or {}).items():
            which = self._SLOT_WHICH.get(k)
            if which is None:
                continue
            a = np.ascontiguousarray(a, np.float32).ravel()
            self._lib.pt_pss_dense_set_slot(
                self._h, name.encode(), which,
                a.ctypes.data_as(fp), a.size)

    def load(self, dirname):
        """Warm boot (see ParameterServer.load): returns the restored
        generation's meta or None."""
        return _ps_checkpoint_load(dirname, self.host, self.port,
                                   self._dense_import, self.sparse)

    # -- observability ----------------------------------------------------
    @property
    def possible_replays(self):
        return int(self._lib.pt_pss_possible_replays(self._h))

    # -- lifecycle --------------------------------------------------------
    def start(self):
        enforce(not self._started, "already started")
        port = self._lib.pt_pss_start(self._h)
        enforce(port > 0, f"native PS server failed to start: "
                          f"{self._lib.pt_pss_error(self._h).decode()}")
        self.port = port
        self._started = True
        return self

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    def run(self):
        """Blocking serve (listen_and_serv RunImpl): waits inside the
        C++ server until a STOP frame or stop() — ctypes releases the
        GIL for the duration."""
        if not self._started:
            self.start()
        self._lib.pt_pss_join(self._h)
        self.stop()

    def stop(self):
        if self._started and not self._stopped:
            self._lib.pt_pss_stop(self._h)
            self._stopped = True

    def __del__(self):
        try:
            self.stop()
            self._lib.pt_pss_free(self._h)
        except Exception:
            pass


def _is_missing_toolchain(e):
    """True for the RuntimeError the lazy native build raises when no
    C++ toolchain is present (native/_build) — the one native-transport
    failure that auto mode swallows silently by design. Shared by
    make_parameter_server and PServerProgram.build_server so the two
    fallback sites can't drift."""
    return isinstance(e, RuntimeError) and "native build failed" in str(e)


def make_parameter_server(endpoint, num_trainers=1, sync_mode=True,
                          transport=None):
    """Factory honoring FLAGS_ps_transport: the C++ server when the
    toolchain is present (hosting may still fall back — see
    PServerProgram.build_server), the Python server otherwise."""
    transport = transport or get_flag("ps_transport")
    enforce(transport in ("auto", "native", "python"),
            f"FLAGS_ps_transport must be auto|native|python, "
            f"got {transport!r}")
    if transport == "python":
        return ParameterServer(endpoint, num_trainers, sync_mode)
    try:
        return NativeParameterServer(endpoint, num_trainers, sync_mode)
    except Exception as e:
        if transport == "native":
            raise
        # auto: a missing toolchain falls back silently by design; any
        # OTHER failure is a native-path bug that must not hide behind
        # the ~2x-slower Python transport unannounced
        if not isinstance(e, NativeUnsupported) \
                and not _is_missing_toolchain(e):
            logging.getLogger("paddle_tpu.ps").warning(
                "native PS transport failed unexpectedly (%s: %s) — "
                "falling back to the Python server",
                type(e).__name__, e)
        return ParameterServer(endpoint, num_trainers, sync_mode)


class _Rerouted(Exception):
    """A call was fenced (WRONG_EPOCH) or its endpoint retired: the
    client adopted a newer shard map and the caller must recompute the
    route and re-send. The fenced server applied NOTHING, so the
    re-send (with a fresh seq) stays exactly-once."""


class PSClient:
    """RPCClient parity (rpc_client.h:33): persistent connections to every
    pserver, var→endpoint routing, send/get/prefetch/barrier/checkpoint.
    Connection failures retry with exponential backoff (grpc_client.cc
    retry path); retried mutating frames carry the same (client_id, seq)
    so the server dedups instead of re-applying.

    Pserver-restart awareness (docs/ELASTIC_TRAINING.md "Pserver
    failover"): a connection-REFUSED/RESET failure is pserver downtime
    under supervised failover, retried against a wall-clock budget
    (``PT_PS_RECONNECT_SECS``, default 60 — sized for respawn backoff
    plus a worker-process warm boot) rather than the fixed attempt
    count transient blips get. Every fresh connection probes
    ``SERVER_INFO``; a changed incarnation token means the server
    restarted from its last snapshot, and the next sync-mode pull
    re-establishes its round expectation at the server's round —
    counting the lost rounds in ``ps_stale_rounds_total`` — instead of
    blocking 120 s for a round the reborn server will never reach."""

    MAX_RETRIES = 5
    BACKOFF = 0.05          # seconds, doubles per attempt (cap 2 s)

    def __init__(self, endpoints, var_ep=None, trainer_id=0,
                 timeout=150.0):
        self.endpoints = list(endpoints)
        self.var_ep = dict(var_ep or {})
        self.trainer_id = trainer_id
        self.client_id = int.from_bytes(os.urandom(8), "little") or 1
        # per-connection reply timeout; the 150 s default stays above
        # the server-side wait timeouts (120 s) so the server's own
        # EnforceNotMet surfaces as a typed error response before the
        # transport gives up. Chaos tests lower it.
        self.timeout = float(timeout)
        self._seq = 0
        self._seq_lock = threading.Lock()
        # connections are per-thread: a blocking pull (sync-mode round
        # wait) in one thread must not serialize pushes from another
        # (the Communicator's send thread, grpc_client's channel pool role)
        self._tls = threading.local()
        self._all_socks = []
        self._all_lock = threading.Lock()
        # failover bookkeeping (shared across threads, under one lock):
        # last SERVER_INFO token per endpoint, the server round captured
        # when a restart was detected (consumed by the next pull), and
        # the cumulative per-endpoint round offset pulls subtract
        self._inc_lock = threading.Lock()
        self._incarnations = {}
        self._stale_pending = {}
        self._round_offset = {}
        self._no_info = set()     # endpoints without SERVER_INFO
        # elastic membership: the newest committed (epoch, shard map)
        # this client has seen — None until a resize fences us
        self._epoch = None
        self._map = None

    @staticmethod
    def _reconnect_budget():
        """Wall-clock budget for connection-refused/reset retries
        (pserver downtime under supervised failover): the supervisor's
        respawn backoff plus a fresh worker process's warm boot."""
        try:
            v = float(os.environ.get("PT_PS_RECONNECT_SECS", "60"))
        except ValueError:
            return 60.0
        import math as _math
        if not _math.isfinite(v):
            return 60.0
        return max(v, 0.0)

    def _next_seq(self):
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def _sock(self, ep, fresh=False):
        socks = getattr(self._tls, "socks", None)
        if socks is None:
            socks = self._tls.socks = {}
        s = socks.get(ep)
        if fresh and s is not None:
            try:
                s.close()
            except OSError:
                pass
            s = None
        if s is None:
            host, port = ep.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            socks[ep] = s
            with self._all_lock:
                self._all_socks.append(s)
            # a NEW connection is the only moment the server identity
            # can have changed under us — probe it before any frame
            # rides this socket
            self._note_incarnation(ep, s)
        return s

    def _note_incarnation(self, ep, s):
        """SERVER_INFO probe on a fresh connection: record the server's
        incarnation token; a CHANGE means the pserver restarted (it
        warm-booted from its last snapshot — updates since are gone)
        and arms the round resync the next pull consumes."""
        with self._inc_lock:
            if ep in self._no_info:
                return
        seq = self._next_seq()
        try:
            _send_frame(s, wire.SERVER_INFO, (), self.client_id, seq)
            rk, _, rseq, rf = _recv_frame(s)
        except (ConnectionError, socket.timeout, OSError,
                wire.WireError):
            # no reply at all — a dying server, not a legacy one;
            # surface as a connection failure so the caller's retry
            # path reconnects (and re-probes)
            self._drop_sock(ep)
            raise ConnectionError(
                f"pserver {ep}: SERVER_INFO probe got no reply")
        if rk != wire.OK_ARR or rseq != seq:
            # a pre-SERVER_INFO server rejects the unknown kind (ERR,
            # then closes the connection): remember it has no failover
            # probe and hand the caller a fresh socket
            with self._inc_lock:
                self._no_info.add(ep)
            self._drop_sock(ep)
            raise ConnectionError(
                f"pserver {ep}: no SERVER_INFO support (legacy "
                f"server); restart detection disabled")
        vals = np.asarray(rf[0]).ravel()
        inc, srv_round = int(vals[0]), int(vals[1])
        with self._inc_lock:
            prev = self._incarnations.get(ep)
            self._incarnations[ep] = inc
            if prev is not None and prev != inc:
                self._stale_pending[ep] = srv_round
                logging.getLogger("paddle_tpu.ps").warning(
                    "pserver %s restarted (incarnation %#x -> %#x): "
                    "serving round %d from its last snapshot; pulls "
                    "resync and lost rounds count in "
                    "ps_stale_rounds_total", ep, prev, inc, srv_round)

    def _effective_round(self, ep, min_round):
        """The round a pull should actually wait for: ``min_round``
        minus this endpoint's accumulated restart offset; a pending
        restart detection is consumed HERE, growing the offset by the
        rounds the reborn server lost (precise staleness — counted
        once, at the resync)."""
        with self._inc_lock:
            off = self._round_offset.get(ep, 0)
            want = min_round - off
            pend = self._stale_pending.get(ep)
            if pend is not None and want > pend:
                # consume the armed resync ONLY when this pull
                # actually outruns the reborn server: popping it on a
                # low-round pull (eval fetch, async min_round=0) would
                # disarm the resync and leave the NEXT training pull
                # deadlocking on a round the server will never reach —
                # the exact failure this machinery exists to prevent
                self._stale_pending.pop(ep, None)
                lost = want - pend
                self._round_offset[ep] = off + lost
                _m_stale_rounds.inc(lost)
                logging.getLogger("paddle_tpu.ps").warning(
                    "pserver %s: pull expected round %d but the "
                    "restarted server is at round %d — %d round(s) of "
                    "updates since its last snapshot were lost; "
                    "resuming from the snapshot round", ep, want, pend,
                    lost)
                want = pend
            return max(0, want)

    def _drop_sock(self, ep):
        """Close + forget the cached connection: a socket whose stream
        position is unknown (timeout, stale reply) must never be
        reused — a late reply would be consumed by the next call."""
        socks = getattr(self._tls, "socks", None)
        s = socks.pop(ep, None) if socks else None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
            with self._all_lock:
                if s in self._all_socks:
                    self._all_socks.remove(s)

    def _call(self, ep, kind, *fields):
        seq = self._next_seq()
        delay = self.BACKOFF
        attempts = 0            # transient failures (fixed budget)
        conn_failures = 0
        refused_deadline = None  # downtime failures (wall-clock budget)
        probed_map = False
        while True:
            try:
                s = self._sock(ep, fresh=conn_failures > 0)
                send_fields = fields
                if kind in (wire.PULL_PARAM, wire.PULL_PARAM_E):
                    # computed AFTER _sock: a reconnect's SERVER_INFO
                    # probe may have just armed the round resync this
                    # pull must consume
                    i = 1 if kind == wire.PULL_PARAM else 2
                    send_fields = fields[:i] + (self._effective_round(
                        ep, int(fields[i])),)
                _send_frame(s, kind, send_fields, self.client_id, seq)
                rk, _, rseq, rf = _recv_frame(s)
                if rseq != seq:
                    if rk == wire.ERR and rseq == 0:
                        # header-level rejection (bad magic/version/
                        # size): the server could not echo our seq —
                        # surface the typed error, don't burn retries
                        # re-sending the same bad frame
                        self._drop_sock(ep)
                        enforce(False, f"pserver {ep} error: "
                                       f"{rf[0] if rf else '?'}")
                    raise ConnectionError(
                        f"stale reply on {ep}: seq {rseq} != {seq}")
                break
            except (ConnectionError, socket.timeout, OSError,
                    wire.WireError) as e:
                self._drop_sock(ep)
                conn_failures += 1
                if isinstance(e, (ConnectionRefusedError,
                                  ConnectionResetError,
                                  BrokenPipeError)):
                    # pserver DOWNTIME (death, or supervised failover
                    # mid-respawn): a fixed attempt count would give up
                    # seconds into a restart that takes tens — retry
                    # against a wall-clock budget instead
                    now = time.monotonic()
                    if refused_deadline is None:
                        refused_deadline = (now
                                            + self._reconnect_budget())
                    if now >= refused_deadline:
                        raise
                    # a refused endpoint may be RETIRED (fleet shrink),
                    # not restarting: once per call, ask a surviving
                    # server for the committed map — if it is newer,
                    # re-route instead of burning the whole budget
                    if not probed_map:
                        probed_map = True
                        if self._maybe_probe_map(ep):
                            raise _Rerouted(
                                f"pserver {ep} unreachable and a newer "
                                f"shard map is committed")
                else:
                    attempts += 1
                    if attempts > self.MAX_RETRIES:
                        raise
                if _goodput._armed:
                    # reconnect backoff is time spent waiting on the
                    # fleet, not computing (goodput ledger)
                    _goodput.attribute(delay, phase="collective_wait")
                time.sleep(delay)
                delay = min(delay * 2, 2.0)
        if conn_failures:
            # the call survived at least one dropped/refused
            # connection — mutating frames stayed exactly-once via the
            # server's (client_id, seq) dedup
            _m_reconnects.inc()
        if rk == wire.WRONG_EPOCH:
            # the server fenced us: it applied NOTHING and handed back
            # the committed (epoch, map) — adopt and re-route
            self._adopt_map(int(rf[0]), rf[1])
            raise _Rerouted(f"pserver {ep} fenced request at epoch "
                            f"{int(rf[0])}")
        enforce(rk != wire.ERR, f"pserver {ep} error: "
                                f"{rf[0] if rf else '?'}")
        if rk == wire.OK_ARR:
            return rf[0]
        if rk == wire.OK_NAMES:
            return tuple(t.split("\n") if t else [] for t in rf)
        if rk == wire.OK_JSON:
            return rf[0]
        return None

    def _ep_of(self, name):
        ep = self.var_ep.get(name)
        enforce(ep is not None, f"var {name!r} not routed to any pserver")
        return ep

    # -- elastic routing (docs/ELASTIC_TRAINING.md "Resizing") -------------
    def _routing(self):
        with self._inc_lock:
            return self._epoch, self._map

    def _adopt_map(self, epoch, map_obj):
        """Adopt a committed (epoch, shard map) if strictly newer.
        Accepts the map as a dict or its JSON wire form."""
        if isinstance(map_obj, str):
            try:
                map_obj = json.loads(map_obj) if map_obj else None
            except ValueError:
                return False
        if not map_obj or "servers" not in map_obj:
            return False
        epoch = int(epoch)
        with self._inc_lock:
            if self._epoch is not None and epoch <= self._epoch:
                return False
            self._epoch, self._map = epoch, map_obj
        logging.getLogger("paddle_tpu.ps").info(
            "adopted fleet epoch %d (%d servers)", epoch,
            len(map_obj.get("servers", [])))
        return True

    def _maybe_probe_map(self, failed_ep):
        """Backstop for a RETIRED endpoint (fleet shrink): ask any
        surviving server for the committed map via EPOCH_MAP. Returns
        True iff a strictly newer map was adopted."""
        _, m = self._routing()
        eps = list(m.get("servers", [])) if m else list(self.endpoints)
        for ep in eps:
            if ep == failed_ep:
                continue
            try:
                host, port = ep.rsplit(":", 1)
                s = socket.create_connection((host, int(port)),
                                             timeout=2.0)
                try:
                    s.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
                    seq = self._next_seq()
                    _send_frame(s, wire.EPOCH_MAP, (),
                                self.client_id, seq)
                    rk, _, rseq, rf = _recv_frame(s)
                finally:
                    s.close()
                if rk != wire.OK_JSON or rseq != seq:
                    continue
                obj = json.loads(rf[0])
                if obj.get("map"):
                    return self._adopt_map(int(obj.get("epoch", 0)),
                                           obj["map"])
                return False
            except (ConnectionError, socket.timeout, OSError,
                    wire.WireError, ValueError):
                continue
        return False

    def _routed(self, fn):
        """Run ``fn`` (which routes off the current map), re-running it
        on _Rerouted — each fence refreshes the map, so the route
        converges on the committed epoch."""
        last = None
        for n in range(20):
            try:
                return fn()
            except _Rerouted as e:
                last = e
                time.sleep(min(0.05 * (n + 1), 0.5))
        enforce(False, f"pserver request never settled on a fleet "
                       f"epoch after 20 re-routes (last: {last})")

    def _dense_ep(self, name):
        """(epoch, endpoint) for a dense var: the committed map when we
        have one and it routes this var, else the static transpile-time
        placement with epoch None (legacy, unfenced frame kinds)."""
        epoch, m = self._routing()
        if m is None or name not in m.get("dense", {}):
            return None, self._ep_of(name)
        return epoch, m["dense"][name]

    def _sparse_route(self, table, ids):
        """[(epoch, endpoint, positions-or-None)] covering ``ids``.
        positions None means "all of ids" (the single-route legacy
        path). Always non-empty, even for empty ids."""
        epoch, m = self._routing()
        owners = (m.get("sparse") or {}).get(table) if m else None
        if owners is None:
            return [(None, self._ep_of(table), None)]
        from paddle_tpu.distributed import membership as mb
        vs = mb.vshard_of(ids)
        groups = {}
        for v in np.unique(vs):
            ep = owners.get(str(int(v))) or self._ep_of(table)
            groups.setdefault(ep, []).append(int(v))
        out = [(epoch, ep,
                np.flatnonzero(np.isin(vs, np.asarray(groups[ep],
                                                      np.int64))))
               for ep in sorted(groups)]
        if not out:
            eps = sorted(set(owners.values()))
            out = [(epoch, eps[0] if eps else self._ep_of(table),
                    np.zeros(0, np.int64))]
        return out

    # -- dense -------------------------------------------------------------
    def push_grad(self, name, grad):
        g = np.asarray(grad)

        def go():
            epoch, ep = self._dense_ep(name)
            if epoch is None:
                self._call(ep, wire.PUSH_GRAD, name, self.trainer_id,
                           g)
            else:
                self._call(ep, wire.PUSH_GRAD_E, epoch, name,
                           self.trainer_id, g)
        self._routed(go)

    def pull_param(self, name, min_round=0):
        def go():
            epoch, ep = self._dense_ep(name)
            if epoch is None:
                return self._call(ep, wire.PULL_PARAM, name, min_round)
            return self._call(ep, wire.PULL_PARAM_E, epoch, name,
                              min_round)
        return self._routed(go)

    # -- sparse (parameter_prefetch.cc parity) -----------------------------
    def pull_sparse(self, table, ids):
        ids = np.asarray(ids, np.int64)
        buf = [None]

        def fetch(pos, depth=0):
            enforce(depth < 20, f"sparse pull on {table!r} never "
                                f"settled on a fleet epoch")
            sub_ids = ids if pos is None else ids[pos]
            for epoch, ep, idx in self._sparse_route(table, sub_ids):
                if pos is None:
                    p = idx
                elif idx is None:
                    p = pos
                else:
                    p = pos[idx]
                si = ids if p is None else ids[p]
                try:
                    if epoch is None:
                        sub = self._call(ep, wire.PULL_SPARSE, table,
                                         si)
                    else:
                        sub = self._call(ep, wire.PULL_SPARSE_E,
                                         epoch, table, si)
                except _Rerouted:
                    time.sleep(min(0.05 * (depth + 1), 0.5))
                    fetch(p, depth + 1)
                    continue
                sub = np.asarray(sub)
                if p is None:
                    buf[0] = sub
                    return
                if buf[0] is None:
                    buf[0] = np.zeros((ids.size,) + sub.shape[1:],
                                      sub.dtype)
                buf[0][p] = sub
        fetch(None)
        return buf[0]

    def push_sparse(self, table, ids, grads, lr=None):
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads)

        def send(pos, depth=0):
            # only the fenced GROUP is re-sent (the fenced server
            # applied nothing), so a re-route mid-multi-server push
            # never double-applies the groups that already landed
            enforce(depth < 20, f"sparse push on {table!r} never "
                                f"settled on a fleet epoch")
            sub_ids = ids if pos is None else ids[pos]
            for epoch, ep, idx in self._sparse_route(table, sub_ids):
                if pos is None:
                    p = idx
                elif idx is None:
                    p = pos
                else:
                    p = pos[idx]
                si = ids if p is None else ids[p]
                sg = grads if p is None else grads[p]
                try:
                    if epoch is None:
                        self._call(ep, wire.PUSH_SPARSE, table, si,
                                   sg, lr)
                    else:
                        self._call(ep, wire.PUSH_SPARSE_E, epoch,
                                   table, si, sg, lr)
                except _Rerouted:
                    time.sleep(min(0.05 * (depth + 1), 0.5))
                    send(p, depth + 1)
        send(None)

    def shrink_table(self, table, max_age):
        """FleetWrapper::ShrinkSparseTable parity: evict rows untouched
        for more than ``max_age`` pull/push calls. Returns evicted
        count (summed across owners when the table is resharded)."""
        def go():
            _, m = self._routing()
            owners = (m.get("sparse") or {}).get(table) if m else None
            eps = sorted(set(owners.values())) if owners \
                else [self._ep_of(table)]
            total = 0
            for ep in eps:
                out = self._call(ep, wire.SHRINK_TABLE, table,
                                 int(max_age))
                total += int(np.asarray(out).ravel()[0])
            return total
        return self._routed(go)

    # -- control -----------------------------------------------------------
    def _all_eps(self):
        _, m = self._routing()
        return list(m["servers"]) if m else list(self.endpoints)

    def barrier(self, tag="global"):
        def go():
            for ep in self._all_eps():
                self._call(ep, wire.BARRIER, tag, self.trainer_id)
        if _goodput._armed:
            # barrier wall time = waiting for the slowest peer
            # (goodput ledger's collective_wait / straggler phase)
            _t_gp = time.perf_counter()
            try:
                self._routed(go)
            finally:
                _goodput.attribute(time.perf_counter() - _t_gp,
                                   phase="collective_wait")
            return
        self._routed(go)

    def checkpoint_notify(self, dirname):
        for ep in self._all_eps():
            self._call(ep, wire.CHECKPOINT_NOTIFY, dirname)

    def list_vars(self, ep=None):
        return self._call(ep or self._all_eps()[0], wire.LIST_VARS)

    def server_info(self, ep=None):
        """(incarnation, min dense round) of one pserver — the
        failover probe, also sent automatically on every fresh
        connection (see ``_note_incarnation``)."""
        out = self._call(ep or self.endpoints[0], wire.SERVER_INFO)
        vals = np.asarray(out).ravel()
        return int(vals[0]), int(vals[1])

    def stop_servers(self):
        for ep in self._all_eps():
            try:
                self._call(ep, wire.STOP)
            except Exception:
                pass

    def close(self):
        with self._all_lock:
            for s in self._all_socks:
                try:
                    s.close()
                except OSError:
                    pass
            self._all_socks.clear()
        self._tls = threading.local()


class Communicator:
    """Async trainer-side grad sender (communicator.h:160 parity): grads
    queue up per var, a background thread merges (sums) pending grads per
    var and pushes merged updates — send_queue semantics of MergeVars."""

    def __init__(self, client, merge_steps=1):
        self.client = client
        self.merge_steps = max(int(merge_steps), 1)
        self._pending = {}
        self._counts = {}
        self._cv = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def send(self, name, grad):
        with self._cv:
            g = np.asarray(grad)
            if name in self._pending:
                self._pending[name] = self._pending[name] + g
            else:
                self._pending[name] = g.copy()
            self._counts[name] = self._counts.get(name, 0) + 1
            self._cv.notify()

    def _drain(self):
        ready = {}
        for n, c in list(self._counts.items()):
            if c >= self.merge_steps or self._stop:
                ready[n] = self._pending.pop(n) / c
                del self._counts[n]
        return ready

    def _loop(self):
        while True:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._stop or any(
                        c >= self.merge_steps for c in self._counts.values()),
                    timeout=0.5)
                ready = self._drain()
                done = self._stop and not self._counts
            for n, g in ready.items():
                self.client.push_grad(n, g)
            if done:
                return

    def flush(self):
        with self._cv:
            ready = {n: self._pending.pop(n) / self._counts.pop(n)
                     for n in list(self._counts)}
        for n, g in ready.items():
            self.client.push_grad(n, g)

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=10.0)


def _maybe_ps_exporter():
    """A RankExporter for THIS pserver process when launched under a
    supervisor (PT_PS_METRICS_DIR, set by launch_ps — deliberately
    NOT PADDLE_HEARTBEAT_DIR, which the launcher reserves for
    trainers so a role-shared script's ``from_env`` hookups can never
    clobber a trainer's files): snapshots land at
    ``rank<worker_num + index>.prom`` — offset past the trainer
    ranks, because pservers share the trainer id numbering and
    ``rank<i>.prom`` would collide with trainer i's. The launcher's
    job aggregation reads every rank*.prom, so the pserver-side
    snapshot metrics reach the job-level metrics.prom; the hang
    watchdog only consults ranks < worker_num, so the offset files
    never vouch for liveness."""
    d = os.environ.get("PT_PS_METRICS_DIR")
    if not d or os.environ.get("TRAINING_ROLE") != "PSERVER":
        return None
    try:
        from paddle_tpu.distributed import health
        from paddle_tpu.monitor.exporter import RankExporter
        rank = (int(os.environ.get("PADDLE_TRAINERS_NUM", "0") or 0)
                + int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0))
        return RankExporter(health.metrics_path(d, rank),
                            interval=1.0).start()
    except Exception:
        return None             # telemetry must not block serving


def _ps_reconcile_epoch(server, state_dir, meta):
    """Warm-boot membership reconcile: line this server up with the
    committed fleet epoch. The snapshot meta carries the epoch + map
    the server was serving when it last saved; ``fleet_epoch.json`` is
    the fleet's single source of truth. If the file is AHEAD of the
    snapshot, this server crashed between the coordinator's commit
    publish and its own post-commit snapshot — adopt the verified
    shadows the file says we won, retire what we lost, and serve the
    committed epoch. All remaining shadows for our tag are then swept:
    at-or-below the committed epoch they are consumed, above it they
    are debris of a migration whose coordinator will abort or restage."""
    from paddle_tpu.distributed import membership as mb
    from paddle_tpu import io_checkpoint as ioc
    server.state_dir = state_dir
    server.epoch = int((meta or {}).get("epoch", 0) or 0)
    server.shard_map = (meta or {}).get("shard_map") or None
    ef = mb.load_epoch_file(state_dir)
    if ef and int(ef.get("epoch", 0)) > server.epoch:
        epoch, new_map = int(ef["epoch"]), ef["map"]
        tag = mb.tag_of_ep(server.endpoint)
        adopted = 0
        for path, _, ep, _ in mb.list_shadows(state_dir, tag=tag):
            if ep != epoch:
                continue
            try:
                manifest, arrays = ioc.verify_npz(path)
            except Exception as e:                  # noqa: BLE001
                _ps_log(f"ignoring unreadable shadow {path}: {e}")
                continue
            unit = (manifest or {}).get("unit")
            if not unit or not _unit_owned_by(new_map, unit,
                                              server.endpoint):
                continue
            adopted += server._adopt_unit(unit, arrays)
        server._retire_units(new_map)
        server.epoch, server.shard_map = epoch, new_map
        if adopted:
            _m_migrated.inc(adopted)
            # persist the adoption before sweeping its shadows: until
            # a snapshot holds these rows the shadows are the only
            # durable copy, and a crash here must find them again
            try:
                server.save(state_dir)
            except Exception as e:                  # noqa: BLE001
                _ps_log(f"post-reconcile snapshot failed ({e}); "
                        f"keeping staged shadows")
                _m_epoch.set(server.epoch)
                return
        _ps_log(f"reconciled to committed fleet epoch {epoch} "
                f"(adopted {adopted} rows from staged shadows)")
    server._sweep_my_shadows()
    tag = mb.tag_of_ep(server.endpoint)
    swept = 0
    for fn in _ps_listdir(state_dir):
        if fn.startswith(f".psshadow_{tag}.") \
                and fn.endswith(".tmp.npz"):
            try:
                os.remove(os.path.join(state_dir, fn))
                swept += 1
            except OSError:
                pass
    if swept:
        _ps_log(f"swept {swept} torn shadow temp file(s)")
    _m_epoch.set(server.epoch)


def run_pserver(pserver_program, state_dir=None, snapshot_secs=None,
                on_server=None, recipes=None):
    """Build + run a blocking ParameterServer from a transpiled
    PServerProgram (the exe.run(pserver_prog) role in §3.3).

    Failover wiring (docs/ELASTIC_TRAINING.md "Pserver failover"):
    with ``state_dir`` (or ``PT_PS_SNAPSHOT_DIR``, exported by
    ``launch_ps --ps_snapshot_secs``) the server WARM-BOOTS from its
    newest integrity-verified snapshot generation before serving —
    quarantining and walking back past corrupt ones — then keeps a
    periodic background snapshot every ``snapshot_secs`` (or
    ``PT_PS_SNAPSHOT_SECS``, default 5 s) plus a final flush on
    graceful stop. ``on_server`` (if given) is called with the built
    server after the warm boot, before serving — the hook chaos tests
    use to install ``testing.faults.install_ps_faults``.

    Elastic membership (``PT_PS_ELASTIC``, set by ``launch_ps
    --ps_max_servers/--ps_min_servers``): forces the python transport
    (the native server has no migration handlers), hands the server
    its hosting ``recipes`` (specs for units it may ADOPT in a future
    resize without hosting them today), and reconciles the warm boot
    against ``fleet_epoch.json`` — see ``_ps_reconcile_epoch``."""
    elastic = bool(os.environ.get("PT_PS_ELASTIC"))
    if elastic:
        from paddle_tpu.core.flags import set_flags
        set_flags({"ps_transport": "python"})
    server = pserver_program.build_server()
    if isinstance(server, ParameterServer):
        server.recipes = dict(recipes or {})
    state_dir = state_dir or os.environ.get("PT_PS_SNAPSHOT_DIR") or None
    exporter = _maybe_ps_exporter()
    if state_dir:
        try:
            meta = server.load(state_dir)
        except OSError as e:
            # a transient I/O error that persisted through retries is
            # NOT corruption (the PR-5 rule): serving initial values
            # would silently discard training, so crash into the
            # supervisor's restart budget and let the respawn retry
            # the read
            _ps_log(f"warm boot failed on an I/O error "
                    f"({type(e).__name__}: {e}); exiting so the "
                    f"supervisor's restart budget can retry the read "
                    f"(a blip is not corruption)")
            raise
        except Exception as e:
            _ps_log(f"warm boot failed ({type(e).__name__}: {e}); "
                    f"starting from initial values")
            meta = None
        if meta is not None:
            _ps_log(f"warm boot: restored pserver state generation "
                    f"{meta.get('gen')} (written by incarnation "
                    f"{meta.get('incarnation', 0):#x}) from "
                    f"{state_dir}; now serving as incarnation "
                    f"{server.incarnation:#x}")
        else:
            _ps_log(f"no restorable pserver snapshot in {state_dir}; "
                    f"starting from initial values")
        if elastic and isinstance(server, ParameterServer):
            _ps_reconcile_epoch(server, state_dir, meta)
        if snapshot_secs is None:
            try:
                snapshot_secs = float(
                    os.environ.get("PT_PS_SNAPSHOT_SECS") or 5.0)
            except ValueError:
                snapshot_secs = 5.0
        server.start_snapshots(state_dir, snapshot_secs)
    if on_server is not None:
        on_server(server)
    try:
        server.run()
    finally:
        if state_dir:
            server.stop_snapshots(final_save=True)
        if exporter is not None:
            exporter.stop()
    return server
