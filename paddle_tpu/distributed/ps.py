"""Parameter-server runtime: server, client, async Communicator.

Parity targets (SURVEY §2.6/§3.3): the reference's RPC substrate
(operators/distributed/rpc_client.h:33 AsyncSendVar/AsyncGetVar/
AsyncPrefetchVar/barriers/checkpoint-notify, request handlers
request_handler_impl.cc), the listen_and_serv op
(distributed_ops/listen_and_serv_op.cc:330 — RunSyncLoop fan-in →
optimize blocks → barrier → serve gets; RunAsyncLoop per-var update on
arrival), the async Communicator (distributed/communicator.h:160 —
background send threads with gradient merging), sparse parameter
prefetch (distributed/parameter_prefetch.cc), and checkpoint notify
(distributed_ops/checkpoint_notify_op.cc).

TPU-native shape: dense data-parallelism belongs to SPMD/XLA collectives
(paddle_tpu.parallel); the PS path remains for what genuinely needs a
host-side service — giant/growing sparse tables and asynchronous
trainers. The transport is a length-prefixed-pickle TCP protocol over
persistent connections (the role of grpc_client.cc's bytebuffer serde;
zero external deps), and the "optimize block" the reference executes per
parameter is the same functional `Optimizer` rule the local executor
uses, applied server-side.

Sync semantics (RunSyncLoop parity): each var carries a round counter.
``pull(name, min_round)`` blocks until the server has applied that many
rounds; trainers push grads for round r+1, the server averages the
fan-in of all trainers and steps the optimizer, then wakes pullers.
Round 0 is the server-side initial value, so every trainer starts from
identical parameters (the reference broadcasts startup from pserver the
same way).
"""

import os
import pickle
import socket
import socketserver
import struct
import threading

import numpy as np

from paddle_tpu.core.enforce import enforce

__all__ = ["ParameterServer", "PSClient", "Communicator", "run_pserver"]

_LEN = struct.Struct("<Q")


def _send_msg(sock, obj):
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class _DenseVar:
    """One hosted parameter: value + optimizer state + round counter.

    The update mirrors the local executor's per-param optimize op
    (optimizer.py _apply_optimizer_compute) exactly: per-param
    regularizer then lr * param_lr then the optimizer rule — and NO
    gradient clipping here, because the trainer program keeps its
    clip_grads op and clips before sending (fluid clips trainer-side in
    PS mode too)."""

    def __init__(self, value, optimizer, regularizer=None, param_lr=1.0):
        self.value = np.asarray(value)
        self.optimizer = optimizer
        self.regularizer = regularizer
        self.param_lr = param_lr
        self.slots = None              # lazy: built on first update
        self.step_count = 0
        self.round = 0
        self.accum = None              # sum of grads this round
        self.pushed = set()            # trainer ids seen this round
        self.cv = threading.Condition()

    def _step(self, grad):
        import jax.numpy as jnp
        opt = self.optimizer
        if opt is None:
            return
        p = jnp.asarray(self.value)
        g = jnp.asarray(grad)
        if self.slots is None:
            self.slots = opt._slots(p)
        self.step_count += 1
        t = jnp.asarray(self.step_count, jnp.int32)
        reg = self.regularizer or opt.regularization
        if reg is not None:
            g = reg(p, g)
        lr = opt._lr_value(t.astype(jnp.float32)) * self.param_lr
        new_p, self.slots = opt._update(p, g, self.slots, lr, t)
        self.value = np.asarray(new_p)

    def push_sync(self, trainer_id, grad, num_trainers, timeout=120.0):
        with self.cv:
            if trainer_id in self.pushed:
                # stale duplicate (e.g. retry) — wait for next round
                ok = self.cv.wait_for(
                    lambda: trainer_id not in self.pushed, timeout=timeout)
                enforce(ok, f"duplicate push from trainer {trainer_id} "
                            f"timed out waiting for round fan-in")
            self.accum = grad if self.accum is None else self.accum + grad
            self.pushed.add(trainer_id)
            if len(self.pushed) >= num_trainers:
                self._step(self.accum / max(num_trainers, 1))
                self.accum = None
                self.pushed.clear()
                self.round += 1
                self.cv.notify_all()

    def push_async(self, grad):
        with self.cv:
            self._step(grad)
            self.round += 1
            self.cv.notify_all()

    def pull(self, min_round, timeout=120.0):
        with self.cv:
            ok = self.cv.wait_for(lambda: self.round >= min_round,
                                  timeout=timeout)
            enforce(ok, f"pull timed out waiting for round {min_round}")
            return self.value


class _SparseTable:
    """Hosted sparse table (lookup_sparse_table / pserver sparse block
    parity): rows materialize on first touch; pushes apply the table's
    optimizer rule — "sgd" or "adagrad" (the pserver optimize-block
    choices the reference runs for sparse params)."""

    def __init__(self, dim, initializer=None, seed=0, lr=1.0,
                 optimizer="sgd", eps=1e-6):
        enforce(optimizer in ("sgd", "adagrad"),
                f"sparse optimizer must be sgd|adagrad, got {optimizer!r}")
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self.eps = eps
        self.rows = {}
        self.accum = {}               # adagrad per-row G accumulators
        self._rng = np.random.RandomState(seed)
        self._init = initializer or (
            lambda rng, dim: rng.normal(0, 0.01, dim).astype(np.float32))
        self.lock = threading.Lock()

    def pull(self, ids):
        with self.lock:
            out = np.empty((len(ids), self.dim), np.float32)
            for i, x in enumerate(ids):
                row = self.rows.get(int(x))
                if row is None:
                    row = self._init(self._rng, self.dim)
                    self.rows[int(x)] = row
                out[i] = row
            return out

    def push(self, ids, grads, lr=None):
        lr = self.lr if lr is None else lr
        with self.lock:
            for x, g in zip(ids, grads):
                x = int(x)
                row = self.rows.get(x)
                if row is None:
                    row = self._init(self._rng, self.dim)
                if self.optimizer == "adagrad":
                    acc = self.accum.get(x)
                    acc = (g * g if acc is None else acc + g * g)
                    self.accum[x] = acc
                    row = row - lr * g / (np.sqrt(acc) + self.eps)
                else:
                    row = row - lr * g
                self.rows[x] = row


class ParameterServer:
    """listen_and_serv parity: hosts a set of dense vars + sparse tables,
    applies optimizer updates on grad fan-in, serves pulls/barriers/
    checkpoint-notify over TCP."""

    def __init__(self, endpoint, num_trainers=1, sync_mode=True):
        self.host, port = endpoint.rsplit(":", 1)
        self.port = int(port)
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self.dense = {}
        self.sparse = {}
        self._barrier_lock = threading.Condition()
        self._barrier_count = {}
        self._barrier_gen = {}
        self._server = None
        self._thread = None

    # -- hosting -----------------------------------------------------------
    def host_dense(self, name, value, optimizer=None, regularizer=None,
                   param_lr=1.0):
        self.dense[name] = _DenseVar(value, optimizer, regularizer,
                                     param_lr)

    def host_sparse(self, name, dim, initializer=None, seed=0, lr=1.0,
                    optimizer="sgd"):
        self.sparse[name] = _SparseTable(dim, initializer, seed, lr,
                                         optimizer)

    # -- request handling (request_handler_impl.cc parity) -----------------
    def _handle(self, msg):
        kind = msg[0]
        if kind == "push_grad":
            _, name, trainer_id, grad = msg
            v = self.dense[name]
            if self.sync_mode:
                v.push_sync(trainer_id, grad, self.num_trainers)
            else:
                v.push_async(grad)
            return ("ok",)
        if kind == "pull_param":
            _, name, min_round = msg
            if not self.sync_mode:
                min_round = 0
            return ("ok", self.dense[name].pull(min_round))
        if kind == "pull_sparse":
            _, name, ids = msg
            return ("ok", self.sparse[name].pull(ids))
        if kind == "push_sparse":
            _, name, ids, grads, lr = msg
            self.sparse[name].push(ids, grads, lr)
            return ("ok",)
        if kind == "barrier":
            _, tag, _trainer_id = msg
            with self._barrier_lock:
                gen = self._barrier_gen.setdefault(tag, 0)
                n = self._barrier_count.get(tag, 0) + 1
                self._barrier_count[tag] = n
                if n >= self.num_trainers:
                    self._barrier_count[tag] = 0
                    self._barrier_gen[tag] = gen + 1
                    self._barrier_lock.notify_all()
                else:
                    ok = self._barrier_lock.wait_for(
                        lambda: self._barrier_gen[tag] > gen, timeout=120.0)
                    enforce(ok, f"barrier {tag!r} timed out")
            return ("ok",)
        if kind == "checkpoint_notify":
            _, dirname = msg
            self.save(dirname)
            return ("ok",)
        if kind == "list_vars":
            return ("ok", sorted(self.dense), sorted(self.sparse))
        if kind == "stop":
            threading.Thread(target=self.stop, daemon=True).start()
            return ("ok",)
        return ("err", f"unknown request {kind!r}")

    # -- checkpoint (kCheckpointBlockId parity) ----------------------------
    def save(self, dirname):
        os.makedirs(dirname, exist_ok=True)
        tag = f"{self.host}_{self.port}".replace(".", "_")
        dense = {n: v.value for n, v in self.dense.items()}
        np.savez(os.path.join(dirname, f"pserver_{tag}.npz"), **dense)
        for n, t in self.sparse.items():
            with t.lock:
                ids = np.fromiter(t.rows, np.int64, len(t.rows))
                rows = (np.stack([t.rows[int(i)] for i in ids])
                        if len(ids) else np.zeros((0, t.dim), np.float32))
                accum = (np.stack([t.accum.get(int(i),
                                               np.zeros(t.dim, np.float32))
                                   for i in ids])
                         if len(ids) else np.zeros((0, t.dim), np.float32))
            np.savez(os.path.join(dirname, f"pserver_{tag}_{n}.npz"),
                     ids=ids, rows=rows, accum=accum)

    def load(self, dirname):
        tag = f"{self.host}_{self.port}".replace(".", "_")
        path = os.path.join(dirname, f"pserver_{tag}.npz")
        if os.path.exists(path):
            blob = np.load(path)
            for n in blob.files:
                if n in self.dense:
                    self.dense[n].value = blob[n]
        for n, t in self.sparse.items():
            p = os.path.join(dirname, f"pserver_{tag}_{n}.npz")
            if os.path.exists(p):
                with np.load(p) as blob:
                    t.rows = {int(i): r for i, r in
                              zip(blob["ids"], blob["rows"])}
                    if "accum" in blob.files:
                        t.accum = {int(i): a for i, a in
                                   zip(blob["ids"], blob["accum"])}
                    else:   # old checkpoint: stale accumulators must not
                        t.accum = {}    # scale the restored rows

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        handle = self._handle

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        _send_msg(self.request, handle(_recv_msg(self.request)))
                except (ConnectionError, EOFError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((self.host, self.port), Handler)
        if self.port == 0:
            self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    def run(self):
        """Blocking serve (the listen_and_serv op's RunImpl): start if
        needed and wait until stop() — used by pserver processes."""
        if self._server is None:
            self.start()
        self._thread.join()

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class PSClient:
    """RPCClient parity (rpc_client.h:33): persistent connections to every
    pserver, var→endpoint routing, send/get/prefetch/barrier/checkpoint."""

    def __init__(self, endpoints, var_ep=None, trainer_id=0):
        self.endpoints = list(endpoints)
        self.var_ep = dict(var_ep or {})
        self.trainer_id = trainer_id
        # connections are per-thread: a blocking pull (sync-mode round
        # wait) in one thread must not serialize pushes from another
        # (the Communicator's send thread, grpc_client's channel pool role)
        self._tls = threading.local()
        self._all_socks = []
        self._all_lock = threading.Lock()

    def _sock(self, ep):
        socks = getattr(self._tls, "socks", None)
        if socks is None:
            socks = self._tls.socks = {}
        s = socks.get(ep)
        if s is None:
            host, port = ep.rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=120.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            socks[ep] = s
            with self._all_lock:
                self._all_socks.append(s)
        return s

    def _call(self, ep, *msg):
        s = self._sock(ep)
        _send_msg(s, msg)
        resp = _recv_msg(s)
        enforce(resp[0] == "ok", f"pserver {ep} error: {resp[1:]}")
        return resp[1] if len(resp) > 1 else None

    def _ep_of(self, name):
        ep = self.var_ep.get(name)
        enforce(ep is not None, f"var {name!r} not routed to any pserver")
        return ep

    # -- dense -------------------------------------------------------------
    def push_grad(self, name, grad):
        self._call(self._ep_of(name), "push_grad", name, self.trainer_id,
                   np.asarray(grad))

    def pull_param(self, name, min_round=0):
        return self._call(self._ep_of(name), "pull_param", name, min_round)

    # -- sparse (parameter_prefetch.cc parity) -----------------------------
    def pull_sparse(self, table, ids):
        return self._call(self._ep_of(table), "pull_sparse", table,
                          np.asarray(ids, np.int64))

    def push_sparse(self, table, ids, grads, lr=None):
        self._call(self._ep_of(table), "push_sparse", table,
                   np.asarray(ids, np.int64), np.asarray(grads), lr)

    # -- control -----------------------------------------------------------
    def barrier(self, tag="global"):
        for ep in self.endpoints:
            self._call(ep, "barrier", tag, self.trainer_id)

    def checkpoint_notify(self, dirname):
        for ep in self.endpoints:
            self._call(ep, "checkpoint_notify", dirname)

    def stop_servers(self):
        for ep in self.endpoints:
            try:
                self._call(ep, "stop")
            except Exception:
                pass

    def close(self):
        with self._all_lock:
            for s in self._all_socks:
                try:
                    s.close()
                except OSError:
                    pass
            self._all_socks.clear()
        self._tls = threading.local()


class Communicator:
    """Async trainer-side grad sender (communicator.h:160 parity): grads
    queue up per var, a background thread merges (sums) pending grads per
    var and pushes merged updates — send_queue semantics of MergeVars."""

    def __init__(self, client, merge_steps=1):
        self.client = client
        self.merge_steps = max(int(merge_steps), 1)
        self._pending = {}
        self._counts = {}
        self._cv = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def send(self, name, grad):
        with self._cv:
            g = np.asarray(grad)
            if name in self._pending:
                self._pending[name] = self._pending[name] + g
            else:
                self._pending[name] = g.copy()
            self._counts[name] = self._counts.get(name, 0) + 1
            self._cv.notify()

    def _drain(self):
        ready = {}
        for n, c in list(self._counts.items()):
            if c >= self.merge_steps or self._stop:
                ready[n] = self._pending.pop(n) / c
                del self._counts[n]
        return ready

    def _loop(self):
        while True:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._stop or any(
                        c >= self.merge_steps for c in self._counts.values()),
                    timeout=0.5)
                ready = self._drain()
                done = self._stop and not self._counts
            for n, g in ready.items():
                self.client.push_grad(n, g)
            if done:
                return

    def flush(self):
        with self._cv:
            ready = {n: self._pending.pop(n) / self._counts.pop(n)
                     for n in list(self._counts)}
        for n, g in ready.items():
            self.client.push_grad(n, g)

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=10.0)


def run_pserver(pserver_program):
    """Build + run a blocking ParameterServer from a transpiled
    PServerProgram (the exe.run(pserver_prog) role in §3.3)."""
    server = pserver_program.build_server()
    server.run()
    return server
