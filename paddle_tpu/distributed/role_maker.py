"""Role makers — who am I in the job.

Parity: python/paddle/fluid/incubate/fleet/base/role_maker.py
(RoleMakerBase:30, env-based MultiProcessRoleMaker:106, MPIRoleMaker:146
— MPI path replaced by the TPU scheduler / jax.distributed).
"""

import os
from enum import Enum

import jax

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "MPISymetricRoleMaker"]


class Role(Enum):
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num

    def worker_endpoints(self):
        """Trainer endpoints — the addresses global_shuffle's sample
        exchange and other trainer-to-trainer traffic ride. Populated
        by generate_role (env-driven role makers) or the constructor;
        role makers with no endpoint wiring return []."""
        return list(getattr(self, "_worker_endpoints", []))

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var driven role maker (the reference's cloud/launch wiring)."""

    def __init__(self, is_collective=True):
        super().__init__()
        self.is_collective = is_collective

    def generate_role(self):
        # trainer endpoints ride the launcher's env contract
        # (launch.py wires PADDLE_TRAINER_ENDPOINTS in collective AND
        # ps mode — trainer-to-trainer traffic like global_shuffle's
        # sample exchange needs them in both)
        teps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = teps.split(",") if teps else []
        if self.is_collective:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get(
                "PADDLE_TRAINER_ID", jax.process_index()))
            self._worker_num = int(os.environ.get(
                "PADDLE_TRAINERS_NUM", jax.process_count()))
        else:
            role = os.environ.get("TRAINING_ROLE", "TRAINER")
            self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
            eps = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "")
            self._server_endpoints = eps.split(",") if eps else []


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []


class MPISymetricRoleMaker(RoleMakerBase):
    """role_maker.py MPISymetricRoleMaker parity: one worker + one
    server per physical node, ranks interleaved (even rank = worker,
    odd = server). The reference derives ranks from MPI; here they come
    from the same env contract the launcher sets (PADDLE_TRAINER_ID as
    the global rank, PADDLE_TRAINERS_NUM as the world size) — the MPI
    runtime's role is played by the TPU scheduler / launcher
    (SURVEY §2.5 Downpour row)."""

    def __init__(self):
        super().__init__()
        self._proc_per_node = 2
        self._generated = False

    def generate_role(self):
        rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", 2))
        if world % 2 != 0:
            raise ValueError(
                f"MPISymetricRoleMaker needs an even world size (one "
                f"worker + one server per node); got {world}")
        self._rank = rank
        self._size = world
        self._role = Role.WORKER if rank % 2 == 0 else Role.SERVER
        self._current_id = rank // 2
        self._worker_num = world // 2
        eps = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "")
        self._server_endpoints = eps.split(",") if eps else []
        self._generated = True

    def _check_role_generation(self):
        if not self._generated:
            raise NameError("generate_role() should be called first")
        return True

    # every role query requires generation — silently returning the
    # base-class defaults would shard data over 1 phantom worker
    def is_worker(self):
        self._check_role_generation()
        return super().is_worker()

    def is_server(self):
        self._check_role_generation()
        return super().is_server()

    def worker_num(self):
        self._check_role_generation()
        return super().worker_num()

    def worker_index(self):
        self._check_role_generation()
        return super().worker_index()

    def server_index(self):
        self._check_role_generation()
        return super().server_index()

    def get_pserver_endpoints(self):
        self._check_role_generation()
        return super().get_pserver_endpoints()

    def get_size(self):
        self._check_role_generation()
        return self._size

    def server_num(self):
        self._check_role_generation()
        return self._size // self._proc_per_node
