"""Distributed user API (fleet) + launcher + sparse path.

Parity: python/paddle/fluid/incubate/fleet (fleet_base.py, role_maker.py,
collective/__init__.py), paddle.distributed.launch (launch.py:132).
"""

from paddle_tpu.distributed.role_maker import (
    RoleMakerBase, PaddleCloudRoleMaker, UserDefinedRoleMaker, Role,
)
from paddle_tpu.distributed.fleet import fleet, DistributedStrategy
from paddle_tpu.distributed.sparse_embedding import SparseEmbeddingTable
from paddle_tpu.distributed.ps import (
    ParameterServer, NativeParameterServer, PSClient, Communicator,
    run_pserver, make_parameter_server,
)
from paddle_tpu.distributed.transpiler import (
    DistributeTranspiler, DistributeTranspilerConfig, PServerProgram,
    RoundRobin, HashName,
)
