"""Framed binary wire protocol for the PS transport — no pickle.

Parity targets: the reference's fixed tensor wire schema
(operators/distributed/send_recv.proto.in + sendrecvop_utils.cc splits
a tensor into typed meta + raw payload) and the RPC client contract
(operators/distributed/rpc_client.h:33, retry path grpc_client.cc).
The previous transport was length-prefixed pickle: unpickling bytes
from a socket is arbitrary-code-execution on any non-loopback
deployment. This codec decodes ONLY fixed-schema scalar/string/ndarray
fields, validates magic/version/size before touching the payload, and
rejects oversized or malformed frames without evaluating anything.

Frame layout (little-endian):
    magic "PT" | version u8 | kind u8 | client_id u64 | seq u64
    | payload_len u64 | payload
Payload is the concatenation of the fields registered for the kind in
SCHEMAS; decoding validates the payload is consumed exactly.

Field encodings:
    STR  -> u16 len | utf-8 bytes
    U64  -> u64
    F64  -> f64 (NaN encodes None for optional floats)
    ARR  -> dtype u8 | ndim u8 | dims u32[ndim] | raw bytes
"""

import struct

import numpy as np

from paddle_tpu.core.flags import define_flag, get_flag

define_flag("ps_max_message_bytes", 1 << 31,
            "Max PS wire frame payload (rpc max-size knob)")

MAGIC = b"PT"
VERSION = 1

# messages
PUSH_GRAD = 1          # name, trainer_id u64, grad arr
PULL_PARAM = 2         # name, min_round u64
PULL_SPARSE = 3        # name, ids arr
PUSH_SPARSE = 4        # name, ids arr, grads arr, lr f64 (NaN=None)
BARRIER = 5            # tag, trainer_id u64
CHECKPOINT_NOTIFY = 6  # dirname
LIST_VARS = 7          # -
STOP = 8               # -
SHRINK_TABLE = 9       # name, max_age u64
SHUFFLE_PUSH = 10      # from_trainer u64, npz-packed sample blob arr
SHUFFLE_DONE = 11      # from_trainer u64, sent-count u64
SERVER_INFO = 12       # - (reply: i64 arr [incarnation, min dense round];
                       #    the failover probe — a client reconnecting
                       #    after a pserver restart reads the new
                       #    incarnation token here and re-establishes its
                       #    round expectations instead of deadlocking)
# elastic-membership migration (docs/ELASTIC_TRAINING.md "Resizing the
# pserver fleet"): the coordinator and migration peers speak these with
# client_id=0 (control plane — no retry dedup; every call is idempotent
# or answered-by-state), data frames carry their fleet epoch so a
# server on a different epoch can fence them with WRONG_EPOCH
MIGRATE_PLAN = 13      # plan json (coordinator -> source: stream these
                       #   units to their targets; reply OK_ARR [rows])
MIGRATE_BEGIN = 14     # spec json (source -> target: units incoming)
MIGRATE_CHUNK = 15     # meta json, npz-blob u8 arr, crc32 u64
MIGRATE_END = 16       # end json (target stages durable shadows;
                       #   reply OK_ARR [staged rows])
MIGRATE_COMMIT = 17    # commit json {"epoch","map"} (idempotent;
                       #   reply OK_ARR [server's epoch])
MIGRATE_ABORT = 18     # abort json {"epoch"} (drop staging, unfreeze)
EPOCH_MAP = 19         # - (reply OK_JSON {"epoch","map"})
# epoch-fenced data variants (PSClient sends these once it holds a
# shard map; schema = epoch u64 + the legacy kind's fields)
PUSH_GRAD_E = 20       # epoch u64, name, trainer_id u64, grad arr
PULL_PARAM_E = 21      # epoch u64, name, min_round u64
PULL_SPARSE_E = 22     # epoch u64, name, ids arr
PUSH_SPARSE_E = 23     # epoch u64, name, ids arr, grads arr, lr f64
# responses
OK = 100               # -
OK_ARR = 101           # arr
OK_NAMES = 102         # dense-names str, sparse-names str ("\n"-joined)
ERR = 103              # message
OK_JSON = 104          # json str
WRONG_EPOCH = 105      # server's epoch u64, shard-map json str (the
                       #   fencing reply: nothing was applied; the
                       #   client adopts the newer map and re-routes)

STR, U64, F64, ARR = "str", "u64", "f64", "arr"

SCHEMAS = {
    PUSH_GRAD: (STR, U64, ARR),
    PULL_PARAM: (STR, U64),
    PULL_SPARSE: (STR, ARR),
    PUSH_SPARSE: (STR, ARR, ARR, F64),
    BARRIER: (STR, U64),
    CHECKPOINT_NOTIFY: (STR,),
    LIST_VARS: (),
    STOP: (),
    SHRINK_TABLE: (STR, U64),
    SHUFFLE_PUSH: (U64, ARR),
    SHUFFLE_DONE: (U64, U64),
    SERVER_INFO: (),
    MIGRATE_PLAN: (STR,),
    MIGRATE_BEGIN: (STR,),
    MIGRATE_CHUNK: (STR, ARR, U64),
    MIGRATE_END: (STR,),
    MIGRATE_COMMIT: (STR,),
    MIGRATE_ABORT: (STR,),
    EPOCH_MAP: (),
    PUSH_GRAD_E: (U64, STR, U64, ARR),
    PULL_PARAM_E: (U64, STR, U64),
    PULL_SPARSE_E: (U64, STR, ARR),
    PUSH_SPARSE_E: (U64, STR, ARR, ARR, F64),
    OK: (),
    OK_ARR: (ARR,),
    OK_NAMES: (STR, STR),
    ERR: (STR,),
    OK_JSON: (STR,),
    WRONG_EPOCH: (U64, STR),
}

# kinds whose server-side effect must not re-apply on a retried frame.
# BARRIER is here because its set-based fan-in is only idempotent
# within an unreleased round: a retry landing after the release would
# enroll the trainer in the NEXT generation and desynchronize rounds.
# The MIGRATE_* control plane is deliberately absent: it is spoken with
# client_id=0 (dedup bypass) and every call is idempotent by state
# (COMMIT/ABORT compare epochs, PLAN/BEGIN/CHUNK/END restage).
MUTATING = {PUSH_GRAD, PUSH_SPARSE, CHECKPOINT_NOTIFY, STOP, BARRIER,
            SHRINK_TABLE, PUSH_GRAD_E, PUSH_SPARSE_E}

_HDR = struct.Struct("<2sBBQQQ")
_U16 = struct.Struct("<H")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")

_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.int64,
           5: np.uint8, 6: np.bool_}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def max_message_bytes():
    """Upper bound on a frame's payload (validated before allocation);
    FLAGS_ps_max_message_bytes overrides (rpc_client.h's max-size knob).
    """
    return int(get_flag("ps_max_message_bytes"))


class WireError(Exception):
    """Malformed / oversized / unsupported frame."""


def _enc_field(ftype, v, out):
    if ftype == STR:
        b = v.encode("utf-8")
        if len(b) > 0xFFFF:
            raise WireError(f"string too long ({len(b)})")
        out.append(_U16.pack(len(b)))
        out.append(b)
    elif ftype == U64:
        out.append(_U64.pack(int(v)))
    elif ftype == F64:
        out.append(_F64.pack(float("nan") if v is None else float(v)))
    elif ftype == ARR:
        a = np.ascontiguousarray(v)
        code = _DTYPE_CODES.get(a.dtype)
        if code is None:
            raise WireError(f"unsupported array dtype {a.dtype}")
        if a.ndim > 0xFF:
            raise WireError(f"array rank {a.ndim} too large")
        out.append(struct.pack("<BB", code, a.ndim))
        for d in a.shape:
            out.append(_U32.pack(d))
        # zero-copy data view (empty arrays can't cast: shape has a 0)
        out.append(memoryview(a).cast("B") if a.size else b"")
    else:  # pragma: no cover
        raise WireError(f"unknown field type {ftype!r}")


def encode_parts(kind, fields, client_id=0, seq=0):
    """Serialize a message to a list of buffers (header first). Large
    array payloads stay as zero-copy memoryviews of the source arrays —
    the sender writes them with writev/sendmsg instead of concatenating
    (the grpc bytebuffer zero-copy serde role, grpc_bytebuffer_stream)."""
    schema = SCHEMAS.get(kind)
    if schema is None:
        raise WireError(f"unknown message kind {kind}")
    if len(fields) != len(schema):
        raise WireError(f"kind {kind} wants {len(schema)} fields, "
                        f"got {len(fields)}")
    out = []
    for ftype, v in zip(schema, fields):
        _enc_field(ftype, v, out)
    n = sum(len(p) for p in out)
    if n > max_message_bytes():
        raise WireError(f"message too large ({n} bytes)")
    hdr = _HDR.pack(MAGIC, VERSION, kind, client_id, seq, n)
    # coalesce small pieces; keep big array buffers as separate views
    parts = [hdr]
    small = []
    for p in out:
        if len(p) < 65536:
            small.append(bytes(p))
        else:
            if small:
                parts.append(b"".join(small))
                small = []
            parts.append(p)
    if small:
        parts.append(b"".join(small))
    return parts


def encode(kind, fields, client_id=0, seq=0):
    """Serialize a message to one bytes blob (header + payload)."""
    return b"".join(bytes(p) for p in
                    encode_parts(kind, fields, client_id, seq))


class _Reader:
    def __init__(self, buf):
        self.buf = memoryview(buf)   # slices below are zero-copy
        self.off = 0

    def take(self, n):
        if self.off + n > len(self.buf):
            raise WireError("truncated payload")
        v = self.buf[self.off:self.off + n]
        self.off += n
        return v

    def done(self):
        if self.off != len(self.buf):
            raise WireError(
                f"trailing bytes in payload ({len(self.buf) - self.off})")


def _dec_field(ftype, r):
    if ftype == STR:
        (n,) = _U16.unpack(bytes(r.take(_U16.size)))
        return bytes(r.take(n)).decode("utf-8")
    if ftype == U64:
        return _U64.unpack(bytes(r.take(_U64.size)))[0]
    if ftype == F64:
        v = _F64.unpack(bytes(r.take(_F64.size)))[0]
        return None if np.isnan(v) else v
    if ftype == ARR:
        code, ndim = struct.unpack("<BB", bytes(r.take(2)))
        dt = _DTYPES.get(code)
        if dt is None:
            raise WireError(f"unknown dtype code {code}")
        dims = [_U32.unpack(bytes(r.take(_U32.size)))[0]
                for _ in range(ndim)]
        # python-int product: attacker-chosen u32 dims must not wrap a
        # fixed-width accumulator past the size guard
        size = 1
        for d in dims:
            size *= int(d)
        nbytes = size * np.dtype(dt).itemsize
        if nbytes > max_message_bytes():
            raise WireError(f"array too large ({nbytes} bytes)")
        raw = r.take(nbytes)
        # zero-copy (read-only) view over the received payload buffer.
        # STR fields precede ARR fields in several schemas, so the view
        # can start at an arbitrary byte offset; when that offset is not
        # a multiple of the itemsize the array is copied to an aligned
        # buffer — these arrays are handed by pointer into the native
        # table, and misaligned loads are UB off x86-64/ARM64 and a
        # hazard for SIMD C++ code.
        arr = np.frombuffer(raw, dtype=dt)
        if not arr.flags.aligned:
            arr = arr.copy()
        return arr.reshape(dims)
    raise WireError(f"unknown field type {ftype!r}")  # pragma: no cover


def decode_header(hdr):
    """Validate and unpack a frame header. Returns
    (kind, client_id, seq, payload_len)."""
    if len(hdr) != _HDR.size:
        raise WireError("short header")
    magic, ver, kind, client_id, seq, n = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if ver != VERSION:
        raise WireError(f"unsupported protocol version {ver}")
    if kind not in SCHEMAS:
        raise WireError(f"unknown message kind {kind}")
    if n > max_message_bytes():
        raise WireError(f"oversized frame ({n} bytes)")
    return kind, client_id, seq, n


def decode_payload(kind, payload):
    """Decode a validated kind's payload into its field tuple. ANY
    decoding failure surfaces as WireError — the malformed-frame
    contract callers rely on (a typed ERR reply, never a crash)."""
    try:
        r = _Reader(payload)
        fields = tuple(_dec_field(ftype, r) for ftype in SCHEMAS[kind])
        r.done()
        return fields
    except WireError:
        raise
    except Exception as e:
        raise WireError(f"malformed payload: {type(e).__name__}: {e}")


HEADER_SIZE = _HDR.size


# -- shared socket framing (one implementation for every wire user) -------

def send_frame(sock, kind, fields, client_id=0, seq=0):
    """writev via sendmsg: large array payloads go out zero-copy."""
    parts = [memoryview(p).cast("B")
             for p in encode_parts(kind, fields, client_id, seq)]
    while parts:
        sent = sock.sendmsg(parts)
        while parts and sent >= len(parts[0]):
            sent -= len(parts[0])
            parts.pop(0)
        if parts and sent:
            parts[0] = parts[0][sent:]


def recv_exact(sock, n):
    """Read exactly n bytes into a preallocated buffer. The buffer is
    an UNINITIALIZED np.empty, not bytearray(n): bytearray zeroes its
    memory, a full extra pass over a 64 MB frame that recv_into
    immediately overwrites."""
    import numpy as _np
    buf = _np.empty(n, _np.uint8)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return buf.data


def recv_frame(sock):
    """Read one validated frame: (kind, client_id, seq, fields).
    Raises WireError on malformed bytes — NOTHING from the socket is
    ever evaluated, only fixed-schema fields are decoded."""
    kind, client_id, seq, n = decode_header(
        recv_exact(sock, HEADER_SIZE))
    fields = decode_payload(kind, recv_exact(sock, n))
    return kind, client_id, seq, fields
