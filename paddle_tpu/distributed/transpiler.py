"""DistributeTranspiler: rewrite a static Program for PS training.

Parity targets (SURVEY §3.3): transpiler/distribute_transpiler.py
(DistributeTranspiler:183, transpile:377, get_trainer_program:702,
get_pserver_program:836, DistributeTranspilerConfig:131) and
ps_dispatcher.py (RoundRobin / HashName placement).

TPU-native shape: the trainer keeps its whole forward+backward as ONE
jitted XLA computation (the reference's per-op graph stays a per-op
graph); the transpiler strips the optimizer-apply ops and brackets the
block with two *host* ops — ``ps_recv`` (pull params for this round,
fetch_barrier role) at the head and ``ps_send`` (push grads, send +
send_barrier role) at the tail. The Executor runs host ops eagerly
between jitted device segments (see executor._compile), so the RPC hop
never enters the XLA program. Parameters are placed whole (XLA arrays
are atomic — the reference's slice_var_up block-slicing exists to
load-balance pservers, which round-robin-by-size already achieves);
optimization runs server-side with the same functional Optimizer rule.

Round/initialization semantics live in ps.py: pserver-side init from the
captured startup initializers makes every trainer start from identical
parameters, so sync-PS loss matches local loss exactly (the
TestDistBase assertion, test_dist_base.py:366).
"""

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.distributed import ps as _ps
from paddle_tpu.static.backward import GRAD_SUFFIX
from paddle_tpu.static.program import (
    OP_REGISTRY, Operator, default_main_program, default_startup_program,
)

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "PServerProgram", "RoundRobin", "HashName"]


# ---------------------------------------------------------------------------
# pservers placement (ps_dispatcher.py parity)
# ---------------------------------------------------------------------------
class PSDispatcher:
    def __init__(self, eplist):
        self._eplist = list(eplist)

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    """Size-balanced round robin: biggest vars placed first onto the
    currently lightest endpoint (subsumes slice_var_up's balancing)."""

    def dispatch(self, varlist):
        load = {ep: 0 for ep in self._eplist}
        out = {}
        for v in sorted(varlist, key=lambda v: -int(np.prod(
                [s if s and s > 0 else 1 for s in (v.shape or (1,))]))):
            ep = min(self._eplist, key=lambda e: load[e])
            out[v.name] = ep
            load[ep] += int(np.prod(
                [s if s and s > 0 else 1 for s in (v.shape or (1,))]))
        return out


class HashName(PSDispatcher):
    def dispatch(self, varlist):
        # md5, not hash(): placement must agree across processes that
        # transpile independently (python hashes are process-salted)
        import hashlib

        def h(name):
            return int(hashlib.md5(name.encode()).hexdigest(), 16)
        return {v.name: self._eplist[h(v.name) % len(self._eplist)]
                for v in varlist}


class DistributeTranspilerConfig:
    """distribute_transpiler.py:131 parity (knobs that still mean
    something here; slice_var_up/min_block_size are subsumed by
    size-balanced whole-var placement)."""

    def __init__(self):
        self.slice_var_up = True
        self.min_block_size = 8192
        self.split_method = RoundRobin
        self.sync_mode = True
        self.runtime_split_send_recv = False
        # async mode: every N steps the Communicator AVERAGES the
        # buffered grads into one merged push (the reference's
        # send_queue_size / merge-vars knob, communicator.h:160); flush
        # trailing partial windows with transpiler.flush_clients()
        self.merge_steps = 1


# ---------------------------------------------------------------------------
# host ops: ps_recv / ps_send
# ---------------------------------------------------------------------------
_CLIENTS = {}


def _get_client(endpoints, var_ep, trainer_id):
    key = (tuple(endpoints), trainer_id)
    c = _CLIENTS.get(key)
    if c is None:
        c = _ps.PSClient(endpoints, var_ep, trainer_id)
        c.step = 0
        _CLIENTS[key] = c
    else:
        c.var_ep.update(var_ep)
    return c


def flush_clients():
    """Push any grads still buffered in async Communicators (the partial
    trailing merge window). Call at the end of async training — the
    reference's Communicator flushes on its Stop/barrier path the same
    way."""
    for c in _CLIENTS.values():
        comm = getattr(c, "communicator", None)
        if comm is not None:
            comm.flush()


def reset_clients():
    for c in _CLIENTS.values():
        comm = getattr(c, "communicator", None)
        if comm is not None:
            comm.stop()           # stop() drains pending sends first
        c.close()
    _CLIENTS.clear()


def _ps_recv_compute(ins, attrs):
    c = _get_client(attrs["endpoints"], attrs["var_ep"],
                    attrs["trainer_id"])
    min_round = c.step if attrs["sync_mode"] else 0
    return {"Out": [c.pull_param(n, min_round)
                    for n in attrs["param_names"]]}


def _ps_send_compute(ins, attrs):
    c = _get_client(attrs["endpoints"], attrs["var_ep"],
                    attrs["trainer_id"])
    merge_steps = attrs.get("merge_steps", 1)
    if not attrs["sync_mode"] and merge_steps > 1:
        # async mode sends through the background Communicator, which
        # AVERAGES ``merge_steps`` grads per var into one merged push
        # (communicator.h:160 MergeVars role); trailing partial windows
        # flush via flush_clients() / reset_clients()
        comm = getattr(c, "communicator", None)
        if comm is not None and comm.merge_steps != merge_steps:
            comm.stop()           # re-transpiled with a new window size
            comm = None
        if comm is None:
            comm = c.communicator = _ps.Communicator(
                c, merge_steps=merge_steps).start()
        for pname, g in zip(attrs["param_names"], ins["X"]):
            comm.send(pname, np.asarray(g))
    else:
        for pname, g in zip(attrs["param_names"], ins["X"]):
            c.push_grad(pname, np.asarray(g))
    c.step += 1
    return {}


OP_REGISTRY["ps_recv"] = _ps_recv_compute
OP_REGISTRY["ps_send"] = _ps_send_compute


# ---------------------------------------------------------------------------
# pserver program artifact
# ---------------------------------------------------------------------------
class PServerProgram:
    """What get_pserver_program returns: the server's share of parameters
    (spec + captured startup initializer + optimizer rule) — consumed by
    ps.run_pserver / build_server (the listen_and_serv block)."""

    def __init__(self, endpoint, num_trainers, sync_mode, startup_seed):
        self.endpoint = endpoint
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self.startup_seed = startup_seed
        self.dense = {}    # name -> dict(shape dtype initializer op_idx opt)

    def add_dense(self, name, shape, dtype, initializer, op_idx, optimizer,
                  regularizer=None, param_lr=1.0):
        self.dense[name] = dict(shape=tuple(shape), dtype=dtype,
                                initializer=initializer, op_idx=op_idx,
                                optimizer=optimizer, regularizer=regularizer,
                                param_lr=param_lr)

    def build_server(self):
        """Materialize the parameter server: init each hosted param with
        the SAME rng the local startup run would use
        (executor._run_eager: fold_in(PRNGKey(seed), op_index)) so
        distributed training starts from the local-run weights.

        Transport selection (FLAGS_ps_transport): the C++ server
        (native/src/ps_server.cc — wire parse, dispatch, dedup and
        optimize kernels all native) when the hosted state is
        expressible there; the Python ParameterServer otherwise (and
        always under transport=python / no toolchain)."""
        import jax

        from paddle_tpu.core.dtypes import convert_dtype

        def host_all(server):
            base = jax.random.PRNGKey(self.startup_seed)
            for name, spec in self.dense.items():
                key = jax.random.fold_in(base, spec["op_idx"])
                val = np.asarray(spec["initializer"](
                    key, spec["shape"], convert_dtype(spec["dtype"])))
                server.host_dense(name, val, spec["optimizer"],
                                  regularizer=spec["regularizer"],
                                  param_lr=spec["param_lr"])
            return server

        import logging

        from paddle_tpu.core.flags import get_flag
        transport = get_flag("ps_transport")
        enforce(transport in ("auto", "native", "python"),
                f"FLAGS_ps_transport must be auto|native|python, "
                f"got {transport!r}")
        if transport != "python":
            try:
                return host_all(_ps.NativeParameterServer(
                    self.endpoint, self.num_trainers, self.sync_mode))
            except Exception as e:
                if transport == "native":
                    raise
                # auto: inexpressible state (NativeUnsupported) and a
                # missing toolchain fall back silently by design; any
                # OTHER failure is a native-path bug that must not hide
                # behind the ~2x-slower Python transport unannounced
                if not isinstance(e, _ps.NativeUnsupported) \
                        and not _ps._is_missing_toolchain(e):
                    logging.getLogger("paddle_tpu.ps").warning(
                        "native PS transport failed unexpectedly "
                        "(%s: %s) — falling back to the Python server",
                        type(e).__name__, e)
        return host_all(_ps.ParameterServer(
            self.endpoint, self.num_trainers, self.sync_mode))


# ---------------------------------------------------------------------------
# the transpiler
# ---------------------------------------------------------------------------
class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._done = False

    def transpile(self, trainer_id, program=None, pservers="",
                  trainers=1, sync_mode=True, startup_program=None):
        program = program or default_main_program()
        startup = startup_program or default_startup_program()
        endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        enforce(bool(endpoints), "pservers must name >=1 endpoint")
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.endpoints = endpoints

        blk = program.global_block()
        # optimized params + their full update spec (optimizer rule,
        # per-param regularizer, per-param lr scale) from the
        # apply_optimizer ops the server will take over
        opt_ops = [op for op in blk.ops if op.type == "apply_optimizer"]
        enforce(bool(opt_ops),
                "transpile() needs optimizer.minimize() applied first")
        param_opt = {op.inputs["Param"][0]:
                     (op.attrs["opt"], op.attrs.get("regularizer"),
                      op.attrs.get("param_lr", 1.0))
                     for op in opt_ops}
        pvars = [blk.var(n) for n in param_opt]
        self.var_ep = self.config.split_method(endpoints).dispatch(pvars)

        # capture startup init specs (op index == rng fold index)
        sblk = startup.global_block()
        init_spec = {}
        for idx, op in enumerate(sblk.ops):
            if op.type == "init_param":
                (out,) = op.outputs["Out"]
                init_spec[out] = (idx, op.attrs["initializer"],
                                  op.attrs["shape"], op.attrs["dtype"])
        self._startup_seed = startup.random_seed

        self._build_trainer_program(program, list(param_opt))
        self._pserver_programs = {}
        for ep in endpoints:
            pp = PServerProgram(ep, trainers, sync_mode, self._startup_seed)
            for name, (opt, reg, param_lr) in param_opt.items():
                if self.var_ep[name] != ep:
                    continue
                enforce(name in init_spec,
                        f"param {name!r} has no startup initializer op")
                idx, init, shape, dtype = init_spec[name]
                pp.add_dense(name, shape, dtype, init, idx, opt,
                             regularizer=reg, param_lr=param_lr)
            self._pserver_programs[ep] = pp
        self._done = True
        return self

    def _build_trainer_program(self, program, param_names):
        t = program.clone()
        blk = t.global_block()
        # strip server-side ops (the optimize sub-block moves to pserver)
        blk.ops = [op for op in blk.ops
                   if op.type not in ("apply_optimizer", "increment_step")]
        common = dict(endpoints=self.endpoints, var_ep=dict(self.var_ep),
                      trainer_id=self.trainer_id,
                      sync_mode=self.sync_mode,
                      merge_steps=self.config.merge_steps, _host=True)
        recv = Operator(blk, "ps_recv", inputs={},
                        outputs={"Out": list(param_names)},
                        attrs=dict(common, param_names=list(param_names)))
        blk.ops.insert(0, recv)
        blk.append_op(
            "ps_send",
            inputs={"X": [n + GRAD_SUFFIX for n in param_names]},
            outputs={},
            attrs=dict(common, param_names=list(param_names)))
        t._bump()
        self._trainer_program = t

    # -- fluid API surface -------------------------------------------------
    def get_trainer_program(self, wait_port=True):
        enforce(self._done, "call transpile() first")
        return self._trainer_program

    def get_pserver_program(self, endpoint, allow_new=False):
        enforce(self._done, "call transpile() first")
        if allow_new and endpoint not in self._pserver_programs:
            # elastic fleet (docs/ELASTIC_TRAINING.md "Resizing the
            # pserver fleet"): a GROWN server sits outside the static
            # transpile-time placement — it starts hosting nothing and
            # acquires state through the epoch-fenced migration
            pp = PServerProgram(endpoint, self.trainer_num,
                                self.sync_mode, self._startup_seed)
            self._pserver_programs[endpoint] = pp
            return pp
        enforce(endpoint in self._pserver_programs,
                f"{endpoint!r} not in {list(self._pserver_programs)}")
        return self._pserver_programs[endpoint]

    def pserver_recipes(self):
        """Hosting recipes for EVERY dense var in the job, regardless
        of placement — what ``ps.run_pserver(recipes=...)`` hands each
        elastic server so it can adopt any unit a future resize
        assigns it (sparse-table recipes are the caller's to add: the
        transpiler never sees ``host_sparse`` tables)."""
        enforce(self._done, "call transpile() first")
        out = {}
        for pp in self._pserver_programs.values():
            for name, spec in pp.dense.items():
                out[name] = dict(spec, kind="dense")
        return out

    def get_pserver_programs(self, endpoint):
        # fluid returns (main, startup); server-side init is embedded
        return (self.get_pserver_program(endpoint),
                self.get_startup_program(endpoint))

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """Pserver startup is embedded in PServerProgram.build_server
        (initializers captured at transpile). Returns an EMPTY Program —
        not None — so the canonical `exe.run(t.get_startup_program(ep))`
        recipe no-ops instead of silently falling back to
        default_main_program()."""
        from paddle_tpu.static.program import Program
        return Program()
