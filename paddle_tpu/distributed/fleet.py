"""Fleet — the distributed-training facade.

Parity: python/paddle/fluid/incubate/fleet/base/fleet_base.py (init,
is_worker/is_server, distributed_optimizer) + collective impl
(incubate/fleet/collective/__init__.py:135 CollectiveOptimizer).

TPU-native: `distributed_optimizer` wraps an Optimizer so its
apply_gradients all-reduces gradients over the "data" mesh axis when
called inside shard_map, and is a pass-through under full-SPMD jit
(where XLA inserts the collective from shardings) — the two styles mirror
the reference's collective transpiler vs ParallelExecutor paths.
"""

import jax

from paddle_tpu.distributed.role_maker import PaddleCloudRoleMaker
from paddle_tpu.parallel.collective import all_reduce
from paddle_tpu.parallel.mesh import DATA_AXIS

__all__ = ["fleet", "DistributedStrategy", "DistributedOptimizer"]


class DistributedStrategy:
    """collective DistributedStrategy parity (subset of knobs that still
    mean something under XLA)."""

    def __init__(self):
        self.nccl_comm_num = 1          # kept for API compat; no-op
        # hierarchical allreduce = reduce over ("dcn_data", "data") on a
        # MeshConfig(dcn_data=N) hybrid mesh (mesh.data_axes); ICI
        # within each slice, one DCN hop across
        self.use_hierarchical_allreduce = False
        self.fuse_all_reduce_ops = True  # XLA buckets automatically
        # bucket size for EXPLICIT (shard_map) gradient allreduce —
        # collective.bucketed_all_reduce consumes it; under pjit
        # sharding annotations XLA owns bucketing and this is unused
        # (reference knob: DistributedStrategy.fuse_grad_size_in_MB)
        self.fuse_grad_size_in_MB = 32
        self.gradient_scale = "avg"      # avg|sum
        # BuildStrategy.reduce_strategy parity (build_strategy.h:38-57):
        # "all_reduce" (kAllReduce, params replicated) or "reduce"
        # (kReduce realized as the ZeRO layout —
        # DataParallelTrainer(param_sharding=...) consumes it via
        # param_sharding_arg())
        self.reduce_strategy = "all_reduce"

    def param_sharding_arg(self):
        """Maps the reduce_strategy knob to DataParallelTrainer's
        param_sharding argument."""
        if self.reduce_strategy in ("all_reduce", None):
            return None
        if self.reduce_strategy in ("reduce", "zero"):
            return "reduce"
        raise ValueError(
            f"reduce_strategy={self.reduce_strategy!r}: expected "
            f"'all_reduce' or 'reduce'")


class DistributedOptimizer:
    def __init__(self, optimizer, strategy=None, axis_name=DATA_AXIS,
                 in_spmd=True):
        self.opt = optimizer
        self.strategy = strategy or DistributedStrategy()
        self.axis = axis_name
        self.in_spmd = in_spmd

    def init(self, params):
        return self.opt.init(params)

    def apply_gradients(self, params, grads, state):
        if not self.in_spmd:
            # explicit (shard_map) path: the strategy knobs act here.
            # fuse_grad_size_in_MB buckets the tree into fused
            # collectives; use_hierarchical_allreduce reduces over the
            # hybrid mesh's ("dcn_data", "data") axes (ICI within a
            # slice, one DCN hop across). Under pjit annotations
            # (in_spmd=True) XLA owns both decisions.
            from paddle_tpu.parallel.collective import bucketed_all_reduce
            op = "avg" if self.strategy.gradient_scale == "avg" else "sum"
            axis = self.axis
            if self.strategy.use_hierarchical_allreduce:
                # widen to the hybrid mesh's DCN axis only when the
                # ambient mesh actually has one — like the reference's
                # knob, this changes the reduction structure, never
                # breaks a flat topology
                from paddle_tpu.parallel.mesh import DCN_AXIS, get_mesh
                if (not isinstance(axis, (tuple, list))
                        and DCN_AXIS in get_mesh().shape):
                    axis = (DCN_AXIS, axis)
            if self.strategy.fuse_all_reduce_ops:
                grads = bucketed_all_reduce(
                    grads, axis_name=axis,
                    bucket_mb=self.strategy.fuse_grad_size_in_MB, op=op)
            else:
                grads = jax.tree.map(
                    lambda g: all_reduce(g, op=op, axis_name=axis),
                    grads)
        return self.opt.apply_gradients(params, grads, state)

    def __getattr__(self, k):
        return getattr(self.opt, k)


class _Fleet:
    def __init__(self):
        self._role_maker = None
        self._strategy = None

    def init(self, role_maker=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        return self

    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker is not None and self._role_maker.is_server()

    def is_first_worker(self):
        return self._role_maker is None or \
            self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_num(self):
        return self._role_maker.worker_num() if self._role_maker else 1

    def worker_endpoints(self):
        if self._role_maker is None:
            return []
        return self._role_maker.worker_endpoints()

    def distributed_optimizer(self, optimizer, strategy=None, **kw):
        self._strategy = strategy or DistributedStrategy()
        return DistributedOptimizer(optimizer, self._strategy, **kw)

    # -- PS-mode lifecycle (fleet_base.py init_worker/init_server/
    #    run_server/stop_worker parity; collective mode needs none of
    #    these — XLA collectives have no server to run) ---------------
    def init_worker(self):
        """No-op in collective mode; in PS mode the transpiled trainer
        program connects lazily on first send/recv."""

    def init_server(self, model_dir=None):
        self._server_dir = model_dir

    def run_server(self, pserver_program):
        """Build the PS from a transpiled pserver program, restore the
        init_server checkpoint BEFORE the socket opens (a trainer must
        never observe pre-checkpoint params), then serve."""
        server = pserver_program.build_server()
        d = getattr(self, "_server_dir", None)
        if d:
            server.load(d)
        return server.start()

    def stop_worker(self):
        from paddle_tpu.distributed.transpiler import flush_clients
        flush_clients()

    def barrier_worker(self):
        """Collective mode: a cross-replica barrier only matters inside
        a jitted collective program (parallel.collective.barrier); here
        the host-side analog is flushing outstanding PS sends."""
        self.stop_worker()

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None):
        import paddle_tpu as pt
        return pt.io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program=main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        import paddle_tpu as pt
        return pt.io.save_persistables(executor, dirname,
                                       main_program=main_program)


fleet = _Fleet()
