"""fluid.transpiler namespace parity (python/paddle/fluid/transpiler/):
DistributeTranspiler & friends live in paddle_tpu.distributed; the
memory-optimization transpilers are no-ops here — XLA's buffer
liveness/reuse (SURVEY §7: memory passes → compiler) does their job."""

import warnings

from paddle_tpu.distributed.transpiler import (          # noqa: F401
    DistributeTranspiler, DistributeTranspilerConfig, HashName,
    RoundRobin,
)

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "HashName", "RoundRobin", "memory_optimize", "release_memory"]


def memory_optimize(input_program=None, skip_opt_set=None,
                    print_log=False, level=0, skip_grads=True):
    """ir/memory_optimize_pass parity — a documented no-op: XLA performs
    buffer reuse/inplace/liveness analysis on every compiled program."""
    warnings.warn("memory_optimize is a no-op: XLA already performs "
                  "buffer reuse and liveness optimization",
                  stacklevel=2)


def release_memory(input_program=None, skip_opt_set=None):
    """eager_deletion_pass parity — no-op (XLA frees dead buffers)."""
    warnings.warn("release_memory is a no-op: XLA frees dead buffers",
                  stacklevel=2)
