"""Stateful metric aggregators.

Parity: python/paddle/fluid/metrics.py (MetricBase, Accuracy, Precision,
Recall, Auc, ChunkEvaluator, EditDistance, CompositeMetric,
DetectionMAP deferred with the detection op family).
"""

import numpy as np

__all__ = [
    "MetricBase", "Accuracy", "Precision", "Recall", "Auc",
    "CompositeMetric", "ChunkEvaluator", "EditDistance", "DetectionMAP",
]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(np.asarray(value)) * weight
        self.weight += weight

    def eval(self):
        return self.value / max(self.weight, 1e-12)


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(MetricBase):
    """metrics.py Auc parity: threshold-bucketed streaming AUC."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self.n = num_thresholds
        self.reset()

    def reset(self):
        self.stat_pos = np.zeros(self.n + 1)
        self.stat_neg = np.zeros(self.n + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        bins = np.clip((pos_prob * self.n).astype(int), 0, self.n)
        pos = labels.astype(bool)
        self.stat_pos += np.bincount(bins[pos], minlength=self.n + 1)
        self.stat_neg += np.bincount(bins[~pos], minlength=self.n + 1)

    def eval(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.n, -1, -1):
            new_pos = tot_pos + self.stat_pos[i]
            new_neg = tot_neg + self.stat_neg[i]
            auc += (new_pos + tot_pos) * self.stat_neg[i] / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return auc / denom if denom else 0.0


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    """metrics.py ChunkEvaluator parity: F1 over chunk counts produced by
    a chunk-matching routine (the reference feeds it from chunk_eval_op)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        recall = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.0
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0
        self.correct = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances)
        self.total += float(d.sum())
        self.count += int(seq_num)
        self.correct += int(np.sum(d == 0))

    def eval(self):
        avg = self.total / max(self.count, 1)
        acc = self.correct / max(self.count, 1)
        return avg, acc


class DetectionMAP(MetricBase):
    """fluid.metrics.DetectionMAP parity: accumulates per-batch
    detections + ground truth and evaluates mean average precision via
    ops.detection.detection_map (detection_map_op.cc)."""

    def __init__(self, name=None, class_num=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__(name)
        self.class_num = class_num
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = []
        self._gt_labels = []
        self._gt_boxes = []

    def update(self, detect_res, gt_label, gt_box):
        self._dets.append(np.asarray(detect_res))
        self._gt_labels.append(np.asarray(gt_label))
        self._gt_boxes.append(np.asarray(gt_box))

    def eval(self):
        from paddle_tpu.ops.detection import detection_map
        if self.class_num is None:
            raise ValueError("DetectionMAP needs class_num")
        return detection_map(
            self._dets, self._gt_labels, self._gt_boxes, self.class_num,
            overlap_threshold=self.overlap_threshold,
            evaluate_difficult=self.evaluate_difficult,
            ap_type=self.ap_version)
