"""Class-shaped control flow builders.

Parity targets: python/paddle/fluid/layers/control_flow.py — While:630,
StaticRNN:280, DynamicRNN:1700, IfElse:1564, Switch:1436.

TPU-first shape: the reference's classes BUILD sub-blocks inside a
`with` statement and an op replays them; under a tracing regime a
with-block body executes once and cannot be replayed, so the looping
builders (While, StaticRNN, DynamicRNN) take the step body as a
CALLABLE and lower straight to lax.while_loop / lax.scan (SURVEY §3
"hard parts": control flow under tracing). Switch and IfElse keep the
reference's with-block surface — they execute each selected branch
exactly once, which traces fine.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce
from paddle_tpu.ops import control_flow as _cf

__all__ = ["While", "Switch", "IfElse", "StaticRNN", "DynamicRNN"]


class While:
    """layers.While parity, callable-body form:

        w = While(cond_fn)               # cond_fn(*loop_vars) -> bool
        out_vars = w(body_fn, loop_vars) # body_fn(*loop_vars) -> new vars
    """

    def __init__(self, cond, is_test=False, name=None):
        enforce(callable(cond),
                "While takes the loop condition as a callable "
                "(cond_fn(*loop_vars) -> bool scalar); a traced block "
                "cannot be re-executed from a with-statement")
        self.cond = cond

    def __call__(self, body, loop_vars):
        return _cf.while_loop(self.cond, body, list(loop_vars))


class Switch:
    """layers.Switch parity:

        with Switch() as switch:
            with switch.case(cond1): out = a
            with switch.case(cond2): out = b
            with switch.default():   out = c

    Branch bodies run once each (building values); the selected value is
    whichever case's condition is first true — materialized with
    jnp.where chains so it traces.
    """

    def __init__(self, name=None):
        self._cases = []           # (cond, result-holder)
        self._default = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    class _Case:
        def __init__(self, parent, cond):
            self.parent = parent
            self.cond = cond

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    def case(self, condition):
        c = Switch._Case(self, condition)
        self._cases.append(c)
        return c

    def default(self):
        c = Switch._Case(self, None)
        self._default = c
        return c

    def select(self, *values):
        """Pick the value of the first true case; the last value is the
        default()'s. A default is REQUIRED (under tracing there is no
        'no branch taken' — some value must materialize)."""
        enforce(self._default is not None,
                "Switch.select needs a default() case: under a tracing "
                "regime some branch value must always materialize")
        enforce(len(values) == len(self._cases) + 1,
                "one value per case, plus the default's")
        out = values[-1]
        for c, v in zip(reversed(self._cases), reversed(values[:-1])):
            out = jax.tree.map(
                lambda a, b, cond=c.cond: jnp.where(cond, a, b), v, out)
        return out


class IfElse:
    """layers.IfElse parity:

        ie = IfElse(cond)                  # cond: [N] bool mask
        with ie.true_block():
            ie.output(fn_true(ie.input(x)))
        with ie.false_block():
            ie.output(fn_false(ie.input(x)))
        out, = ie()                        # rows re-merged in order

    Row-partitioning semantics like the reference (split_lod_tensor /
    merge_lod_tensor machinery): each block sees only its rows.
    """

    def __init__(self, cond, name=None):
        self.cond = jnp.asarray(cond).reshape(-1).astype(bool)
        self._in_true = None
        self._outputs = {True: [], False: []}
        self._restore = None

    class _Branch:
        def __init__(self, parent, flag):
            self.parent = parent
            self.flag = flag

        def __enter__(self):
            self.parent._in_true = self.flag
            return self

        def __exit__(self, *exc):
            self.parent._in_true = None
            return False

    def true_block(self):
        return IfElse._Branch(self, True)

    def false_block(self):
        return IfElse._Branch(self, False)

    def input(self, x):
        """Rows of ``x`` belonging to the current branch."""
        enforce(self._in_true is not None,
                "IfElse.input() only inside true_block()/false_block()")
        from paddle_tpu.ops.tensor_array import split_lod_tensor
        t, f, restore = split_lod_tensor(jnp.asarray(x), self.cond)
        self._restore = restore
        return t if self._in_true else f

    def output(self, *outs):
        enforce(self._in_true is not None,
                "IfElse.output() only inside true_block()/false_block()")
        self._outputs[self._in_true].extend(outs)

    def __call__(self):
        from paddle_tpu.ops.tensor_array import merge_lod_tensor
        ts, fs = self._outputs[True], self._outputs[False]
        enforce(len(ts) == len(fs),
                "true and false blocks must emit the same outputs")
        enforce(self._restore is not None,
                "IfElse blocks must read their rows via ie.input(x) "
                "before ie.output(...) — outputs built from unpartitioned "
                "tensors cannot be row-merged")
        return [merge_lod_tensor(t, f, self._restore)
                for t, f in zip(ts, fs)]


class StaticRNN:
    """layers.StaticRNN parity, callable-step form:

        rnn = StaticRNN()
        rnn.step_input(x)                    # [B, T, D] (or several)
        h = rnn.memory(init=h0)
        def step(x_t, h_prev):
            h_new = cell(x_t, h_prev)
            return {"mem": [h_new], "out": [h_new]}
        outs = rnn(step)                     # [[B, T, H], ...]
    """

    def __init__(self, name=None):
        self._inputs = []
        self._mems = []

    def step_input(self, x):
        self._inputs.append(jnp.asarray(x))
        return x

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               dtype=jnp.float32):
        if init is None:
            enforce(batch_ref is not None and shape is not None,
                    "memory needs init= or (shape=, batch_ref=)")
            b = jnp.asarray(batch_ref).shape[0]
            init = jnp.full((b,) + tuple(shape), value, dtype)
        self._mems.append(jnp.asarray(init))
        return init

    def __call__(self, step):
        enforce(bool(self._inputs), "call step_input() first")
        xs = tuple(jnp.moveaxis(x, 1, 0) for x in self._inputs)  # T-major

        def body(mems, xts):
            res = step(*xts, *mems)
            return tuple(res["mem"]), tuple(res.get("out", ()))

        mems, outs = jax.lax.scan(body, tuple(self._mems), xs)
        return [jnp.moveaxis(o, 0, 1) for o in outs]


class DynamicRNN(StaticRNN):
    """layers.DynamicRNN parity: like StaticRNN but with per-sequence
    lengths — steps beyond a sequence's length hold its memory and
    zero its outputs (the LoD semantics, dense-padded)."""

    def __init__(self, lengths=None, name=None):
        super().__init__(name)
        self.lengths = None if lengths is None else jnp.asarray(lengths)

    def __call__(self, step):
        enforce(bool(self._inputs), "call step_input() first")
        xs = tuple(jnp.moveaxis(x, 1, 0) for x in self._inputs)
        T = xs[0].shape[0]
        ts = jnp.arange(T)

        def body(mems, scan_in):
            t, xts = scan_in
            res = step(*xts, *mems)
            new_mems = tuple(res["mem"])
            outs = tuple(res.get("out", ()))
            if self.lengths is not None:
                alive = (t < self.lengths)          # [B]
                def sel(new, old):
                    m = alive.reshape((-1,) + (1,) * (new.ndim - 1))
                    return jnp.where(m, new, old)
                new_mems = tuple(sel(n, o)
                                 for n, o in zip(new_mems, mems))
                outs = tuple(o * alive.reshape(
                    (-1,) + (1,) * (o.ndim - 1)).astype(o.dtype)
                    for o in outs)
            return new_mems, outs

        mems, outs = jax.lax.scan(body, tuple(self._mems), (ts, xs))
        return [jnp.moveaxis(o, 0, 1) for o in outs]
