"""fluid.layers.io parity surface.

Parity: python/paddle/fluid/layers/io.py (data, py_reader,
create_py_reader_by_data, double_buffer, batch, shuffle, read_file,
load, open_files, random_data_generator, Preprocessor) over the
reference's reader-op machinery (operators/reader/create_py_reader_op.cc,
double_buffer, shuffle/batch readers, open_files; Preprocessor sub-block).

TPU-native shape: the reference builds a chain of *reader ops* inside the
program, drained by a blocking queue; here a reader is a host-side object
that yields ready feed dicts (device transfer is double-buffered by the
dataio.PyReader thread — buffered_reader.cc's role). Two protocols, like
the reference:

- iterable: ``for feed in reader: exe.run(main, feed=feed)``
- start/reset (the reference's non-iterable mode): ``reader.start()``
  then ``exe.run(main)`` with NO feed — the executor pulls the next
  batch from every started reader attached to the program — until
  ``core.EOFException`` is raised; then ``reader.reset()``.
"""

import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet, EOFException
from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.framework import unique_name
from paddle_tpu.static.program import (
    data, default_main_program, in_static_mode, Program, program_guard,
)

__all__ = [
    "data", "py_reader", "create_py_reader_by_data", "read_file",
    "double_buffer", "batch", "shuffle", "load", "open_files",
    "random_data_generator", "Preprocessor",
]


class StaticPyReader:
    """The object `layers.py_reader` returns: owns the program's data
    vars and a host-side source; yields feed dicts with async
    device-transfer (dataio.PyReader worker thread)."""

    def __init__(self, vars_, capacity, use_double_buffer=True,
                 program=None):
        self.vars = list(vars_)
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self._source = None          # callable -> iterator of feed dicts
        self._started = False
        self._it = None
        prog = program or default_main_program()
        if not hasattr(prog, "_py_readers"):
            prog._py_readers = []
        prog._py_readers.append(self)

    # -- decoration (fluid PyReader surface) ------------------------------
    def decorate_paddle_reader(self, reader, places=None):
        """reader yields BATCHES as lists of sample tuples (the
        fluid idiom: decorate_paddle_reader(paddle.batch(...)))."""
        names = [v.name for v in self.vars]

        def src():
            from paddle_tpu.dataio.feeder import DataFeeder
            feeder = DataFeeder(names)
            for samples in reader():
                yield feeder.feed(samples)
        self._source = src
        return self

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_tensor_provider(self, reader, places=None):
        """reader yields tuples of already-batched arrays."""
        names = [v.name for v in self.vars]

        def src():
            for arrays in reader():
                if not isinstance(arrays, (tuple, list)):
                    arrays = (arrays,)
                yield {n: np.asarray(a) for n, a in zip(names, arrays)}
        self._source = src
        return self

    decorate_batch_generator = decorate_tensor_provider

    # -- iterable protocol -------------------------------------------------
    def _iter_feeds(self):
        if self._source is None:
            raise EnforceNotMet(
                "py_reader has no data source: call "
                "decorate_paddle_reader / decorate_tensor_provider first")
        if not self.use_double_buffer:
            yield from self._source()
            return
        # async prefetch: stage batches ahead on a worker thread
        from paddle_tpu.dataio.pyreader import PyReader as _AsyncReader
        r = _AsyncReader(capacity=self.capacity)
        r.decorate_batch_generator(self._source)
        yield from iter(r)

    def __iter__(self):
        return self._iter_feeds()

    # -- start/reset protocol (non-iterable fluid mode) -------------------
    def start(self):
        self.reset()     # close any abandoned iterator (+ its worker)
        self._it = self._iter_feeds()
        self._started = True

    def reset(self):
        # close the generator explicitly: with use_double_buffer the
        # underlying dataio.PyReader prefetch worker is blocked on
        # queue.put holding device-staged batches — generator close
        # runs the consumer's finally block, which signals it to stop
        # (otherwise start()/reset() cycles accumulate live threads)
        if self._it is not None and hasattr(self._it, "close"):
            self._it.close()
        self._it = None
        self._started = False

    def _next_feed(self):
        try:
            return next(self._it)
        except StopIteration:
            self._started = False
            raise EOFException(
                "py_reader exhausted — call reader.reset()") from None


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """fluid.layers.py_reader parity: creates one data var per
    (shape, dtype) and returns the reader object (the reference returns
    a reader Variable; read_file() recovers the vars either way)."""
    base = name or unique_name.generate("py_reader")
    vars_ = []
    for i, (shp, dt) in enumerate(zip(shapes, dtypes)):
        shp = list(shp)
        # fluid passes batch-full shapes; keep them verbatim
        vars_.append(data(f"{base}_{i}", shp, dtype=convert_dtype(dt),
                          append_batch_size=False))
    return StaticPyReader(vars_, capacity, use_double_buffer)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """fluid.layers.create_py_reader_by_data parity: like py_reader but
    reuses existing data vars."""
    return StaticPyReader(feed_list, capacity, use_double_buffer)


def read_file(reader):
    """fluid.layers.read_file parity: the vars a reader feeds."""
    vars_ = reader.vars
    return vars_[0] if len(vars_) == 1 else list(vars_)


def double_buffer(reader, place=None, name=None):
    """fluid.layers.double_buffer parity. The dataio.PyReader worker
    thread IS the double buffer (host→HBM transfer overlapped with the
    step — buffered_reader.cc's role); this just forces it on."""
    if isinstance(reader, StaticPyReader):
        reader.use_double_buffer = True
        return reader
    from paddle_tpu import reader as _rdr
    return _rdr.buffered(reader, 2)


class _TransformedReader:
    """A reader-op chain link (batch/shuffle applied to a py_reader /
    open_files reader): keeps the StaticPyReader interface — ``vars``,
    iteration, start()/reset() — while transforming the feed stream,
    the way the reference chains create_batch_reader /
    create_shuffle_reader ops over an underlying file reader."""

    def __init__(self, underlying, transform):
        self.underlying = underlying
        self._transform = transform
        self._started = False
        self._it = None
        prog = default_main_program()
        if not hasattr(prog, "_py_readers"):
            prog._py_readers = []
        prog._py_readers.append(self)

    @property
    def vars(self):
        return self.underlying.vars

    def __iter__(self):
        return self._transform(iter(self.underlying))

    def start(self):
        self.reset()     # close any abandoned iterator (+ its worker)
        self._it = iter(self)
        self._started = True

    def reset(self):
        # close the transform generator so the underlying reader's
        # prefetch machinery (if any) is torn down, mirroring
        # StaticPyReader.reset
        if self._it is not None and hasattr(self._it, "close"):
            self._it.close()
        self._it = None
        self._started = False

    def _next_feed(self):
        try:
            return next(self._it)
        except StopIteration:
            self._started = False
            raise EOFException(
                "reader exhausted — call reader.reset()") from None


def batch(reader, batch_size):
    """fluid.layers.batch parity (create_batch_reader op). Accepts
    either a reader object from this module (open_files / py_reader —
    stacks each var's per-record arrays into a batch axis) or a plain
    sample-yielding callable (returns a callable yielding lists of
    sample tuples, the decorate_paddle_reader format)."""
    if hasattr(reader, "vars"):          # reader-op chain form
        def transform(feeds):
            buf = []
            for feed in feeds:
                buf.append(feed)
                if len(buf) == batch_size:
                    yield {k: np.stack([np.asarray(f[k]) for f in buf])
                           for k in buf[0]}
                    buf = []
            if buf:
                yield {k: np.stack([np.asarray(f[k]) for f in buf])
                       for k in buf[0]}
        return _TransformedReader(reader, transform)

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample if isinstance(sample, tuple) else (sample,))
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf:
            yield buf
    return batched


def shuffle(reader, buffer_size, seed=None):
    """fluid.layers.shuffle parity (create_shuffle_reader op): buffered
    shuffle over a reader object or a plain reader callable.

    ``seed`` varies the shuffle order across workers/epochs (the
    reference's create_shuffle_reader is randomly seeded,
    reader_op_registry.cc); default keeps the repo's deterministic-key
    convention (seed 0)."""
    if hasattr(reader, "vars"):          # reader-op chain form
        rng = np.random.RandomState(0 if seed is None else seed)

        def transform(feeds):
            buf = []
            for feed in feeds:
                buf.append(feed)
                if len(buf) >= buffer_size:
                    rng.shuffle(buf)
                    while buf:
                        yield buf.pop()
            rng.shuffle(buf)
            while buf:
                yield buf.pop()
        return _TransformedReader(reader, transform)
    from paddle_tpu import reader as _rdr
    return _rdr.shuffle(reader, buffer_size, seed=seed)


def load(out, file_path, load_as_fp16=None):
    """fluid.layers.load parity (load_op.cc): append a load op writing
    ``file_path``'s value into var ``out`` when the program runs."""
    from paddle_tpu.static.io import append_load_op
    return append_load_op(default_main_program(), [out], file_path)


def open_files(filenames, shapes, dtypes, thread_num=None,
               buffer_size=None, pass_num=1, is_test=None, name=None):
    """fluid.layers.open_files parity (open_files_op): a py_reader fed
    from RecordIO files. Record format: each record is an ``np.savez``
    archive holding arrays ``f0..fN`` for the N slots (the TPU-native
    stand-in for the reference's LoDTensor wire records)."""
    import io as _io
    rdr = py_reader(buffer_size or 64, shapes, dtypes, name=name)

    def source():
        from paddle_tpu import native
        for _ in range(pass_num):
            for path in filenames:
                with native.RecordIOScanner(path) as scan:
                    for rec in scan:
                        with np.load(_io.BytesIO(rec)) as z:
                            yield tuple(z[f"f{i}"]
                                        for i in range(len(shapes)))
    rdr.decorate_tensor_provider(source)
    return rdr


def random_data_generator(low, high, shapes, lod_levels=None,
                          for_parallel=True, seed=None):
    """fluid.layers.random_data_generator parity: a reader producing
    uniform floats in [low, high) with the given shapes (test-data
    generator, create_random_data_generator_op). ``seed`` varies the
    stream across workers; default keeps the deterministic-key
    convention (seed 0)."""
    rdr = py_reader(8, shapes, ["float32"] * len(shapes))
    rng = np.random.RandomState(0 if seed is None else seed)

    def source():
        while True:
            yield tuple(rng.uniform(low, high, size=s).astype(np.float32)
                        for s in shapes)
    rdr.decorate_tensor_provider(source)
    return rdr


class Preprocessor:
    """fluid.layers.Preprocessor parity: a per-batch transform expressed
    as a sub-program (the reference builds a sub-block executed by the
    preprocessing reader op; here the block is traced into its own
    Program and run — jit-compiled and cached — over each batch before
    it is fed).

    Usage (same as fluid)::

        p = Preprocessor(reader)
        with p.block():
            x, y = p.inputs()
            p.outputs(x / 255., y)
        out_vars = fluid.layers.read_file(p)
        for feed in p: exe.run(main, feed=feed)
    """

    def __init__(self, reader, name=None):
        self.underlying = reader
        self.sub_program = Program()
        self._in_vars = None
        self._out_vars = None
        self.vars = None             # main-program output vars
        self._guard = None

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            with program_guard(self.sub_program, Program()):
                yield
            self._finalize()
        return guard()

    def inputs(self):
        self._in_vars = [
            data(f"_pp_in_{i}", list(v.shape), dtype=str(np.dtype(v.dtype)),
                 append_batch_size=False)
            for i, v in enumerate(self.underlying.vars)]
        return list(self._in_vars)

    def outputs(self, *outs):
        self._out_vars = list(outs)

    def _finalize(self):
        if not self._out_vars:
            raise EnforceNotMet("Preprocessor.block set no outputs()")
        # declare main-program vars carrying the transformed batches
        self.vars = [
            data(f"_pp_out_{i}", list(v.shape),
                 dtype=str(np.dtype(v.dtype)), append_batch_size=False)
            for i, v in enumerate(self._out_vars)]

    def __iter__(self):
        from paddle_tpu.static.executor import Executor
        exe = Executor()
        in_names = [v.name for v in self._in_vars]
        out_names = [v.name for v in self._out_vars]
        new_names = [v.name for v in self.vars]
        for feed in self.underlying:
            vals = list(feed.values()) if isinstance(feed, dict) else feed
            sub_feed = {n: np.asarray(v) for n, v in zip(in_names, vals)}
            outs = exe.run(self.sub_program, feed=sub_feed,
                           fetch_list=out_names)
            yield {n: o for n, o in zip(new_names, outs)}
