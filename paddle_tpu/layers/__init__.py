"""fluid.layers parity surface.

Parity: python/paddle/fluid/layers/{nn.py (184 fns), tensor.py,
control_flow.py, learning_rate_scheduler.py, sequence ops, metric_op.py}.

Every function works in BOTH modes, like the reference's layers do
(static program building vs dygraph):
- **eager**: computes immediately via the functional op library
  (paddle_tpu.ops). Parameterized layers (fc, conv2d, …) additionally
  work inside an nn module context, collecting params functionally.
- **static** (inside `program_guard`): appends an op to the current
  Program and returns a symbolic Variable; output shapes are inferred by
  `jax.eval_shape` over the same functional implementation — the op's
  compute IS its shape function, so there is no separate InferShape
  (ref: framework/shape_inference.h is subsumed).
"""

import contextlib
import functools
import inspect

# the fluid surface exports a `range` op (ops.aliases); the auto-wrap
# loop below injects it into this module's globals, so capture the
# builtin before it is shadowed
_builtin_range = range

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import initializer as I
from paddle_tpu import ops as _ops
from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.framework import ParamAttr, WeightNormParamAttr, unique_name
from paddle_tpu.nn import module as _module
from paddle_tpu.static.program import (
    OP_REGISTRY, Variable, default_main_program, default_startup_program,
    in_static_mode, data,
)
from paddle_tpu.layers import learning_rate_scheduler
from paddle_tpu.layers.control_flow_classes import (
    While, Switch, IfElse, StaticRNN, DynamicRNN,
)
from paddle_tpu.layers.learning_rate_scheduler import (
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup,
)

# ---------------------------------------------------------------------------
# generic static-dispatch machinery for stateless ops
# ---------------------------------------------------------------------------

# ops whose leading-N args are tensors (default 1)
_NARGS = {
    "elementwise_add": 2, "elementwise_sub": 2, "elementwise_mul": 2,
    "elementwise_div": 2, "elementwise_min": 2, "elementwise_max": 2,
    "elementwise_pow": 2, "elementwise_mod": 2, "elementwise_floordiv": 2,
    "minus": 2, "matmul": 2, "mul": 2, "bmm": 2, "dot": 2,
    "cross_entropy": 2, "softmax_with_cross_entropy": 2,
    "sigmoid_cross_entropy_with_logits": 2, "square_error_cost": 2,
    "smooth_l1": 2, "huber_loss": 2, "log_loss": 2, "hinge_loss": 2,
    "margin_rank_loss": 3, "rank_loss": 3, "kldiv_loss": 2, "bpr_loss": 2,
    "cos_sim": 2, "modified_huber_loss": 2, "mse_loss": 2,
    "teacher_student_sigmoid_loss": 2, "npair_loss": 3,
    "gather": 2, "gather_nd": 2, "scatter": 3, "scatter_nd_add": 3,
    "where": 3, "expand_as": 2, "pad_constant_like": 2,
    "logical_and": 2, "logical_or": 2, "logical_xor": 2,
    "equal": 2, "not_equal": 2, "less_than": 2, "less_equal": 2,
    "greater_than": 2, "greater_equal": 2,
    "accuracy": 2, "auc": 2,
    "fill_constant": 0, "zeros": 0, "ones": 0, "eye": 0,
    "linspace": 0, "arange": 0, "gaussian_random": 0, "uniform_random": 0,
    "truncated_gaussian_random": 0, "randint": 0,
    "prelu": 2, "conv2d": 2, "conv2d_transpose": 2, "conv3d": 2,
    "depthwise_conv2d": 2, "embedding": 2,
    # quantization family
    "fake_quantize_range_abs_max": 3,
    "fake_quantize_moving_average_abs_max": 3,
    "fake_quantize_dequantize_moving_average_abs_max": 3,
    "moving_average_abs_max_scale": 3,
    "fake_dequantize_max_abs": 2, "quantize_linear": 2,
    "dequantize_linear": 2, "fake_channel_wise_dequantize_max_abs": 1,
    "quantized_mul": 2, "quantized_conv2d": 2,
    # crf / ctc families (optional trailing tensors promote dynamically)
    "linear_chain_crf": 3, "crf_decoding": 2, "ctc_loss": 2,
    "warpctc": 2, "edit_distance": 2,
    # detection family
    "iou_similarity": 2, "box_coder": 3, "prior_box": 2,
    "density_prior_box": 2, "bipartite_match": 1, "target_assign": 2,
    "multiclass_nms": 2, "detection_output": 4, "ssd_loss": 5,
    "yolo_box": 2, "yolov3_loss": 3, "box_clip": 2,
    "sigmoid_focal_loss": 3, "roi_align": 2, "roi_pool": 2,
    "roi_perspective_transform": 2, "mine_hard_examples": 4,
    "psroi_pool": 2, "generate_proposals": 5, "box_decoder_and_assign": 4,
    "dice_loss": 2, "sampled_softmax_with_cross_entropy": 2,
    "deformable_roi_pooling": 3, "conv3d_transpose": 2,
    "create_tensor": 0, "hierarchical_sigmoid": 4,
}

# ops whose first arg is a LIST of tensors
_LIST_FIRST = {"concat", "sums", "stack", "multiplex"}

# ops that draw randomness (executor must feed them a key)
_NEEDS_RNG = {"dropout", "gaussian_random", "uniform_random",
              "truncated_gaussian_random", "randint", "sampling_id",
              "random_crop", "shuffle_batch",
              "uniform_random_batch_size_like",
              "gaussian_random_batch_size_like",
              "sampled_softmax_with_cross_entropy"}

_MULTI_OUT = {"topk": 2, "argsort": 2, "ctc_align": 2, "edit_distance": 2,
              "fake_quantize_abs_max": 2,
              "fake_quantize_dequantize_abs_max": 2,
              "fake_channel_wise_quantize_abs_max": 2,
              "fake_channel_wise_quantize_dequantize_abs_max": 2,
              "fake_quantize_range_abs_max": 2,
              "moving_average_abs_max_scale": 3,
              "fake_quantize_moving_average_abs_max": 4,
              "fake_quantize_dequantize_moving_average_abs_max": 4,
              "prior_box": 2,
              "density_prior_box": 2, "anchor_generator": 2,
              "bipartite_match": 2, "yolo_box": 2, "target_assign": 2,
              "generate_proposals": 3,
              "roi_perspective_transform": 3,
              "mine_hard_examples": 2,
              "ctc_greedy_decoder": 2, "unique": 2}


def _bind_tensor_params(tparams, xs):
    """Rebuild {param: tensor-or-list} from the flattened input list."""
    out = {}
    i = 0
    for entry in tparams:
        if isinstance(entry, tuple):
            pname, cnt = entry
            out[pname] = list(xs[i:i + cnt])
            i += cnt
        else:
            out[entry] = xs[i]
            i += 1
    return out


def _register(name, fn):
    n_tensor = _NARGS.get(name, 1)
    listy = name in _LIST_FIRST

    def compute(ins, attrs):
        xs = ins.get("X", [])
        attrs = dict(attrs)
        attrs.pop("_needs_rng", None)
        tparams = attrs.pop("_tensor_params", None)
        if listy:
            out = fn(list(xs), **attrs)
        elif tparams is not None:
            # inputs bound by parameter name (op had optional tensor args
            # promoted from attr positions — e.g. ssd_loss's prior_box_var);
            # (name, count) entries regroup list-valued tensor params
            out = fn(**{**attrs, **_bind_tensor_params(tparams, xs)})
        else:
            out = fn(*xs, **attrs)
        return {"Out": list(out) if isinstance(out, tuple) else [out]}

    OP_REGISTRY[name] = compute
    return n_tensor, listy


def _sub_dyn(shape, val=2):
    return tuple(val if (s is None or s == -1) else int(s) for s in shape)


def _spec_of(v, val=2):
    if v.shape is None:
        raise EnforceNotMet(
            f"variable '{v.name}' has unknown shape (producer op's shape "
            f"inference failed: {getattr(v, '_shape_error', 'unknown')})")
    return jax.ShapeDtypeStruct(_sub_dyn(v.shape, val), v.dtype)


def _append_static(name, fn, tensor_vals, attrs, listy,
                   tensor_params=None, promoted=None):
    """Append one op to the current program.

    ``tensor_params`` names the leading tensor parameters; ``promoted`` is
    an ordered {param: Variable} of OPTIONAL tensor args found in attr
    positions (they must ride the input list, not the attr dict — a
    Variable baked into attrs would crash the executor)."""
    blk = default_main_program().global_block()
    program = default_main_program()
    in_names = []
    specs2, specs3 = [], []
    had_dyn = False
    flat = list(tensor_vals[0] if listy else tensor_vals)
    all_params = list(tensor_params) if tensor_params is not None else []
    if promoted:
        for pname, pval in promoted.items():
            if isinstance(pval, (list, tuple)):
                # a LIST of tensors in an attr position (e.g.
                # fake_channel_wise_dequantize_max_abs's scales):
                # flatten into inputs, record (name, count) to regroup
                flat.extend(pval)
                all_params.append((pname, len(pval)))
            else:
                flat.append(pval)
                all_params.append(pname)
        attrs = {k: v for k, v in attrs.items() if k not in promoted}
    for tv in flat:
        if isinstance(tv, Variable):
            in_names.append(tv.name)
            specs2.append(_spec_of(tv, 2))
            specs3.append(_spec_of(tv, 3))
            if tv.shape and any(s in (-1, None) for s in tv.shape):
                had_dyn = True
        else:
            arr = jnp.asarray(tv)
            cname = unique_name.generate(f"const_{name}")
            blk.create_var(name=cname, shape=arr.shape, dtype=arr.dtype,
                           persistable=False)
            program._constants[cname] = arr
            in_names.append(cname)
            sp = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
            specs2.append(sp)
            specs3.append(sp)

    eval_attrs = dict(attrs)
    if name in _NEEDS_RNG:
        eval_attrs["rng"] = jax.random.PRNGKey(0)

    def infer(specs):
        if listy:
            return jax.eval_shape(lambda *xs: fn(list(xs), **eval_attrs),
                                  *specs)
        if promoted:
            return jax.eval_shape(
                lambda *xs: fn(**{**eval_attrs,
                                  **_bind_tensor_params(all_params, xs)}),
                *specs)
        return jax.eval_shape(lambda *xs: fn(*xs, **eval_attrs), *specs)

    # dynamic dims are probed with two substitute sizes (2 and 3): any
    # output dim that shifts between the probes depends on a dynamic input
    # dim and is recorded as -1, not a literal
    shape_error = None
    legacy_batch_fixup = False
    try:
        out_spec = infer(specs2)
    except Exception as e:  # shape inference failure -> unknown shape
        out_spec = out_spec3 = None
        shape_error = f"{type(e).__name__}: {e}"
    else:
        try:
            out_spec3 = infer(specs3) if had_dyn else out_spec
        except Exception:
            # op only traces at the first probe size (e.g. a reshape attr
            # tied to it): fall back to marking just the batch dim dynamic
            out_spec3 = out_spec
            legacy_batch_fixup = had_dyn

    n_out = _MULTI_OUT.get(name, 1)
    outs = []

    def listify(spec):
        return (list(spec) if isinstance(spec, (tuple, list))
                else [spec] * n_out if spec is None else [spec])

    out_specs = listify(out_spec)
    out_specs3 = listify(out_spec3)
    for i in _builtin_range(n_out):
        sp = out_specs[i] if i < len(out_specs) else None
        sp3 = out_specs3[i] if i < len(out_specs3) else None
        shape = None
        dtype = jnp.float32
        if sp is not None:
            dtype = sp.dtype
            shape = [d if sp3 is None or d == sp3.shape[j] else -1
                     for j, d in enumerate(sp.shape)]
            if legacy_batch_fixup and shape and shape[0] == 2:
                shape[0] = -1
        v = blk.create_var(name=unique_name.generate(f"{name}.out"),
                           shape=shape, dtype=dtype)
        if shape is None:
            v._shape_error = shape_error
        outs.append(v)
    op_attrs = dict(attrs)
    if name in _NEEDS_RNG:
        op_attrs["_needs_rng"] = True
    if promoted:
        op_attrs["_tensor_params"] = tuple(all_params)
    blk.append_op(type=name, inputs={"X": in_names},
                  outputs={"Out": [v.name for v in outs]}, attrs=op_attrs)
    return outs[0] if n_out == 1 else tuple(outs)


def _has_variable(vals):
    for v in vals:
        if isinstance(v, Variable):
            return True
        if isinstance(v, (list, tuple)) and any(
                isinstance(x, Variable) for x in v):
            return True
    return False


def _dual(name, fn):
    n_tensor, listy = _register(name, fn)
    sig = inspect.signature(fn)
    pnames = list(sig.parameters)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        bound = sig.bind(*args, **kwargs)
        bound.apply_defaults()
        vals = bound.arguments
        if listy:
            tensor_vals = [list(vals[pnames[0]])]
            attr_names = pnames[1:]
        else:
            tensor_vals = [vals[p] for p in pnames[:n_tensor]]
            attr_names = pnames[n_tensor:]
        attrs = {p: vals[p] for p in attr_names
                 if p in vals and p not in ("name", "rng")
                 and vals[p] is not inspect.Parameter.empty}
        if in_static_mode():
            promoted = {p: v for p, v in attrs.items()
                        if isinstance(v, Variable)
                        or (isinstance(v, (list, tuple))
                            and any(isinstance(x, Variable) for x in v))}
            if promoted or _has_variable(
                    tensor_vals[0] if listy else tensor_vals):
                return _append_static(name, fn, tensor_vals, attrs, listy,
                                      tensor_params=pnames[:n_tensor],
                                      promoted=promoted)
        return fn(*args, **kwargs)

    return wrapper


# auto-wrap every exported functional op
_EXCLUDE = {"fc_act", "batch_norm", "sequence_mask",
            # host/numpy or list-in/list-out detection ops: exposed
            # directly below, no static-program wrapper
            "rpn_target_assign", "generate_proposal_labels",
            "detection_map", "distribute_fpn_proposals",
            "collect_fpn_proposals", "retinanet_detection_output",
            "retinanet_target_assign", "generate_mask_labels",
            # host/list ops from ops.aliases: no static wrapper either
            "delete_var", "alloc_continuous_space"}
_this = globals()
for _n in dir(_ops):
    if _n.startswith("_") or _n in _EXCLUDE:
        continue
    _f = getattr(_ops, _n)
    if callable(_f) and getattr(_f, "__module__", "").startswith("paddle_tpu.ops"):
        _this[_n] = _dual(_n, _f)

# sequence_mask needs maxlen attr; expose directly (works both modes)
sequence_mask = _dual("sequence_mask", _ops.sequence_mask)


# control flow with callable bodies: the auto-wrap treats every
# positional arg as a tensor, so these get explicit duals. In static
# mode the bodies are traced into serializable sub-programs
# (static/nested.py, ref while_op.cc / recurrent_op.cc sub-blocks);
# eager mode lowers straight to lax.while_loop / lax.scan.
def while_loop(cond, body, loop_vars, is_test=False, name=None):
    lv = loop_vars if isinstance(loop_vars, (list, tuple)) else [loop_vars]
    if in_static_mode() and _has_variable(list(lv)):
        from paddle_tpu.static.nested import static_while_loop
        return static_while_loop(cond, body, loop_vars)
    return _ops.while_loop(cond, body, loop_vars)


def static_rnn(step_fn, inputs, initial_state):
    if in_static_mode() and _has_variable(
            list(inputs if isinstance(inputs, (list, tuple))
                 else [inputs])):
        from paddle_tpu.static.nested import static_rnn_block
        return static_rnn_block(step_fn, inputs, initial_state)
    return _ops.static_rnn(step_fn, inputs, initial_state)

# host/list detection ops: eager-only passthroughs
rpn_target_assign = _ops.rpn_target_assign
generate_proposal_labels = _ops.generate_proposal_labels
detection_map = _ops.detection_map
distribute_fpn_proposals = _ops.distribute_fpn_proposals
collect_fpn_proposals = _ops.collect_fpn_proposals
retinanet_detection_output = _ops.retinanet_detection_output
retinanet_target_assign = _ops.retinanet_target_assign
generate_mask_labels = _ops.generate_mask_labels
delete_var = _ops.delete_var
alloc_continuous_space = _ops.alloc_continuous_space


# ---------------------------------------------------------------------------
# parameterized layer functions
# ---------------------------------------------------------------------------
def _make_param(prefix, shape, dtype, attr, default_init, trainable=True):
    """Create a parameter in whichever context is active (static program
    or nn module frame)."""
    attr = ParamAttr.to_attr(attr) if attr is not None else ParamAttr()
    if isinstance(attr, WeightNormParamAttr):
        return _make_weight_norm_param(prefix, shape, dtype, attr,
                                       default_init, trainable)
    init = attr.initializer or default_init
    if in_static_mode():
        blk = default_main_program().global_block()
        name = attr.name or unique_name.generate(prefix)
        p = blk.create_parameter(
            name, shape, dtype, trainable=attr.trainable and trainable,
            regularizer=attr.regularizer, gradient_clip=attr.gradient_clip,
            optimize_attr={"learning_rate": attr.learning_rate},
            initializer=init)
        sblk = default_startup_program().global_block()
        if not sblk.has_var(name):
            sblk.create_parameter(name, shape, dtype, initializer=init)
            sblk.append_op(
                type="init_param", inputs={},
                outputs={"Out": [name]},
                attrs={"initializer": init, "shape": tuple(shape),
                       "dtype": np.dtype(dtype).name if not isinstance(dtype, str) else dtype,
                       "_needs_rng": True})
        return p
    if _module.in_module_ctx():
        return _module.create_parameter(prefix, shape, dtype,
                                        initializer=init, attr=attr)
    raise EnforceNotMet(
        f"parameterized layer needs a Program (use program_guard) or a "
        f"module context (nn.transform / Layer.init)")


def _make_weight_norm_param(prefix, shape, dtype, attr, default_init,
                            trainable):
    """Weight normalization (WeightNormParamAttr, ref param_attr.py +
    layers/__init__ weight-norm rewrite): reparameterize w = g * v/||v||
    with the norm over every axis except ``dim``. v carries the
    direction, g the magnitude; g is initialized to ||v_init|| so the
    initial effective weight equals the plain initialization."""
    if attr.name:
        base = attr.name
    elif in_static_mode():
        base = unique_name.generate(prefix + "_wn")
    else:
        # module ctx: init AND apply both execute this code, so the name
        # must be deterministic — name by prefix and let the module
        # frame scope it (the rule plain unnamed params follow);
        # unique_name's global counter would diverge between the two
        # passes and apply would miss the param
        base = prefix + "_wn"
    init = attr.initializer or default_init
    plain = ParamAttr(name=base + "_v", initializer=init,
                      learning_rate=attr.learning_rate,
                      regularizer=attr.regularizer,
                      trainable=attr.trainable and trainable,
                      gradient_clip=attr.gradient_clip)
    v = _make_param(prefix + "_v", shape, dtype, plain, init, trainable)
    dim = attr.dim
    # dim=None: one scalar g (norm over everything). dim=k: per-slice g
    # over axis k; when the param is 1-D that means per-element (norm of
    # each slice is just |v_i|) — keep the two cases distinct, an empty
    # axes tuple is NOT the same as "reduce all".
    norm_axes = (None if dim is None else
                 tuple(i for i in _builtin_range(len(shape)) if i != dim))
    g_shape = (shape[dim],) if dim is not None else (1,)

    if in_static_mode():
        gname = base + "_g"
        blk = default_main_program().global_block()
        gp = blk.create_parameter(
            gname, g_shape, dtype, trainable=attr.trainable and trainable,
            regularizer=attr.regularizer,
            gradient_clip=attr.gradient_clip,
            optimize_attr={"learning_rate": attr.learning_rate},
            initializer=I.Constant(1.0))
        sblk = default_startup_program().global_block()
        if not sblk.has_var(gname):
            sblk.create_parameter(gname, g_shape, dtype,
                                  initializer=I.Constant(1.0))
            # g starts at ||v_init||: computed FROM v in the startup
            # program (the reference appends norm ops the same way)
            sblk.append_op(type="weight_norm_init_g",
                           inputs={"X": [base + "_v"]},
                           outputs={"Out": [gname]},
                           attrs={"dim": dim})
        g = gp
    else:
        # g starts at ||v_init||; the initializer closure is only CALLED
        # at parameter creation (module frame mode == "init"), so apply/
        # grad never touch it — and it uses jnp ops, because under
        # nn.transform's init v may be a tracer (np.asarray would crash)
        class _GInit(I.Initializer):
            def __call__(self, key, gshape_, gdtype=jnp.float32):
                return _wn_norm_jnp(v, dim).reshape(gshape_) \
                    .astype(gdtype)
        g = _make_param(prefix + "_g", g_shape, dtype,
                        ParamAttr(name=base + "_g",
                                  initializer=_GInit(),
                                  learning_rate=attr.learning_rate,
                                  regularizer=attr.regularizer,
                                  gradient_clip=attr.gradient_clip,
                                  trainable=attr.trainable and trainable),
                        I.Constant(1.0), trainable)

    # w = g * v / ||v||, built from wrapped ops so it works in BOTH
    # modes (static: appends square/reduce/scale/rsqrt/mul ops)
    if norm_axes is None:
        sq = reduce_sum(square(v), keep_dim=True)
    elif norm_axes:
        sq = reduce_sum(square(v), dim=list(norm_axes), keep_dim=True)
    else:
        sq = square(v)            # 1-D with dim set: per-element norm
    inv = rsqrt(scale(sq, scale=1.0, bias=1e-12))
    gshape = [1] * len(shape)
    if dim is not None:
        gshape[dim] = shape[dim]
    gb = reshape(g, shape=gshape)
    return elementwise_mul(elementwise_mul(v, inv), gb)


def _wn_norm_jnp(v, dim):
    """||v|| over all axes but ``dim`` (all axes when dim is None;
    per-element when v is 1-D and dim is set)."""
    v = jnp.asarray(v)
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v))).reshape(1)
    axes = tuple(i for i in _builtin_range(v.ndim) if i != dim)
    if not axes:
        return jnp.abs(v)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes))


def _weight_norm_init_g_compute(ins, attrs):
    return {"Out": [_wn_norm_jnp(ins["X"][0], attrs.get("dim"))]}


OP_REGISTRY["weight_norm_init_g"] = _weight_norm_init_g_compute


def register_op_init_param():
    def compute(ins, attrs):
        init = attrs["initializer"]
        rng = attrs.get("rng", jax.random.PRNGKey(0))
        return {"Out": [init(rng, tuple(attrs["shape"]),
                             convert_dtype(attrs["dtype"]))]}
    OP_REGISTRY["init_param"] = compute


register_op_init_param()


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """fluid.layers.create_parameter parity."""
    default = default_initializer or (
        I.Constant(0.0) if is_bias else I.Xavier())
    if attr is None and name is not None:
        attr = ParamAttr(name=name)
    return _make_param(name or "param", tuple(shape), convert_dtype(dtype),
                       attr, default)


def create_global_var(shape, value, dtype="float32", persistable=False,
                      force_cpu=False, name=None):
    """fluid.layers.create_global_var parity (static only)."""
    return _make_param(name or "gvar", tuple(shape), convert_dtype(dtype),
                       ParamAttr(name=name, trainable=False),
                       I.Constant(value), trainable=False)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """fluid.layers.fc parity (ref: python/paddle/fluid/layers/nn.py fc).

    On TPU this is the canonical MXU op: a flattened matmul + fused bias +
    fused activation (the reference's separate fc/fused-fc ops collapse
    into XLA fusion)."""
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    attrs = (list(param_attr) if isinstance(param_attr, (list, tuple))
             else [param_attr] * len(inputs))
    out = None
    for x, pa in zip(inputs, attrs):
        in_dim = 1
        for d in x.shape[num_flatten_dims:]:
            if d in (-1, None):
                raise EnforceNotMet(
                    f"fc: flattened input dims must be static, got shape "
                    f"{x.shape} with num_flatten_dims={num_flatten_dims}")
            in_dim *= int(d)
        w = _make_param("fc_w", (in_dim, size), jnp.float32, pa, I.Xavier())
        o = mul(x, w, x_num_col_dims=num_flatten_dims)
        out = o if out is None else elementwise_add(out, o)
    # one shared bias regardless of how many input branches (fluid layout)
    if bias_attr is not False:
        b = _make_param("fc_b", (size,), jnp.float32, bias_attr,
                        I.Constant(0.0))
        out = elementwise_add(out, b, axis=num_flatten_dims)
    return _apply_act(out, act)


def _apply_act(x, act):
    if act is None:
        return x
    return globals()[act](x)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """fluid.layers.embedding / lookup_table parity. is_sparse/
    is_distributed are advisory on TPU (see distributed/sparse.py for the
    host-sharded big-table path)."""
    w = _make_param("emb_w", tuple(size), convert_dtype(dtype), param_attr,
                    I.Xavier())
    pi = padding_idx if padding_idx is None or padding_idx >= 0 \
        else size[0] + padding_idx
    return _emb_dispatch(input, w, pi)


def _emb_dispatch(input, w, padding_idx):
    if in_static_mode() and isinstance(input, Variable):
        return _append_static("embedding", _ops.embedding, [input, w],
                              {"padding_idx": padding_idx}, False)
    return _ops.embedding(input, w, padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           use_cudnn=True, name=None, data_format="NCHW"):
    """fluid.layers.conv2d parity (use_cudnn accepted and ignored — XLA
    owns kernel choice on TPU)."""
    c_in = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _make_param("conv2d_w",
                    (num_filters, c_in // groups) + tuple(fs),
                    jnp.float32, param_attr, I.MSRA(uniform=False))
    out = _conv_dispatch("conv2d", _ops.conv2d, input, w,
                         dict(stride=stride, padding=padding,
                              dilation=dilation, groups=groups,
                              data_format=data_format))
    if bias_attr is not False:
        b = _make_param("conv2d_b", (num_filters,), jnp.float32, bias_attr,
                        I.Constant(0.0))
        out = elementwise_add(out, b, axis=1)
    return _apply_act(out, act)


def _infer_transpose_fs(input, output_size, stride, padding, dilation,
                        nd):
    """conv_transpose filter-size inference when only output_size is
    given (ref layers/nn.py conv2d_transpose: filter_size =
    (output + 2*pad - (in-1)*stride + stride - 1) // dilation, per dim,
    with dilation-adjusted rounding)."""
    outs = output_size if isinstance(output_size, (list, tuple)) \
        else (output_size,) * nd
    sts = stride if isinstance(stride, (list, tuple)) else (stride,) * nd
    pds = padding if isinstance(padding, (list, tuple)) else (padding,) * nd
    dls = dilation if isinstance(dilation, (list, tuple)) \
        else (dilation,) * nd
    fs = []
    for i in _builtin_range(nd):
        in_sz = int(input.shape[2 + i])
        k = (int(outs[i]) + 2 * pds[i] - (in_sz - 1) * sts[i]
             + dls[i] - 1) // dls[i]
        fs.append(k)
    return tuple(fs)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     use_cudnn=True, name=None):
    c_in = int(input.shape[1])
    if filter_size is None:
        if output_size is None:
            raise EnforceNotMet(
                "conv2d_transpose: one of output_size or filter_size "
                "is required (layers/nn.py conv2d_transpose)")
        filter_size = _infer_transpose_fs(input, output_size, stride,
                                          padding, dilation, 2)
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _make_param("conv2dT_w", (c_in, num_filters // groups) + tuple(fs),
                    jnp.float32, param_attr, I.Xavier())
    out = _conv_dispatch("conv2d_transpose", _ops.conv2d_transpose, input, w,
                         dict(stride=stride, padding=padding,
                              dilation=dilation, groups=groups))
    if bias_attr is not False:
        b = _make_param("conv2dT_b", (num_filters,), jnp.float32, bias_attr,
                        I.Constant(0.0))
        out = elementwise_add(out, b, axis=1)
    return _apply_act(out, act)


def _conv_dispatch(name, fn, input, w, attrs):
    if in_static_mode() and isinstance(input, Variable):
        return _append_static(name, fn, [input, w], attrs, False)
    return fn(input, w, **attrs)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               use_global_stats=False):
    """fluid.layers.batch_norm parity. Running stats are persistable state:
    static mode stores them as non-trainable parameters updated by the op;
    module mode uses nn state."""
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    scale = _make_param("bn_scale", (c,), jnp.float32, param_attr,
                        I.Constant(1.0))
    bias = _make_param("bn_bias", (c,), jnp.float32, bias_attr,
                       I.Constant(0.0))
    if in_static_mode() and isinstance(input, Variable):
        mean = _make_param(moving_mean_name or "bn_mean", (c,), jnp.float32,
                           ParamAttr(name=moving_mean_name, trainable=False),
                           I.Constant(0.0), trainable=False)
        var = _make_param(moving_variance_name or "bn_variance", (c,),
                          jnp.float32,
                          ParamAttr(name=moving_variance_name,
                                    trainable=False),
                          I.Constant(1.0), trainable=False)
        blk = default_main_program().global_block()
        out = blk.create_var(name=unique_name.generate("bn.out"),
                             shape=input.shape, dtype=input.dtype)
        blk.append_op(
            type="batch_norm",
            inputs={"X": [input.name, scale.name, bias.name, mean.name,
                          var.name]},
            outputs={"Out": [out.name], "MeanOut": [mean.name],
                     "VarianceOut": [var.name]},
            attrs={"epsilon": epsilon, "momentum": momentum,
                   "is_test": is_test,
                   "data_layout": data_layout,
                   "use_global_stats": use_global_stats})
        return _apply_act(out, act)
    # module/eager path
    mean = _module.create_state("bn_mean", (c,), jnp.float32, 0.0)
    var = _module.create_state("bn_variance", (c,), jnp.float32, 1.0)
    out, m_out, v_out, _, _ = _ops.batch_norm(
        input, scale, bias, mean, var, epsilon, momentum, is_test,
        data_layout, use_global_stats)
    if not is_test:
        _module.set_state("bn_mean", m_out)
        _module.set_state("bn_variance", v_out)
    return _apply_act(out, act)


def _bn_compute(ins, attrs):
    x, scale, bias, mean, var = ins["X"]
    out, m_out, v_out, _, _ = _ops.batch_norm(
        x, scale, bias, mean, var, attrs["epsilon"], attrs["momentum"],
        attrs["is_test"], attrs["data_layout"], attrs["use_global_stats"])
    return {"Out": [out], "MeanOut": [m_out], "VarianceOut": [v_out]}


OP_REGISTRY["batch_norm"] = _bn_compute


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = tuple(int(s) for s in input.shape[begin_norm_axis:])
    flat = 1
    for s in shape:
        flat *= s
    s = _make_param("ln_scale", (flat,), jnp.float32, param_attr,
                    I.Constant(1.0)) if scale else None
    b = _make_param("ln_bias", (flat,), jnp.float32, bias_attr,
                    I.Constant(0.0)) if shift else None
    tensors = [t for t in (input, s, b) if t is not None]
    if in_static_mode() and isinstance(input, Variable):
        attrs = {"begin_norm_axis": begin_norm_axis, "epsilon": epsilon,
                 "has_scale": s is not None, "has_bias": b is not None}
        out = _append_static("layer_norm_flex", _ln_flex, tensors, attrs,
                             False)
        return _apply_act(out, act)
    return _apply_act(_ln_flex(*tensors, begin_norm_axis=begin_norm_axis,
                               epsilon=epsilon, has_scale=s is not None,
                               has_bias=b is not None), act)


def _ln_flex(*tensors, begin_norm_axis=1, epsilon=1e-5, has_scale=True,
             has_bias=True):
    it = iter(tensors)
    x = next(it)
    s = next(it) if has_scale else None
    b = next(it) if has_bias else None
    return _ops.layer_norm(x, s, b, begin_norm_axis, epsilon)


_register("layer_norm_flex", _ln_flex)
_NARGS["layer_norm_flex"] = 3


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    c = int(input.shape[1])
    s = _make_param("gn_scale", (c,), jnp.float32, param_attr,
                    I.Constant(1.0))
    b = _make_param("gn_bias", (c,), jnp.float32, bias_attr,
                    I.Constant(0.0))
    if in_static_mode() and isinstance(input, Variable):
        return _apply_act(
            _append_static("group_norm_p", _gn_p, [input, s, b],
                           {"groups": groups, "epsilon": epsilon}, False),
            act)
    return _apply_act(_gn_p(input, s, b, groups=groups, epsilon=epsilon),
                      act)


def _gn_p(x, s, b, groups=32, epsilon=1e-5):
    return _ops.group_norm(x, s, b, groups, epsilon)


_register("group_norm_p", _gn_p)
_NARGS["group_norm_p"] = 3


def softmax(input, use_cudnn=False, name=None, axis=-1):
    if in_static_mode() and isinstance(input, Variable):
        return _append_static("softmax", _ops.softmax, [input],
                              {"axis": axis}, False)
    return _ops.softmax(input, axis=axis)


def mean(x, name=None):
    if in_static_mode() and isinstance(x, Variable):
        return _append_static("mean", _ops.mean, [x], {}, False)
    return _ops.mean(x)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    if in_static_mode() and isinstance(x, Variable):
        return _append_static(
            "dropout", _ops.dropout, [x],
            {"dropout_prob": dropout_prob, "is_test": is_test,
             "dropout_implementation": dropout_implementation}, False)
    rng = _module.current_rng() if _module.in_module_ctx() and not is_test \
        else None
    return _ops.dropout(x, dropout_prob, is_test, seed,
                        dropout_implementation, rng=rng)


# simple data helpers
def shape(input):
    if isinstance(input, Variable):
        return jnp.array([-1 if s in (None, -1) else s
                          for s in input.shape], jnp.int32)
    return _ops.shape(input)


def linear_chain_crf(input, label, param_attr=None, length=None):
    """fluid.layers.linear_chain_crf parity: creates the ``crfw``
    transition parameter ([num_tags+2, num_tags], ref:
    operators/linear_chain_crf_op.cc OpMaker) and returns the per-sequence
    negative log-likelihood. Decode with crf_decoding(input, crfw)."""
    num_tags = int(input.shape[-1])
    w = _make_param("crfw", (num_tags + 2, num_tags), jnp.float32,
                    param_attr, I.Xavier())
    if in_static_mode() and isinstance(input, Variable):
        tensors = [input, w, label]
        attrs = {}
        if length is not None:
            tensors.append(length)
        return _append_static("linear_chain_crf", _ops.linear_chain_crf,
                              tensors, attrs, False)
    return _ops.linear_chain_crf(input, w, label, length)


# ---------------------------------------------------------------------------
# host ops: Print / py_func (run eagerly between jitted device segments,
# see executor._compile; ref: operators/print_op.cc, operators/py_func_op.cc)
# ---------------------------------------------------------------------------
def _print_cb(msg, summarize, counter, first_n, arr):
    import sys
    counter["n"] += 1
    if first_n and first_n > 0 and counter["n"] > first_n:
        return
    arr = np.asarray(arr)
    flat = arr.reshape(-1)[:summarize] if summarize and summarize > 0 \
        else arr.reshape(-1)
    print(f"{msg}shape={arr.shape} dtype={arr.dtype} "
          f"data={np.array2string(flat, precision=6)}",
          file=sys.stderr)


def _backend_has_callbacks():
    # the axon PJRT tunnel rejects host send/recv callbacks; standard
    # cpu/gpu/tpu backends support them
    return jax.default_backend() in ("cpu", "gpu", "tpu", "cuda", "rocm")


def _print_compute(ins, attrs):
    x = ins["X"][0]
    # device op, not a host op: jax.debug.callback keeps Print inside
    # the jitted (and differentiated) segment — identity for autodiff,
    # so a mid-network Print never perturbs training (print_op.cc's
    # grad op forwards gradients the same way)
    if _backend_has_callbacks():
        jax.debug.callback(
            functools.partial(_print_cb, attrs.get("message", ""),
                              attrs.get("summarize", 20),
                              attrs["_counter"], attrs.get("first_n", -1)),
            x)
    elif not attrs["_counter"].get("warned"):
        attrs["_counter"]["warned"] = True
        import warnings
        warnings.warn(
            f"layers.Print({attrs.get('message', '')!r}) is inert: "
            f"backend {jax.default_backend()!r} does not support host "
            f"callbacks; the op passes its input through unchanged")
    return {"Out": [x]}


OP_REGISTRY["print"] = _print_compute


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """fluid.layers.Print parity (operators/print_op.cc): passthrough op
    that logs the tensor's value each execution (at most ``first_n``
    times)."""
    msg = (message + " ") if message else ""
    counter = {"n": 0}
    if in_static_mode() and isinstance(input, Variable):
        blk = input.block
        out = blk.create_var(shape=input.shape, dtype=input.dtype)
        blk.append_op("print", inputs={"X": [input.name]},
                      outputs={"Out": [out.name]},
                      attrs={"message": msg, "summarize": summarize,
                             "first_n": first_n, "_counter": counter})
        return out
    _print_cb(msg, summarize, counter, -1, input)
    return input


def _py_func_compute(ins, attrs):
    fn = attrs["func"]
    outs = fn(*ins["X"])
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return {"Out": [jnp.asarray(o) for o in outs]}


OP_REGISTRY["py_func"] = _py_func_compute


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """fluid.layers.py_func parity (operators/py_func_op.cc): run an
    arbitrary python callable on host values mid-program. Host op — the
    executor materializes inputs, calls ``func``, and feeds results back
    into the surrounding jitted segments. backward_func is accepted for
    API parity; the autodiff boundary treats py_func outputs as
    constants (like the reference when no backward_func is given)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    if in_static_mode() and all(isinstance(v, Variable) for v in xs):
        blk = xs[0].block
        blk.append_op("py_func",
                      inputs={"X": [v.name for v in xs]},
                      outputs={"Out": [o.name for o in outs]},
                      attrs={"func": func, "_host": True})
        return outs if isinstance(out, (list, tuple)) else outs[0]
    res = _py_func_compute({"X": list(xs)}, {"func": func})["Out"]
    return res if isinstance(out, (list, tuple)) else res[0]


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multi-box head (ref python/paddle/fluid/layers/detection.py:1737):
    a composite over prior_box + conv2d + transpose/flatten/concat —
    per feature map, priors are generated and two convs predict
    locations (P*4 channels) and confidences (P*num_classes channels);
    everything concatenates across maps. Works in both modes like every
    other layer (the convs create parameters).

    Returns (mbox_locs [N, B, 4], mbox_confs [N, B, num_classes],
    boxes [B, 4], variances [B, 4]) with B = total prior count.
    """
    import math as _math
    if not isinstance(inputs, (list, tuple)):
        raise EnforceNotMet("inputs should be a list or tuple")
    num_layer = len(inputs)
    if num_layer <= 2:
        if min_sizes is None or max_sizes is None or \
                len(min_sizes) != num_layer or len(max_sizes) != num_layer:
            raise EnforceNotMet(
                "with <=2 input layers, min_sizes/max_sizes must be "
                "given per layer")
    elif min_sizes is None and max_sizes is None:
        min_sizes, max_sizes = [], []
        step = int(_math.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in _builtin_range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes
    if steps:
        step_w = step_h = steps

    # uniqueness of default param names across multiple heads: in the
    # eager module context the FRAME scope uniquifies deterministically
    # (resets every init/apply, so names line up between the two); in
    # static mode the program-level unique_name counter does it
    if _module.in_module_ctx():
        _mbh_scope = _module._frame().scope("multi_box_head")
        _mbh_tag = "mbh"
    else:
        _mbh_scope = contextlib.nullcontext()
        _mbh_tag = name or unique_name.generate("multi_box_head")
    with _mbh_scope:
        return _multi_box_head_body(
            inputs, image, num_classes, aspect_ratios, min_sizes,
            max_sizes, step_w, step_h, offset, variance, flip, clip,
            kernel_size, pad, stride, min_max_aspect_ratios_order,
            name, _mbh_tag)


def _multi_box_head_body(inputs, image, num_classes, aspect_ratios,
                         min_sizes, max_sizes, step_w, step_h, offset,
                         variance, flip, clip, kernel_size, pad, stride,
                         min_max_aspect_ratios_order, name, _mbh_tag):
    mbox_locs, mbox_confs, box_results, var_results = [], [], [], []
    for i, inp in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i]
        if not isinstance(min_size, (list, tuple)):
            min_size = [min_size]
        if not isinstance(max_size, (list, tuple)):
            max_size = [max_size]
        ar = aspect_ratios[i] if aspect_ratios is not None else []
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        step = (step_w[i] if step_w else 0.0,
                step_h[i] if step_h else 0.0)
        box, var = prior_box(inp, image, list(min_size), list(max_size),
                             list(ar), list(variance), flip, clip,
                             step, offset,
                             min_max_aspect_ratios_order)
        box_results.append(box)
        var_results.append(var)
        num_boxes = box.shape[2]           # priors per cell

        # explicit per-map param names: repeated bare conv2d calls in
        # one scope would otherwise share a single parameter (and two
        # heads in one network must not share either -> unique default)
        tag = name or _mbh_tag
        loc = conv2d(inp, num_boxes * 4, kernel_size, stride=stride,
                     padding=pad,
                     param_attr=ParamAttr(name=f"{tag}_loc{i}_w"),
                     bias_attr=ParamAttr(name=f"{tag}_loc{i}_b"))
        loc = transpose(loc, perm=[0, 2, 3, 1])
        mbox_locs.append(flatten(loc, axis=1))
        conf = conv2d(inp, num_boxes * num_classes, kernel_size,
                      stride=stride, padding=pad,
                      param_attr=ParamAttr(name=f"{tag}_conf{i}_w"),
                      bias_attr=ParamAttr(name=f"{tag}_conf{i}_b"))
        conf = transpose(conf, perm=[0, 2, 3, 1])
        mbox_confs.append(flatten(conf, axis=1))

    if len(box_results) == 1:
        box, var = box_results[0], var_results[0]
        locs_concat = mbox_locs[0]
        confs_concat = mbox_confs[0]
    else:
        box = concat([flatten(b, axis=3) for b in box_results])
        var = concat([flatten(v, axis=3) for v in var_results])
        locs_concat = concat(mbox_locs, axis=1)
        confs_concat = concat(mbox_confs, axis=1)
    box = reshape(box, shape=[-1, 4])
    var = reshape(var, shape=[-1, 4])
    locs_concat = reshape(locs_concat, shape=[0, -1, 4])
    confs_concat = reshape(confs_concat, shape=[0, -1, num_classes])
    return locs_concat, confs_concat, box, var


# ---------------------------------------------------------------------------
# remaining fluid.layers.nn surface (r3 tail): parameterized 3-D convs,
# hsigmoid, hash, cvm alias, step counter
# ---------------------------------------------------------------------------

def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           use_cudnn=True, name=None):
    """fluid.layers.conv3d parity (conv_op.cc 3-D); NCDHW."""
    c_in = int(input.shape[1])
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = _make_param("conv3d_w", (num_filters, c_in // groups) + tuple(fs),
                    jnp.float32, param_attr, I.MSRA(uniform=False))
    out = _conv_dispatch("conv3d", _ops.conv3d, input, w,
                         dict(stride=stride, padding=padding,
                              dilation=dilation, groups=groups))
    if bias_attr is not False:
        b = _make_param("conv3d_b", (num_filters,), jnp.float32, bias_attr,
                        I.Constant(0.0))
        out = elementwise_add(out, b, axis=1)
    return _apply_act(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     use_cudnn=True, name=None):
    """fluid.layers.conv3d_transpose parity (conv_transpose_op.cc 3-D);
    weight layout IODHW like the reference."""
    c_in = int(input.shape[1])
    if filter_size is None:
        if output_size is None:
            raise EnforceNotMet(
                "conv3d_transpose: one of output_size or filter_size "
                "is required")
        filter_size = _infer_transpose_fs(input, output_size, stride,
                                          padding, dilation, 3)
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = _make_param("conv3dT_w", (c_in, num_filters // groups) + tuple(fs),
                    jnp.float32, param_attr, I.Xavier())
    out = _conv_dispatch("conv3d_transpose", _ops.conv3d_transpose, input, w,
                         dict(stride=stride, padding=padding,
                              dilation=dilation, groups=groups))
    if bias_attr is not False:
        b = _make_param("conv3dT_b", (num_filters,), jnp.float32, bias_attr,
                        I.Constant(0.0))
        out = elementwise_add(out, b, axis=1)
    return _apply_act(out, act)


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    """fluid.layers.hsigmoid parity (hierarchical_sigmoid_op.cc): creates
    the internal-node weight/bias like the reference layer, then runs the
    complete-binary-tree walk in ops.misc.hierarchical_sigmoid. Custom
    trees (path_table/path_code) are not supported on this path."""
    if is_custom or path_table is not None or path_code is not None:
        raise NotImplementedError("hsigmoid: default complete tree only")
    dim = int(input.shape[-1])
    w = _make_param("hsigmoid_w", (num_classes - 1, dim), jnp.float32,
                    param_attr, I.Xavier())
    b = (_make_param("hsigmoid_b", (num_classes - 1,), jnp.float32,
                     bias_attr, I.Constant(0.0))
         if bias_attr is not False else jnp.zeros((num_classes - 1,)))
    lab = reshape(label, shape=[-1])      # op walks flat [B] leaf ids
    out = hierarchical_sigmoid(input, w, b, lab, num_classes)
    return reshape(out, shape=[-1, 1])


def hash(input, hash_size, num_hash=1, name=None):  # noqa: A001 (fluid name)
    """fluid.layers.hash parity over ops.misc.hash_embedding_ids
    (hash_op.cc): num_hash independent hashes of the id sequence modulo
    hash_size."""
    return hash_embedding_ids(input, hash_size, num_hash=num_hash)


def continuous_value_model(input, cvm_input=None, use_cvm=True):
    """fluid.layers.continuous_value_model parity (cvm_op.cc). The
    second argument (the raw show/click columns) is part of the input's
    first two columns in this implementation, matching the op kernel."""
    return cvm(input, use_cvm=use_cvm)      # wrapped op: works both modes


def _increment_inplace_compute(ins, attrs):
    return {"Out": [jnp.asarray(ins["X"][0])
                    + jnp.asarray(attrs.get("value", 1)).astype(
                        jnp.asarray(ins["X"][0]).dtype)]}


OP_REGISTRY["increment_inplace"] = _increment_inplace_compute


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """fluid.layers.autoincreased_step_counter parity (layers/nn.py):
    a persistable int64 counter incremented once per executor run (the
    output var IS the counter var, so the whole-block jit writes it back
    to the scope — the in-place semantics of the reference's increment
    op)."""
    name = counter_name or "@STEP_COUNTER@"
    blk = default_main_program().global_block()
    if blk.has_var(name):
        counter = blk.var(name)
    else:
        # reference init is Constant(begin - 1) then increment-by-step,
        # so the first read is begin - 1 + step (layers/nn.py)
        counter = create_global_var([1], float(begin - 1), dtype="int64",
                                    persistable=True, name=name)
    blk.append_op(type="increment_inplace", inputs={"X": [name]},
                  outputs={"Out": [name]}, attrs={"value": step})
    return counter


# fluid.layers.io surface (reader builders; see layers/io.py)
from paddle_tpu.layers import io as io                       # noqa: E402
from paddle_tpu.layers.io import (                           # noqa: E402
    py_reader, create_py_reader_by_data, read_file, double_buffer,
    batch, shuffle, load, open_files, random_data_generator, Preprocessor,
)
