"""Learning-rate schedules.

Parity: python/paddle/fluid/layers/learning_rate_scheduler.py (noam_decay,
exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup).

A schedule is a callable ``step -> lr`` built from jnp ops, traced into
the compiled train step (the reference materializes a lr Variable updated
by ops; here the schedule is just math on the step counter inside the same
XLA computation).
"""

import math

import jax.numpy as jnp

__all__ = [
    "noam_decay", "exponential_decay", "natural_exp_decay",
    "inverse_time_decay", "polynomial_decay", "piecewise_decay",
    "cosine_decay", "linear_lr_warmup",
]


class Schedule:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, step):
        return self._fn(jnp.asarray(step, jnp.float32))


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    def fn(step):
        step = jnp.maximum(step, 1.0)
        a = step ** -0.5
        b = step * (warmup_steps ** -1.5)
        return learning_rate * (d_model ** -0.5) * jnp.minimum(a, b)
    return Schedule(fn)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    def fn(step):
        e = step / decay_steps
        if staircase:
            e = jnp.floor(e)
        return learning_rate * (decay_rate ** e)
    return Schedule(fn)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    def fn(step):
        e = step / decay_steps
        if staircase:
            e = jnp.floor(e)
        return learning_rate * jnp.exp(-decay_rate * e)
    return Schedule(fn)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    def fn(step):
        e = step / decay_steps
        if staircase:
            e = jnp.floor(e)
        return learning_rate / (1.0 + decay_rate * e)
    return Schedule(fn)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    def fn(step):
        if cycle:
            div = jnp.maximum(jnp.ceil(step / decay_steps), 1.0)
            ds = decay_steps * div
        else:
            ds = decay_steps
            step = jnp.minimum(step, ds)
        return ((learning_rate - end_learning_rate)
                * (1 - step / ds) ** power + end_learning_rate)
    return Schedule(fn)


def piecewise_decay(boundaries, values):
    bs = jnp.asarray(boundaries, jnp.float32)
    vs = jnp.asarray(values, jnp.float32)

    def fn(step):
        idx = jnp.sum((step >= bs).astype(jnp.int32))
        return vs[idx]
    return Schedule(fn)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    def fn(step):
        epoch = jnp.floor(step / step_each_epoch)
        return learning_rate * 0.5 * (jnp.cos(epoch * math.pi / epochs) + 1)
    return Schedule(fn)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    base = learning_rate if not isinstance(learning_rate, Schedule) else None

    def fn(step):
        lr = learning_rate(step) if base is None else base
        warm = start_lr + (end_lr - start_lr) * (step / warmup_steps)
        return jnp.where(step < warmup_steps, warm, lr)
    return Schedule(fn)
