"""fluid.recordio_writer parity.

Parity: python/paddle/fluid/recordio_writer.py
(convert_reader_to_recordio_file, convert_reader_to_recordio_files)
over the native RecordIO writer (native/src/recordio.cc — chunked,
CRC-checked, the reference's paddle/fluid/recordio format role).

Record payload: each sample tuple is serialized as an ``np.savez``
archive with arrays ``f0..fN`` — the exact format
``layers.open_files`` reads back, so convert + open_files round-trips.
"""

import io as _io

import numpy as np

__all__ = ["convert_reader_to_recordio_file",
           "convert_reader_to_recordio_files"]


def _serialize_sample(sample, feeder=None):
    if feeder is not None:
        # reference signature compatibility: the feeder defines field
        # order; we just need positional arrays
        sample = tuple(sample)
    if not isinstance(sample, (tuple, list)):
        sample = (sample,)
    arrays = {}
    for i, v in enumerate(sample):
        arr = np.asarray(v)
        if arr.dtype == object:
            # np.savez would pickle object arrays and layers.open_files
            # (allow_pickle=False) could never read the record back
            raise TypeError(
                f"recordio sample field {i} is object-dtype (ragged/"
                "non-numeric); convert fields to rectangular arrays")
        arrays[f"f{i}"] = arr
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None,
                                    compressor=None,
                                    max_num_records=1000,
                                    feed_order=None):
    """Write every sample the reader yields into one RecordIO file;
    returns the record count (reference behavior)."""
    from paddle_tpu import native
    count = 0
    with native.RecordIOWriter(filename,
                               compress=compressor is not None,
                               max_chunk_records=max_num_records) as w:
        for sample in reader_creator():
            w.write(_serialize_sample(sample, feeder))
            count += 1
    return count


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder=None,
                                     compressor=None,
                                     max_num_records=1000,
                                     feed_order=None):
    """Split the stream into numbered files of batch_per_file records
    each (filename-00000, filename-00001, ...); returns the paths."""
    from paddle_tpu import native
    paths, w, count = [], None, 0
    try:
        for sample in reader_creator():
            if w is None or count % batch_per_file == 0:
                if w is not None:
                    w.close()
                path = f"{filename}-{len(paths):05d}"
                paths.append(path)
                w = native.RecordIOWriter(
                    path, compress=compressor is not None,
                    max_chunk_records=max_num_records)
            w.write(_serialize_sample(sample, feeder))
            count += 1
    finally:
        if w is not None:
            w.close()
    return paths
