"""Automatic mixed precision.

Parity: python/paddle/fluid/contrib/mixed_precision/ (decorator.py:27
OptimizerWithMixedPrecision, fp16_utils.py rewrite, fp16_lists.py
black/white lists, dynamic loss scaling).

TPU-native: bfloat16 is the first-class policy (MXU-native, needs NO loss
scaling — this is where the TPU build beats the reference's fp16
machinery); fp16+dynamic-loss-scaling is kept for compatibility. Instead
of rewriting a program's ops through black/white lists, the policy casts
at the function boundary: params stay fp32 ("master weights",
ref: decorator.py master-weight logic), compute runs in the chosen
half dtype, and the loss scaler wraps the grad computation.
"""

import functools

import jax
import jax.numpy as jnp

__all__ = [
    "Policy", "bfloat16_policy", "float16_policy", "cast_tree",
    "LossScaler", "decorate", "black_list", "white_list",
    "AutoMixedPrecisionLists",
]

# fp16_lists.py parity: ops that must stay fp32 under half policies
black_list = {"softmax_with_cross_entropy", "cross_entropy", "mean",
              "layer_norm", "batch_norm", "reduce_sum", "exp", "log"}
white_list = {"matmul", "mul", "conv2d", "fc"}


class Policy:
    def __init__(self, compute_dtype, param_dtype=jnp.float32,
                 output_dtype=jnp.float32):
        self.compute_dtype = compute_dtype
        self.param_dtype = param_dtype
        self.output_dtype = output_dtype


def bfloat16_policy():
    return Policy(jnp.bfloat16)


def float16_policy():
    return Policy(jnp.float16)


def cast_tree(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


class LossScaler:
    """Dynamic loss scaling (decorator.py incr/decr_every_n semantics).
    State is a small pytree so it lives inside the jitted step."""

    def __init__(self, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n = incr_every_n_steps
        self.decr_every_n = decr_every_n_nan_or_inf
        self.dynamic = use_dynamic_loss_scaling
        self.init_scale = init_loss_scaling

    def init(self):
        return {"scale": jnp.float32(self.init_scale),
                "good": jnp.int32(0), "bad": jnp.int32(0)}

    def scale_loss(self, loss, state):
        return loss * state["scale"]

    def unscale_and_update(self, grads, state):
        """Returns (unscaled_grads, grads_finite, new_state)."""
        inv = 1.0 / state["scale"]
        grads = jax.tree.map(lambda g: g * inv, grads)
        finite = jnp.all(jnp.stack(
            [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]))
        if not self.dynamic:
            return grads, finite, state
        good = jnp.where(finite, state["good"] + 1, 0)
        bad = jnp.where(finite, 0, state["bad"] + 1)
        scale = state["scale"]
        scale = jnp.where(good >= self.incr_every_n,
                          scale * self.incr_ratio, scale)
        good = jnp.where(good >= self.incr_every_n, 0, good)
        scale = jnp.where(bad >= self.decr_every_n,
                          jnp.maximum(scale * self.decr_ratio, 1.0), scale)
        bad = jnp.where(bad >= self.decr_every_n, 0, bad)
        return grads, finite, {"scale": scale, "good": good, "bad": bad}


class OptimizerWithMixedPrecision:
    """decorate() product: wraps an Optimizer for half-precision training.

    Functional protocol mirrors Optimizer: init(params) / apply_gradients.
    grads are expected to be computed from a loss scaled by
    `scaler.scale_loss`; non-finite steps are skipped (params unchanged),
    matching the reference's update-halting
    (mixed_precision/decorator.py)."""

    def __init__(self, optimizer, policy=None, scaler=None):
        self.opt = optimizer
        self.policy = policy or bfloat16_policy()
        needs_scaler = self.policy.compute_dtype == jnp.float16
        self.scaler = scaler or (LossScaler() if needs_scaler else None)

    def init(self, params):
        st = {"opt": self.opt.init(params)}
        if self.scaler:
            st["loss_scale"] = self.scaler.init()
        return st

    def cast_params(self, params):
        return cast_tree(params, self.policy.compute_dtype)

    def scale_loss(self, loss, state):
        if self.scaler:
            return self.scaler.scale_loss(loss, state["loss_scale"])
        return loss

    def apply_gradients(self, params, grads, state):
        grads = cast_tree(grads, jnp.float32)
        if self.scaler:
            grads, finite, ls = self.scaler.unscale_and_update(
                grads, state["loss_scale"])
            new_p, new_o = self.opt.apply_gradients(params, grads,
                                                    state["opt"])
            new_p = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_p, params)
            sel = lambda n, o: jnp.where(finite, n, o)
            new_o = jax.tree.map(sel, new_o, state["opt"])
            return new_p, {"opt": new_o, "loss_scale": ls}
        new_p, new_o = self.opt.apply_gradients(params, grads, state["opt"])
        return new_p, {"opt": new_o}

    def monitor_state(self, state, step=None):
        """Publish the loss-scale state to monitor.tensorwatch: the
        ``loss_scale`` gauge plus a ``loss_scale_decrements_total``
        count for each observed decrement (= a non-finite fp16
        gradient event the scaler absorbed). Call between steps with
        the MATERIALIZED state — the scale is a scalar the caller's
        next dispatch already waits on, so this adds no extra device
        round-trip. Returns the float scale (None without a scaler:
        bf16 needs no scaling, so there is nothing to watch)."""
        if not self.scaler or "loss_scale" not in state:
            return None
        from paddle_tpu.monitor import tensorwatch
        return tensorwatch.record_loss_scale(
            state["loss_scale"]["scale"], step=step)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
             use_dynamic_loss_scaling=True, use_bf16=True):
    """contrib.mixed_precision.decorate parity."""
    policy = bfloat16_policy() if use_bf16 else float16_policy()
    scaler = None
    if not use_bf16:
        scaler = LossScaler(init_loss_scaling,
                            use_dynamic_loss_scaling=use_dynamic_loss_scaling)
    return OptimizerWithMixedPrecision(optimizer, policy, scaler)


class AutoMixedPrecisionLists:
    """contrib.mixed_precision.fp16_lists.AutoMixedPrecisionLists
    parity: merge user-custom white/black lists into the defaults (an op
    custom-listed white is removed from black, and vice versa)."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.gray_list = set()
        if custom_white_list:
            for op in custom_white_list:
                self.black_list.discard(op)
                self.white_list.add(op)
        if custom_black_list:
            for op in custom_black_list:
                if op in (custom_white_list or ()):
                    raise ValueError(
                        f"op {op} in both custom white and black lists")
                self.white_list.discard(op)
                self.black_list.add(op)
