"""Test-support utilities shipped with the package (fault injection for
elastic-supervision tests — see `paddle_tpu.testing.faults`)."""

from paddle_tpu.testing import faults  # noqa: F401
