"""Env-driven fault injection for elastic-supervision tests.

Production code never imports this module: a test's worker script opts
in by calling ``maybe_fault(step)`` inside its training loop (and
``install_slow_write()`` once at startup), and the *test* selects the
fault through the environment — which crosses the launcher's process
boundary for free:

- ``PT_FAULT_CRASH_AT_STEP=N``  — hard-exit (``os._exit``, code 23) when
  the loop reaches step N: a rank crash.
- ``PT_FAULT_HANG_AT_STEP=N``   — stop making progress at step N while
  staying alive (and not heartbeating): a hang, for the watchdog.
- ``PT_FAULT_SLOW_WRITE=S``     — ``install_slow_write()`` patches
  ``CheckpointManager._write`` to sleep S seconds first: an in-flight
  async checkpoint, for preemption tests.
- ``PT_FAULT_NAN_AT_STEP=N``    — ``poison_feed(step, feed)`` writes a
  NaN into the first float array of the feed at step N: a numerics
  blow-up, for the FLAGS_check_nan_inf sentinel/localizer tests.
- ``PT_FAULT_TORN_CKPT=N``      — at step N, truncate the newest
  published checkpoint shard to half its size (a torn write / torn
  replication) and hard-exit with code 29: the restarted rank must
  quarantine it and fall back to the previous verified step.
- ``PT_FAULT_BITFLIP_CKPT=N``   — at step N, flip one byte in the
  middle of the newest shard's last array member (bit rot the zip
  layer can't mask) and hard-exit 29. The checkpoint dir comes from
  ``maybe_fault(step, ckpt_dir=...)`` or ``PT_FAULT_CKPT_DIR``; if no
  shard has been published yet the fault stays armed for a later step
  (the once-marker is only claimed when a shard actually got hit).
- ``PT_FAULT_RANK=R``           — scope injection to PADDLE_TRAINER_ID R
  (default: every rank).
- ``PT_FAULT_ONCE_DIR=dir``     — fire each fault once *per job*, not
  per incarnation: the first firing drops a marker file in ``dir``, and
  a restarted process that sees the marker runs clean. Without it a
  crash-at-step fault would re-kill every restart and the job could
  never finish.

Exit codes 23 (plain crash) and 29 (checkpoint corruption + crash) are
deliberately distinct from each other and from the launcher's own codes
(124 timeout, 143 preemption) and the numerics trip (17) so tests can
assert who died and why.
"""

import os
import sys
import time

__all__ = ["maybe_fault", "poison_feed", "install_slow_write",
           "corrupt_checkpoint", "corrupt_newest_checkpoint",
           "CRASH_EXIT_CODE", "CKPT_FAULT_EXIT_CODE"]

CRASH_EXIT_CODE = 23
CKPT_FAULT_EXIT_CODE = 29


def _int_env(name):
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def _applies_to_rank():
    want = os.environ.get("PT_FAULT_RANK")
    if want in (None, ""):
        return True
    return os.environ.get("PADDLE_TRAINER_ID", "0") == want


def _fire_once(tag):
    """True exactly once per (tag, PT_FAULT_ONCE_DIR) across process
    incarnations; always True when no once-dir is configured."""
    d = os.environ.get("PT_FAULT_ONCE_DIR")
    if not d:
        return True
    os.makedirs(d, exist_ok=True)
    marker = os.path.join(d, f"{tag}.fired")
    try:
        # O_EXCL: two racing ranks can't both claim the firing
        fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    os.write(fd, f"pid={os.getpid()} time={time.time()}\n".encode())
    os.close(fd)
    return True


def corrupt_checkpoint(path, mode):
    """Deterministically damage one shard file. ``torn`` truncates to
    half (a torn write); ``bitflip`` flips one byte in the middle of
    the LAST zip member's data region — guaranteed inside array/npy
    payload, never in ignorable zip metadata, so verification MUST
    trip. Reading the zip layout to aim the flip is fine: this is a
    test tool, not a model of where cosmic rays land."""
    if mode == "torn":
        os.truncate(path, max(os.path.getsize(path) // 2, 1))
        return
    if mode != "bitflip":
        raise ValueError(f"mode must be 'torn' or 'bitflip', got {mode!r}")
    import struct
    import zipfile
    with zipfile.ZipFile(path) as zf:
        info = max(zf.infolist(), key=lambda i: i.header_offset)
    with open(path, "r+b") as f:
        # the LOCAL header's name/extra lengths (offsets 26/28) — the
        # central directory's can differ, and np.savez pads npy
        # members through the local extra field
        f.seek(info.header_offset + 26)
        name_len, extra_len = struct.unpack("<HH", f.read(4))
        target = (info.header_offset + 30 + name_len + extra_len
                  + max(info.compress_size // 2, 0))
        f.seek(target)
        b = f.read(1)
        f.seek(target)
        f.write(bytes([b[0] ^ 0xFF]))


def _newest_shard(ckpt_dir):
    # the writer's own filename grammar, not a re-guessed copy (a
    # format change must break loudly here, not no-op the fault);
    # lazy import: this module stays importable without jax on path
    from paddle_tpu.io_checkpoint import SHARD_NAME_RE
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    best, best_step = None, -1
    for f in names:
        m = SHARD_NAME_RE.match(f)
        if m and int(m.group(1)) > best_step:
            best_step, best = int(m.group(1)), f
    return os.path.join(ckpt_dir, best) if best else None


def _already_fired(tag):
    """Marker peek WITHOUT claiming (unlike _fire_once): restarted
    incarnations must decide to run clean before doing any damage."""
    d = os.environ.get("PT_FAULT_ONCE_DIR")
    if not d:
        return False
    return os.path.exists(os.path.join(d, f"{tag}.fired"))


def corrupt_newest_checkpoint(ckpt_dir, mode):
    """Damage the newest published ``ckpt_<step>.shard*.npz`` under
    ``ckpt_dir``. Returns the path, or None when no shard exists yet
    (nothing to corrupt — the caller's fault stays armed)."""
    path = _newest_shard(ckpt_dir)
    if path is None:
        return None
    try:
        corrupt_checkpoint(path, mode)
    except FileNotFoundError:
        return None         # pruned between listdir and open
    return path


def _maybe_ckpt_fault(step, ckpt_dir):
    for env_name, mode in (("PT_FAULT_TORN_CKPT", "torn"),
                           ("PT_FAULT_BITFLIP_CKPT", "bitflip")):
        at = _int_env(env_name)
        if at is None or step < at:
            continue
        tag = f"{mode}_ckpt"
        if _already_fired(tag):
            continue        # restarted incarnation runs clean
        d = ckpt_dir or os.environ.get("PT_FAULT_CKPT_DIR")
        if not d:
            continue
        # probe BEFORE claiming the once-marker: no shard published yet
        # means the fault stays armed for a later step (>= above) —
        # mirroring poison_feed's claim-on-injection rule
        if _newest_shard(d) is None:
            continue
        if not _fire_once(tag):
            return
        path = corrupt_newest_checkpoint(d, mode)
        if path is None:
            return          # shard vanished under us (prune race)
        sys.stderr.write(f"[faults] {mode}-corrupted {path} at step "
                         f"{step}; exiting {CKPT_FAULT_EXIT_CODE}\n")
        sys.stderr.flush()
        os._exit(CKPT_FAULT_EXIT_CODE)


def maybe_fault(step, ckpt_dir=None):
    """Call from the training-loop body; injects whatever fault the
    environment configures for this rank at this step. ``ckpt_dir``
    (this rank's checkpoint directory) is only needed for the
    checkpoint-corruption faults; PT_FAULT_CKPT_DIR is the env
    fallback."""
    if not _applies_to_rank():
        return
    _maybe_ckpt_fault(step, ckpt_dir)
    crash_at = _int_env("PT_FAULT_CRASH_AT_STEP")
    if crash_at is not None and step == crash_at and _fire_once("crash"):
        sys.stderr.write(f"[faults] injected crash at step {step}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)       # no atexit, no flush: a crash
    hang_at = _int_env("PT_FAULT_HANG_AT_STEP")
    if hang_at is not None and step == hang_at and _fire_once("hang"):
        sys.stderr.write(f"[faults] injected hang at step {step}\n")
        sys.stderr.flush()
        while True:                     # alive but silent: heartbeats
            time.sleep(3600)            # stop, SIGKILL is the only exit


def poison_feed(step, feed):
    """Return ``feed`` with a NaN written into the first float array
    when PT_FAULT_NAN_AT_STEP selects this (rank, step); the original
    dict is never mutated. Call on the feed just before
    ``Executor.run`` — with FLAGS_check_nan_inf on, the sentinel must
    trip within this very step."""
    nan_at = _int_env("PT_FAULT_NAN_AT_STEP")
    if nan_at is None or step != nan_at or not _applies_to_rank():
        return feed
    import numpy as np
    out = dict(feed)
    for name in sorted(out):
        arr = np.asarray(out[name])
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        # claim the once-per-job marker only now that injection will
        # actually happen: a float-less feed at the target step must
        # not silently consume the fault
        if not _fire_once("nan"):
            return feed
        arr = arr.copy()
        arr.flat[0] = np.nan
        out[name] = arr
        sys.stderr.write(f"[faults] injected NaN into feed "
                         f"{name!r} at step {step}\n")
        sys.stderr.flush()
        return out
    return feed


def install_slow_write():
    """If PT_FAULT_SLOW_WRITE is set, patch CheckpointManager._write to
    sleep that many seconds before writing (models a slow disk / large
    shard, keeping an async checkpoint in flight when SIGTERM lands).
    Returns True if the patch was installed."""
    v = os.environ.get("PT_FAULT_SLOW_WRITE")
    if v in (None, ""):
        return False
    secs = float(v)
    from paddle_tpu.io_checkpoint import CheckpointManager
    orig = CheckpointManager._write

    def slow_write(self, payload):
        time.sleep(secs)
        return orig(self, payload)

    CheckpointManager._write = slow_write
    return True
