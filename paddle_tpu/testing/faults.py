"""Env-driven fault injection for elastic-supervision tests.

Production code never imports this module: a test's worker script opts
in by calling ``maybe_fault(step)`` inside its training loop (and
``install_slow_write()`` once at startup), and the *test* selects the
fault through the environment — which crosses the launcher's process
boundary for free:

- ``PT_FAULT_CRASH_AT_STEP=N``  — hard-exit (``os._exit``, code 23) when
  the loop reaches step N: a rank crash.
- ``PT_FAULT_HANG_AT_STEP=N``   — stop making progress at step N while
  staying alive (and not heartbeating): a hang, for the watchdog.
- ``PT_FAULT_SHRINK_AT_STEP=N`` — hard-exit with code 31
  (``SHRINK_EXIT_CODE``, = ``launch.SHRINK_RC``) at step N: the rank
  *permanently departs* (a spot reclaim / node repair saying goodbye).
  An elastic supervisor (``--min_ranks``) must resume the job at the
  reduced world size — the checkpoint re-shards, the data cursor
  rescales — instead of respawning a gang that can never be whole
  again. Scope with ``PT_FAULT_RANK``.
- ``PT_FAULT_SLOW_WRITE=S``     — ``install_slow_write()`` patches
  ``CheckpointManager._write`` to sleep S seconds first: an in-flight
  async checkpoint, for preemption tests.
- ``PT_FAULT_NAN_AT_STEP=N``    — ``poison_feed(step, feed)`` writes a
  NaN into the first float array of the feed at step N: a numerics
  blow-up, for the FLAGS_check_nan_inf sentinel/localizer tests.
- ``PT_FAULT_TORN_CKPT=N``      — at step N, truncate the newest
  *complete* (meta-published) checkpoint's shard to half its size (a
  torn write / torn replication) and hard-exit with code 29: the
  restarted rank must quarantine it and fall back to the previous
  verified step.
- ``PT_FAULT_BITFLIP_CKPT=N``   — at step N, flip one byte in the
  middle of that shard's last array member (bit rot the zip layer
  can't mask) and hard-exit 29. The checkpoint dir comes from
  ``maybe_fault(step, ckpt_dir=...)`` or ``PT_FAULT_CKPT_DIR``.
  Both corruption faults wait (bounded, ``PT_FAULT_CKPT_WAIT``
  seconds, default 30) for the dir to hold TWO complete steps, then
  FREEZE the in-process async writer (``_write`` patched to a no-op,
  plus a bounded grace for one already-in-flight publish to land) and
  corrupt the newest complete step PLUS every newer already-published
  shard, re-probing until stable — the quarantine-and-fall-back path
  they exist to exercise needs a verified predecessor to land on, and
  a healthy newer step published between the probe and ``os._exit``
  would mask the corruption entirely (restore() stops at the first
  verifying step). Corrupting the ONLY complete step (nothing to fall
  back to) would test a different — wrong — path. If the wait times
  out the fault stays armed for a later step (the once-marker is only
  claimed when a shard actually got hit).
- ``PT_FAULT_AWAIT_CKPTS=K``    — before a crash/hang fault fires,
  wait (same bounded wait) until the rank's checkpoint dir holds K
  complete steps, so "restarts resume from a checkpoint" assertions
  never race the async writer; fires anyway after the timeout.
- ``PT_FAULT_REPLICA_STALL=N``  — ``install_serving_faults()`` patches
  the serving ``Replica.run_batch``: the scoped replica's Nth batch
  pickup wedges (sleeps, not heartbeating) until the pool supervisor
  abandons the thread — the wedged-dispatch path: riders must get
  typed errors and the replica must quarantine + respawn.
- ``PT_FAULT_REPLICA_DIE=N``    — same hook; the Nth pickup raises
  ``SystemExit`` so the replica THREAD dies by uncaught exception
  (the exact path that used to leave ``serving_replicas`` lying).
- ``PT_FAULT_DISPATCH_ERROR=N`` — same hook; the Nth pickup raises a
  RuntimeError the replica loop catches: the batch's riders get the
  error, the replica keeps serving.
  All three are scoped by ``PT_FAULT_REPLICA=R`` (replica index;
  default: every replica) on top of ``PT_FAULT_RANK``, count pickups
  PER REPLICA (batch N is deterministic per worker), and share the
  once-marker semantics below. ``PT_FAULT_STALL_SECS`` bounds the
  stall (default 3600 — effectively until abandoned or process exit).
- ``PT_FAULT_SWAP_BITFLIP=1``   — ``install_swap_faults()`` patches
  the hot-swap ``SwapController._gate``: flip one byte in the middle
  of the target model dir's first AOT artifact BEFORE the gate's
  integrity pass runs — the gate must refuse (``SwapFailedError``
  stage ``gate``, outcome ``gate_failed``) and the LIVE version must
  keep serving. Requires an ``export_aot``'d target dir (no artifacts
  = nothing for the gate to catch).
- ``PT_FAULT_SWAP_STANDBY_STALL=1`` — same install; the standby
  warm-boot wedges (sleeps up to ``PT_FAULT_STALL_SECS``, then raises
  so the abandoned thread unwinds) — the swap must quarantine
  (``SwapFailedError`` stage ``standby``, outcome ``rolled_back``)
  while live traffic never notices.
- ``PT_FAULT_SWAP_ERROR_STORM=N`` — same install; AFTER a real
  cutover commits, the NEW pool's next N batch dispatches raise — the
  post-cutover watchdog must trip and roll traffic back to the old,
  still-resident version (stage ``watchdog``, outcome
  ``rolled_back``). The storm never touches the old pool, so
  post-rollback serving is provably unaffected.
  All three swap faults fire once per process (plus the once-dir
  marker across incarnations) and are scoped by ``PT_FAULT_RANK``.
- ``PT_FAULT_PS_CRASH_AT_STEP=N`` — ``install_ps_faults(server)``
  (called by a pserver worker script, e.g. via ``run_pserver``'s
  ``on_server=`` hook): a watcher thread polls the server's applied
  optimizer rounds and hard-exits with code 37
  (``PS_CRASH_EXIT_CODE``) once they reach N — a pserver crash
  mid-training. The supervisor (``launch_ps --ps_snapshot_secs``)
  must respawn it at the same endpoint, the respawn must warm-boot
  from the last-good snapshot, and the trainers' clients must
  reconnect. Scoped by ``PT_FAULT_RANK`` (= the pserver index — the
  launcher numbers pservers through PADDLE_TRAINER_ID too).
- ``PT_FAULT_PS_AWAIT_SNAPS=K`` — before the pserver crash fires,
  wait (bounded, ``PT_FAULT_CKPT_WAIT``) until the snapshot dir holds
  K complete generations, so "the respawn restored state" assertions
  never race the background snapshot thread.
- ``PT_FAULT_PS_BITFLIP_SNAP=1`` — at pserver-crash time, STOP the
  in-process snapshot thread (a generation it publishes between the
  flip and ``os._exit`` would mask the corruption — the PR-5 writer-
  freeze lesson) and flip one byte in the newest complete
  generation's dense artifact before exiting: the respawned server
  must quarantine it and walk back to the previous generation.
  Implies awaiting 2 complete generations (a walk-back needs a
  predecessor).
- ``PT_FAULT_PS_DROP_EVERY=N`` / ``PT_FAULT_PS_DELAY_EVERY=K`` (+
  ``PT_FAULT_PS_DELAY_MS=M``) — ``install_ps_wire_faults()``: wire-
  level reply chaos on the PYTHON transport's reply hook
  (``ps._reply_frame``, mirroring ``install_serving_faults``'s patch
  idiom). Drop closes the connection with every Nth reply UNSENT —
  the mutation is already applied and cached, so the client's retry
  must be answered from the (client_id, seq) dedup cache, never
  re-applied; delay holds every Kth reply M ms, past a short client
  timeout. Continuous chaos (not fire-once): the exactly-once
  contract must hold under sustained adversity.
- ``PT_FAULT_PS_MIGRATE_CRASH=stage`` — ``install_ps_migrate_faults()``
  patches the pserver's migration fault hook
  (``ps._migrate_fault_point``): hard-exit (code 37) when THIS server
  reaches that migration stage — ``plan`` (source, freeze time),
  ``chunk`` (source, mid-stream), ``staged`` (target, shadow just
  published), or ``commit`` (any server, MIGRATE_COMMIT arrival —
  i.e. AFTER the coordinator's atomic epoch publish, exercising the
  warm-boot reconcile instead of the abort path). Scoped by
  ``PT_FAULT_RANK`` (= the pserver index) + the once-marker.
- ``PT_FAULT_PS_MIGRATE_TORN=1`` — same install; at the ``staged``
  stage, truncate the shadow file the target just published (a torn
  stage the coordinator's pre-commit ``verify_npz`` gate must catch,
  aborting + rolling back the migration) and keep serving.
- ``PT_FAULT_HTTP_SLOWLORIS_EVERY=N`` / ``PT_FAULT_HTTP_DISCONNECT_EVERY=N``
  / ``PT_FAULT_HTTP_HEADER_BOMB_EVERY=N`` (+
  ``PT_FAULT_HTTP_BOMB_HEADERS=K``, default 200) —
  ``install_http_faults()``: wire-level chaos against the serving
  front door, patched into the CLIENT (``frontdoor.WireClient._send``)
  so the server under test runs unpatched production code. Slow-loris
  stalls every Nth request after half its body (the server's socket
  timeout must answer a typed 408); disconnect hangs up after sending
  (the server must detect it and release the rider); header-bomb
  injects K junk headers (stdlib parsing refuses >100 → typed 431).
  Continuous chaos like the PS wire faults: the zero-hangs invariant
  must hold under sustained adversity.
- ``PT_FAULT_RANK=R``           — scope injection to PADDLE_TRAINER_ID R
  (default: every rank).
- ``PT_FAULT_ONCE_DIR=dir``     — fire each fault once *per job*, not
  per incarnation: the first firing drops a marker file in ``dir``, and
  a restarted process that sees the marker runs clean. Without it a
  crash-at-step fault would re-kill every restart and the job could
  never finish.

Exit codes 23 (plain crash), 29 (checkpoint corruption + crash), 31
(elastic shrink — a rank departing for good) and 37 (pserver crash —
the supervisor respawns it at the same endpoint) are deliberately
distinct from each other and from the launcher's own codes (124
timeout, 143 preemption) and the numerics trip (17) so tests can
assert who died and why — and so the supervisor can tell "restart me"
from "carry on without me" from "respawn my endpoint".
"""

import os
import sys
import time

__all__ = ["maybe_fault", "poison_feed", "install_slow_write",
           "install_serving_faults", "install_swap_faults",
           "install_http_faults",
           "install_ps_faults", "install_ps_wire_faults",
           "install_ps_migrate_faults",
           "corrupt_checkpoint", "corrupt_newest_checkpoint",
           "CRASH_EXIT_CODE", "CKPT_FAULT_EXIT_CODE",
           "SHRINK_EXIT_CODE", "PS_CRASH_EXIT_CODE"]

CRASH_EXIT_CODE = 23
CKPT_FAULT_EXIT_CODE = 29
#: must equal distributed.launch.SHRINK_RC (not imported: this module
#: stays importable without the launcher, and the pair is pinned by a
#: tier-1 test instead)
SHRINK_EXIT_CODE = 31
#: pserver crash (install_ps_faults): distinct so the supervisor log
#: names the cause and tests can assert WHICH process died; labeled in
#: launch.EXIT_CODE_LABELS (pinned by a tier-1 test like SHRINK)
PS_CRASH_EXIT_CODE = 37


def _int_env(name):
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def _applies_to_rank():
    want = os.environ.get("PT_FAULT_RANK")
    if want in (None, ""):
        return True
    return os.environ.get("PADDLE_TRAINER_ID", "0") == want


def _fire_once(tag):
    """True exactly once per (tag, PT_FAULT_ONCE_DIR) across process
    incarnations; always True when no once-dir is configured."""
    d = os.environ.get("PT_FAULT_ONCE_DIR")
    if not d:
        return True
    os.makedirs(d, exist_ok=True)
    marker = os.path.join(d, f"{tag}.fired")
    try:
        # O_EXCL: two racing ranks can't both claim the firing
        fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    os.write(fd, f"pid={os.getpid()} time={time.time()}\n".encode())
    os.close(fd)
    return True


def corrupt_checkpoint(path, mode):
    """Deterministically damage one shard file. ``torn`` truncates to
    half (a torn write); ``bitflip`` flips one byte in the middle of
    the LAST zip member's data region — guaranteed inside array/npy
    payload, never in ignorable zip metadata, so verification MUST
    trip. Reading the zip layout to aim the flip is fine: this is a
    test tool, not a model of where cosmic rays land."""
    if mode == "torn":
        os.truncate(path, max(os.path.getsize(path) // 2, 1))
        return
    if mode != "bitflip":
        raise ValueError(f"mode must be 'torn' or 'bitflip', got {mode!r}")
    import struct
    import zipfile
    with zipfile.ZipFile(path) as zf:
        info = max(zf.infolist(), key=lambda i: i.header_offset)
    with open(path, "r+b") as f:
        # the LOCAL header's name/extra lengths (offsets 26/28) — the
        # central directory's can differ, and np.savez pads npy
        # members through the local extra field
        f.seek(info.header_offset + 26)
        name_len, extra_len = struct.unpack("<HH", f.read(4))
        target = (info.header_offset + 30 + name_len + extra_len
                  + max(info.compress_size // 2, 0))
        f.seek(target)
        b = f.read(1)
        f.seek(target)
        f.write(bytes([b[0] ^ 0xFF]))


def _newest_shard(ckpt_dir):
    # the writer's own filename grammar, not a re-guessed copy (a
    # format change must break loudly here, not no-op the fault);
    # lazy import: this module stays importable without jax on path
    from paddle_tpu.io_checkpoint import SHARD_NAME_RE
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    best, best_step = None, -1
    for f in names:
        m = SHARD_NAME_RE.match(f)
        if m and int(m.group(1)) > best_step:
            best_step, best = int(m.group(1)), f
    return os.path.join(ckpt_dir, best) if best else None


def _already_fired(tag):
    """Marker peek WITHOUT claiming (unlike _fire_once): restarted
    incarnations must decide to run clean before doing any damage."""
    d = os.environ.get("PT_FAULT_ONCE_DIR")
    if not d:
        return False
    return os.path.exists(os.path.join(d, f"{tag}.fired"))


def _complete_ckpt_steps(ckpt_dir):
    """Steps with a parseable meta AND every shard it promises on
    disk — the steps restore() will actually consider. Mirrors
    CheckpointManager._complete_steps through the shared filename
    grammar."""
    import json

    from paddle_tpu.io_checkpoint import META_NAME_RE, SHARD_NAME_RE
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    shards = {}
    for f in names:
        m = SHARD_NAME_RE.match(f)
        if m:
            shards.setdefault(int(m.group(1)), set()).add(
                int(m.group(2)))
    steps = []
    for f in names:
        m = META_NAME_RE.match(f)
        if not m:
            continue
        step = int(m.group(1))
        try:
            with open(os.path.join(ckpt_dir, f)) as fh:
                nproc = int(json.load(fh).get("nproc", 1))
        except (OSError, ValueError, TypeError):
            continue
        if shards.get(step, set()) >= set(range(nproc)):
            steps.append(step)
    return sorted(steps)


def _corrupt_newest_and_newer(ckpt_dir, mode):
    """Corrupt the newest COMPLETE step's shard plus every
    already-published shard of any NEWER step, re-probing until a pass
    finds nothing new. The async writer lives in this same process and
    keeps draining its queue while the fault runs: corrupting only the
    step probed a moment ago would let the writer publish a healthy
    newer step before ``os._exit``, and restore() (newest-first, stops
    at the first verifying step) would then succeed without
    quarantining anything — the exact path the fault exists to deny.
    A meta-less newer shard is corrupted too: if its meta lands before
    the exit the step becomes complete-but-corrupt (quarantined, same
    outcome); if not, it stays invisible to restore. Returns the list
    of corrupted paths (empty if nothing could be hit)."""
    from paddle_tpu.io_checkpoint import SHARD_NAME_RE
    hit, tried = [], set()
    while True:
        steps = _complete_ckpt_steps(ckpt_dir)
        if not steps:
            return hit
        target = steps[-1]
        try:
            names = os.listdir(ckpt_dir)
        except OSError:
            return hit
        fresh = []
        for f in sorted(names):
            m = SHARD_NAME_RE.match(f)
            path = os.path.join(ckpt_dir, f)
            if m and int(m.group(1)) >= target and path not in tried:
                fresh.append(path)
        if not fresh:
            return hit
        for path in fresh:
            # a path is "tried" whether or not the damage landed —
            # re-selecting one that raises persistently (EACCES, a
            # sick mount) would spin this loop forever
            tried.add(path)
            try:
                corrupt_checkpoint(path, mode)
            except OSError:
                continue    # pruned/unwritable between listdir and open
            hit.append(path)


def _touch_heartbeat():
    """Keep the launcher's hang watchdog quiet while a fault WAITS on
    the async writer — the wait is harness machinery, not a hang."""
    try:
        from paddle_tpu.distributed.health import Heartbeat
        hb = Heartbeat.from_env()
        if hb is not None:
            hb.beat(force=True)
    except Exception:
        pass


def _await_complete_steps(ckpt_dir, k):
    """Poll until ``ckpt_dir`` holds >= k complete checkpoint steps or
    PT_FAULT_CKPT_WAIT seconds (default 30) elapse; returns the step
    list either way. A fault that fires before anything is durable
    tests start-from-scratch, not the resume/fallback path the test
    meant to exercise."""
    timeout = float(os.environ.get("PT_FAULT_CKPT_WAIT") or 30.0)
    deadline = time.monotonic() + timeout
    while True:
        steps = _complete_ckpt_steps(ckpt_dir)
        if len(steps) >= k or time.monotonic() >= deadline:
            return steps
        _touch_heartbeat()
        time.sleep(0.05)


def corrupt_newest_checkpoint(ckpt_dir, mode):
    """Damage the newest published ``ckpt_<step>.shard*.npz`` under
    ``ckpt_dir``. Returns the path, or None when no shard exists yet
    (nothing to corrupt — the caller's fault stays armed)."""
    path = _newest_shard(ckpt_dir)
    if path is None:
        return None
    try:
        corrupt_checkpoint(path, mode)
    except FileNotFoundError:
        return None         # pruned between listdir and open
    return path


#: checkpoint-fault tags whose bounded _await_complete_steps already
#: timed out once this process: later maybe_fault calls probe cheaply
#: instead of re-paying the full PT_FAULT_CKPT_WAIT every step (a dir
#: that can never hold two complete steps — keep_max=1 — would
#: otherwise stall the loop ~30s/step with no error until the
#: harness's own timeout)
_ckpt_wait_spent = set()


def _maybe_ckpt_fault(step, ckpt_dir):
    for env_name, mode in (("PT_FAULT_TORN_CKPT", "torn"),
                           ("PT_FAULT_BITFLIP_CKPT", "bitflip")):
        at = _int_env(env_name)
        if at is None or step < at:
            continue
        tag = f"{mode}_ckpt"
        if _already_fired(tag):
            continue        # restarted incarnation runs clean
        d = ckpt_dir or os.environ.get("PT_FAULT_CKPT_DIR")
        if not d:
            continue
        # wait (bounded, ONCE) for a FALLBACK, then corrupt the newest
        # complete step — restore() must quarantine it and land on the
        # verified predecessor. Probe BEFORE claiming the once-marker:
        # fewer than two complete steps means the fault stays armed
        # for a later step (>= above) — mirroring poison_feed's
        # claim-on-injection rule
        if tag in _ckpt_wait_spent:
            steps = _complete_ckpt_steps(d)
        else:
            steps = _await_complete_steps(d, 2)
            if len(steps) < 2:
                _ckpt_wait_spent.add(tag)
        if len(steps) < 2:
            continue
        if not _fire_once(tag):
            return
        # FREEZE the async writer before corrupting: it shares this
        # process, and a step it publishes between the sweep's final
        # probe and os._exit would hand restore() a healthy newer
        # step, masking the corruption entirely. Any _write starting
        # after this point is a no-op; the bounded grace lets one
        # already past the patch point finish publishing so the sweep
        # below sees (and corrupts) its step. os._exit never returns
        # in production — the restore after it only runs under tests
        # that stub _exit, and un-breaks their later checkpoints.
        from paddle_tpu.io_checkpoint import CheckpointManager
        orig_write = CheckpointManager._write
        CheckpointManager._write = lambda self, payload: None
        grace = min(1.0, float(os.environ.get("PT_FAULT_CKPT_WAIT")
                               or 30.0))
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            _touch_heartbeat()
            time.sleep(0.05)
        hit = _corrupt_newest_and_newer(d, mode)
        if not hit:         # shards vanished under us (prune race)
            CheckpointManager._write = orig_write
            return
        sys.stderr.write(f"[faults] {mode}-corrupted "
                         f"{', '.join(hit)} at step {step}; exiting "
                         f"{CKPT_FAULT_EXIT_CODE}\n")
        sys.stderr.flush()
        os._exit(CKPT_FAULT_EXIT_CODE)
        CheckpointManager._write = orig_write


def maybe_fault(step, ckpt_dir=None):
    """Call from the training-loop body; injects whatever fault the
    environment configures for this rank at this step. ``ckpt_dir``
    (this rank's checkpoint directory) is only needed for the
    checkpoint-corruption faults; PT_FAULT_CKPT_DIR is the env
    fallback."""
    if not _applies_to_rank():
        return
    _maybe_ckpt_fault(step, ckpt_dir)

    def gate(tag):
        # peek (no claim) first so restarted incarnations never wait;
        # then optionally await K durable checkpoints (the test is
        # about to assert "the restart resumed from one"), then claim
        if _already_fired(tag):
            return False
        k = _int_env("PT_FAULT_AWAIT_CKPTS")
        d = ckpt_dir or os.environ.get("PT_FAULT_CKPT_DIR")
        if k and d:
            _await_complete_steps(d, k)     # fire anyway on timeout
        return _fire_once(tag)

    crash_at = _int_env("PT_FAULT_CRASH_AT_STEP")
    if crash_at is not None and step == crash_at and gate("crash"):
        sys.stderr.write(f"[faults] injected crash at step {step}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)       # no atexit, no flush: a crash
    shrink_at = _int_env("PT_FAULT_SHRINK_AT_STEP")
    if shrink_at is not None and step == shrink_at and gate("shrink"):
        sys.stderr.write(f"[faults] injected elastic shrink (rank "
                         f"departs for good) at step {step}\n")
        sys.stderr.flush()
        os._exit(SHRINK_EXIT_CODE)
    hang_at = _int_env("PT_FAULT_HANG_AT_STEP")
    if hang_at is not None and step == hang_at and gate("hang"):
        sys.stderr.write(f"[faults] injected hang at step {step}\n")
        sys.stderr.flush()
        while True:                     # alive but silent: heartbeats
            time.sleep(3600)            # stop, SIGKILL is the only exit


def poison_feed(step, feed):
    """Return ``feed`` with a NaN written into the first float array
    when PT_FAULT_NAN_AT_STEP selects this (rank, step); the original
    dict is never mutated. Call on the feed just before
    ``Executor.run`` — with FLAGS_check_nan_inf on, the sentinel must
    trip within this very step."""
    nan_at = _int_env("PT_FAULT_NAN_AT_STEP")
    if nan_at is None or step != nan_at or not _applies_to_rank():
        return feed
    import numpy as np
    out = dict(feed)
    for name in sorted(out):
        arr = np.asarray(out[name])
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        # claim the once-per-job marker only now that injection will
        # actually happen: a float-less feed at the target step must
        # not silently consume the fault
        if not _fire_once("nan"):
            return feed
        arr = arr.copy()
        arr.flat[0] = np.nan
        out[name] = arr
        sys.stderr.write(f"[faults] injected NaN into feed "
                         f"{name!r} at step {step}\n")
        sys.stderr.flush()
        return out
    return feed


_SERVING_FAULT_ENVS = ("PT_FAULT_REPLICA_STALL",
                       "PT_FAULT_REPLICA_DIE",
                       "PT_FAULT_DISPATCH_ERROR")

#: serving-fault tags already fired IN THIS PROCESS: a respawned
#: replica restarts its pickup counter at 0, so without a process-
#: local claim a stall-at-batch-N fault would wedge every respawn in
#: turn and the pool could never heal — the exact recovery the fault
#: exists to prove. PT_FAULT_ONCE_DIR still scopes the firing across
#: process incarnations on top of this.
_serving_fired = set()


def _serving_fire_once(tag):
    if tag in _serving_fired:
        return False
    if not _fire_once(tag):
        _serving_fired.add(tag)
        return False
    _serving_fired.add(tag)
    return True


def _applies_to_replica(replica):
    want = os.environ.get("PT_FAULT_REPLICA")
    if want in (None, ""):
        return True
    return str(replica.index) == want


def _maybe_serving_fault(replica):
    """Fire-once serving chaos, scoped (rank, replica), counted in
    per-replica batch PICKUPS — deterministic "batch N of replica R"
    semantics regardless of how the shared queue interleaves."""
    if not _applies_to_rank() or not _applies_to_replica(replica):
        return
    n = replica._fault_batch_n = getattr(replica, "_fault_batch_n",
                                         0) + 1
    stall_at = _int_env("PT_FAULT_REPLICA_STALL")
    if stall_at is not None and n == stall_at and \
            _serving_fire_once("replica_stall"):
        sys.stderr.write(f"[faults] injected replica stall: replica "
                         f"{replica.index} wedges at its batch {n}\n")
        sys.stderr.flush()
        limit = float(os.environ.get("PT_FAULT_STALL_SECS") or 3600.0)
        deadline = time.monotonic() + limit
        # wedge WITHOUT heartbeating until the supervisor abandons
        # this thread (quarantine observed) or the bound expires —
        # then raise so the thread unwinds instead of lingering
        while not getattr(replica, "_abandoned", False) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        raise RuntimeError(
            f"[faults] injected stall on replica {replica.index} "
            f"released (abandoned="
            f"{getattr(replica, '_abandoned', False)})")
    die_at = _int_env("PT_FAULT_REPLICA_DIE")
    if die_at is not None and n == die_at and _serving_fire_once("replica_die"):
        sys.stderr.write(f"[faults] injected replica thread death: "
                         f"replica {replica.index} at its batch {n}\n")
        sys.stderr.flush()
        # SystemExit escapes the replica loop's `except Exception` and
        # kills ONLY this thread, silently — the uncaught-exception
        # death the supervisor must detect
        raise SystemExit(CRASH_EXIT_CODE)
    err_at = _int_env("PT_FAULT_DISPATCH_ERROR")
    if err_at is not None and n == err_at and \
            _serving_fire_once("dispatch_error"):
        sys.stderr.write(f"[faults] injected dispatch error: replica "
                         f"{replica.index} at its batch {n}\n")
        sys.stderr.flush()
        raise RuntimeError(
            f"[faults] injected dispatch error on replica "
            f"{replica.index} at batch {n}")


def install_serving_faults():
    """If any serving chaos env (PT_FAULT_REPLICA_STALL /
    PT_FAULT_REPLICA_DIE / PT_FAULT_DISPATCH_ERROR) is set, patch the
    serving ``Replica.run_batch`` to consult the fault gate before
    executing. Production never imports this module — a chaos test or
    ``bench.py serving`` (BENCH_SERVING_CHAOS=1) opts in explicitly,
    mirroring ``install_slow_write``. Returns an uninstall callable
    when installed, False otherwise."""
    if not any(os.environ.get(k) for k in _SERVING_FAULT_ENVS):
        return False
    from paddle_tpu.serving.replica import Replica
    orig = Replica.run_batch

    def chaos_run_batch(self, bucket, feeds):
        _maybe_serving_fault(self)
        return orig(self, bucket, feeds)

    Replica.run_batch = chaos_run_batch

    def uninstall():
        Replica.run_batch = orig

    return uninstall


_HTTP_FAULT_ENVS = ("PT_FAULT_HTTP_SLOWLORIS_EVERY",
                    "PT_FAULT_HTTP_DISCONNECT_EVERY",
                    "PT_FAULT_HTTP_HEADER_BOMB_EVERY")


def install_http_faults():
    """If any front-door wire chaos env (PT_FAULT_HTTP_SLOWLORIS_EVERY
    / PT_FAULT_HTTP_DISCONNECT_EVERY / PT_FAULT_HTTP_HEADER_BOMB_EVERY
    = N) is set, patch the serving ``WireClient._send`` — the
    client-side wire seam — to misbehave on every Nth request.
    CONTINUOUS chaos like the PS wire faults (not fire-once): the
    front door's "every request resolves typed, zero hangs" invariant
    must hold under sustained adversity, and the faults are
    client-side because the server code under test must be the
    UNPATCHED production path. Three behaviors:

    - **slow-loris**: send the head + first half of the body, then go
      silent. The server's per-connection socket timeout must fire
      and answer a typed 408 (read back by the normal client path).
    - **disconnect**: send the full request, then close the socket
      before reading the response — the injected
      disconnect-mid-response. Raises ``WireReset`` so the CLIENT side
      resolves typed too; the server must detect the hangup and
      release the rider (outcome="disconnect").
    - **header-bomb**: inject PT_FAULT_HTTP_BOMB_HEADERS (default
      200) junk headers before the blank line. stdlib parsing refuses
      >100 headers, so the server answers 431 — counted, typed,
      connection closed.

    Returns an uninstall callable when installed, False otherwise."""
    if not any(os.environ.get(k) for k in _HTTP_FAULT_ENVS) or \
            not _applies_to_rank():
        return False
    import threading

    from paddle_tpu.serving.frontdoor import WireClient, WireReset

    loris_every = _int_env("PT_FAULT_HTTP_SLOWLORIS_EVERY")
    drop_every = _int_env("PT_FAULT_HTTP_DISCONNECT_EVERY")
    bomb_every = _int_env("PT_FAULT_HTTP_HEADER_BOMB_EVERY")
    bomb_n = _int_env("PT_FAULT_HTTP_BOMB_HEADERS") or 200
    orig = WireClient._send
    lock = threading.Lock()
    state = {"n": 0}

    def _nth():
        with lock:
            state["n"] += 1
            return state["n"]

    def chaos_send(self, head, body):
        n = _nth()
        if loris_every and n % loris_every == 0:
            sys.stderr.write(f"[faults] injected slow-loris: request "
                             f"{n} stalls after half its body\n")
            sys.stderr.flush()
            self._sock.sendall(head + body[:len(body) // 2])
            return      # silence: the server's socket timeout must fire
        if bomb_every and n % bomb_every == 0:
            sys.stderr.write(f"[faults] injected header bomb: request "
                             f"{n} carries {bomb_n} junk headers\n")
            sys.stderr.flush()
            junk = "".join(f"X-Bomb-{k}: {'b' * 100}\r\n"
                           for k in range(bomb_n)).encode("utf-8")
            self._sock.sendall(head[:-2] + junk + b"\r\n" + body)
            return
        orig(self, head, body)
        if drop_every and n % drop_every == 0:
            sys.stderr.write(f"[faults] injected client disconnect: "
                             f"request {n} hangs up after sending\n")
            sys.stderr.flush()
            self._drop()
            raise WireReset(f"[faults] injected client disconnect "
                            f"after request {n} was sent")

    WireClient._send = chaos_send

    def uninstall():
        WireClient._send = orig

    return uninstall


_SWAP_FAULT_ENVS = ("PT_FAULT_SWAP_BITFLIP",
                    "PT_FAULT_SWAP_STANDBY_STALL",
                    "PT_FAULT_SWAP_ERROR_STORM")


def _bitflip_file(path):
    """Flip one byte in the middle of an opaque artifact file — the
    AOT analog of the checkpoint bitflip (no zip layout to aim at:
    CRC32 over the whole byte image catches any flip)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([(b[0] if b else 0) ^ 0xFF]))


def _bitflip_first_aot_artifact(model_dir):
    """Corrupt the first artifact the AOT index's integrity manifest
    vouches for; returns its path or None when the dir has no
    manifest (nothing a gate could catch — the fault stays armed)."""
    import json
    from paddle_tpu.inference import AOT_DIR, AOT_INDEX
    index_path = os.path.join(model_dir, AOT_DIR, AOT_INDEX)
    try:
        with open(index_path) as f:
            entries = json.load(f)
    except (OSError, ValueError):
        return None
    for e in entries if isinstance(entries, list) else []:
        if not isinstance(e, dict):
            continue
        for name in sorted(e.get("integrity", {})):
            path = os.path.join(model_dir, AOT_DIR, name)
            try:
                _bitflip_file(path)
            except OSError:
                continue
            return path
    return None


def install_swap_faults():
    """If any hot-swap chaos env (PT_FAULT_SWAP_BITFLIP /
    PT_FAULT_SWAP_STANDBY_STALL / PT_FAULT_SWAP_ERROR_STORM) is set,
    patch the serving ``SwapController`` stage methods to consult the
    fault gates. Production never imports this module — a chaos test
    or worker opts in explicitly, mirroring
    ``install_serving_faults``. Returns an uninstall callable when
    installed, False otherwise. Each fault proves the same invariant
    from a different stage: THE LIVE VERSION KEEPS SERVING."""
    if not any(os.environ.get(k) for k in _SWAP_FAULT_ENVS):
        return False
    from paddle_tpu.serving.swap import SwapController
    orig_gate = SwapController._gate
    orig_build = SwapController._build_standby_pool
    orig_cutover = SwapController._cutover

    def chaos_gate(self, model_dir):
        if os.environ.get("PT_FAULT_SWAP_BITFLIP") and \
                _applies_to_rank() \
                and "swap_bitflip" not in _serving_fired \
                and not _already_fired("swap_bitflip"):
            # peek BEFORE flipping (a later swap to a fresh export
            # must run clean), claim only on an actual hit (the
            # poison_feed rule: a manifest-less dir must not silently
            # consume the fault)
            hit = _bitflip_first_aot_artifact(model_dir)
            if hit is not None and _serving_fire_once("swap_bitflip"):
                sys.stderr.write(f"[faults] bitflipped swap artifact "
                                 f"{hit} before the gate\n")
                sys.stderr.flush()
        return orig_gate(self, model_dir)

    def chaos_build(self, bundle):
        if os.environ.get("PT_FAULT_SWAP_STANDBY_STALL") and \
                _applies_to_rank() and \
                _serving_fire_once("swap_standby_stall"):
            limit = float(os.environ.get("PT_FAULT_STALL_SECS")
                          or 3600.0)
            sys.stderr.write(f"[faults] injected standby stall: swap "
                             f"warm boot wedges (<= {limit:g}s)\n")
            sys.stderr.flush()
            time.sleep(limit)
            raise RuntimeError(
                "[faults] injected standby stall released")
        return orig_build(self, bundle)

    def chaos_cutover(self, standby, bundle):
        out = orig_cutover(self, standby, bundle)
        n = _int_env("PT_FAULT_SWAP_ERROR_STORM")
        if n and _applies_to_rank() and \
                _serving_fire_once("swap_error_storm"):
            sys.stderr.write(f"[faults] injected post-cutover error "
                             f"storm: the new pool's next {n} batch "
                             f"dispatch(es) raise\n")
            sys.stderr.flush()
            left = [n]          # shared across the pool's replicas

            def storm(orig_run):
                def run_batch(bucket, feeds):
                    if left[0] > 0:
                        left[0] -= 1
                        raise RuntimeError(
                            "[faults] injected post-cutover dispatch "
                            "error (swap error storm)")
                    return orig_run(bucket, feeds)
                return run_batch

            # instance-level wrap: ONLY the freshly promoted pool's
            # replicas storm — the old pool must stay provably healthy
            # for the post-rollback traffic
            for r in standby.replicas:
                r.run_batch = storm(r.run_batch)
        return out

    SwapController._gate = chaos_gate
    SwapController._build_standby_pool = chaos_build
    SwapController._cutover = chaos_cutover

    def uninstall():
        SwapController._gate = orig_gate
        SwapController._build_standby_pool = orig_build
        SwapController._cutover = orig_cutover

    return uninstall


def _await_ps_snapshots(server, snap_dir, k):
    """Poll until the server's snapshot dir holds >= k complete
    generations or PT_FAULT_CKPT_WAIT (default 30 s) elapses — a
    pserver crash that fires before anything durable exists tests
    start-from-scratch, not the warm-boot path the test meant to
    exercise."""
    from paddle_tpu.distributed.ps import _ps_complete_gens, _ps_tag
    tag = _ps_tag(server.host, server.port)
    timeout = float(os.environ.get("PT_FAULT_CKPT_WAIT") or 30.0)
    deadline = time.monotonic() + timeout
    while True:
        gens = _ps_complete_gens(snap_dir, tag)
        if len(gens) >= k or time.monotonic() >= deadline:
            return gens
        time.sleep(0.05)


def _bitflip_newest_ps_snapshot(snap_dir, host, port):
    """Flip one byte mid-file in the newest COMPLETE generation's
    dense artifact; returns its path or None when no complete
    generation exists. The caller must have stopped the snapshot
    thread first (a healthy generation published after the flip would
    mask the corruption — restore stops at the first verifying one)."""
    from paddle_tpu.distributed.ps import (_ps_complete_gens,
                                           _ps_dense_path, _ps_tag)
    tag = _ps_tag(host, port)
    gens = _ps_complete_gens(snap_dir, tag)
    if not gens:
        return None
    path = _ps_dense_path(snap_dir, tag, gens[-1][0])
    try:
        corrupt_checkpoint(path, "bitflip")
    except OSError:
        return None
    return path


def install_ps_faults(server):
    """If PT_FAULT_PS_CRASH_AT_STEP selects this pserver, start a
    watcher thread that polls the server's applied optimizer rounds
    (transport-agnostic: both the Python and the C++ server expose
    per-var rounds through ``server.dense``) and hard-exits with
    PS_CRASH_EXIT_CODE once they reach N — optionally after awaiting
    durable snapshot generations and/or bitflipping the newest one
    (PT_FAULT_PS_AWAIT_SNAPS / PT_FAULT_PS_BITFLIP_SNAP). Production
    never imports this module: a pserver worker script opts in via
    ``run_pserver(..., on_server=faults.install_ps_faults)``. Returns
    True when the watcher was armed."""
    at = _int_env("PT_FAULT_PS_CRASH_AT_STEP")
    if at is None or not _applies_to_rank():
        return False
    if _already_fired("ps_crash"):
        return False            # respawned incarnation runs clean
    import threading

    def rounds():
        best = 0
        for v in server.dense.values():
            try:
                best = max(best, int(v.round))
            except Exception:
                pass
        return best

    def watch():
        while rounds() < at:
            time.sleep(0.02)
        if _already_fired("ps_crash"):
            return
        snap_dir = os.environ.get("PT_PS_SNAPSHOT_DIR")
        bitflip = bool(os.environ.get("PT_FAULT_PS_BITFLIP_SNAP"))
        k = _int_env("PT_FAULT_PS_AWAIT_SNAPS") or (2 if bitflip else 0)
        if k and snap_dir:
            _await_ps_snapshots(server, snap_dir, k)
        if not _fire_once("ps_crash"):
            return
        hit = None
        if bitflip and snap_dir:
            # FREEZE the snapshot thread before corrupting: it shares
            # this process, and a generation it publishes between the
            # flip and os._exit would hand the warm boot a healthy
            # newer generation, masking the corruption entirely (the
            # PR-5 checkpoint-writer-freeze lesson)
            try:
                server.stop_snapshots(final_save=False)
            except Exception:
                pass
            hit = _bitflip_newest_ps_snapshot(snap_dir, server.host,
                                              server.port)
        sys.stderr.write(
            f"[faults] injected pserver crash at round {rounds()}"
            + (f" after bitflipping {hit}" if hit else "")
            + f"; exiting {PS_CRASH_EXIT_CODE}\n")
        sys.stderr.flush()
        os._exit(PS_CRASH_EXIT_CODE)

    threading.Thread(target=watch, daemon=True,
                     name="pt-fault-ps-crash").start()
    return True


_PS_WIRE_ENVS = ("PT_FAULT_PS_DROP_EVERY", "PT_FAULT_PS_DELAY_EVERY")


def install_ps_wire_faults():
    """If any PS wire-chaos env is set, patch the Python transport's
    server-side reply hook (``ps._reply_frame`` — ONLY the server
    sends through it) with frame drop/delay chaos, mirroring
    ``install_serving_faults``'s patch idiom. Dropping a reply closes
    the connection AFTER the request was handled and its reply cached,
    so the client's retried frame (same client_id+seq) must be
    answered from the dedup cache — the exactly-once contract under
    the nastiest wire conditions. Returns an uninstall callable when
    installed, False otherwise. Python transport only: the C++
    server's reply path never touches this hook (chaos tests pin
    ``transport='python'``)."""
    drop_every = _int_env("PT_FAULT_PS_DROP_EVERY")
    delay_every = _int_env("PT_FAULT_PS_DELAY_EVERY")
    if not drop_every and not delay_every:
        return False
    delay_ms = _int_env("PT_FAULT_PS_DELAY_MS") or 0
    import threading

    from paddle_tpu.distributed import ps as _ps
    orig = _ps._reply_frame
    lock = threading.Lock()
    count = [0]

    def chaos_reply(sock, kind, fields, client_id=0, seq=0):
        with lock:
            count[0] += 1
            n = count[0]
        if drop_every and n % drop_every == 0:
            try:
                sock.close()
            except OSError:
                pass
            raise ConnectionError(
                f"[faults] injected reply drop (server frame {n})")
        if delay_every and n % delay_every == 0:
            time.sleep(delay_ms / 1000.0)
        return orig(sock, kind, fields, client_id, seq)

    _ps._reply_frame = chaos_reply

    def uninstall():
        _ps._reply_frame = orig

    return uninstall


def install_ps_migrate_faults():
    """If a PS migration-chaos env is set, patch the pserver's
    migration fault hook (``ps._migrate_fault_point`` — a no-op in
    production, called at each migration stage boundary) with crash /
    torn-shadow injection. Returns an uninstall callable when
    installed, False otherwise. Python transport only (elastic fleets
    force it)."""
    crash_stage = os.environ.get("PT_FAULT_PS_MIGRATE_CRASH")
    torn = os.environ.get("PT_FAULT_PS_MIGRATE_TORN")
    if not crash_stage and not torn:
        return False

    from paddle_tpu.distributed import ps as _ps
    orig = _ps._migrate_fault_point

    def chaos_point(stage, path=None):
        if crash_stage and stage == crash_stage \
                and _applies_to_rank() \
                and _fire_once(f"ps_migrate_crash_{stage}"):
            print(f"[faults] pserver crash at migration stage "
                  f"{stage!r} (exit {PS_CRASH_EXIT_CODE})",
                  file=sys.stderr, flush=True)
            sys.stderr.flush()
            os._exit(PS_CRASH_EXIT_CODE)
        if torn and stage == "staged" and path \
                and _applies_to_rank() \
                and _fire_once("ps_migrate_torn"):
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(size // 2, 1))
            print(f"[faults] tore staged migration shadow "
                  f"{os.path.basename(path)} ({size} -> "
                  f"{max(size // 2, 1)} bytes)",
                  file=sys.stderr, flush=True)
        return orig(stage, path)

    _ps._migrate_fault_point = chaos_point

    def uninstall():
        _ps._migrate_fault_point = orig

    return uninstall


def install_slow_write():
    """If PT_FAULT_SLOW_WRITE is set, patch CheckpointManager._write to
    sleep that many seconds before writing (models a slow disk / large
    shard, keeping an async checkpoint in flight when SIGTERM lands).
    Returns True if the patch was installed."""
    v = os.environ.get("PT_FAULT_SLOW_WRITE")
    if v in (None, ""):
        return False
    secs = float(v)
    from paddle_tpu.io_checkpoint import CheckpointManager
    orig = CheckpointManager._write

    def slow_write(self, payload):
        time.sleep(secs)
        return orig(self, payload)

    CheckpointManager._write = slow_write
    return True
