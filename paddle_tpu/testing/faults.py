"""Env-driven fault injection for elastic-supervision tests.

Production code never imports this module: a test's worker script opts
in by calling ``maybe_fault(step)`` inside its training loop (and
``install_slow_write()`` once at startup), and the *test* selects the
fault through the environment — which crosses the launcher's process
boundary for free:

- ``PT_FAULT_CRASH_AT_STEP=N``  — hard-exit (``os._exit``, code 23) when
  the loop reaches step N: a rank crash.
- ``PT_FAULT_HANG_AT_STEP=N``   — stop making progress at step N while
  staying alive (and not heartbeating): a hang, for the watchdog.
- ``PT_FAULT_SLOW_WRITE=S``     — ``install_slow_write()`` patches
  ``CheckpointManager._write`` to sleep S seconds first: an in-flight
  async checkpoint, for preemption tests.
- ``PT_FAULT_NAN_AT_STEP=N``    — ``poison_feed(step, feed)`` writes a
  NaN into the first float array of the feed at step N: a numerics
  blow-up, for the FLAGS_check_nan_inf sentinel/localizer tests.
- ``PT_FAULT_RANK=R``           — scope injection to PADDLE_TRAINER_ID R
  (default: every rank).
- ``PT_FAULT_ONCE_DIR=dir``     — fire each fault once *per job*, not
  per incarnation: the first firing drops a marker file in ``dir``, and
  a restarted process that sees the marker runs clean. Without it a
  crash-at-step fault would re-kill every restart and the job could
  never finish.

Exit code 23 is deliberately distinct from the launcher's own codes
(124 timeout, 143 preemption) so tests can assert who died and why.
"""

import os
import sys
import time

__all__ = ["maybe_fault", "poison_feed", "install_slow_write",
           "CRASH_EXIT_CODE"]

CRASH_EXIT_CODE = 23


def _int_env(name):
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def _applies_to_rank():
    want = os.environ.get("PT_FAULT_RANK")
    if want in (None, ""):
        return True
    return os.environ.get("PADDLE_TRAINER_ID", "0") == want


def _fire_once(tag):
    """True exactly once per (tag, PT_FAULT_ONCE_DIR) across process
    incarnations; always True when no once-dir is configured."""
    d = os.environ.get("PT_FAULT_ONCE_DIR")
    if not d:
        return True
    os.makedirs(d, exist_ok=True)
    marker = os.path.join(d, f"{tag}.fired")
    try:
        # O_EXCL: two racing ranks can't both claim the firing
        fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    os.write(fd, f"pid={os.getpid()} time={time.time()}\n".encode())
    os.close(fd)
    return True


def maybe_fault(step):
    """Call from the training-loop body; injects whatever fault the
    environment configures for this rank at this step."""
    if not _applies_to_rank():
        return
    crash_at = _int_env("PT_FAULT_CRASH_AT_STEP")
    if crash_at is not None and step == crash_at and _fire_once("crash"):
        sys.stderr.write(f"[faults] injected crash at step {step}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)       # no atexit, no flush: a crash
    hang_at = _int_env("PT_FAULT_HANG_AT_STEP")
    if hang_at is not None and step == hang_at and _fire_once("hang"):
        sys.stderr.write(f"[faults] injected hang at step {step}\n")
        sys.stderr.flush()
        while True:                     # alive but silent: heartbeats
            time.sleep(3600)            # stop, SIGKILL is the only exit


def poison_feed(step, feed):
    """Return ``feed`` with a NaN written into the first float array
    when PT_FAULT_NAN_AT_STEP selects this (rank, step); the original
    dict is never mutated. Call on the feed just before
    ``Executor.run`` — with FLAGS_check_nan_inf on, the sentinel must
    trip within this very step."""
    nan_at = _int_env("PT_FAULT_NAN_AT_STEP")
    if nan_at is None or step != nan_at or not _applies_to_rank():
        return feed
    import numpy as np
    out = dict(feed)
    for name in sorted(out):
        arr = np.asarray(out[name])
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        # claim the once-per-job marker only now that injection will
        # actually happen: a float-less feed at the target step must
        # not silently consume the fault
        if not _fire_once("nan"):
            return feed
        arr = arr.copy()
        arr.flat[0] = np.nan
        out[name] = arr
        sys.stderr.write(f"[faults] injected NaN into feed "
                         f"{name!r} at step {step}\n")
        sys.stderr.flush()
        return out
    return feed


def install_slow_write():
    """If PT_FAULT_SLOW_WRITE is set, patch CheckpointManager._write to
    sleep that many seconds before writing (models a slow disk / large
    shard, keeping an async checkpoint in flight when SIGTERM lands).
    Returns True if the patch was installed."""
    v = os.environ.get("PT_FAULT_SLOW_WRITE")
    if v in (None, ""):
        return False
    secs = float(v)
    from paddle_tpu.io_checkpoint import CheckpointManager
    orig = CheckpointManager._write

    def slow_write(self, payload):
        time.sleep(secs)
        return orig(self, payload)

    CheckpointManager._write = slow_write
    return True
