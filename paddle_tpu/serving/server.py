"""InferenceServer: the serving front-end tying the pieces together.

``InferenceServer(model_dir, ServingConfig(...))`` loads a
``save_inference_model`` artifact, verifies any AOT artifacts' integrity
manifest (a torn export fails loudly at boot, naming the first bad
file — never mid-traffic), warm-boots one compiled executable per
(replica device, bucket), and only then starts accepting requests:

    server = InferenceServer(model_dir, ServingConfig(replicas=2))
    outs = server.infer({"x": batch})          # blocking convenience
    pending = server.submit({"x": batch})      # pipelined
    outs = pending.result(timeout=5)
    server.close()                             # drains, then stops

Request contract: every feed carries a leading batch dim (1..max_batch
rows); outputs come back in fetch order, sliced to the request's own
rows. Telemetry rides the process registry (docs/OBSERVABILITY.md,
``serving_*`` rows) and therefore the per-rank Prometheus exporter and
``bench.py`` snapshots for free.
"""

import os

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.serving.replica import ReplicaPool
from paddle_tpu.serving.resilience import ShedController
from paddle_tpu.serving.scheduler import (
    MicroBatchScheduler, ServerClosedError, bucket_ladder,
)

__all__ = ["ServingConfig", "InferenceServer"]


class ServingConfig:
    """Knobs for one server (docs/SERVING.md has the tuning guide).

    - ``max_batch``: top of the power-of-two bucket ladder (one AOT
      executable per rung per replica device).
    - ``max_wait_ms``: batching deadline — the most latency a lone
      request trades for fill.
    - ``max_queue``: admission bound; beyond it ``submit`` raises
      ``QueueFullError`` (typed backpressure).
    - ``replicas``: worker count; devices are assigned round-robin
      over ``devices`` (default: all visible).
    - ``feed_specs``: optional {feed name: (sample_shape, dtype)}
      override when the program declares dynamic non-batch dims.
    - ``verify_aot``: verify the model dir's AOT integrity manifest at
      boot (on by default; only skips work when no manifest exists).

    Resilience knobs (docs/SERVING.md "Resilience"):

    - ``default_deadline_ms``: deadline applied to every request that
      doesn't pass its own ``submit(deadline_ms=)``; None (default) =
      no deadline. Past it a request fails with
      ``DeadlineExceededError`` at whichever stage observes the
      expiry.
    - ``replica_stall_ms`` / ``max_consecutive_stalls`` /
      ``respawn_backoff_ms`` / ``supervise``: the replica-pool
      supervisor (wedge detection, quarantine + warm respawn,
      permanent retirement) — see ``ReplicaPool``.
    - ``shed_mode``: ``"off"`` (default — admission is bit-for-bit the
      pre-resilience path) or ``"adaptive"`` (brownout shedding with
      ``OverloadedError``; requires ``default_deadline_ms``).
    - ``shed_enter_frac`` / ``shed_exit_frac``: brownout hysteresis
      thresholds as fractions of the deadline (see
      ``resilience.ShedController``).
    """

    def __init__(self, max_batch=8, max_wait_ms=5.0, max_queue=256,
                 replicas=1, devices=None, feed_specs=None,
                 verify_aot=True, default_deadline_ms=None,
                 replica_stall_ms=30_000.0, max_consecutive_stalls=3,
                 respawn_backoff_ms=100.0, supervise=True,
                 shed_mode="off", shed_enter_frac=0.5,
                 shed_exit_frac=0.25):
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_queue = max_queue
        self.replicas = replicas
        self.devices = devices
        self.feed_specs = feed_specs
        self.verify_aot = verify_aot
        self.default_deadline_ms = default_deadline_ms
        self.replica_stall_ms = replica_stall_ms
        self.max_consecutive_stalls = max_consecutive_stalls
        self.respawn_backoff_ms = respawn_backoff_ms
        self.supervise = supervise
        self.shed_mode = shed_mode
        self.shed_enter_frac = shed_enter_frac
        self.shed_exit_frac = shed_exit_frac


def _infer_sample_specs(program, feed_names, overrides):
    """{feed name: (sample shape, dtype)} from the program's feed var
    declarations — dim 0 is the batch dim the scheduler owns; every
    other dim must be static (or overridden) because each bucket
    compiles ONE executable."""
    blk = program.global_block()
    out = {}
    for n in feed_names:
        if overrides and n in overrides:
            shape, dtype = overrides[n]
            out[n] = (tuple(int(d) for d in shape), np.dtype(dtype))
            continue
        v = blk.vars.get(n)
        enforce(v is not None, f"feed {n!r} not declared in program")
        shape = list(v.shape)
        # dim 0 is ALWAYS the batch dim the scheduler owns — for
        # append_batch_size=False declarations too (the request
        # contract puts batch first regardless of how the var spelled
        # its leading dim)
        sample = shape[1:]
        enforce(all(d >= 0 for d in sample),
                f"feed {n!r} has dynamic non-batch dims {shape}; "
                f"serving compiles fixed-shape bucket executables — "
                f"pass ServingConfig(feed_specs={{{n!r}: (shape, "
                f"dtype)}})")
        out[n] = (tuple(int(d) for d in sample), np.dtype(v.dtype))
    return out


class InferenceServer:
    """Continuous micro-batching server over a frozen inference model.

    Construction performs the full warm boot (load + verify + compile
    every bucket executable on every replica device + start workers);
    when ``__init__`` returns the server is serving.
    """

    def __init__(self, model_dir, config=None):
        from paddle_tpu import inference as inf
        from paddle_tpu.core.place import CPUPlace
        from paddle_tpu.static import io as static_io
        from paddle_tpu.static.executor import Executor, Scope

        self.config = config = config or ServingConfig()
        self.model_dir = model_dir
        self._scope = Scope()
        exe = Executor(CPUPlace())
        prog, feed_names, fetch_names = static_io.load_inference_model(
            model_dir, exe, scope=self._scope)
        if config.verify_aot:
            # boot-time integrity gate: a torn/bit-rotted AOT export
            # names its first bad file here, not as a mid-traffic
            # deserialization traceback (legacy dirs without a
            # manifest verify vacuously)
            inf.verify_aot_dir(model_dir)
        self._program = prog
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        self._sample_specs = _infer_sample_specs(
            prog, self._feed_names, config.feed_specs)
        pure_fn, state_names = inf._build_pure_fn(
            prog, self._feed_names, self._fetch_names)
        raw = [self._scope.find_var(n) for n in state_names]
        missing = [n for n, v in zip(state_names, raw) if v is None]
        enforce(not missing,
                f"scope missing persistables for serving: {missing[:5]}")
        params_np = [np.asarray(v) for v in raw]
        ladder = bucket_ladder(config.max_batch)
        # shed_mode gates the whole adaptive controller: "off" (the
        # default) constructs NOTHING — admission stays bit-for-bit
        # the pre-resilience path
        enforce(config.shed_mode in ("off", "adaptive"),
                f"shed_mode must be 'off' or 'adaptive', got "
                f"{config.shed_mode!r}")
        shed = None
        if config.shed_mode == "adaptive":
            enforce(config.default_deadline_ms is not None,
                    "shed_mode='adaptive' requires "
                    "default_deadline_ms: the controller sheds "
                    "against deadline headroom, and without a "
                    "deadline there is none")
            shed = ShedController(
                deadline_ms=config.default_deadline_ms,
                enter_frac=config.shed_enter_frac,
                exit_frac=config.shed_exit_frac)
        # the scheduler validates every config knob (max_batch ladder,
        # max_wait_ms, max_queue, default_deadline_ms) — construct it
        # BEFORE the expensive warm boot so a bad knob fails in
        # microseconds instead of after compiling (and leaking) every
        # bucket executable; the dispatch is late-bound to the pool
        # built below
        self.scheduler = MicroBatchScheduler(
            dispatch=lambda mb: self.pool.dispatch(mb),
            feed_names=self._feed_names,
            max_batch=config.max_batch,
            max_wait_ms=config.max_wait_ms,
            max_queue=config.max_queue,
            sample_specs=self._sample_specs,
            default_deadline_ms=config.default_deadline_ms,
            shed=shed)
        self._check_fetch_contract(pure_fn, params_np, ladder)
        self.pool = ReplicaPool(
            pure_fn, params_np, self._feed_names, self._sample_specs,
            ladder=ladder,
            n_replicas=config.replicas, devices=config.devices,
            replica_stall_ms=config.replica_stall_ms,
            max_consecutive_stalls=config.max_consecutive_stalls,
            respawn_backoff_ms=config.respawn_backoff_ms,
            supervise=config.supervise)
        self.scheduler.start()

    def _check_fetch_contract(self, pure_fn, params_np, ladder):
        """Micro-batched serving requires every fetch to be per-row
        (leading dim = batch): a batch-reduced or rank-0 fetch would
        boot fine and then error EVERY request at result-slicing time.
        One cheap ``jax.eval_shape`` at the top bucket catches it at
        load — the fail-at-boot contract — with a message naming the
        fetch."""
        import jax
        top = ladder[-1]
        param_sds = tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                          for p in params_np)
        feed_sds = tuple(
            jax.ShapeDtypeStruct((top,) + tuple(shape), np.dtype(dt))
            for shape, dt in (self._sample_specs[n]
                              for n in self._feed_names))
        outs = jax.eval_shape(pure_fn, param_sds, feed_sds)
        for name, o in zip(self._fetch_names, outs):
            enforce(
                len(o.shape) >= 1 and int(o.shape[0]) == top,
                f"fetch {name!r} has output shape {tuple(o.shape)} for "
                f"a batch of {top}: not per-row, so micro-batched "
                f"results cannot be sliced back to requests — move the "
                f"reduction out of the served graph or use the "
                f"single-request Predictor")

    # -- introspection -----------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    @property
    def ladder(self):
        return self.pool.ladder

    # -- serving -----------------------------------------------------------
    def submit(self, feeds, deadline_ms=None):
        """Admit one request; returns a ``PendingResult``.
        ``deadline_ms`` bounds it end to end (None = the config's
        ``default_deadline_ms``); past the deadline the request fails
        with ``DeadlineExceededError`` at whichever serving stage
        observes the expiry."""
        # no server-level pre-gate: the scheduler validates ARGUMENTS
        # first and then refuses with ServerClosedError — so a
        # malformed request fails the same deterministic typed way on
        # a closed server as on an open one (the documented
        # precedence; server.close() closes the scheduler, so the
        # closed refusal is never lost)
        return self.scheduler.submit(feeds, deadline_ms=deadline_ms)

    def infer(self, feeds, timeout=None, deadline_ms=None):
        """Blocking convenience: submit + result."""
        return self.submit(feeds, deadline_ms=deadline_ms).result(timeout)

    def close(self, timeout=None):
        """Graceful shutdown: stop admission, drain every accepted
        request through the replicas, stop the workers. Returns True
        when fully stopped. With a ``timeout`` that expires mid-drain,
        returns False and leaves the batcher AND replicas running
        (daemon threads) so every accepted request still completes —
        stopping the replicas early would let their shutdown sentinels
        overtake still-forming batches in the FIFO and strand those
        requests forever. Call close() again to finish. Idempotent."""
        # order matters: the scheduler drains its request queue into
        # the batch queue first, THEN the pool's per-replica sentinels
        # land behind every formed batch
        if not self.scheduler.close(timeout):
            return False
        if not self.pool.close(timeout):
            return False
        if self.scheduler._shed is not None:
            # gauge truth on the way out: a closed server is not in
            # brownout, whatever the last minutes looked like
            self.scheduler._shed.shutdown()
        return True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
