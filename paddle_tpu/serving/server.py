"""InferenceServer: the serving front-end tying the pieces together.

``InferenceServer(model_dir, ServingConfig(...))`` loads a
``save_inference_model`` artifact, verifies any AOT artifacts' integrity
manifest (a torn export fails loudly at boot, naming the first bad
file — never mid-traffic), warm-boots one compiled executable per
(replica device, bucket), and only then starts accepting requests:

    server = InferenceServer(model_dir, ServingConfig(replicas=2))
    outs = server.infer({"x": batch})          # blocking convenience
    pending = server.submit({"x": batch})      # pipelined
    outs = pending.result(timeout=5)
    server.swap(new_model_dir)                 # zero-downtime deploy
    server.close()                             # drains, then stops

Request contract: every feed carries a leading batch dim (1..max_batch
rows); outputs come back in fetch order, sliced to the request's own
rows. Telemetry rides the process registry (docs/OBSERVABILITY.md,
``serving_*`` rows) and therefore the per-rank Prometheus exporter and
``bench.py`` snapshots for free.

Deploying a new model version is a first-class, supervised operation:
``swap(model_dir)`` runs the staged gate → standby warm-boot → canary →
atomic cutover → watchdog pipeline (serving/swap.py, docs/SERVING.md
"Hot model swap"), and ``watch_dir()`` keeps doing it automatically as
training publishes new ``export_aot`` outputs. Model loading is split
out of the server boot (``_load_bundle``/``_boot_pool``) exactly so the
swap controller can build a SECOND pool alongside the live one.
"""

import os

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.serving.replica import ReplicaPool
from paddle_tpu.serving.resilience import ShedController, _log
from paddle_tpu.serving.scheduler import (
    MicroBatchScheduler, ServerClosedError, bucket_ladder,
)
from paddle_tpu.serving import swap as _swap

__all__ = ["ServingConfig", "InferenceServer"]


class ServingConfig:
    """Knobs for one server (docs/SERVING.md has the tuning guide).

    - ``max_batch``: top of the power-of-two bucket ladder (one AOT
      executable per rung per replica device).
    - ``max_wait_ms``: batching deadline — the most latency a lone
      request trades for fill.
    - ``max_queue``: admission bound; beyond it ``submit`` raises
      ``QueueFullError`` (typed backpressure).
    - ``replicas``: worker count; devices are assigned round-robin
      over ``devices`` (default: all visible).
    - ``feed_specs``: optional {feed name: (sample_shape, dtype)}
      override when the program declares dynamic non-batch dims.
    - ``verify_aot``: verify the model dir's AOT integrity manifest at
      boot (on by default; only skips work when no manifest exists).
      ``swap()`` always re-gates regardless — a server that outlives
      an artifact rewrite must never promote bits it didn't verify.

    Resilience knobs (docs/SERVING.md "Resilience"):

    - ``default_deadline_ms``: deadline applied to every request that
      doesn't pass its own ``submit(deadline_ms=)``; None (default) =
      no deadline. Past it a request fails with
      ``DeadlineExceededError`` at whichever stage observes the
      expiry.
    - ``replica_stall_ms`` / ``max_consecutive_stalls`` /
      ``respawn_backoff_ms`` / ``supervise``: the replica-pool
      supervisor (wedge detection, quarantine + warm respawn,
      permanent retirement) — see ``ReplicaPool``. A hot-swap standby
      pool inherits the same knobs.
    - ``shed_mode``: ``"off"`` (default — admission is bit-for-bit the
      pre-resilience path) or ``"adaptive"`` (brownout shedding with
      ``OverloadedError``; requires ``default_deadline_ms``).
    - ``shed_enter_frac`` / ``shed_exit_frac``: brownout hysteresis
      thresholds as fractions of the deadline (see
      ``resilience.ShedController``).
    - ``hbm_limit_bytes``: per-device HBM capacity the memory-aware
      admission projects against (hot-swap standby boot refuses when
      the two pools cannot co-reside under it — docs/SERVING.md
      "Memory-aware admission"). Default None falls back to the
      backend-reported limit / ``PADDLE_TPU_HBM_LIMIT_BYTES``; with
      neither, admission is advisory (never refuses).
    - ``shed_hbm_frac``: optional HBM-pressure shed input — worst-
      device utilization at/above this fraction sheds new admissions
      (``reason="hbm_pressure"``); requires the memory poller
      (``monitor.memory.enable()``) for live samples. None disables.
    """

    def __init__(self, max_batch=8, max_wait_ms=5.0, max_queue=256,
                 replicas=1, devices=None, feed_specs=None,
                 verify_aot=True, default_deadline_ms=None,
                 replica_stall_ms=30_000.0, max_consecutive_stalls=3,
                 respawn_backoff_ms=100.0, supervise=True,
                 shed_mode="off", shed_enter_frac=0.5,
                 shed_exit_frac=0.25, hbm_limit_bytes=None,
                 shed_hbm_frac=None):
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_queue = max_queue
        self.replicas = replicas
        self.devices = devices
        self.feed_specs = feed_specs
        self.verify_aot = verify_aot
        self.default_deadline_ms = default_deadline_ms
        self.replica_stall_ms = replica_stall_ms
        self.max_consecutive_stalls = max_consecutive_stalls
        self.respawn_backoff_ms = respawn_backoff_ms
        self.supervise = supervise
        self.shed_mode = shed_mode
        self.shed_enter_frac = shed_enter_frac
        self.shed_exit_frac = shed_exit_frac
        self.hbm_limit_bytes = hbm_limit_bytes
        self.shed_hbm_frac = shed_hbm_frac


def _infer_sample_specs(program, feed_names, overrides):
    """{feed name: (sample shape, dtype)} from the program's feed var
    declarations — dim 0 is the batch dim the scheduler owns; every
    other dim must be static (or overridden) because each bucket
    compiles ONE executable."""
    blk = program.global_block()
    out = {}
    for n in feed_names:
        if overrides and n in overrides:
            shape, dtype = overrides[n]
            out[n] = (tuple(int(d) for d in shape), np.dtype(dtype))
            continue
        v = blk.vars.get(n)
        enforce(v is not None, f"feed {n!r} not declared in program")
        shape = list(v.shape)
        # dim 0 is ALWAYS the batch dim the scheduler owns — for
        # append_batch_size=False declarations too (the request
        # contract puts batch first regardless of how the var spelled
        # its leading dim)
        sample = shape[1:]
        enforce(all(d >= 0 for d in sample),
                f"feed {n!r} has dynamic non-batch dims {shape}; "
                f"serving compiles fixed-shape bucket executables — "
                f"pass ServingConfig(feed_specs={{{n!r}: (shape, "
                f"dtype)}})")
        out[n] = (tuple(int(d) for d in sample), np.dtype(v.dtype))
    return out


class _ModelBundle:
    """Everything one model version needs to serve, loaded but not yet
    compiled: the frozen program, its feed/fetch contract, the
    jittable pure fn and host param arrays, and the manifest's
    ``model_version``. The server boots from one; the swap controller
    loads a SECOND one for the standby pool — the split that lets two
    versions coexist in one server."""

    __slots__ = ("model_dir", "program", "feed_names", "fetch_names",
                 "sample_specs", "pure_fn", "params_np", "version",
                 "scope", "quantized")

    def __init__(self, model_dir, program, feed_names, fetch_names,
                 sample_specs, pure_fn, params_np, version, scope,
                 quantized=None):
        self.model_dir = model_dir
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.sample_specs = sample_specs
        self.pure_fn = pure_fn
        self.params_np = params_np
        self.version = version
        self.scope = scope
        #: "int8"/"bf16" when the dir carries a quantized export the
        #: bundle loaded (docs/SERVING.md "Quantized serving"), else None
        self.quantized = quantized


def _load_bundle(model_dir, feed_specs=None, verify=True):
    """Load + (optionally) integrity-verify one model version into a
    :class:`_ModelBundle`. Commits NO device resources — compilation
    and ``device_put`` happen in ``_boot_pool``, so a gate refusal
    costs a few file reads."""
    from paddle_tpu import inference as inf
    from paddle_tpu.core.place import CPUPlace
    from paddle_tpu.static import io as static_io
    from paddle_tpu.static.executor import Executor, Scope

    scope = Scope()
    exe = Executor(CPUPlace())
    prog, feed_names, fetch_names = static_io.load_inference_model(
        model_dir, exe, scope=scope)
    if verify:
        # integrity gate: a torn/bit-rotted AOT export names its first
        # bad file here, not as a mid-traffic deserialization
        # traceback (legacy dirs without a manifest verify vacuously);
        # the verify result also carries the manifest model_version
        version = inf.verify_aot_dir(model_dir).model_version
    else:
        version = inf.read_aot_version(model_dir)
    feed_names = list(feed_names)
    fetch_names = list(fetch_names)
    sample_specs = _infer_sample_specs(prog, feed_names, feed_specs)
    # program-level pass pipeline on the served graph (same lever as
    # the executor's compile path; sample_specs read the feed var
    # declarations above, which passes never touch)
    from paddle_tpu.core.flags import get_flag
    if bool(get_flag("apply_ir_passes")):
        from paddle_tpu.static import opt_passes as _opt
        prog = _opt.optimize_inference(prog, fetch_names)
    # quantized export sidecar (export_aot(quantize=...)): rewrite the
    # served program per the manifest's weight list and make the
    # QUANTIZED arrays the resident params — the whole point of
    # weight-only PTQ for serving (int8: ~4x smaller resident params,
    # more replicas per device). Transparent: the swap gate/canary and
    # warm boot run the same path.
    quant = inf.load_quantized_params(model_dir)
    if quant is not None:
        from paddle_tpu.static import opt_passes as _opt
        prog = _opt.apply_weight_quant(prog, quant["weights"],
                                       quant["mode"])
        for n, v in quant["values"].items():
            scope.set_var(n, v)
        _log(f"loaded {quant['mode']} weight-quantized params for "
             f"{len(quant['weights'])} weight(s) from {model_dir}")
    pure_fn, state_names = inf._build_pure_fn(prog, feed_names,
                                              fetch_names)
    raw = [scope.find_var(n) for n in state_names]
    missing = [n for n, v in zip(state_names, raw) if v is None]
    enforce(not missing,
            f"scope missing persistables for serving: {missing[:5]}")
    params_np = [np.asarray(v) for v in raw]
    return _ModelBundle(model_dir, prog, feed_names, fetch_names,
                        sample_specs, pure_fn, params_np, version,
                        scope,
                        quantized=quant["mode"] if quant else None)


def _check_fetch_contract(bundle, ladder):
    """Micro-batched serving requires every fetch to be per-row
    (leading dim = batch): a batch-reduced or rank-0 fetch would boot
    fine and then error EVERY request at result-slicing time. One
    cheap ``jax.eval_shape`` at the top bucket catches it at load (and
    at the swap gate) — the fail-at-boot contract — with a message
    naming the fetch."""
    import jax
    top = ladder[-1]
    param_sds = tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                      for p in bundle.params_np)
    feed_sds = tuple(
        jax.ShapeDtypeStruct((top,) + tuple(shape), np.dtype(dt))
        for shape, dt in (bundle.sample_specs[n]
                          for n in bundle.feed_names))
    outs = jax.eval_shape(bundle.pure_fn, param_sds, feed_sds)
    for name, o in zip(bundle.fetch_names, outs):
        enforce(
            len(o.shape) >= 1 and int(o.shape[0]) == top,
            f"fetch {name!r} has output shape {tuple(o.shape)} for "
            f"a batch of {top}: not per-row, so micro-batched "
            f"results cannot be sliced back to requests — move the "
            f"reduction out of the served graph or use the "
            f"single-request Predictor")


def _boot_pool(bundle, config, role="live"):
    """Warm-boot a replica pool for one model bundle: compile every
    (device, bucket) executable and ``device_put`` the params. The
    expensive half of a server boot — and of a hot-swap standby, which
    passes ``role="standby"`` so the live pool keeps gauge ownership
    while both are resident (the documented ~2x-param-memory
    window)."""
    return ReplicaPool(
        bundle.pure_fn, bundle.params_np, bundle.feed_names,
        bundle.sample_specs, ladder=bucket_ladder(config.max_batch),
        n_replicas=config.replicas, devices=config.devices,
        replica_stall_ms=config.replica_stall_ms,
        max_consecutive_stalls=config.max_consecutive_stalls,
        respawn_backoff_ms=config.respawn_backoff_ms,
        supervise=config.supervise, role=role)


class InferenceServer:
    """Continuous micro-batching server over a frozen inference model.

    Construction performs the full warm boot (load + verify + compile
    every bucket executable on every replica device + start workers);
    when ``__init__`` returns the server is serving. ``swap()`` /
    ``watch_dir()`` replace the served model version with zero
    downtime (docs/SERVING.md "Hot model swap").
    """

    def __init__(self, model_dir, config=None):
        self.config = config = config or ServingConfig()
        # shed_mode gates the whole adaptive controller: "off" (the
        # default) constructs NOTHING — admission stays bit-for-bit
        # the pre-resilience path
        enforce(config.shed_mode in ("off", "adaptive"),
                f"shed_mode must be 'off' or 'adaptive', got "
                f"{config.shed_mode!r}")
        shed = None
        if config.shed_mode == "adaptive":
            enforce(config.default_deadline_ms is not None,
                    "shed_mode='adaptive' requires "
                    "default_deadline_ms: the controller sheds "
                    "against deadline headroom, and without a "
                    "deadline there is none")
            shed = ShedController(
                deadline_ms=config.default_deadline_ms,
                enter_frac=config.shed_enter_frac,
                exit_frac=config.shed_exit_frac,
                hbm_high_frac=config.shed_hbm_frac)
        bundle = _load_bundle(model_dir, config.feed_specs,
                              verify=config.verify_aot)
        self._apply_bundle(bundle)
        # the scheduler validates every config knob (max_batch ladder,
        # max_wait_ms, max_queue, default_deadline_ms) — construct it
        # BEFORE the expensive warm boot so a bad knob fails in
        # microseconds instead of after compiling (and leaking) every
        # bucket executable; dispatch targets the live pool through
        # ONE attribute read (_dispatch_batch), which is also the
        # hot-swap cutover point (scheduler.set_dispatch)
        self.scheduler = MicroBatchScheduler(
            dispatch=self._dispatch_batch,
            feed_names=self._feed_names,
            max_batch=config.max_batch,
            max_wait_ms=config.max_wait_ms,
            max_queue=config.max_queue,
            sample_specs=self._sample_specs,
            default_deadline_ms=config.default_deadline_ms,
            shed=shed)
        _check_fetch_contract(bundle, bucket_ladder(config.max_batch))
        self.pool = _boot_pool(bundle, config, role="live")
        self._swap_controller = None
        self._closing = False
        # the operator must always be able to answer "which version is
        # this server serving" from plain logs — at boot and after
        # every cutover (swap.py logs the latter)
        _swap.publish_model_version(self.model_version)
        _log(f"serving model version "
             f"{self.model_version or 'unversioned'} from "
             f"{model_dir} (boot)")
        self.scheduler.start()

    def _apply_bundle(self, bundle):
        """Point the server's introspection surface at one model
        bundle — called at boot and at every hot-swap cutover/rollback
        (the gate guarantees feed/fetch/spec compatibility, so
        in-flight requests validated under the previous bundle stay
        valid)."""
        self._bundle = bundle
        self.model_dir = bundle.model_dir
        self._program = bundle.program
        self._feed_names = bundle.feed_names
        self._fetch_names = bundle.fetch_names
        self._sample_specs = bundle.sample_specs

    def _dispatch_batch(self, mb):
        # ONE attribute read of self.pool per formed batch: the
        # hot-swap cutover rebinds the scheduler's dispatch directly
        # (set_dispatch), so this late-bound path only carries boot
        # traffic — but it must keep the same batch-atomicity contract
        self.pool.dispatch(mb)

    # -- introspection -----------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    @property
    def ladder(self):
        return self.pool.ladder

    @property
    def model_version(self):
        """The manifest ``model_version`` this server is serving
        (None for unversioned exports) — updated atomically at every
        hot-swap cutover and rollback."""
        return self._bundle.version

    # -- serving -----------------------------------------------------------
    def submit(self, feeds, deadline_ms=None, trace_attrs=None):
        """Admit one request; returns a ``PendingResult``.
        ``deadline_ms`` bounds it end to end (None = the config's
        ``default_deadline_ms``); past the deadline the request fails
        with ``DeadlineExceededError`` at whichever serving stage
        observes the expiry. ``trace_attrs`` (optional dict) rides the
        request's kept trace as root-span attributes — the HTTP front
        door stamps the tenant id here."""
        # no server-level pre-gate: the scheduler validates ARGUMENTS
        # first and then refuses with ServerClosedError — so a
        # malformed request fails the same deterministic typed way on
        # a closed server as on an open one (the documented
        # precedence; server.close() closes the scheduler, so the
        # closed refusal is never lost)
        return self.scheduler.submit(feeds, deadline_ms=deadline_ms,
                                     trace_attrs=trace_attrs)

    def infer(self, feeds, timeout=None, deadline_ms=None):
        """Blocking convenience: submit + result."""
        return self.submit(feeds, deadline_ms=deadline_ms).result(timeout)

    # -- graceful drain ----------------------------------------------------
    @property
    def draining(self):
        """True between ``begin_drain()`` and ``close()``: admission
        refuses with the retryable ``ServerDrainingError`` while
        accepted requests complete."""
        return self.scheduler.draining

    def begin_drain(self):
        """Begin a graceful drain: admission flips to the retryable
        :class:`~.scheduler.ServerDrainingError` (a
        ``ServerClosedError`` subclass, so existing handlers keep
        working) while every already-accepted request completes
        through the normal path. The terminal half is still
        ``close()`` — a drain stops new work WITHOUT committing to
        teardown, which is what a rolling restart wants between
        "readiness off" and "process exit". Idempotent; returns
        whether this call flipped the state."""
        flipped = self.scheduler.begin_drain()
        if flipped:
            _log(f"drain begun: model version "
                 f"{self.model_version or 'unversioned'} refusing "
                 f"new admissions (ServerDrainingError, retryable); "
                 f"accepted requests completing")
        return flipped

    # -- hot model swap ----------------------------------------------------
    def _swap_ctl(self):
        if self._swap_controller is None:
            self._swap_controller = _swap.SwapController(self)
            if self._closing:
                # a controller created lazily AFTER close() must
                # inherit the closed state — otherwise swap() on a
                # closed server would warm-boot and promote a pool
                # nothing will ever close
                self._swap_controller._closed = True
        return self._swap_controller

    def swap(self, model_dir, **kwargs):
        """Zero-downtime hot model swap: gate (integrity +
        compatibility) → standby warm-boot (new pool alongside the
        live one; ~2x param memory for the window) → canary (golden
        requests through the standby executables) → atomic cutover at
        a batch boundary → post-cutover watchdog, with automatic
        rollback to the still-resident old version on any failure
        (typed :class:`~.resilience.SwapFailedError` naming the
        stage). Returns the swap report dict. Keyword knobs:
        ``canary_feeds``, ``canary_check``, ``parity_rtol``/
        ``parity_atol``, ``standby_timeout_ms``, ``watchdog_ms``,
        ``watchdog_max_errors``, ``watchdog_latency_x`` — see
        :class:`~.swap.SwapController` and docs/SERVING.md
        "Hot model swap"."""
        return self._swap_ctl().swap(model_dir, **kwargs)

    def watch_dir(self, model_dir=None, poll_ms=1000.0, **swap_kwargs):
        """Continuous-deploy mode: poll ``model_dir`` (default: the
        dir this server booted from) for a NEW manifest
        ``model_version`` — the cheap index-only probe — and ``swap``
        to it as training publishes fresh ``export_aot`` outputs. A
        failed version is remembered and not retried until the
        publisher writes a different one (no crash-loop on a bad
        artifact; the live version keeps serving). Returns the
        :class:`~.swap.SwapController`; ``stop_watch()`` or
        ``close()`` ends it."""
        return self._swap_ctl().watch_dir(model_dir, poll_ms=poll_ms,
                                          **swap_kwargs)

    def close(self, timeout=None):
        """Graceful shutdown: stop admission, drain every accepted
        request through the replicas, stop the workers. Returns True
        when fully stopped. With a ``timeout`` that expires mid-drain,
        returns False and leaves the batcher AND replicas running
        (daemon threads) so every accepted request still completes —
        stopping the replicas early would let their shutdown sentinels
        overtake still-forming batches in the FIFO and strand those
        requests forever. Call close() again to finish. Idempotent."""
        # swap machinery brackets the close: the FAST half first (no
        # new swap can start, an in-flight one will abort before
        # cutover, the watcher stops) so admission shutdown below is
        # never raced by a version flip... — and the flag survives for
        # a controller lazily created after this close (_swap_ctl)
        self._closing = True
        if self._swap_controller is not None:
            self._swap_controller.begin_shutdown()
        # order matters: the scheduler drains its request queue into
        # the batch queue first, THEN the pool's per-replica sentinels
        # land behind every formed batch
        if not self.scheduler.close(timeout):
            return False
        if not self.pool.close(timeout):
            return False
        # ...and the SLOW half last: wait out the aborting swap and
        # the background pool drains — a False here means swap
        # machinery is still running, and claiming "fully stopped"
        # over it would let a caller tear down scopes under live
        # replica threads (admission is already stopped either way)
        if self._swap_controller is not None and \
                not self._swap_controller.finish_shutdown(timeout):
            return False
        if self.scheduler._shed is not None:
            # gauge truth on the way out: a closed server is not in
            # brownout, whatever the last minutes looked like
            self.scheduler._shed.shutdown()
        # gauge truth is the SERVER's on a true close: a rollback
        # racing this close can leave the pool we just closed demoted
        # (its role-gated zeroing skipped), so re-assert zeros here
        # rather than trust whichever pool object we happened to hold
        from paddle_tpu.serving.replica import zero_pool_gauges
        zero_pool_gauges()
        # a closed server serves nothing: a lingering version series
        # in exports would read as a live deployment
        _swap.clear_model_version(self.model_version)
        return True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
