"""Multi-replica dispatch: the execution half of the serving subsystem.

Each :class:`Replica` owns a device, a device-resident copy of the
frozen program's params, and one ahead-of-time compiled XLA executable
per bucket of the ladder — compiled at **warm boot** (pool
construction), before the server accepts traffic, so the first real
request never pays a trace or an XLA compile. When the PR-2 persistent
compilation cache is armed (``PADDLE_TPU_CACHE_DIR``, wired at
``paddle_tpu.core`` import), warm boot itself is a disk read on every
boot after the first.

Replicas are fed from ONE shared batch queue (the scheduler's dispatch
target): a slow replica simply takes fewer batches, it cannot convoy
the others — the reference's multi-stream serving shape
(inference/api: one AnalysisPredictor clone per stream), with streams
replaced by device-pinned executables.

Device pinning uses sharding-annotated avals
(``jax.ShapeDtypeStruct(..., sharding=SingleDeviceSharding(dev))``), so
each replica's executables are compiled FOR its device and feeds are
``device_put`` onto it at dispatch; replicas that share a device (more
replicas than devices) share one executable map and one param copy —
the extra replicas then only add pipelining across the Python/dispatch
gap, which is exactly what they are for on a single-chip host.
"""

import queue
import threading

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.monitor.registry import gauge, histogram

__all__ = ["Replica", "ReplicaPool"]

_m_replicas = gauge(
    "serving_replicas",
    "Replica workers serving the shared batch queue")
_m_exec_ms = histogram(
    "serving_batch_execute_ms",
    "Wall ms a replica spent executing one micro-batch (device_put + "
    "compiled call + host fetch)")

#: batch-queue sentinel, one per replica at shutdown
_STOP = object()


class Replica:
    """One worker: a device, resident params, per-bucket executables,
    and a thread draining the shared batch queue."""

    def __init__(self, index, device, params, executables, feed_names,
                 batch_queue):
        self.index = index
        self.device = device
        self._params = params
        self._executables = executables
        self._feed_names = tuple(feed_names)
        self._q = batch_queue
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serving-replica-{index}")
        self.batches_run = 0

    def start(self):
        self._thread.start()
        return self

    def join(self, timeout=None):
        self._thread.join(timeout)

    def is_alive(self):
        return self._thread.is_alive()

    def _loop(self):
        import time
        while True:
            mb = self._q.get()
            if mb is _STOP:
                break
            t0 = time.perf_counter()
            # trace stamps only (dispatch_wait ends / execute starts
            # here; fakes enqueued by tests may lack the slots): the
            # per-request spans assemble from these at tail-sampling
            # keep time, so the serving hot path pays attribute
            # stores, never span construction
            stamped = hasattr(mb, "t_pick")
            if stamped:
                mb.t_pick = t0
                mb.tid_replica = threading.get_ident()
                mb.replica = self.index
            try:
                outs = self.run_batch(mb.bucket, mb.feeds)
            except Exception as e:
                # deliver the failure to the batch's requests and keep
                # serving: one poisoned batch must not kill the replica
                mb.fail(e)
                continue
            if stamped:
                mb.t_exec = time.perf_counter()
            try:
                mb.complete(outs)
            except Exception as e:
                # complete() itself failed (e.g. an executable returned
                # a wrong leading dim): sweep the undelivered requests
                # with the error (first-wins delivery) and keep serving
                mb.fail(e)
                continue
            self.batches_run += 1
            _m_exec_ms.observe((time.perf_counter() - t0) * 1e3)

    def run_batch(self, bucket, feeds):
        """Execute one padded batch dict on this replica's executable
        for ``bucket``; returns host arrays in fetch order."""
        import jax
        exe = self._executables.get(bucket)
        enforce(exe is not None,
                f"replica {self.index} has no executable for bucket "
                f"{bucket} (ladder {sorted(self._executables)})")
        fd = tuple(jax.device_put(feeds[n], self.device)
                   for n in self._feed_names)
        outs = exe(self._params, fd)
        return [np.asarray(o) for o in outs]


class ReplicaPool:
    """N replicas over the visible devices (round-robin), all draining
    one shared bounded batch queue. Construction IS the warm boot:
    every (device, bucket) executable compiles before this returns.

    ``pure_fn`` is the jittable ``fn(params_tuple, feeds_tuple) ->
    outputs_tuple`` from ``inference._build_pure_fn``; ``params_np``
    the state arrays in its order; ``sample_specs`` {feed name:
    (sample_shape, dtype)} fixing every non-batch dim."""

    def __init__(self, pure_fn, params_np, feed_names, sample_specs,
                 ladder, n_replicas=1, devices=None, queue_depth=None):
        import jax
        from jax.sharding import SingleDeviceSharding

        enforce(n_replicas >= 1, f"n_replicas < 1 ({n_replicas})")
        self._feed_names = tuple(feed_names)
        self.ladder = tuple(ladder)
        devices = list(devices if devices is not None else jax.devices())
        enforce(devices, "no devices visible for serving")
        if queue_depth is None:
            # deep enough that the batcher never stalls behind an idle
            # replica, shallow enough that batches don't age in queue
            queue_depth = max(2 * n_replicas, 2)
        self.batch_queue = queue.Queue(maxsize=queue_depth)
        jitted = jax.jit(pure_fn)
        self._by_device = {}        # device -> (params, {bucket: exe})
        for dev in {devices[i % len(devices)]: None
                    for i in range(n_replicas)}:
            sh = SingleDeviceSharding(dev)
            params = tuple(jax.device_put(np.asarray(p), dev)
                           for p in params_np)
            param_sds = tuple(
                jax.ShapeDtypeStruct(p.shape, p.dtype, sharding=sh)
                for p in params)
            exes = {}
            for bucket in self.ladder:
                feed_sds = tuple(
                    jax.ShapeDtypeStruct((bucket,) + tuple(shape),
                                         np.dtype(dtype), sharding=sh)
                    for shape, dtype in
                    (sample_specs[n] for n in self._feed_names))
                exes[bucket] = jitted.lower(param_sds,
                                            feed_sds).compile()
            self._by_device[dev] = (params, exes)
        self._stopped = False
        self.replicas = []
        for i in range(n_replicas):
            dev = devices[i % len(devices)]
            params, exes = self._by_device[dev]
            self.replicas.append(Replica(
                i, dev, params, exes, self._feed_names,
                self.batch_queue))
        for r in self.replicas:
            r.start()
        _m_replicas.set(len(self.replicas))

    def dispatch(self, micro_batch):
        """The scheduler's dispatch target: blocking put, so a saturated
        pool backpressures the batcher (and through it the bounded
        request queue) instead of queueing unboundedly."""
        self.batch_queue.put(micro_batch)

    def executables(self, device=None):
        """{bucket: executable} for ``device`` (default: first replica's
        device) — warm-boot introspection for tests and doctors."""
        if device is None:
            device = self.replicas[0].device
        return dict(self._by_device[device][1])

    def close(self, timeout=None):
        """Stop every replica after the in-queue batches drain.
        Returns True when every replica has exited; with a ``timeout``,
        False means some replica is still finishing (its batches will
        complete — call again). The gauge only zeroes on a TRUE stop.
        Idempotent: sentinels are enqueued once (a repeat close on the
        bounded queue must not block behind its own earlier
        sentinels)."""
        if not self._stopped:
            self._stopped = True
            for _ in self.replicas:
                self.batch_queue.put(_STOP)
        for r in self.replicas:
            r.join(timeout)
        if any(r.is_alive() for r in self.replicas):
            return False
        _m_replicas.set(0)
        return True
