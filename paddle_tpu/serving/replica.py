"""Multi-replica dispatch: the execution half of the serving subsystem.

Each :class:`Replica` owns a device, a device-resident copy of the
frozen program's params, and one ahead-of-time compiled XLA executable
per bucket of the ladder — compiled at **warm boot** (pool
construction), before the server accepts traffic, so the first real
request never pays a trace or an XLA compile. When the PR-2 persistent
compilation cache is armed (``PADDLE_TPU_CACHE_DIR``, wired at
``paddle_tpu.core`` import), warm boot itself is a disk read on every
boot after the first.

Replicas are fed from ONE shared batch queue (the scheduler's dispatch
target): a slow replica simply takes fewer batches, it cannot convoy
the others — the reference's multi-stream serving shape
(inference/api: one AnalysisPredictor clone per stream), with streams
replaced by device-pinned executables.

Device pinning uses sharding-annotated avals
(``jax.ShapeDtypeStruct(..., sharding=SingleDeviceSharding(dev))``), so
each replica's executables are compiled FOR its device and feeds are
``device_put`` onto it at dispatch; replicas that share a device (more
replicas than devices) share one executable map and one param copy —
the extra replicas then only add pipelining across the Python/dispatch
gap, which is exactly what they are for on a single-chip host.

**Resilience** (docs/SERVING.md "Resilience"): every replica
heartbeats per dispatch — the ``distributed/health.py`` idiom, with
mtime-touches replaced by in-process stamps (``busy_since``,
``current``) — and a supervisor thread in :class:`ReplicaPool`
watches them. A replica wedged mid-dispatch past ``replica_stall_ms``,
or whose thread died by uncaught exception, is **quarantined**: its
in-flight batch's riders are failed with a typed
:class:`~.resilience.ReplicaLostError` (never a silent hang), the
``serving_replica_state`` gauge tells the truth, and the slot is
**respawned** against the already-compiled executable map after a
capped exponential backoff. ``max_consecutive_stalls`` losses with no
successful batch in between permanently retire the slot and shrink
the pool — loudly. If every slot retires, the supervisor keeps
draining the batch queue and failing riders so no request ever hangs.
"""

import itertools
import queue
import sys
import threading
import time

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.monitor.registry import counter, gauge, histogram
from paddle_tpu.serving.resilience import ReplicaLostError, _log

__all__ = ["Replica", "ReplicaPool"]

_m_replicas = gauge(
    "serving_replicas",
    "Replica workers serving the shared batch queue (supervisor-owned "
    "truth: a dead or quarantined replica leaves this gauge, a "
    "respawned one re-enters)")
_m_exec_ms = histogram(
    "serving_batch_execute_ms",
    "Wall ms a replica spent executing one micro-batch (device_put + "
    "compiled call + host fetch)")
_m_state = gauge(
    "serving_replica_state",
    "Replica count by lifecycle state: up (draining the batch queue), "
    "quarantined (lost mid-dispatch, awaiting respawn backoff), "
    "retired (permanently removed after max_consecutive_stalls)",
    labels=("state",))
_m_respawns = counter(
    "serving_replica_respawns_total",
    "Replica worker threads respawned by the pool supervisor after a "
    "stall or thread death (against the already-compiled executable "
    "map — a respawn never recompiles)")
_m_param_bytes = gauge(
    "serving_param_bytes",
    "Device-resident model-parameter bytes per replica device of the "
    "LIVE pool (weight-quantized serving shrinks this ~4x for int8, "
    "2x for bf16 — docs/SERVING.md \"Quantized serving\")")

#: batch-queue sentinel, one per live replica at shutdown
_STOP = object()

#: monotonic pool tags scoping memory-ledger entities — two pools
#: coexist during a hot swap, so role alone cannot name residency
_POOL_SEQ = itertools.count()

#: replica lifecycle states (the serving_replica_state vocabulary)
_UP, _QUARANTINED, _RETIRED = "up", "quarantined", "retired"


def zero_pool_gauges():
    """Zero every pool gauge — a TRULY closed server has nothing up,
    nothing awaiting respawn, nothing newly retired. Called by a live
    pool's own close AND by the server's close epilogue: during a
    hot-swap rollback racing a close, the pool the server closes may
    already be demoted (role-gated zeroing skips it), so the server
    re-asserts gauge truth itself."""
    _m_replicas.set(0)
    _m_param_bytes.set(0)
    for s in (_UP, _QUARANTINED, _RETIRED):
        _m_state.set(0, state=s)


class Replica:
    """One worker: a device, resident params, per-bucket executables,
    and a thread draining the shared batch queue."""

    def __init__(self, index, device, params, executables, feed_names,
                 batch_queue, pool=None):
        self.index = index
        self.device = device
        self._params = params
        self._executables = executables
        self._feed_names = tuple(feed_names)
        self._q = batch_queue
        #: owning pool (None in direct unit-test construction) — the
        #: failure-attribution home: a batch failed HERE counts
        #: against THIS pool, which is what the hot-swap watchdog
        #: needs (the process-global error counter can't tell a new
        #: version's errors from the old pool's draining stragglers)
        self._pool = pool
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serving-replica-{index}")
        self.batches_run = 0
        # -- supervisor-visible health stamps (the health.py heartbeat
        # idiom, in-process: the attribute stores below are the mtime
        # touches, written once per dispatch) --
        #: perf_counter at the current batch's pickup, None while idle
        #: — a non-None value older than replica_stall_ms is a wedged
        #: dispatch (the stale_ranks asymmetry: only a replica that
        #: STARTED a batch and stopped progressing is hung; idle is
        #: idle, however long)
        self.busy_since = None
        #: the in-flight micro-batch, so the supervisor can fail its
        #: riders if this thread is lost
        self.current = None
        #: set by the supervisor at quarantine: the thread must stop
        #: taking work the moment it can observe the flag (its slot is
        #: respawned; two drainers would race the queue)
        self._abandoned = False
        #: distinguishes a clean _STOP exit from a death — the
        #: supervisor must not quarantine a replica that shut down
        self._exited_clean = False

    def start(self):
        self._thread.start()
        return self

    def join(self, timeout=None):
        self._thread.join(timeout)

    def is_alive(self):
        return self._thread.is_alive()

    def _loop(self):
        while True:
            mb = self._q.get()
            if self._abandoned:
                # quarantined while blocked in get(): this slot
                # belongs to the respawn now — hand back WHATEVER was
                # grabbed and bow out. The _abandoned check must come
                # before the sentinel check: at close() sentinels are
                # enqueued one per LIVE replica, and an abandoned
                # thread consuming one would leave a live replica
                # blocked in get() forever (close joins it forever)
                self._q.put(mb)
                break
            if mb is _STOP:
                self._exited_clean = True
                break
            t0 = time.perf_counter()
            # heartbeat-per-dispatch: current BEFORE busy_since here,
            # current cleared first in _idle — the supervisor's
            # unlocked read pair (batch, then stamp, both non-None +
            # stale) is sound under those write orders
            self.current = mb
            self.busy_since = t0
            # trace stamps only (dispatch_wait ends / execute starts
            # here; fakes enqueued by tests may lack the slots): the
            # per-request spans assemble from these at tail-sampling
            # keep time, so the serving hot path pays attribute
            # stores, never span construction
            stamped = hasattr(mb, "t_pick")
            if stamped:
                mb.t_pick = t0
                mb.tid_replica = threading.get_ident()
                mb.replica = self.index
            # dispatch-wait deadline stage: riders that expired while
            # the batch sat in the queue get their typed error here,
            # and a batch with NO live rider never consumes a dispatch
            if hasattr(mb, "expire_riders") and \
                    mb.expire_riders(now=t0) == 0:
                self._idle()
                if self._abandoned:
                    break
                continue
            try:
                outs = self.run_batch(mb.bucket, mb.feeds)
            except Exception as e:
                # deliver the failure to the batch's requests and keep
                # serving: one poisoned batch must not kill the replica
                self._note_failure()
                mb.fail(e)
                self._idle()
                if self._abandoned:
                    break
                continue
            if stamped:
                mb.t_exec = time.perf_counter()
            try:
                mb.complete(outs)
            except Exception as e:
                # complete() itself failed (e.g. an executable returned
                # a wrong leading dim): sweep the undelivered requests
                # with the error (first-wins delivery) and keep serving
                self._note_failure()
                mb.fail(e)
                self._idle()
                if self._abandoned:
                    break
                continue
            self.batches_run += 1
            self._idle()
            _m_exec_ms.observe((time.perf_counter() - t0) * 1e3)
            if self._abandoned:
                break

    def _idle(self):
        self.current = None
        self.busy_since = None

    def _note_failure(self):
        if self._pool is not None:
            self._pool._note_batch_failures()

    def run_batch(self, bucket, feeds):
        """Execute one padded batch dict on this replica's executable
        for ``bucket``; returns host arrays in fetch order."""
        import jax
        exe = self._executables.get(bucket)
        enforce(exe is not None,
                f"replica {self.index} has no executable for bucket "
                f"{bucket} (ladder {sorted(self._executables)})")
        fd = tuple(jax.device_put(feeds[n], self.device)
                   for n in self._feed_names)
        try:
            outs = exe(self._params, fd)
            return [np.asarray(o) for o in outs]
        except Exception as e:
            from paddle_tpu.monitor import memory as _memory
            if _memory.is_oom_error(e):
                # typed postmortem instead of a raw RESOURCE_EXHAUSTED
                # traceback; flows through _loop's failure handling to
                # mb.fail, so riders see the attributed error
                _memory.handle_oom(e, f"serving.replica/bucket{bucket}")
            raise


class ReplicaPool:
    """N replicas over the visible devices (round-robin), all draining
    one shared bounded batch queue. Construction IS the warm boot:
    every (device, bucket) executable compiles before this returns.

    ``pure_fn`` is the jittable ``fn(params_tuple, feeds_tuple) ->
    outputs_tuple`` from ``inference._build_pure_fn``; ``params_np``
    the state arrays in its order; ``sample_specs`` {feed name:
    (sample_shape, dtype)} fixing every non-batch dim.

    Resilience knobs (docs/SERVING.md "Resilience"):
    ``replica_stall_ms`` — a dispatch running longer than this is a
    wedge (quarantine + respawn); ``max_consecutive_stalls`` — losses
    with no successful batch in between before the slot permanently
    retires; ``respawn_backoff_ms`` — base of the capped (5s)
    exponential respawn backoff; ``supervise=False`` disables the
    supervisor thread entirely (the pre-resilience pool).

    ``role`` makes two pools coexist for the hot model swap
    (docs/SERVING.md "Hot model swap"): only the ``"live"`` pool
    publishes the ``serving_replicas``/``serving_replica_state``
    gauges — a ``"standby"`` pool warm-boots and drains its own queue
    silently (its supervisor still heals it), and ``promote()``/
    ``demote()`` hand gauge ownership over at cutover. A demoted
    pool's ``close()`` never zeroes the gauges the new live pool now
    owns."""

    def __init__(self, pure_fn, params_np, feed_names, sample_specs,
                 ladder, n_replicas=1, devices=None, queue_depth=None,
                 replica_stall_ms=30_000.0, max_consecutive_stalls=3,
                 respawn_backoff_ms=100.0, supervise=True, role="live"):
        import jax
        from jax.sharding import SingleDeviceSharding

        enforce(n_replicas >= 1, f"n_replicas < 1 ({n_replicas})")
        enforce(replica_stall_ms > 0,
                f"replica_stall_ms must be positive, got "
                f"{replica_stall_ms!r}")
        enforce(max_consecutive_stalls >= 1,
                f"max_consecutive_stalls must be >= 1, got "
                f"{max_consecutive_stalls!r}")
        enforce(respawn_backoff_ms >= 0,
                f"respawn_backoff_ms must be >= 0, got "
                f"{respawn_backoff_ms!r}")
        enforce(role in ("live", "standby"),
                f"role must be 'live' or 'standby', got {role!r}")
        self.role = role
        self._feed_names = tuple(feed_names)
        self.ladder = tuple(ladder)
        devices = list(devices if devices is not None else jax.devices())
        enforce(devices, "no devices visible for serving")
        if queue_depth is None:
            # deep enough that the batcher never stalls behind an idle
            # replica, shallow enough that batches don't age in queue
            queue_depth = max(2 * n_replicas, 2)
        self.batch_queue = queue.Queue(maxsize=queue_depth)
        #: bytes of ONE device's resident param copy — int8/bf16
        #: quantized bundles land here ~4x/2x smaller than fp32, the
        #: replicas-per-device headroom the quantized export buys
        #: (bench.py serving BENCH_SERVING_QUANT A/B reads this)
        self._param_bytes = int(sum(np.asarray(p).nbytes
                                    for p in params_np))
        #: per-bucket CompiledMemoryStats (one device's — buckets
        #: compile identically per device); feeds projected_bytes()
        #: and the memory ledger
        self._bucket_mem = {}
        self._pool_tag = f"pool{next(_POOL_SEQ)}"
        self._ledger_entities = ()
        jitted = jax.jit(pure_fn)
        self._by_device = {}        # device -> (params, {bucket: exe})
        for dev in {devices[i % len(devices)]: None
                    for i in range(n_replicas)}:
            sh = SingleDeviceSharding(dev)
            params = tuple(jax.device_put(np.asarray(p), dev)
                           for p in params_np)
            param_sds = tuple(
                jax.ShapeDtypeStruct(p.shape, p.dtype, sharding=sh)
                for p in params)
            exes = {}
            for bucket in self.ladder:
                feed_sds = tuple(
                    jax.ShapeDtypeStruct((bucket,) + tuple(shape),
                                         np.dtype(dtype), sharding=sh)
                    for shape, dtype in
                    (sample_specs[n] for n in self._feed_names))
                exes[bucket] = jitted.lower(param_sds,
                                            feed_sds).compile()
                if bucket not in self._bucket_mem:
                    try:
                        from paddle_tpu.monitor import memory as _memory
                        self._bucket_mem[bucket] = \
                            _memory.analyze_compiled(exes[bucket])
                    except Exception:
                        self._bucket_mem[bucket] = None
            self._by_device[dev] = (params, exes)
        self._ledger_publish()
        self._stopped = False
        #: True only after a TRUE close finished its final sweep — the
        #: dispatch() post-put sweep keys on it (see dispatch)
        self._closed_done = False
        #: batches this pool delivered as typed FAILURES (replica
        #: execution/complete errors, supervisor-failed in-flight
        #: batches, dead-pool/close sweeps) — per-pool attribution for
        #: the hot-swap watchdog; deadline expiries are load symptoms,
        #: not version faults, and don't count
        self.batch_failures = 0
        self._fail_lock = threading.Lock()
        self._stall_s = replica_stall_ms / 1e3
        self._max_stalls = int(max_consecutive_stalls)
        self._backoff_s = respawn_backoff_ms / 1e3
        self._lock = threading.Lock()
        self._slot_device = [devices[i % len(devices)]
                             for i in range(n_replicas)]
        self._states = [_UP] * n_replicas
        self._stall_counts = [0] * n_replicas
        self._respawn_due = {}          # slot -> monotonic due time
        self._live_at_close = []
        self._stops_pending = 0
        self._drained_dead_pool = False
        self.replicas = []
        for i in range(n_replicas):
            params, exes = self._by_device[self._slot_device[i]]
            self.replicas.append(Replica(
                i, self._slot_device[i], params, exes,
                self._feed_names, self.batch_queue, pool=self))
        for r in self.replicas:
            r.start()
        self._publish_states()
        self._sup_stop = threading.Event()
        self._supervisor = None
        if supervise:
            self._supervisor = threading.Thread(
                target=self._supervise, daemon=True,
                name="serving-supervisor")
            self._supervisor.start()

    # -- supervision -------------------------------------------------------
    def _publish_states(self):
        if self.role != "live":
            # a standby pool coexists with the live one during a hot
            # swap: publishing its counts would overwrite the live
            # pool's gauge truth with the not-yet-serving pool's
            return
        counts = {_UP: 0, _QUARANTINED: 0, _RETIRED: 0}
        for s in self._states:
            counts[s] += 1
        for s, c in counts.items():
            _m_state.set(c, state=s)
        # the supervisor owns gauge truth: serving_replicas is the
        # count actually draining the queue, not the count booted
        _m_replicas.set(counts[_UP])
        _m_param_bytes.set(self._param_bytes)

    def projected_bytes(self):
        """Per-device bytes this pool needs to co-reside: the worst
        bucket's compile-time peak estimate (params ride as arguments,
        so the estimate already covers them + feeds + temps + outputs)
        when the backend reported one, else the raw param bytes — the
        number swap admission projects BEFORE booting a standby."""
        peaks = [m.get("peak_bytes_estimate", 0.0)
                 for m in self._bucket_mem.values() if m]
        return int(max([self._param_bytes] + peaks))

    def _ledger_publish(self):
        """Attribute this pool's device residency in the memory
        ledger: params (summed across the pool's distinct devices) +
        each bucket executable's compile-time peak. Entities are
        scoped by the pool's own tag, NOT the role alone — during a
        hot swap two pools coexist and the ledger must show BOTH
        (that ~2x-param window is exactly what memory-aware admission
        guards). Never fatal — telemetry must not fail a boot or a
        cutover."""
        try:
            from paddle_tpu.monitor import memory as _memory
            self._ledger_drop()
            ndev = max(1, len(self._by_device))
            pre = f"serving/{self._pool_tag}:{self.role}"
            entities = {f"{pre}/params": self._param_bytes * ndev}
            for bucket, m in self._bucket_mem.items():
                if m:
                    entities[f"{pre}/bucket{bucket}"] = \
                        m.get("peak_bytes_estimate", 0.0)
            for e, b in entities.items():
                _memory.ledger_set(e, b)
            self._ledger_entities = tuple(entities)
        except Exception:
            pass

    def _ledger_drop(self):
        try:
            from paddle_tpu.monitor import memory as _memory
            for e in getattr(self, "_ledger_entities", ()):
                _memory.ledger_remove(e)
            self._ledger_entities = ()
        except Exception:
            pass

    def promote(self):
        """Standby -> live at hot-swap cutover: take gauge ownership
        and publish this pool's current states (flip and publish under
        the pool lock — see ``demote`` for why the serialization
        matters)."""
        with self._lock:
            self.role = "live"
            self._publish_states()
            self._ledger_publish()

    def demote(self):
        """Live -> draining-out at hot-swap cutover (or rollback of a
        freshly promoted standby): stop publishing gauges — the other
        pool owns them now — while the replicas keep draining whatever
        batches were already dispatched here. Taken under the pool
        lock so a supervisor mid-``_publish_states`` finishes BEFORE
        the role flips: an unserialized flip would let this pool's
        in-flight publish land after the new owner's, leaving the
        gauges describing the demoted pool until its next (never)
        state change."""
        with self._lock:
            self.role = "standby"
            # its residency is still real until release(): re-attribute
            # under the draining role rather than vanish from the ledger
            self._ledger_publish()

    def release(self):
        """Drop the device-resident param copies and executable maps
        after a TRUE close — the hot swap's ~2x-param-memory window
        ends here, when the drained old pool lets go. A released pool
        cannot respawn; only call once close() returned True."""
        self._ledger_drop()
        self._by_device.clear()
        for r in self.replicas:
            r._params = ()
            r._executables = {}

    def _supervise(self):
        """Detect wedged/dead replicas, quarantine, respawn (capped
        exponential backoff), retire after repeated stalls — and while
        the pool has NO live replica, drain the batch queue and fail
        riders so an accepted request can never hang on a dead pool."""
        poll = max(min(0.05, self._stall_s / 4.0), 0.005)
        while not self._sup_stop.wait(poll):
            now = time.perf_counter()
            mono = time.monotonic()
            to_fail = []            # (micro-batch, error) outside lock
            with self._lock:
                if self._stopped:
                    break
                for i, r in enumerate(self.replicas):
                    st = self._states[i]
                    if st == _QUARANTINED:
                        if mono >= self._respawn_due.get(i,
                                                         float("inf")):
                            self._respawn_locked(i)
                        continue
                    if st != _UP:
                        continue
                    if r.batches_run > 0 and self._stall_counts[i]:
                        # a batch has completed since the last loss:
                        # the stall streak is broken, the slot earned
                        # its consecutive-count back
                        self._stall_counts[i] = 0
                    if not r.is_alive() and not r._exited_clean:
                        to_fail.append(self._lose_locked(
                            i, r, "thread died by uncaught exception"))
                    elif r.busy_since is not None and \
                            now - r.busy_since > self._stall_s:
                        # re-validate before acting: the replica holds
                        # no pool lock, so between the check above and
                        # here it may have FINISHED the judged dispatch
                        # (and even picked a fresh batch). _loop's
                        # write orders (current before busy_since on
                        # pickup; current cleared before busy_since on
                        # idle) make this read pair sound: a fresh or
                        # ended dispatch shows a young/None busy_since
                        # or a None batch, and quarantining then would
                        # fail a HEALTHY batch's riders with spurious
                        # ReplicaLostError
                        mb = r.current
                        t2 = r.busy_since
                        if mb is not None and t2 is not None and \
                                now - t2 > self._stall_s:
                            to_fail.append(self._lose_locked(
                                i, r,
                                f"wedged mid-dispatch (> "
                                f"{self._stall_s * 1e3:.0f}ms)",
                                mb=mb))
                dead_pool = all(s == _RETIRED for s in self._states)
            for mb, exc in to_fail:
                if mb is not None and hasattr(mb, "fail"):
                    self._note_batch_failures()
                    mb.fail(exc)
            if dead_pool:
                self._drain_dead_pool()

    def _lose_locked(self, i, r, cause, mb=None):
        """Quarantine slot ``i`` (or retire it after max consecutive
        stalls); returns (in-flight batch, error) for the caller to
        fail OUTSIDE the pool lock. ``mb`` pins the judged batch for
        the stall path (re-validated by the caller); the dead-thread
        path reads whatever the corpse last held."""
        r._abandoned = True
        if mb is None:
            mb = r.current
        self._stall_counts[i] += 1
        cons = self._stall_counts[i]
        retire = cons >= self._max_stalls
        self._states[i] = _RETIRED if retire else _QUARANTINED
        if retire:
            up = sum(1 for s in self._states if s == _UP)
            _log(f"replica {i} {cause}; PERMANENTLY RETIRED after "
                 f"{cons} consecutive losses with no completed batch "
                 f"— pool shrinks to {up} live replica(s)"
                 + ("" if up else
                    " (ZERO live replicas: queued batches will be "
                    "failed, not hung — restart the server)"))
        else:
            backoff = min(self._backoff_s * (2 ** (cons - 1)), 5.0)
            self._respawn_due[i] = time.monotonic() + backoff
            _log(f"replica {i} {cause}; quarantined "
                 f"(consecutive losses: {cons}/{self._max_stalls}), "
                 f"failing its in-flight batch, respawn in "
                 f"{backoff * 1e3:.0f}ms")
        self._publish_states()
        exc = ReplicaLostError(
            f"serving replica {i} {cause}; its in-flight micro-batch "
            f"was failed by the pool supervisor and the replica was "
            f"{'retired' if retire else 'quarantined for respawn'} — "
            f"the request is safe to retry")
        return mb, exc

    def _note_batch_failures(self, n=1):
        with self._fail_lock:
            self.batch_failures += n

    def _respawn_locked(self, i):
        self._respawn_due.pop(i, None)
        dev = self._slot_device[i]
        params, exes = self._by_device[dev]     # warm: never recompiles
        nr = Replica(i, dev, params, exes, self._feed_names,
                     self.batch_queue, pool=self)
        self.replicas[i] = nr
        self._states[i] = _UP
        nr.start()
        _m_respawns.inc()
        self._publish_states()
        _log(f"replica {i} respawned against the warm executable map")

    def _fail_queued(self, why):
        """Drain the batch queue non-blocking, failing every rider
        with a typed ReplicaLostError — the shared no-hang backstop
        for a dead pool and for shutdown."""
        while True:
            try:
                mb = self.batch_queue.get_nowait()
            except queue.Empty:
                return
            if mb is not _STOP and hasattr(mb, "fail"):
                self._note_batch_failures()
                mb.fail(ReplicaLostError(why))

    def _drain_dead_pool(self):
        """Every slot retired: nothing will ever drain the batch
        queue, so the supervisor does — failing riders typed instead
        of letting accepted requests hang forever."""
        if not self._drained_dead_pool:
            self._drained_dead_pool = True
            _log("serving pool has ZERO live replicas; the supervisor "
                 "is draining the batch queue and failing riders")
        self._fail_queued(
            "serving pool has no live replicas (every slot "
            "permanently retired); the batch was failed without "
            "dispatch — restart the server")

    # -- dispatch ----------------------------------------------------------
    def dispatch(self, micro_batch):
        """The scheduler's dispatch target: blocking put, so a saturated
        pool backpressures the batcher (and through it the bounded
        request queue) instead of queueing unboundedly. The post-put
        sweep closes the hot-swap cutover's one standing race: the
        batcher can load THIS pool's dispatch, be descheduled, and put
        only after a committed swap's background drain fully closed
        the pool — nothing would ever consume that batch, so its
        riders would hang. If the pool is truly stopped, the batch is
        failed typed right here (first-wins delivery makes a double
        sweep harmless); the in-close window is covered by close()'s
        OWN final sweep, which runs after ``_closed_done`` is set."""
        self.batch_queue.put(micro_batch)
        if self._closed_done:
            self._fail_queued(
                "serving pool was already closed when this batch was "
                "dispatched (hot-swap drain completed); the batch was "
                "failed without dispatch — the request is safe to "
                "retry")

    def resident_param_bytes(self):
        """Bytes of one device-resident param copy (every replica
        device holds one) — the quantized-serving A/B's memory
        evidence."""
        return self._param_bytes

    def executables(self, device=None):
        """{bucket: executable} for ``device`` (default: first replica's
        device) — warm-boot introspection for tests and doctors."""
        if device is None:
            device = self.replicas[0].device
        return dict(self._by_device[device][1])

    def _judge_losses_at_close(self):
        """The supervisor is stopped for the whole close phase, so the
        drain carries its own loss handling ("no accepted request ever
        hangs" includes shutdown): a replica wedged past the stall
        threshold is failed+abandoned (never waited on), and one whose
        thread died mid-drain has its in-flight batch failed. Returns
        the replicas still draining."""
        now = time.perf_counter()
        remaining = []
        for r in self._live_at_close:
            if r._abandoned:
                continue
            if not r.is_alive():
                if not r._exited_clean and r.current is not None \
                        and hasattr(r.current, "fail"):
                    self._note_batch_failures()
                    r.current.fail(ReplicaLostError(
                        f"serving replica {r.index} thread died "
                        f"during shutdown with this batch in flight; "
                        f"the batch was failed — the request is safe "
                        f"to retry"))
                continue
            mb, t = r.current, r.busy_since
            if mb is not None and t is not None \
                    and now - t > self._stall_s:
                r._abandoned = True
                if hasattr(mb, "fail"):
                    self._note_batch_failures()
                    mb.fail(ReplicaLostError(
                        f"serving replica {r.index} wedged "
                        f"mid-dispatch during shutdown; its in-flight "
                        f"batch was failed — the request is safe to "
                        f"retry"))
                continue
            remaining.append(r)
        return remaining

    def close(self, timeout=None):
        """Stop every live replica after the in-queue batches drain.
        Returns True when every live replica has exited; with a
        ``timeout``, False means some replica is still finishing (its
        batches will complete — call again). The gauge only zeroes on
        a TRUE stop. Idempotent — sentinels are budgeted once, for the
        replicas LIVE at first close. The drain is a poll loop, not a
        bare join: the supervisor is already stopped, so close itself
        must keep judging losses (a replica that wedges past
        ``replica_stall_ms`` or dies MID-DRAIN gets its riders failed
        and stops gating the close), and sentinels are enqueued
        non-blocking as capacity appears — a blocking put on a queue
        whose only consumers are lost would ignore ``timeout``
        forever."""
        if not self._stopped:
            self._sup_stop.set()
            with self._lock:
                self._stopped = True
                self._live_at_close = [
                    r for i, r in enumerate(self.replicas)
                    if self._states[i] == _UP]
                self._stops_pending = len(self._live_at_close)
            if self._supervisor is not None:
                self._supervisor.join(5)
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            remaining = self._judge_losses_at_close()
            while self._stops_pending > 0:
                try:
                    self.batch_queue.put_nowait(_STOP)
                except queue.Full:
                    break
                self._stops_pending -= 1
            if not remaining:
                # no consumer left to need a sentinel: drained (or
                # every drainer lost — the sweep below covers both)
                self._stops_pending = 0
                break
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.005)
        # true stop: nothing will ever drain the queue again. Set the
        # flag BEFORE the final sweep so a dispatch racing this close
        # either lands before the sweep (swept here) or sees the flag
        # and sweeps itself — either way its riders get a typed error,
        # never silence.
        self._closed_done = True
        self._ledger_drop()
        self._fail_queued(
            "serving pool closed with this batch undispatched (no "
            "live replica remained to run it)")
        if self.role == "live":
            # gauge truth on the way out: a closed pool has nothing
            # up, nothing awaiting respawn, nothing newly retired — a
            # stale {quarantined}=1 on a dead server would read as a
            # respawn that can never come. A DEMOTED pool draining out
            # after a hot-swap cutover skips this: the promoted pool
            # owns the gauges now.
            zero_pool_gauges()
        return True
