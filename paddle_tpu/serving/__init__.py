"""High-QPS serving subsystem (docs/SERVING.md).

The reference devotes a whole side stack to serving
(``paddle/fluid/inference/``, AnalysisPredictor and its multi-thread
clone contract); this package is its TPU-native shape: continuous
micro-batching over per-bucket ahead-of-time compiled XLA executables,
multi-replica dispatch from one shared queue, warm-boot compile
preloading, and per-request SLO telemetry riding ``paddle_tpu.monitor``.

Layering: ``resilience`` (typed failure vocabulary + shed controller —
stdlib only), ``scheduler`` (queueing/batching — numpy + stdlib only),
``replica`` (device-pinned execution + pool supervisor), ``server``
(front-end), ``swap`` (zero-downtime hot model swap: gate → standby
warm-boot → canary → atomic cutover → watchdog/rollback, plus a
watch-dir continuous-deploy mode — docs/SERVING.md "Hot model swap").
The single-request ``paddle_tpu.inference.Predictor`` remains the
simple embedded path; this package is the "millions of users" one —
and it fails TYPED: request deadlines, replica quarantine/respawn,
adaptive load shedding, and supervised reversible deploys are
documented in docs/SERVING.md. ``frontdoor`` extends the same typed
discipline to the network boundary: HTTP/1.1 over ``submit`` with
wire-to-device deadline propagation, per-tenant admission, connection
robustness and graceful drain (docs/SERVING.md "Front door").
"""

from paddle_tpu.serving.resilience import (  # noqa: F401
    DeadlineExceededError, OverloadedError, ReplicaLostError,
    ShedController, SwapFailedError, SwapWatchdog, TenantFairShare,
)
from paddle_tpu.serving.scheduler import (  # noqa: F401
    MicroBatch, MicroBatchScheduler, PendingResult, QueueFullError,
    ServerClosedError, ServerDrainingError, bucket_ladder, pick_bucket,
)
from paddle_tpu.serving.replica import Replica, ReplicaPool  # noqa: F401
from paddle_tpu.serving.server import (  # noqa: F401
    InferenceServer, ServingConfig,
)
from paddle_tpu.serving.swap import SwapController  # noqa: F401
from paddle_tpu.serving.frontdoor import (  # noqa: F401
    FrontDoorConfig, HttpFrontDoor, WireClient, WireReset,
)

__all__ = [
    "InferenceServer", "ServingConfig", "MicroBatchScheduler",
    "MicroBatch", "PendingResult", "Replica", "ReplicaPool",
    "QueueFullError", "ServerClosedError", "ServerDrainingError",
    "DeadlineExceededError", "OverloadedError", "ReplicaLostError",
    "ShedController", "TenantFairShare",
    "SwapController", "SwapFailedError", "SwapWatchdog",
    "FrontDoorConfig", "HttpFrontDoor", "WireClient", "WireReset",
    "bucket_ladder", "pick_bucket",
]
