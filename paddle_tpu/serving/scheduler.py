"""Continuous micro-batching scheduler: the queueing half of the
serving subsystem (docs/SERVING.md).

Concurrent requests are coalesced into padded-bucket micro-batches over
a power-of-two bucket ladder: a request of 3 rows rides the 4-bucket,
the pad rows are zeros, and the waste is accounted
(``serving_padded_waste_total``) rather than hidden. The bucket ladder
exists because each bucket has its OWN ahead-of-time compiled XLA
executable (replica.py) — serving an arbitrary batch size would retrace
and recompile per request shape, which is exactly what a latency SLO
cannot afford.

Scheduling contract, in order of priority:

1. **A lone request is never starved.** The batcher waits at most
   ``max_wait_ms`` past the FIRST request of a forming batch; when the
   deadline fires the batch dispatches at whatever fill it reached.
2. **A full batch never waits.** As soon as the forming batch reaches
   the top bucket it dispatches immediately; a request that would
   overflow the bucket carries over to start the next batch.
3. **Backpressure is typed.** The request queue is bounded
   (``max_queue``); ``submit`` on a full queue raises
   :class:`QueueFullError` (counted ``outcome="rejected"``) instead of
   stretching the tail latency of every queued request behind it.
4. **Shutdown drains.** ``close()`` stops admission, then processes
   every already-accepted request before the batcher exits — an
   accepted request always gets a result or an error, never silence.

The scheduler is executor-agnostic: it hands formed
:class:`MicroBatch` objects to a ``dispatch`` callable (the server
wires this to the shared replica batch queue; tests wire a fake) and
the batch completes via ``MicroBatch.complete``/``fail`` from whatever
thread ran it. That keeps this module import-light (numpy + stdlib) and
unit-testable without jax.

Distributed tracing (``monitor.trace``, docs/OBSERVABILITY.md): each
request can carry a span tree ``request -> queue_wait -> batch_form ->
dispatch_wait -> execute -> deliver``. The HOT PATH only stamps
per-batch timestamps (``MicroBatch._TRACE_STAMPS``); the tail-sampling
screen runs once per batch at delivery, and only kept traces
materialize spans retroactively — so tracing costs the request path a
handful of attribute stores and compares, not span construction.
"""

import queue
import threading
import time

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.monitor import trace as _trace
from paddle_tpu.monitor.registry import counter, gauge, histogram
from paddle_tpu.serving.resilience import (
    DeadlineExceededError, OverloadedError,
)

__all__ = [
    "QueueFullError", "ServerClosedError", "ServerDrainingError",
    "PendingResult", "MicroBatch", "MicroBatchScheduler",
    "bucket_ladder", "pick_bucket",
]


class QueueFullError(RuntimeError):
    """``submit`` refused: the bounded request queue is full. The
    caller should shed load or retry after backoff — queueing deeper
    would only move the failure into every request's tail latency."""


class ServerClosedError(RuntimeError):
    """``submit`` refused: the server is shutting down (or never
    started). Already-accepted requests still drain to completion."""


class ServerDrainingError(ServerClosedError):
    """``submit`` refused: the server is DRAINING (``begin_drain()``)
    — a deliberate, bounded wind-down ahead of a restart or deploy,
    not the terminal close. Subclassing :class:`ServerClosedError`
    keeps existing closed-handlers working unchanged, while callers
    that can route traffic (the HTTP front door, a multi-server
    client) read ``retryable`` and retry AGAINST ANOTHER SERVER after
    backoff: this one's already-accepted requests still complete, but
    it will not take new work again."""

    retryable = True


_m_requests = counter(
    "serving_requests_total",
    "Serving requests by outcome: ok (result delivered), rejected "
    "(typed backpressure at submit), error (replica/scheduler failure "
    "delivered as an exception), deadline (request deadline exceeded "
    "at admission/batch-formation/dispatch-wait/delivery), shed "
    "(refused by the adaptive brownout controller)",
    labels=("outcome",))
_m_latency = histogram(
    "serving_request_latency_ms",
    "End-to-end serving request latency in wall ms: submit accept -> "
    "result ready (queue wait + batching wait + execute); p50/p99 "
    "derive from the buckets")
_m_queue_depth = gauge(
    "serving_queue_depth",
    "Requests currently waiting in the serving request queue "
    "(admitted, not yet batched)")
_m_fill = histogram(
    "serving_batch_fill_ratio",
    "Real rows / bucket size per dispatched micro-batch (1.0 = no "
    "padding; persistently low = lower the bucket ladder or raise "
    "max_wait_ms)",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_m_padded = counter(
    "serving_padded_waste_total",
    "Pad rows dispatched to round micro-batches up to their bucket "
    "(compute spent on zeros)")
_m_batches = counter(
    "serving_batches_total",
    "Micro-batches dispatched to the replica pool")


def bucket_ladder(max_batch):
    """The power-of-two bucket ladder ``(1, 2, 4, ..., max_batch)``.
    ``max_batch`` must itself be a power of two — every ladder rung is
    a compiled executable, and a non-power top rung would make the
    ladder's coverage/waste story shape-dependent."""
    enforce(isinstance(max_batch, int) and max_batch >= 1,
            f"max_batch must be a positive int, got {max_batch!r}")
    enforce(max_batch & (max_batch - 1) == 0,
            f"max_batch must be a power of two (one AOT executable per "
            f"ladder rung), got {max_batch}")
    out, b = [], 1
    while b <= max_batch:
        out.append(b)
        b *= 2
    return tuple(out)


def pick_bucket(rows, ladder):
    """Smallest ladder bucket holding ``rows`` rows."""
    enforce(rows >= 1, f"empty request (rows={rows})")
    enforce(rows <= ladder[-1],
            f"request of {rows} rows exceeds the top bucket "
            f"{ladder[-1]}; raise max_batch or split the request")
    for b in ladder:
        if rows <= b:
            return b
    raise AssertionError("unreachable")  # pragma: no cover


class PendingResult:
    """Future-like handle for one submitted request. ``result()``
    blocks until the micro-batch carrying the request completes and
    returns the outputs in fetch order (each with this request's
    leading rows), or raises the delivered error. When tracing is on
    (``monitor.trace``) and this request's trace was KEPT by tail
    sampling (errors, slow/exemplar requests, the head-sampled rate —
    every request at ``sample_rate=1.0``), ``trace_id`` names its span
    tree; None otherwise. The trace is materialized retroactively at
    delivery, so read it after ``result()``."""

    __slots__ = ("_event", "_outs", "_error", "t_done", "trace_id",
                 "_claim")

    def __init__(self):
        self._event = threading.Event()
        self._outs = None
        self._error = None
        self.t_done = None          # perf_counter at completion
        self.trace_id = None        # monitor.trace id (kept traces)
        self._claim = threading.Lock()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serving request not completed within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._outs

    def claim(self):
        """Atomically win the right to deliver this request — first
        wins, losers get False and must deliver NOTHING. The claim
        (not ``done()``) is the delivery arbiter: ``complete`` racing
        ``fail`` on another thread would otherwise both pass a
        ``done()`` pre-check and materialize two traces for one
        request, with ``trace_id`` naming whichever finished last —
        possibly an "ok" tree for a request that was delivered the
        error. The winner may do pre-wake work (retroactive trace
        assembly, so ``trace_id`` is readable the moment ``result()``
        returns) and MUST then call ``_deliver(claimed=True)``."""
        return self._claim.acquire(False)

    def _deliver(self, outs=None, error=None, claimed=False):
        """First delivery wins: a failure-path sweep (``MicroBatch.
        fail`` after a partial ``complete``) must not overwrite a
        result a caller may already be reading. Returns whether this
        call delivered."""
        if not claimed and not self.claim():
            return False
        self._outs = outs
        self._error = error
        self.t_done = time.perf_counter()
        self._event.set()
        return True


class _Request:
    __slots__ = ("feeds", "rows", "t_enqueue", "pending", "deadline",
                 "deadline_ms", "trace_attrs")

    def __init__(self, feeds, rows, deadline=None, deadline_ms=None,
                 trace_attrs=None):
        self.feeds = feeds
        self.rows = rows
        self.t_enqueue = time.perf_counter()
        self.pending = PendingResult()
        #: absolute perf_counter second past which this request is
        #: dead (anchored at submit ENTRY — the client's clock), or
        #: None for no deadline; deadline_ms kept for error messages
        self.deadline = deadline
        self.deadline_ms = deadline_ms
        #: caller-attributed trace attrs (the front door stamps the
        #: tenant id here); None — the in-process default — costs the
        #: hot path one attribute store and nothing at delivery
        self.trace_attrs = trace_attrs

    def expired(self, now=None):
        return self.deadline is not None and \
            (time.perf_counter() if now is None else now) >= self.deadline


def _deadline_error(req, stage, now=None):
    now = time.perf_counter() if now is None else now
    return DeadlineExceededError(
        f"request deadline {req.deadline_ms:g}ms exceeded at {stage} "
        f"({(now - req.t_enqueue) * 1e3:.1f}ms since submit); the "
        f"request was failed without consuming further serving work")


def _trace_root_error(t0, attrs=None):
    """Keep a root-only error trace for a request that never joined a
    batch (no stamps, no phases — errors are always kept). ``attrs``
    (e.g. the front door's tenant id) land on the root span. Returns
    the trace id, or None when tracing is off or telemetry failed —
    telemetry must never block delivery of a claimed request."""
    if not _trace._enabled:
        return None
    try:
        ctx = _trace.start_trace("serving/request")
        ctx.t0 = t0
        if attrs:
            ctx.attrs.update(attrs)
        _trace.end_trace(ctx, error=True)
        return ctx.trace_id
    except Exception:
        return None


def _fail_request(r, exc, outcome):
    """Deliver a typed failure to one request OUTSIDE any formed
    micro-batch (queue-time deadline expiry, formation-time drop):
    claims first-wins, keeps a root-only error trace, counts the
    outcome. Returns whether this call delivered."""
    if not r.pending.claim():
        return False
    r.pending.trace_id = _trace_root_error(
        r.t_enqueue, getattr(r, "trace_attrs", None))
    r.pending._deliver(error=exc, claimed=True)
    _m_requests.inc(outcome=outcome)
    return True


class MicroBatch:
    """A formed batch: requests concatenated along dim 0 and
    zero-padded up to ``bucket`` rows. ``feeds`` is the padded
    {name: array} the executor runs; ``complete(outs)`` slices each
    output back to per-request rows and delivers every pending result
    (latency observed per request); ``fail(exc)`` delivers the
    exception to every request instead."""

    #: per-batch trace timestamps, stamped by whatever thread ran the
    #: phase (batcher: form; replica: pick/execute). Per-REQUEST spans
    #: derive from these at tail-sampling KEEP time only
    #: (_assemble_trace) — the hot path pays attribute stores, never
    #: span construction.
    _TRACE_STAMPS = ("t_form", "t_formed", "t_dispatch", "t_pick",
                     "t_exec", "tid_batcher", "tid_replica", "replica")

    def __init__(self, requests, bucket, feed_names):
        self.requests = list(requests)
        self.bucket = int(bucket)
        for n in self._TRACE_STAMPS:
            setattr(self, n, None)
        self.rows = sum(r.rows for r in self.requests)
        enforce(self.rows <= self.bucket,
                f"batch of {self.rows} rows formed for bucket "
                f"{self.bucket}")
        self.feed_names = tuple(feed_names)
        self.feeds = {}
        pad = self.bucket - self.rows
        for n in self.feed_names:
            parts = [r.feeds[n] for r in self.requests]
            if pad:
                parts.append(np.zeros((pad,) + parts[0].shape[1:],
                                      dtype=parts[0].dtype))
            # the exact-fit single-request alias is safe: request
            # feeds are already PRIVATE copies (ownership taken at
            # submit in _validate)
            self.feeds[n] = (parts[0] if len(parts) == 1
                             else np.concatenate(parts, axis=0))

    def complete(self, outs):
        """``outs``: sequence of arrays in fetch order, leading dim ==
        bucket. Routes each request its own row slice."""
        now = time.perf_counter()
        outs = [np.asarray(o) for o in outs]
        for o in outs:
            enforce(o.shape[:1] == (self.bucket,),
                    f"micro-batch output leading dim {o.shape[:1]} != "
                    f"bucket {self.bucket}")
        hint = None
        if _trace._enabled and self.requests:
            # the whole trace is RETROACTIVE, and the tail screen runs
            # ONCE per micro-batch: the riders share the execute
            # window, the FIRST rider (FIFO formation) carries the max
            # latency, and only screened-in batches (head-sampled,
            # slow-reservoir/exemplar candidates — a few percent)
            # materialize contexts and assemble spans from the batch
            # stamps, BEFORE the _deliver wakes (the woken clients
            # contend for the GIL the moment the events set). The
            # exemplar force-keeps the slowest request's tree so the
            # SLO histogram's trace_id always dereferences.
            lat0 = (now - self.requests[0].t_enqueue) * 1e3
            hint = _trace.tail_candidate(
                "serving_request_latency_ms", lat0, lat0 / 1e3,
                count=len(self.requests))
        off = 0
        for r in self.requests:
            sliced = [o[off:off + r.rows] for o in outs]
            lat_ms = (now - r.t_enqueue) * 1e3
            # delivery-stage deadline: the result exists, but past the
            # deadline it is useless to the caller — the SLO contract
            # says fail typed, not hand back a late answer
            if r.expired(now):
                self._fail_one(r, _deadline_error(r, "delivery", now),
                               outcome="deadline")
                off += r.rows
                continue
            # claim BEFORE trace assembly: the claim is the first-wins
            # arbiter against a racing fail(), so exactly one thread
            # materializes exactly one trace — and it is the thread
            # whose outcome the client actually receives
            if r.pending.claim():
                if hint is not None:
                    self._finish_trace(r, lat_ms, now, hint=hint)
                r.pending._deliver(outs=sliced, claimed=True)
                _m_requests.inc(outcome="ok")
                _m_latency.observe(lat_ms)
            off += r.rows

    def _finish_trace(self, r, lat_ms, t_deliver0, error=None,
                      hint=None):
        """Retroactive trace materialization for one delivered request
        of a screened-in batch (``hint`` from the per-batch
        ``tail_candidate``). ``error`` skips the screen entirely —
        errors are always kept."""
        if error is None and hint is None:
            return
        try:
            ctx = _trace.start_trace("serving/request")
            ctx.t0 = r.t_enqueue
            r_attrs = getattr(r, "trace_attrs", None)
            if r_attrs:
                # caller attribution (front-door tenant id): on the
                # ROOT span, so a tenant's p99 is queryable
                # socket-to-device from the kept trees
                ctx.attrs.update(r_attrs)
            if error is None:
                # the per-batch screen already consumed this request's
                # sampling credit — end_trace must not count it again
                ctx.screened = True
                if hint == "sampled":
                    ctx.keep_reason = "sampled"
                _trace.record_exemplar("serving_request_latency_ms",
                                       lat_ms, ctx)
            reason = _trace.end_trace(
                ctx, error=error is not None,
                assemble=lambda c: self._assemble_trace(
                    c, r, t_deliver0,
                    None if error is not None else time.perf_counter()))
            if reason is not None:
                # only a trace that was actually kept is worth handing
                # to the client — a dropped candidate's id dereferences
                # to nothing
                r.pending.trace_id = ctx.trace_id
        except Exception:
            # telemetry must not break delivery: this runs INSIDE the
            # claim->_deliver window, and an escaped exception would
            # strand the claimed request forever (no sweep can re-claim
            # it, so result() would never wake)
            pass

    def _assemble_trace(self, ctx, r, t_deliver0, t_done):
        """Materialize one request's span tree from the batch-level
        timestamps — invoked by ``end_trace`` ONLY for kept traces.
        Each span carries the tid of the thread that actually ran its
        phase (stamped alongside the timestamps), so the cross-thread
        story in the timeline stays truthful even though assembly runs
        on the delivering thread. Phases whose stamps are missing
        (fail before pickup) are simply absent."""
        if self.t_form is not None:
            _trace.record_span(ctx, "serving/queue_wait",
                               r.t_enqueue, self.t_form,
                               tid=self.tid_batcher)
            _trace.record_span(
                ctx, "serving/batch_form", self.t_form, self.t_formed,
                tid=self.tid_batcher,
                attrs={"bucket": self.bucket, "rows": self.rows,
                       "fill": round(self.rows / self.bucket, 4),
                       "pad_rows": self.bucket - self.rows})
        if self.t_pick is not None:
            _trace.record_span(
                ctx, "serving/dispatch_wait",
                self.t_dispatch if self.t_dispatch is not None
                else self.t_pick,
                self.t_pick, tid=self.tid_replica,
                attrs={"replica": self.replica})
        if self.t_exec is not None:
            _trace.record_span(
                ctx, "serving/execute", self.t_pick, self.t_exec,
                tid=self.tid_replica,
                attrs={"replica": self.replica,
                       "bucket": self.bucket})
        if t_done is not None:
            _trace.record_span(ctx, "serving/deliver", t_deliver0,
                               t_done)

    def fail(self, exc):
        """Deliver ``exc`` to every request not already delivered —
        safe to call after a partial ``complete`` (first-wins), so an
        executor failure can always sweep the stragglers."""
        for r in self.requests:
            self._fail_one(r, exc, outcome="error")

    def _fail_one(self, r, exc, outcome):
        """Typed failure for one rider of THIS batch: first-wins claim,
        error trace carrying whatever phase stamps exist (errors are
        always kept), delivery, outcome accounting. Returns whether
        this call delivered."""
        if not r.pending.claim():   # first-wins vs a racing complete()
            return False
        if _trace._enabled:
            self._finish_trace(r, None, None, error=exc)
        r.pending._deliver(error=exc, claimed=True)
        _m_requests.inc(outcome=outcome)
        return True

    def expire_riders(self, now=None, stage="dispatch-wait"):
        """Fail every undelivered rider whose deadline has passed with
        a typed :class:`DeadlineExceededError` (``outcome="deadline"``,
        trace kept) and return the count of undelivered LIVE riders
        remaining. The replica calls this at pickup: a batch whose
        every rider is already dead must never consume a dispatch —
        the executable run would compute answers nobody can use."""
        now = time.perf_counter() if now is None else now
        live = 0
        for r in self.requests:
            if r.pending.done():
                continue
            if r.expired(now):
                self._fail_one(r, _deadline_error(r, stage, now),
                               outcome="deadline")
            else:
                live += 1
        return live


#: queue sentinel: admission is closed and everything before it has
#: been admitted — the batcher drains up to here, then exits
_STOP = object()


class MicroBatchScheduler:
    """The continuous batcher. ``dispatch(micro_batch)`` is called from
    the batcher thread for every formed batch; it must arrange for
    ``micro_batch.complete``/``fail`` to run eventually (inline is
    fine). ``sample_specs``: optional {feed name: (sample_shape tuple,
    np.dtype)} validated at submit so a malformed request fails ITSELF
    with a precise error instead of poisoning a whole micro-batch."""

    def __init__(self, dispatch, feed_names, max_batch=8,
                 max_wait_ms=5.0, max_queue=256, sample_specs=None,
                 default_deadline_ms=None, shed=None):
        self._dispatch = dispatch
        self._feed_names = tuple(feed_names)
        self._ladder = bucket_ladder(max_batch)
        self._max_bucket = self._ladder[-1]
        enforce(max_wait_ms >= 0, f"max_wait_ms < 0 ({max_wait_ms})")
        self._max_wait = max_wait_ms / 1e3
        enforce(max_queue >= 1, f"max_queue < 1 ({max_queue})")
        self._max_queue = max_queue
        enforce(default_deadline_ms is None
                or float(default_deadline_ms) > 0,
                f"default_deadline_ms must be positive or None, got "
                f"{default_deadline_ms!r}")
        self._default_deadline_ms = (None if default_deadline_ms is None
                                     else float(default_deadline_ms))
        #: resilience.ShedController (or None = shedding off; off is
        #: the default and takes the exact legacy admission path)
        self._shed = shed
        self._q = queue.Queue(maxsize=max_queue + 1)  # +1: _STOP always fits
        self._specs = dict(sample_specs or {})
        self._closed = False
        self._draining = False
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-batcher")
        self._started = False

    @property
    def ladder(self):
        return self._ladder

    @property
    def draining(self):
        return self._draining

    def begin_drain(self):
        """Flip admission into DRAINING: every subsequent ``submit``
        refuses with the retryable :class:`ServerDrainingError` while
        already-accepted requests keep flowing to completion — the
        reversible first half of a graceful shutdown (``close()`` is
        the terminal second half, and still drains the same way).
        Idempotent; returns whether THIS call flipped the state (False
        when already draining or closed)."""
        with self._lock:
            if self._draining or self._closed:
                return False
            self._draining = True
        return True

    def set_dispatch(self, dispatch):
        """Retarget batch dispatch — the hot-swap cutover primitive
        (serving/swap.py). The batcher reads the target exactly ONCE
        per formed batch (a single GIL-atomic attribute load in
        ``_form_and_dispatch``), so the flip lands at a batch
        boundary: every micro-batch executes WHOLLY on the target it
        was dispatched to, never split across the old and new model
        version. Requests admitted mid-swap simply form batches
        against whichever target is current at their formation
        instant."""
        self._dispatch = dispatch

    def start(self):
        with self._lock:
            if self._closed:
                # a resurrected batcher would have no _STOP coming and
                # the next close() would join it forever
                raise ServerClosedError(
                    "serving scheduler already closed")
            if not self._started:
                self._started = True
                self._thread.start()
        return self

    # -- admission ---------------------------------------------------------
    def _validate_deadline(self, deadline_ms):
        """Argument validation for ``deadline_ms`` — runs with the
        feed validation, BEFORE any server-state check, so a malformed
        argument is a deterministic typed EnforceNotMet whether the
        server is open, closed, or mid-brownout. None means "use the
        configured default"; 0 is a legal already-exhausted budget
        (it expires at admission, with the deadline outcome — useful
        for propagated upstream deadlines)."""
        if deadline_ms is None:
            return self._default_deadline_ms
        enforce(isinstance(deadline_ms,
                           (int, float, np.integer, np.floating))
                and not isinstance(deadline_ms, bool)
                and float(deadline_ms) >= 0,   # also rejects NaN
                f"deadline_ms must be a non-negative number of "
                f"milliseconds, got {deadline_ms!r}")
        return float(deadline_ms)

    def _validate(self, feeds):
        missing = [n for n in self._feed_names if n not in feeds]
        enforce(not missing, f"request missing feeds: {missing}")
        arrs = {n: np.asarray(feeds[n]) for n in self._feed_names}
        rows = None
        for n, a in arrs.items():
            enforce(a.ndim >= 1,
                    f"feed {n!r} must carry a leading batch dim")
            if rows is None:
                rows = int(a.shape[0])
            else:
                enforce(int(a.shape[0]) == rows,
                        f"feed {n!r} rows {a.shape[0]} != {rows} (all "
                        f"feeds of one request share the batch dim)")
            spec = self._specs.get(n)
            if spec is not None:
                shape, dtype = spec
                enforce(tuple(a.shape[1:]) == tuple(shape),
                        f"feed {n!r} sample shape {tuple(a.shape[1:])} "
                        f"!= served model's {tuple(shape)}")
            else:
                dtype = a.dtype
            # the request takes OWNERSHIP here: submit is async, so
            # aliasing the caller's buffer would let a post-submit
            # overwrite change this request's answer in flight
            # (astype/np.array both copy)
            arrs[n] = (a.astype(dtype) if a.dtype != dtype
                       else np.array(a))
        # bucket-fit check runs through pick_bucket for the precise
        # message; rows >= 1 enforced there too
        pick_bucket(rows, self._ladder)
        return arrs, rows

    def submit(self, feeds, deadline_ms=None, trace_attrs=None):
        """Admit one request ({feed name: array with leading batch
        dim}); returns a :class:`PendingResult`. ``deadline_ms``
        bounds the request end to end (None = the scheduler's
        ``default_deadline_ms``; 0 = already exhausted).
        ``trace_attrs`` (optional dict) rides the request's kept trace
        as root-span attributes — the front door stamps the tenant id
        here. Failure precedence, deterministic regardless of server
        state: malformed arguments (bad feed, negative deadline, non-
        dict trace_attrs) raise ``EnforceNotMet`` first; then
        :class:`ServerClosedError` (with the retryable
        :class:`ServerDrainingError` subclass during a drain); then
        :class:`DeadlineExceededError` (admission-stage expiry,
        ``outcome="deadline"``); then
        :class:`~.resilience.OverloadedError` (adaptive shed,
        ``outcome="shed"``); then :class:`QueueFullError`
        (``outcome="rejected"``)."""
        t_adm = time.perf_counter()
        # ALL argument validation before any state check: a malformed
        # request must fail the same typed way on a closed server as
        # on an open one (satellite-pinned precedence)
        arrs, rows = self._validate(feeds)
        deadline_ms = self._validate_deadline(deadline_ms)
        enforce(trace_attrs is None or isinstance(trace_attrs, dict),
                f"trace_attrs must be a dict or None, got "
                f"{type(trace_attrs).__name__}")
        deadline = (None if deadline_ms is None
                    else t_adm + deadline_ms / 1e3)
        with self._lock:
            if self._closed or not self._started:
                raise ServerClosedError(
                    "serving scheduler is closed" if self._closed
                    else "serving scheduler not started")
            if self._draining:
                # draining beats deadline/shed/queue checks: the
                # verdict is about THIS server's lifecycle, and the
                # retryable type tells the caller to take the request
                # elsewhere rather than burn its remaining budget here
                raise ServerDrainingError(
                    "serving scheduler is draining (begin_drain); "
                    "already-accepted requests are completing — retry "
                    "against another server")
            if deadline is not None and \
                    time.perf_counter() >= deadline:
                # admission-stage expiry (deadline_ms=0, or a budget
                # so tight validation ate it): typed, counted, and the
                # trace kept (errors-always-kept) — no queue slot, no
                # batch, no dispatch ever spent on it
                _m_requests.inc(outcome="deadline")
                _trace_root_error(t_adm, trace_attrs)
                raise DeadlineExceededError(
                    f"request deadline {deadline_ms:g}ms already "
                    f"exceeded at admission; nothing was enqueued")
            if self._shed is not None:
                reason = self._shed.should_shed(deadline_ms,
                                                self._q.qsize())
                if reason is not None:
                    _m_requests.inc(outcome="shed")
                    raise OverloadedError(
                        f"request shed at admission ({reason}): "
                        f"queue-wait p50 "
                        f"{self._shed.p50_wait_ms:.1f}ms already "
                        f"exceeds the headroom of a "
                        f"{deadline_ms:g}ms deadline — slow down or "
                        f"route elsewhere until serving_brownout "
                        f"clears")
            if self._q.qsize() >= self._max_queue:
                _m_requests.inc(outcome="rejected")
                raise QueueFullError(
                    f"serving queue full (max_queue={self._max_queue}); "
                    f"shed load or retry after backoff")
            # constructed AFTER admission: a shed request must not pay
            # the Event/Lock allocation, and t_enqueue (the batcher's
            # max_wait deadline anchor AND the latency-metric origin)
            # must not start ticking while submit contends for the lock
            req = _Request(arrs, rows, deadline=deadline,
                           deadline_ms=deadline_ms,
                           trace_attrs=trace_attrs)
            self._q.put_nowait(req)
        _m_queue_depth.set(self._q.qsize())
        return req.pending

    def close(self, timeout=None):
        """Stop admission, drain every accepted request, join the
        batcher. Returns True when the batcher has fully drained and
        exited; with a ``timeout``, False means the join expired while
        the drain is STILL RUNNING (accepted requests will complete —
        call again, or wait on their PendingResults). Idempotent."""
        with self._lock:
            if not self._started:
                self._closed = True
                return True
            already = self._closed
            self._closed = True
        if not already:
            self._q.put(_STOP)      # maxsize has the +1 slot reserved
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # -- the batching loop -------------------------------------------------
    def _expire_in_queue(self, r):
        """A request found already past deadline as the batcher pulls
        it from the queue: its wait STILL feeds the shed controller —
        the casualties are the strongest overload evidence there is,
        and sampling only survivors would understate p50 exactly when
        shedding matters — then the typed failure."""
        now = time.perf_counter()
        if self._shed is not None:
            self._shed.observe_wait((now - r.t_enqueue) * 1e3)
        _fail_request(r, _deadline_error(r, "batch-formation", now),
                      outcome="deadline")

    def _loop(self):
        carry = None
        while True:
            if carry is not None:
                first, carry = carry, None
            else:
                first = self._q.get()
            if first is _STOP:
                break
            if first.expired():
                # dead on arrival at the batcher: fail it now instead
                # of anchoring a max_wait window on a request nobody
                # can be answered
                self._expire_in_queue(first)
                continue
            batch, rows = [first], first.rows
            wait_deadline = first.t_enqueue + self._max_wait
            saw_stop = False
            while rows < self._max_bucket:
                remaining = wait_deadline - time.perf_counter()
                try:
                    if remaining > 0:
                        nxt = self._q.get(timeout=remaining)
                    else:
                        # past the deadline: absorb whatever is already
                        # waiting (free fill), never wait for more
                        nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _STOP:
                    saw_stop = True
                    break
                if nxt.expired():
                    self._expire_in_queue(nxt)
                    continue
                if rows + nxt.rows > self._max_bucket:
                    carry = nxt     # overflow starts the next batch
                    break
                batch.append(nxt)
                rows += nxt.rows
            _m_queue_depth.set(self._q.qsize())
            self._form_and_dispatch(batch, rows)
            if saw_stop:
                # FIFO: everything admitted precedes _STOP, and a carry
                # cannot coexist with saw_stop in one pass — drained
                break
        _m_queue_depth.set(0)

    def _form_and_dispatch(self, requests, rows):
        t_form = time.perf_counter()
        if self._shed is not None:
            # queue-wait observations feed the brownout controller —
            # including the casualties below, whose waits are exactly
            # the overload evidence the controller exists to see
            for r in requests:
                self._shed.observe_wait((t_form - r.t_enqueue) * 1e3)
        live = [r for r in requests if not r.expired(t_form)]
        if len(live) != len(requests):
            # expired riders drop OUT of the forming batch BEFORE
            # padding: the bucket is picked for the survivors, and the
            # dead get their typed error now
            for r in requests:
                if r.expired(t_form):
                    _fail_request(
                        r, _deadline_error(r, "batch-formation",
                                           t_form),
                        outcome="deadline")
            if not live:
                return      # never dispatch a batch with no live rider
            requests, rows = live, sum(r.rows for r in live)
        try:
            bucket = pick_bucket(rows, self._ladder)
            mb = MicroBatch(requests, bucket, self._feed_names)
        except Exception as e:
            # batch FORMATION failed (e.g. two spec-less requests with
            # incompatible trailing shapes hit np.concatenate): the
            # riders get the error (root-only kept trace, no stamps)
            # and the batcher survives — an exception here used to
            # kill the thread, hanging every pending and future
            # request while submit kept accepting
            for r in requests:
                _fail_request(r, e, outcome="error")
            return
        _m_batches.inc()
        _m_fill.observe(rows / bucket)
        if bucket > rows:
            _m_padded.inc(bucket - rows)
        # trace stamps only — four attribute stores per BATCH; the
        # per-request spans assemble from them at keep time
        mb.t_form = t_form
        mb.t_formed = mb.t_dispatch = time.perf_counter()
        mb.tid_batcher = threading.get_ident()
        try:
            self._dispatch(mb)
        except Exception as e:      # dispatch itself failed: the batch
            mb.fail(e)              # must still deliver, not hang
