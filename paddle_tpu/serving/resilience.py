"""Serving resilience: typed request-level failure modes and the
adaptive load-shed controller (docs/SERVING.md "Resilience").

The training side is hardened end to end (elastic gang restart,
verified checkpoints, fault injection); this module is the serving
analog's shared vocabulary. Three coordinated mechanisms live across
the serving package:

- **Request deadlines** (``scheduler.py``): ``submit(deadline_ms=)``
  fails a request past its deadline with
  :class:`DeadlineExceededError` at every stage the expiry can be
  observed — admission, batch formation (an expired rider is dropped
  from the forming batch *before* padding), dispatch-wait (replica
  pickup), and delivery — counted ``outcome="deadline"`` and its
  trace kept under the errors-always-kept policy. An expired rider
  never consumes replica dispatch.
- **Replica health + quarantine/respawn** (``replica.py``): a
  supervisor thread detects a wedged or dead replica thread, fails the
  in-flight batch's riders with :class:`ReplicaLostError`, quarantines
  the replica (``serving_replica_state`` gauge) and respawns it
  against the already-compiled executable map with capped exponential
  backoff; N consecutive stalls permanently retire it.
- **Adaptive load shedding** (:class:`ShedController`, wired by
  ``server.py`` under ``ServingConfig(shed_mode="adaptive")``): when
  queue-wait p50 eats the deadline headroom, admission sheds with
  :class:`OverloadedError` — typed distinctly from ``QueueFullError``
  (the *bounded-queue* refusal) because the remedies differ: a full
  queue wants retry-after-backoff, a brownout wants the client to slow
  down or route elsewhere until ``serving_brownout`` drops.
- **Per-tenant fair share** (:class:`TenantFairShare`, wired by the
  HTTP front door — ``serving/frontdoor.py``, docs/SERVING.md "Front
  door"): bounded per-tenant in-flight quotas plus a brownout
  fair-share layer over the shed controller, so one abusive tenant
  brownouts ITSELF instead of the fleet. The state machine lives here
  (stdlib, unit-testable); the front door owns the metrics and the
  429 mapping.

Everything here is numpy-free stdlib so the scheduler half of serving
stays importable (and unit-testable) without jax.
"""

import collections
import statistics
import sys
import threading
import time

from paddle_tpu.core.enforce import enforce
from paddle_tpu.monitor.registry import REGISTRY, counter, gauge

__all__ = [
    "DeadlineExceededError", "OverloadedError", "ReplicaLostError",
    "ShedController", "SwapFailedError", "SwapWatchdog",
    "TenantFairShare",
]


class DeadlineExceededError(RuntimeError):
    """The request's deadline (``submit(deadline_ms=)`` or
    ``ServingConfig.default_deadline_ms``) passed before a result
    could be delivered. The message names the stage that observed the
    expiry (admission / batch-formation / dispatch-wait / delivery).
    Counted ``outcome="deadline"``; the request's trace is kept
    (errors-always-kept)."""


class OverloadedError(RuntimeError):
    """Admission refused by the adaptive shed controller: queue-wait
    p50 says this request would miss its deadline anyway, so failing
    it NOW costs nothing and saves the batch/dispatch work for
    requests that can still make it. Distinct from ``QueueFullError``
    (the bounded-queue refusal): a shed wants the client to slow down
    or route elsewhere until ``serving_brownout`` clears, not merely
    retry after backoff."""


class ReplicaLostError(RuntimeError):
    """The replica executing this request's micro-batch was lost —
    its thread wedged past ``replica_stall_ms`` or died — and the
    supervisor failed the in-flight riders rather than let them hang.
    The replica is quarantined and respawned (or permanently retired
    after repeated stalls); the request itself is safe to retry."""


class SwapFailedError(RuntimeError):
    """A hot model swap (``InferenceServer.swap``, docs/SERVING.md
    "Hot model swap") was refused or rolled back. ``stage`` names
    where: ``gate`` (integrity/compatibility refusal before any
    resource was committed), ``standby`` (the new version's warm boot
    failed or wedged past its timeout), ``canary`` (golden requests
    through the standby executables failed shape/finiteness/parity),
    ``cutover`` (the dispatch flip itself failed and was reverted), or
    ``watchdog`` (the post-cutover error/latency window tripped and
    traffic was reverted). In EVERY case the previously-live version
    is still serving — a failed swap costs the standby resources, not
    the old version's traffic.

    ``retryable`` distinguishes refusals that say nothing about the
    TARGET version (a concurrent swap held the lock, the server is
    closing) from verdicts against the artifact itself: the watch-dir
    failed-version memo only records the latter — blacklisting a
    never-evaluated publish would silently strand a good deploy."""

    def __init__(self, message, stage=None, retryable=False):
        super().__init__(message)
        self.stage = stage
        self.retryable = retryable


_m_shed = counter(
    "serving_shed_total",
    "Requests shed at admission by the adaptive brownout controller, "
    "by reason: brownout (queue-wait p50 exceeded the request's "
    "deadline headroom while the brownout was active), hbm_pressure "
    "(worst-device HBM utilization at/above shed_hbm_frac)",
    labels=("reason",))
_m_brownout = gauge(
    "serving_brownout",
    "1 while the adaptive shed controller is in brownout (shedding "
    "requests whose deadline headroom is already eaten by queue "
    "wait), 0 otherwise")


def _log(msg):
    """Loud, unbuffered operator-facing line (the launcher/faults
    idiom): resilience decisions must be visible in plain stderr, not
    only in metrics."""
    sys.stderr.write(f"[serving] {msg}\n")
    sys.stderr.flush()


class ShedController:
    """Brownout-with-hysteresis admission control.

    The batcher feeds it one ``observe_wait(wait_ms)`` per request at
    batch-formation time (queue wait = enqueue -> formation, the part
    of latency admission can still save); admission asks
    ``should_shed(deadline_ms, queue_depth)``. Control law:

    - **enter** brownout when the p50 of the recent-wait window
      exceeds ``enter_frac * deadline_ms`` (the reference deadline is
      the server's default; per-request deadlines are compared
      per-request at admission) — queue wait alone is already eating
      most of the headroom, so marginal requests will miss;
    - while in brownout, shed exactly the requests whose OWN deadline
      headroom is below the observed p50 wait over ``enter_frac`` — a
      long-deadline request still gets admitted;
    - **exit** (hysteresis) when p50 falls below ``exit_frac *
      deadline_ms``, or immediately when the queue is observed EMPTY
      at admission (drained: the waits in the window are history).
      The window is cleared on exit so stale overload samples cannot
      re-trigger instantly.

    Optional HBM-pressure input (``hbm_high_frac``): worst-device
    utilization from the memory poller (``monitor.memory``) at/above
    the fraction sheds new admissions with ``reason="hbm_pressure"``
    regardless of queue-wait state — device-memory exhaustion, unlike
    queue wait, does not heal by admitting fewer marginal requests,
    so there is no hysteresis: the shed lasts exactly as long as the
    pressure reading does. None (the default) disables the input.

    The clean path stays cheap: ``should_shed`` is a few unlocked
    float compares when not in brownout; the median runs on the
    batcher thread (bounded window), never on ``submit``.
    """

    def __init__(self, deadline_ms, enter_frac=0.5, exit_frac=0.25,
                 window=64, min_samples=8, hbm_high_frac=None):
        enforce(deadline_ms is not None and float(deadline_ms) > 0,
                f"ShedController needs a positive reference "
                f"deadline_ms (ServingConfig.default_deadline_ms), "
                f"got {deadline_ms!r} — without a deadline there is "
                f"no headroom to shed against")
        enforce(0.0 < float(exit_frac) < float(enter_frac),
                f"shed hysteresis needs 0 < exit_frac < enter_frac, "
                f"got enter={enter_frac} exit={exit_frac}")
        enforce(int(min_samples) >= 1 and int(window) >= int(min_samples),
                f"shed window must hold min_samples "
                f"(window={window}, min_samples={min_samples})")
        enforce(hbm_high_frac is None or
                0.0 < float(hbm_high_frac) <= 1.0,
                f"shed_hbm_frac must be in (0, 1], got "
                f"{hbm_high_frac!r}")
        self.deadline_ms = float(deadline_ms)
        self.enter_frac = float(enter_frac)
        self.exit_frac = float(exit_frac)
        self.hbm_high_frac = None if hbm_high_frac is None \
            else float(hbm_high_frac)
        self._min_samples = int(min_samples)
        self._waits = collections.deque(maxlen=int(window))
        self._p50 = 0.0         # GIL-atomic float, read by submit
        self._brownout = False
        self._lock = threading.Lock()
        _m_brownout.set(0)

    @property
    def brownout(self):
        return self._brownout

    @property
    def p50_wait_ms(self):
        return self._p50

    def observe_wait(self, wait_ms):
        """One request's queue wait, observed at batch formation (the
        batcher thread). Drives the brownout state machine."""
        # append + median under the lock: a brownout exit on a submit
        # thread clears the deque, and an unlocked median iterating it
        # at that moment raises "deque mutated during iteration"
        with self._lock:
            self._waits.append(float(wait_ms))
            if len(self._waits) < self._min_samples:
                return
            p50 = statistics.median(self._waits)
            self._p50 = p50
        if not self._brownout:
            if p50 > self.enter_frac * self.deadline_ms:
                self._enter(p50)
        elif p50 < self.exit_frac * self.deadline_ms:
            self._exit(f"queue-wait p50 {p50:.1f}ms fell below "
                       f"{self.exit_frac:.2f}x deadline")

    def should_shed(self, deadline_ms, queue_depth):
        """Admission-time verdict: a shed reason string, or None to
        admit. ``deadline_ms`` is THIS request's effective deadline;
        ``queue_depth`` the request queue's current depth (0 exits the
        brownout on the spot — drained means the window is history)."""
        if self.hbm_high_frac is not None:
            try:
                from paddle_tpu.monitor import memory as _memory
                util = _memory.hbm_utilization_max()
            except Exception:
                util = None
            if util is not None and util >= self.hbm_high_frac:
                _m_shed.inc(reason="hbm_pressure")
                return "hbm_pressure"
        if not self._brownout:
            return None
        if queue_depth == 0:
            self._exit("request queue drained")
            return None
        if deadline_ms is not None and \
                self._p50 > self.enter_frac * float(deadline_ms):
            _m_shed.inc(reason="brownout")
            return "brownout"
        return None

    def _enter(self, p50):
        with self._lock:
            if self._brownout:
                return
            # re-validate against the LIVE p50: a concurrent
            # drain-exit just cleared the window (and zeroed _p50),
            # and entering from this thread's stale pre-clear read
            # would re-trip exactly the stale overload the clear
            # exists to forget
            if self._p50 <= self.enter_frac * self.deadline_ms:
                return
            self._brownout = True
        _m_brownout.set(1)
        _log(f"BROWNOUT: queue-wait p50 {p50:.1f}ms > "
             f"{self.enter_frac:.2f}x deadline {self.deadline_ms:.1f}ms"
             f" — shedding requests whose headroom is already spent "
             f"(OverloadedError; serving_shed_total counts)")

    def _exit(self, why):
        with self._lock:
            if not self._brownout:
                return
            self._brownout = False
            # fresh window: the overload samples that tripped the
            # brownout must not re-trip it the moment load resumes
            self._waits.clear()
            self._p50 = 0.0
        _m_brownout.set(0)
        _log(f"brownout cleared: {why}; re-admitting")

    def shutdown(self):
        """Server close: drop the brownout state and gauge quietly —
        a closed server is not shedding, and a lingering
        ``serving_brownout 1`` in exports would read as a live
        overload."""
        with self._lock:
            self._brownout = False
            self._waits.clear()
            self._p50 = 0.0
        _m_brownout.set(0)


class SwapWatchdog:
    """Post-cutover rollback verdict for the hot model swap
    (docs/SERVING.md "Hot model swap"): for a bounded window after the
    dispatch flip, watch the process serving telemetry for evidence
    the NEW version is hurting live traffic —

    - **error storm**: the error count grew by ``max_errors`` or more
      since the flip. ``errors_fn`` supplies the count — the swap
      controller passes the NEW pool's ``batch_failures``, so errors
      from the OLD pool's still-draining batches can never roll back
      a healthy new version (attribution, not just a threshold);
      without ``errors_fn`` the process-global
      ``serving_requests_total{outcome="error"}`` counter is the
      fallback.
    - **latency regression** (opt-in, ``latency_x``): the window's
      mean request latency exceeds ``latency_x`` times the
      ``baseline_ms`` captured before the swap, judged only once
      ``min_latency_samples`` requests have landed (a 2-request window
      is noise, not a verdict). The latency histogram is
      process-global — run one server per process when this verdict
      must be attributable.

    The swap controller polls :meth:`verdict` until :meth:`expired`;
    a non-None verdict reason triggers the automatic rollback."""

    def __init__(self, window_ms, max_errors=3, latency_x=None,
                 baseline_ms=None, min_latency_samples=8,
                 errors_fn=None):
        enforce(window_ms >= 0,
                f"watchdog window_ms must be >= 0, got {window_ms!r}")
        enforce(int(max_errors) >= 1,
                f"watchdog max_errors must be >= 1, got {max_errors!r}")
        enforce(latency_x is None or float(latency_x) > 1.0,
                f"watchdog latency_x must be > 1.0 (a ratio) or None, "
                f"got {latency_x!r}")
        self.window_s = float(window_ms) / 1e3
        self.max_errors = int(max_errors)
        self.latency_x = None if latency_x is None else float(latency_x)
        self.baseline_ms = baseline_ms
        self.min_latency_samples = int(min_latency_samples)
        self._errors_fn = errors_fn
        self._t0 = None
        self._err0 = 0.0
        self._lat0 = (0.0, 0)

    def _errors(self):
        if self._errors_fn is not None:
            return float(self._errors_fn())
        m = REGISTRY.get("serving_requests_total")
        return m.value(outcome="error") if m is not None else 0.0

    @staticmethod
    def _latency():
        m = REGISTRY.get("serving_request_latency_ms")
        return (m.sum(), m.count()) if m is not None else (0.0, 0)

    def start(self):
        """Anchor the window at the cutover instant: only errors and
        latency observed AFTER the flip count against the new
        version."""
        self._t0 = time.monotonic()
        self._err0 = self._errors()
        self._lat0 = self._latency()
        return self

    def expired(self):
        return self._t0 is not None and \
            time.monotonic() - self._t0 >= self.window_s

    def verdict(self):
        """A rollback reason string, or None while the window looks
        healthy."""
        errs = self._errors() - self._err0
        if errs >= self.max_errors:
            return (f"{errs:.0f} request error(s) within "
                    f"{(time.monotonic() - self._t0) * 1e3:.0f}ms of "
                    f"cutover (watchdog max_errors={self.max_errors})")
        if self.latency_x is not None and self.baseline_ms:
            s, c = self._latency()
            ds, dc = s - self._lat0[0], c - self._lat0[1]
            if dc >= self.min_latency_samples:
                mean = ds / dc
                if mean > self.latency_x * float(self.baseline_ms):
                    return (f"post-cutover mean latency {mean:.1f}ms > "
                            f"{self.latency_x:g}x pre-swap baseline "
                            f"{float(self.baseline_ms):.1f}ms over "
                            f"{dc} request(s)")
        return None


class TenantFairShare:
    """Per-tenant in-flight admission: a hard quota always, plus a
    fair-share squeeze while the shed controller is in brownout.

    The HTTP front door (``serving/frontdoor.py``) asks
    :meth:`admit` before submitting a tenant's request and MUST pair
    every successful admit with exactly one :meth:`release` (the front
    door's try/finally owns that contract, including the
    client-disconnected-mid-wait path). Two refusal verdicts:

    - ``"quota"`` — the tenant already holds ``max_inflight``
      requests. An absolute per-tenant bound, active in any load
      state: no single key can occupy the whole request queue.
    - ``"fair_share"`` — the shed controller is in brownout AND
      admitting this request would push the tenant past
      ``fair_frac`` of ALL in-flight front-door requests. This is the
      "one abusive tenant brownouts itself, not the fleet" rule: in
      overload the heavy key gets squeezed back toward its fair
      share while light tenants keep flowing untouched.
      ``fair_min_inflight`` exempts small holdings — with one tenant
      and two requests the share test would otherwise refuse
      everyone.

    Verdicts are strings rather than exceptions because the caller
    maps them to BOTH a metric label and a status code; the counting
    itself (``serving_tenant_refused_total``) stays in the front door
    with the rest of the HTTP metrics. Stdlib-only and lock-cheap:
    one dict update under one lock per admit/release.
    """

    def __init__(self, max_inflight=64, fair_frac=0.5,
                 fair_min_inflight=4, shed=None):
        enforce(int(max_inflight) >= 1,
                f"tenant max_inflight must be >= 1, got "
                f"{max_inflight!r}")
        enforce(0.0 < float(fair_frac) <= 1.0,
                f"tenant fair_frac must be in (0, 1], got "
                f"{fair_frac!r}")
        enforce(int(fair_min_inflight) >= 1,
                f"tenant fair_min_inflight must be >= 1, got "
                f"{fair_min_inflight!r}")
        self.max_inflight = int(max_inflight)
        self.fair_frac = float(fair_frac)
        self.fair_min_inflight = int(fair_min_inflight)
        self.shed = shed
        self._inflight = {}
        self._total = 0
        self._lock = threading.Lock()

    def admit(self, tenant):
        """Refusal verdict (``"quota"`` / ``"fair_share"``) or None.
        None means the tenant's in-flight count was incremented and
        the caller OWES a :meth:`release`; a verdict changes no
        state."""
        with self._lock:
            cur = self._inflight.get(tenant, 0)
            if cur >= self.max_inflight:
                return "quota"
            if self.shed is not None and self.shed.brownout \
                    and cur >= self.fair_min_inflight \
                    and cur + 1 > self.fair_frac * (self._total + 1):
                return "fair_share"
            self._inflight[tenant] = cur + 1
            self._total += 1
        return None

    def release(self, tenant):
        """Return the tenant's remaining in-flight count (0 removes
        the entry, so idle tenants cost nothing and the front door
        knows to drop the per-tenant gauge)."""
        with self._lock:
            cur = self._inflight.get(tenant, 0)
            enforce(cur > 0,
                    f"TenantFairShare.release({tenant!r}) without a "
                    f"matching admit — the front door's "
                    f"admit/release pairing is broken")
            if cur == 1:
                del self._inflight[tenant]
            else:
                self._inflight[tenant] = cur - 1
            self._total -= 1
            return cur - 1

    def inflight(self, tenant):
        with self._lock:
            return self._inflight.get(tenant, 0)

    @property
    def total_inflight(self):
        return self._total
