"""HTTP/1.1 front door: the serving stack's network boundary
(docs/SERVING.md "Front door").

The PR-8..13 serving stack holds one invariant inside the process —
**no accepted request ever hangs, every failure is typed** — and this
module extends it to the socket, where requests actually arrive. A
stdlib threaded HTTP server (``monitor/httpd.py`` base, no new deps)
over :meth:`InferenceServer.submit`:

- **Deadline propagation**: an ``X-Deadline-Ms`` header anchors the
  absolute deadline at request arrival on the socket; by the time the
  body is parsed, the wire/parse time is already spent, so the
  scheduler receives the REMAINING budget via ``submit(deadline_ms=)``
  — a request whose budget was eaten by a slow wire is refused at
  admission (504) without ever being enqueued. Every typed serving
  error maps to a stable status code (table in docs/SERVING.md) so a
  client can distinguish retry-after-backoff (429 queue_full) from
  slow-down (429 overloaded) from route-elsewhere (503 draining).
- **Per-tenant admission**: the ``X-Tenant`` header keys bounded
  per-tenant in-flight quotas and a brownout fair-share layer
  (:class:`~.resilience.TenantFairShare`) over the PR-12 shed
  controller — one abusive tenant brownouts itself, not the fleet.
  The tenant id is stamped into the request's kept trace
  (``submit(trace_attrs=)``), so a tenant's p99 is attributable
  socket-to-device.
- **Connection robustness**: per-connection socket timeouts (the
  slow-loris bound — a stalled body read gets a typed 408, not a
  pinned thread), a bounded request body (413), and client-disconnect
  detection while waiting for the result (``MSG_PEEK`` probe) that
  releases the tenant slot instead of leaking it. ``/healthz`` says
  the listener is alive; ``/readyz`` flips with drain state.
- **Graceful drain**: ``begin_drain()`` (or SIGTERM via
  :meth:`HttpFrontDoor.install_signal_handlers`) flips readiness,
  new requests get 503 + Retry-After, in-flight requests complete
  through the server's existing drain contract, and :meth:`drain` is
  bounded and loud.

With the front door off nothing here runs: ``InferenceServer.submit``
is untouched (``trace_attrs=None`` is a no-op), so the in-process
path stays bit-for-bit legacy — pinned by test.

Chaos: ``testing/faults.py install_http_faults`` arms wire-level
faults (slow-loris, disconnect-mid-response, header-bomb) against
:class:`WireClient`; ``tests/serving_http_worker.py`` proves zero
hangs and per-request typed accounting under each.
"""

import json
import select
import signal
import socket
import threading
import time

import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet, enforce
from paddle_tpu.monitor.httpd import ThreadedHTTPServerBase
from paddle_tpu.monitor.registry import counter, gauge, histogram
from paddle_tpu.serving.resilience import (
    DeadlineExceededError, OverloadedError, ReplicaLostError,
    TenantFairShare, _log,
)
from paddle_tpu.serving.scheduler import (
    QueueFullError, ServerClosedError, ServerDrainingError,
)

__all__ = [
    "FrontDoorConfig", "HttpFrontDoor", "WireClient", "WireReset",
]

_m_http = counter(
    "serving_http_requests_total",
    "Front-door HTTP requests by outcome: ok (200), bad_request "
    "(400/404/405/413/431 — malformed body, unknown path, oversized "
    "or bomb headers), timeout (408 slow-loris body read), deadline "
    "(504), overloaded (429 shed), queue_full (429 bounded queue), "
    "tenant_quota / tenant_fair_share (429 per-tenant admission), "
    "draining (503 + Retry-After), closed (503 terminal), "
    "replica_lost (503 retryable), disconnect (client gone before "
    "the response could be delivered), internal (500)",
    labels=("outcome",))
_m_http_ms = histogram(
    "serving_http_request_ms",
    "Front-door request wall time in milliseconds: request-line "
    "arrival on the socket -> response written (wire parse + "
    "admission + queue + execute + serialization); compare with "
    "serving_request_latency_ms to attribute wire overhead")
_m_http_inflight = gauge(
    "serving_http_inflight",
    "HTTP requests currently inside the front door (admitted into a "
    "handler thread, response not yet written)")
_m_http_draining = gauge(
    "serving_http_draining",
    "1 while the front door is draining (refusing new requests with "
    "503 + Retry-After while in-flight requests complete), else 0")
_m_tenant_requests = counter(
    "serving_tenant_requests_total",
    "Front-door requests per tenant (the X-Tenant header, "
    "default_tenant when absent) that passed tenant admission",
    labels=("tenant",))
_m_tenant_inflight = gauge(
    "serving_tenant_inflight",
    "In-flight front-door requests per tenant; series are removed at "
    "zero so idle tenants do not accumulate export cardinality",
    labels=("tenant",))
_m_tenant_refused = counter(
    "serving_tenant_refused_total",
    "Tenant admission refusals by reason: quota (the tenant already "
    "holds max_tenant_inflight requests), fair_share (brownout "
    "squeeze — admitting would push the tenant past fair_frac of all "
    "in-flight requests)",
    labels=("reason",))


class FrontDoorConfig:
    """Knobs for :class:`HttpFrontDoor` (docs/SERVING.md has the
    operator table). Defaults are loopback, 10s socket timeout, 8 MiB
    body bound, 64 in-flight per tenant."""

    def __init__(self, port=0, host="127.0.0.1", socket_timeout_s=10.0,
                 max_body_bytes=8 << 20, tenant_header="X-Tenant",
                 default_tenant="anonymous", max_tenant_inflight=64,
                 fair_frac=0.5, fair_min_inflight=4, retry_after_s=1.0,
                 drain_retry_after_s=5.0, drain_timeout_s=30.0,
                 result_timeout_s=600.0):
        enforce(int(max_body_bytes) >= 1,
                f"max_body_bytes must be >= 1, got {max_body_bytes!r}")
        enforce(float(result_timeout_s) > 0,
                f"result_timeout_s must be > 0, got "
                f"{result_timeout_s!r} — it is the front door's "
                f"last-ditch hang bound for deadline-less requests")
        self.port = port
        self.host = host
        self.socket_timeout_s = socket_timeout_s
        self.max_body_bytes = int(max_body_bytes)
        self.tenant_header = tenant_header
        self.default_tenant = default_tenant
        self.max_tenant_inflight = int(max_tenant_inflight)
        self.fair_frac = float(fair_frac)
        self.fair_min_inflight = int(fair_min_inflight)
        self.retry_after_s = float(retry_after_s)
        self.drain_retry_after_s = float(drain_retry_after_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.result_timeout_s = float(result_timeout_s)


class _ClientGone(Exception):
    """Internal: the client hung up while we held its request."""


class HttpFrontDoor(ThreadedHTTPServerBase):
    """The production HTTP boundary over one
    :class:`~.server.InferenceServer`.

    ``POST /v1/infer`` with a JSON body ``{"feeds": {name: nested
    list}}`` returns ``{"outputs": [...], "model_version": ...,
    "trace_id": ...}``; ``GET /healthz`` / ``GET /readyz`` are the
    probe pair. Every response carries a stable status code mapped
    from the serving stack's typed errors, and every request lands in
    ``serving_http_requests_total`` under exactly one outcome — the
    wire-level mirror of the scheduler's accounting invariant.
    """

    thread_name = "pt-serving-frontdoor"

    def __init__(self, server, config=None):
        self.config = config or FrontDoorConfig()
        super().__init__(port=self.config.port, host=self.config.host,
                         socket_timeout_s=self.config.socket_timeout_s)
        self.server = server
        # the fair-share layer reads the LIVE shed controller so the
        # brownout squeeze and the scheduler's own shedding trip
        # together; servers without one (shed_mode off, test fakes)
        # just never fair-share
        self.tenants = TenantFairShare(
            max_inflight=self.config.max_tenant_inflight,
            fair_frac=self.config.fair_frac,
            fair_min_inflight=self.config.fair_min_inflight,
            shed=getattr(getattr(server, "scheduler", None), "_shed",
                         None))
        self._draining = False
        self._inflight = 0
        self._flock = threading.Lock()
        _m_http_draining.set(0)
        _m_http_inflight.set(0)

    # -- drain lifecycle ---------------------------------------------------
    @property
    def draining(self):
        return self._draining

    @property
    def inflight(self):
        return self._inflight

    def ready(self):
        """The /readyz verdict: listening and not draining (front
        door OR server — a server mid-drain must stop attracting
        traffic even if the front door was not told directly)."""
        return self.running and not self._draining and \
            not getattr(self.server, "draining", False)

    def begin_drain(self, why="begin_drain"):
        """Flip the front door into draining: /readyz goes 503, every
        new request gets 503 + Retry-After, in-flight requests keep
        completing. Also begins the server's own drain so in-process
        callers see the retryable ``ServerDrainingError``. Idempotent;
        returns whether THIS call flipped the state."""
        with self._flock:
            if self._draining:
                return False
            self._draining = True
        _m_http_draining.set(1)
        _log(f"front door draining ({why}): /readyz now 503, new "
             f"requests refused 503 + Retry-After "
             f"{self.config.drain_retry_after_s:.0f}s; "
             f"{self._inflight} in flight completing")
        if hasattr(self.server, "begin_drain"):
            self.server.begin_drain()
        return True

    def drain(self, timeout_s=None, close=True):
        """Bounded, loud graceful shutdown: begin the drain, wait up
        to ``timeout_s`` (config ``drain_timeout_s``) for in-flight
        requests to finish, then close the server (its own drain
        contract completes accepted work) and stop the listener.
        Returns True when every in-flight request finished inside the
        bound — False means the bound expired with stragglers, and
        the log line says how many."""
        self.begin_drain(why="drain")
        bound = self.config.drain_timeout_s if timeout_s is None \
            else float(timeout_s)
        t_end = time.monotonic() + bound
        while self._inflight > 0 and time.monotonic() < t_end:
            time.sleep(0.02)
        drained = self._inflight == 0
        if drained:
            _log("front door drain complete: 0 in flight")
        else:
            _log(f"front door drain TIMED OUT after {bound:.1f}s: "
                 f"{self._inflight} request(s) still in flight "
                 f"(daemon handler threads; responses may still land)")
        if close and hasattr(self.server, "close"):
            self.server.close()
        self.stop()
        return drained

    def install_signal_handlers(self, signals=(signal.SIGTERM,)):
        """SIGTERM -> background :meth:`drain` (the rolling-restart
        contract: the orchestrator sends SIGTERM, readiness flips,
        in-flight completes, process exits cleanly). Returns the
        previous handler map for restoration; main-thread only (a
        no-op with a loud line elsewhere, so embedding in a worker
        thread degrades visibly rather than raising)."""
        prev = {}
        for sig in signals:
            try:
                prev[sig] = signal.signal(
                    sig, lambda *_a: threading.Thread(
                        target=self.drain, name="pt-frontdoor-drain",
                        daemon=True).start())
            except ValueError:
                _log(f"front door: cannot install handler for "
                     f"{sig!r} off the main thread; call "
                     f"begin_drain()/drain() directly")
        return prev

    def _enter(self):
        with self._flock:
            self._inflight += 1
            _m_http_inflight.set(self._inflight)

    def _exit(self):
        with self._flock:
            self._inflight -= 1
            _m_http_inflight.set(self._inflight)

    # -- the handler -------------------------------------------------------
    def _handler_class(self):
        import http.server

        door = self

        class Handler(http.server.BaseHTTPRequestHandler):
            server_version = "paddle-tpu-frontdoor"
            sys_version = ""

            # ---- plumbing ----
            def parse_request(self):
                # the deadline anchor: request-line arrival on the
                # socket (~= accept for fresh connections; keep-alive
                # idle time between requests is deliberately NOT
                # charged against the next request's budget)
                self._t_anchor = time.perf_counter()
                return super().parse_request()

            def log_message(self, *a):
                pass                   # metrics + _log, not stderr spam

            def send_error(self, code, message=None, explain=None):
                # stdlib-generated refusals (431 header bomb, 414,
                # 501...) and our own 404/405 funnel through here:
                # count them so every wire request lands in the
                # accounting, then answer; a client that vanished
                # mid-refusal flips the count to disconnect
                if code >= 400:
                    _m_http.inc(outcome="bad_request")
                try:
                    super().send_error(code, message, explain)
                except OSError:
                    self.close_connection = True

            def _client_gone(self):
                """Probe the connection without consuming request
                data: a readable-but-empty socket means the client
                closed; nothing to read means it is still there.
                select() with a zero timeout first — a bare
                recv(MSG_DONTWAIT) would still park in the socket
                timeout's readiness wait and misreport a healthy
                but silent client as gone."""
                try:
                    readable, _, _ = select.select(
                        [self.connection], [], [], 0)
                    if not readable:
                        return False
                    chunk = self.connection.recv(
                        1, socket.MSG_PEEK | socket.MSG_DONTWAIT)
                except (BlockingIOError, InterruptedError):
                    return False
                except (OSError, ValueError):
                    return True
                return chunk == b""

            def _finish(self, status, payload, outcome,
                        retry_after=None, t0=None):
                """Send one JSON response and count EXACTLY one
                outcome for the request — a write failure converts
                the outcome to disconnect rather than double-count."""
                body = json.dumps(payload).encode("utf-8")
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    if retry_after is not None:
                        self.send_header(
                            "Retry-After",
                            str(max(1, int(round(retry_after)))))
                    if self.close_connection:
                        self.send_header("Connection", "close")
                    self.end_headers()
                    self.wfile.write(body)
                except (TimeoutError, socket.timeout, OSError):
                    outcome = "disconnect"
                    self.close_connection = True
                _m_http.inc(outcome=outcome)
                if t0 is not None:
                    _m_http_ms.observe(
                        (time.perf_counter() - t0) * 1e3)

            def _probe(self, body, status=200):
                """Uncounted plumbing response (health probes): a
                kubelet scraping /healthz every 2s must not dominate
                serving_http_requests_total."""
                data = body.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                if status == 503:
                    self.send_header(
                        "Retry-After",
                        str(max(1, int(round(
                            door.config.drain_retry_after_s)))))
                self.end_headers()
                self.wfile.write(data)

            # ---- routes ----
            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/healthz":
                    self._probe("ok\n")
                elif path == "/readyz":
                    if door.ready():
                        self._probe("ready\n")
                    else:
                        self._probe("draining\n", status=503)
                elif path == "/v1/infer":
                    self.send_error(405, "POST /v1/infer")
                else:
                    self.send_error(404)

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path != "/v1/infer":
                    self.send_error(404)
                    return
                door._enter()
                try:
                    self._infer(getattr(self, "_t_anchor",
                                        time.perf_counter()))
                finally:
                    door._exit()

            # ---- the request pipeline ----
            def _read_body(self, t0):
                """Bounded, timeout-typed body read. Returns bytes or
                None after having fully answered (and counted) the
                request."""
                raw_len = self.headers.get("Content-Length")
                if raw_len is None:
                    self._finish(400, {"error": "Content-Length "
                                       "required"},
                                 outcome="bad_request", t0=t0)
                    return None
                try:
                    length = int(raw_len)
                    enforce(length >= 0, "negative Content-Length")
                except (ValueError, EnforceNotMet):
                    self._finish(400, {"error": f"bad Content-Length "
                                       f"{raw_len!r}"},
                                 outcome="bad_request", t0=t0)
                    return None
                if length > door.config.max_body_bytes:
                    self.close_connection = True
                    self._finish(413, {"error": f"body {length} bytes "
                                       f"exceeds max_body_bytes "
                                       f"{door.config.max_body_bytes}"},
                                 outcome="bad_request", t0=t0)
                    return None
                try:
                    body = self.rfile.read(length)
                except (TimeoutError, socket.timeout):
                    # slow-loris: the client stalled mid-body past the
                    # socket timeout; the byte stream is now torn, so
                    # answer typed and drop the connection
                    self.close_connection = True
                    self._finish(408, {"error": "body read timed out "
                                       "(slow client)"},
                                 outcome="timeout", t0=t0)
                    return None
                except OSError:
                    self.close_connection = True
                    _m_http.inc(outcome="disconnect")
                    return None
                if len(body) < length:
                    # EOF mid-body: client hung up; no one to answer
                    self.close_connection = True
                    _m_http.inc(outcome="disconnect")
                    return None
                return body

            def _parse(self, body):
                """-> (feeds, budget_ms, tenant); raises EnforceNotMet
                with the operator-facing message on any malformation
                (mapped to 400 by the caller)."""
                try:
                    payload = json.loads(body)
                except (ValueError, UnicodeDecodeError) as e:
                    raise EnforceNotMet(f"request body is not valid "
                                        f"JSON: {e}") from None
                enforce(isinstance(payload, dict) and
                        isinstance(payload.get("feeds"), dict) and
                        payload["feeds"],
                        'request body must be {"feeds": {name: '
                        'nested-list}} with at least one feed')
                feeds = {}
                for name, val in payload["feeds"].items():
                    try:
                        feeds[str(name)] = np.asarray(val)
                    except (ValueError, TypeError) as e:
                        raise EnforceNotMet(
                            f"feed {name!r} is not array-like: "
                            f"{e}") from None
                budget_ms = None
                raw = self.headers.get("X-Deadline-Ms")
                if raw is not None:
                    try:
                        budget_ms = float(raw)
                        enforce(budget_ms >= 0 and
                                budget_ms == budget_ms and
                                budget_ms != float("inf"),
                                "out of range")
                    except (ValueError, EnforceNotMet):
                        raise EnforceNotMet(
                            f"X-Deadline-Ms must be a finite "
                            f"non-negative number of milliseconds, "
                            f"got {raw!r}") from None
                tenant = (self.headers.get(door.config.tenant_header)
                          or "").strip() or door.config.default_tenant
                enforce(len(tenant) <= 128,
                        f"{door.config.tenant_header} header exceeds "
                        f"128 chars")
                return feeds, budget_ms, tenant

            def _await(self, pending, deadline_ms):
                """Wait for the result in short slices, probing for a
                client hangup between slices (a disconnected client's
                rider is released, not leaked). The overall bound is
                the request deadline plus slack — the scheduler's own
                deadline machinery fails the rider first in every
                healthy case; the bound only catches a broken stack."""
                if deadline_ms is not None:
                    bound_s = deadline_ms / 1e3 + 30.0
                else:
                    bound_s = door.config.result_timeout_s
                t_end = time.monotonic() + bound_s
                while True:
                    try:
                        return pending.result(timeout=0.05)
                    except TimeoutError:
                        pass
                    if self._client_gone():
                        raise _ClientGone()
                    if time.monotonic() >= t_end:
                        raise TimeoutError(
                            f"result not delivered within "
                            f"{bound_s:.1f}s (front-door bound; the "
                            f"scheduler's deadline should have fired "
                            f"first — this is a bug, not load)")

            def _infer(self, t0):
                body = self._read_body(t0)
                if body is None:
                    return
                retry_s = door.config.retry_after_s
                try:
                    feeds, budget_ms, tenant = self._parse(body)
                except EnforceNotMet as e:
                    self._finish(400, {"error": str(e)},
                                 outcome="bad_request", t0=t0)
                    return
                if door.draining or getattr(door.server, "draining",
                                            False):
                    self._finish(
                        503, {"error": "draining: retry against "
                              "another replica"},
                        outcome="draining",
                        retry_after=door.config.drain_retry_after_s,
                        t0=t0)
                    return
                verdict = door.tenants.admit(tenant)
                if verdict == "quota":
                    _m_tenant_refused.inc(reason="quota")
                    self._finish(
                        429, {"error": f"tenant {tenant!r} at "
                              f"max_tenant_inflight "
                              f"{door.tenants.max_inflight}"},
                        outcome="tenant_quota", retry_after=retry_s,
                        t0=t0)
                    return
                if verdict == "fair_share":
                    _m_tenant_refused.inc(reason="fair_share")
                    self._finish(
                        429, {"error": f"tenant {tenant!r} over fair "
                              f"share during brownout"},
                        outcome="tenant_fair_share",
                        retry_after=retry_s, t0=t0)
                    return
                _m_tenant_requests.inc(tenant=tenant)
                _m_tenant_inflight.set(door.tenants.inflight(tenant),
                                       tenant=tenant)
                try:
                    self._submit_and_respond(t0, feeds, budget_ms,
                                             tenant, retry_s)
                finally:
                    if door.tenants.release(tenant) == 0:
                        _m_tenant_inflight.remove(tenant=tenant)
                    else:
                        _m_tenant_inflight.set(
                            door.tenants.inflight(tenant),
                            tenant=tenant)

            def _submit_and_respond(self, t0, feeds, budget_ms,
                                    tenant, retry_s):
                try:
                    deadline_ms = None
                    if budget_ms is not None:
                        # the deduction: wire + parse time already
                        # spent against the budget anchored at t0; a
                        # zero remainder still goes to submit, where
                        # admission refuses it typed WITHOUT enqueueing
                        deadline_ms = max(
                            0.0, budget_ms -
                            (time.perf_counter() - t0) * 1e3)
                    pending = door.server.submit(
                        feeds, deadline_ms=deadline_ms,
                        trace_attrs={"tenant": tenant,
                                     "transport": "http"})
                    outs = self._await(pending, deadline_ms)
                except _ClientGone:
                    self.close_connection = True
                    _m_http.inc(outcome="disconnect")
                    return
                except EnforceNotMet as e:
                    self._finish(400, {"error": str(e)},
                                 outcome="bad_request", t0=t0)
                    return
                except DeadlineExceededError as e:
                    self._finish(504, {"error": str(e)},
                                 outcome="deadline", t0=t0)
                    return
                except ServerDrainingError as e:
                    self._finish(503, {"error": str(e)},
                                 outcome="draining",
                                 retry_after=(
                                     door.config.drain_retry_after_s),
                                 t0=t0)
                    return
                except ServerClosedError as e:
                    self._finish(503, {"error": str(e)},
                                 outcome="closed", t0=t0)
                    return
                except OverloadedError as e:
                    self._finish(429, {"error": str(e)},
                                 outcome="overloaded",
                                 retry_after=retry_s, t0=t0)
                    return
                except QueueFullError as e:
                    self._finish(429, {"error": str(e)},
                                 outcome="queue_full",
                                 retry_after=retry_s, t0=t0)
                    return
                except ReplicaLostError as e:
                    self._finish(503, {"error": str(e)},
                                 outcome="replica_lost",
                                 retry_after=retry_s, t0=t0)
                    return
                except Exception as e:
                    self._finish(500, {"error": f"{type(e).__name__}: "
                                       f"{e}"},
                                 outcome="internal", t0=t0)
                    return
                self._finish(
                    200,
                    {"outputs": [np.asarray(o).tolist() for o in outs],
                     "model_version": getattr(door.server,
                                              "model_version", None),
                     "trace_id": pending.trace_id},
                    outcome="ok", t0=t0)

        return Handler


class WireReset(RuntimeError):
    """The wire connection died mid-exchange (reset, EOF, injected
    disconnect): a TYPED wire-level resolution — the request's fate on
    the server is unknown, but the client call itself never hangs."""


class WireClient:
    """Minimal raw-socket HTTP/1.1 client for tests, chaos and bench
    (stdlib urllib would hide the socket, and the fault injector
    needs the seam): one persistent connection, blocking with a hard
    timeout, every failure surfacing as :class:`WireReset` or
    ``TimeoutError`` — never a hang."""

    def __init__(self, host, port, timeout_s=30.0):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self._sock = None

    # -- connection --------------------------------------------------------
    def connect(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            # mirror the server's TCP_NODELAY: a Nagle-held segment
            # against a delayed ACK costs ~40ms flat per request
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
        return self

    def close(self):
        self._drop()

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- requests ----------------------------------------------------------
    def infer(self, feeds, deadline_ms=None, tenant=None, headers=None):
        """POST /v1/infer -> (status, headers, payload). ``feeds``
        maps name -> array-like (serialized via tolist)."""
        hdrs = dict(headers or ())
        if deadline_ms is not None:
            hdrs["X-Deadline-Ms"] = str(float(deadline_ms))
        if tenant is not None:
            hdrs["X-Tenant"] = tenant
        body = json.dumps(
            {"feeds": {k: np.asarray(v).tolist()
                       for k, v in feeds.items()}}).encode("utf-8")
        return self.request("POST", "/v1/infer", body, hdrs)

    def get(self, path):
        return self.request("GET", path, b"", {})

    def request(self, method, path, body, headers):
        self.connect()
        head_lines = [f"{method} {path} HTTP/1.1",
                      f"Host: {self.host}:{self.port}",
                      f"Content-Length: {len(body)}"]
        head_lines += [f"{k}: {v}" for k, v in headers.items()]
        head = ("\r\n".join(head_lines) + "\r\n\r\n").encode("utf-8")
        try:
            self._send(head, body)
            return self._recv_response()
        except (TimeoutError, socket.timeout):
            self._drop()
            raise
        except OSError as e:
            self._drop()
            raise WireReset(f"wire failure during {method} {path}: "
                            f"{e}") from e

    def _send(self, head, body):
        """THE fault-injection seam (testing/faults.py
        install_http_faults patches exactly this method)."""
        self._sock.sendall(head + body)

    def _recv_file(self):
        return self._sock.makefile("rb")

    def _recv_response(self):
        f = self._recv_file()
        try:
            status_line = f.readline()
            if not status_line:
                self._drop()
                raise WireReset("connection closed before status line")
            parts = status_line.decode("latin-1").split(None, 2)
            status = int(parts[1])
            headers = {}
            while True:
                line = f.readline()
                if not line:
                    self._drop()
                    raise WireReset("connection closed mid-headers")
                line = line.decode("latin-1").strip()
                if not line:
                    break
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
            length = int(headers.get("content-length", "0"))
            raw = f.read(length) if length else b""
            if len(raw) < length:
                self._drop()
                raise WireReset("connection closed mid-body")
        finally:
            f.close()
        if headers.get("connection", "").lower() == "close":
            self._drop()
        payload = None
        if raw:
            try:
                payload = json.loads(raw)
            except ValueError:
                payload = raw.decode("utf-8", "replace")
        return status, headers, payload
