"""Zero-downtime hot model swap: the supervised, reversible
training→serving handoff (docs/SERVING.md "Hot model swap").

Deploying a new model version used to mean tearing the server down and
cold-booting — dropping every in-flight and queued request. The
:class:`SwapController` turns the deploy into a staged, abortable
pipeline in which the LIVE version keeps serving until the new one has
proven itself, and keeps serving if it never does:

1. **gate** — ``verify_aot_dir`` integrity pass (CRC every artifact the
   manifest vouches for — a bit-rotted export refuses HERE, before any
   resource is committed) plus compatibility against the live config:
   same feed names, fetch names and per-feed sample specs, per-row
   fetches at the ladder top. ``swap()`` always re-gates even when the
   server booted with ``verify_aot=False`` — a server that outlives an
   artifact rewrite must never promote bits it didn't verify.
2. **standby warm-boot** — the new version's per-bucket executable map
   compiles and its params ``device_put`` ALONGSIDE the live pool
   (``ReplicaPool(role="standby")`` — the live pool keeps gauge
   ownership), so the window costs ~2x param memory and zero live
   traffic. The build runs on a worker thread bounded by
   ``standby_timeout_ms``: a wedged or failing compile quarantines the
   SWAP (the thread is abandoned; a pool it eventually builds is
   discarded), never the live traffic — the slot-respawn discipline
   applied to deployment.
3. **canary** — golden requests run through the standby executables
   directly (no real traffic touches them): per-row output shapes,
   finiteness of float fetches, optional caller-supplied parity bounds
   against the live version (``parity_rtol``/``parity_atol``) and an
   arbitrary ``canary_check(feeds, outs)`` hook.
4. **atomic cutover** — the scheduler's dispatch target flips at a
   batch boundary (``MicroBatchScheduler.set_dispatch``: the batcher
   reads the target once per formed batch), so every micro-batch
   executes WHOLLY on one version; batches already queued on the old
   pool drain there in the background, and the old params release only
   after the drain.
5. **rollback** — any failure in stages 2–4, or the post-cutover
   :class:`~.resilience.SwapWatchdog` window tripping (error storm /
   latency regression), automatically reverts dispatch to the
   still-resident old version and surfaces a typed
   :class:`~.resilience.SwapFailedError` naming the stage. The old
   version is untouched in every failure mode.

``watch_dir()`` runs the same pipeline continuously: poll the export
directory's manifest ``model_version`` (a cheap index-only read) and
swap whenever training publishes a new one — with a failed version
remembered so a bad artifact logs once and waits for the next publish
instead of crash-looping the gate.

Telemetry: ``serving_model_version{version}`` (1 for the live version,
superseded series removed) and
``serving_swaps_total{outcome=ok|gate_failed|refused_memory|
canary_failed|rolled_back}`` (docs/OBSERVABILITY.md).
"""

import threading
import time

import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet, enforce
from paddle_tpu.monitor.registry import counter, gauge
from paddle_tpu.serving.resilience import (
    SwapFailedError, SwapWatchdog, _log,
)
from paddle_tpu.serving.scheduler import pick_bucket

__all__ = ["SwapController", "publish_model_version",
           "clear_model_version", "default_canary_feeds"]

_m_version = gauge(
    "serving_model_version",
    "1 for the model version this process is currently serving "
    "(label: version = the AOT manifest's model_version, or "
    "'unversioned'); superseded series are removed at cutover so "
    "cardinality stays one per process",
    labels=("version",))
_m_swaps = counter(
    "serving_swaps_total",
    "Hot model swaps by outcome: ok (cutover committed and the "
    "watchdog window passed), gate_failed (integrity/compatibility "
    "refusal before any resource was committed — includes a "
    "concurrent-swap refusal), canary_failed (golden requests "
    "through the standby executables failed shape/finiteness/parity), "
    "rolled_back (standby warm-boot failed or wedged, cutover "
    "reverted, or the post-cutover watchdog tripped — the old "
    "version is serving again), refused_memory (memory-aware "
    "admission projected the standby could not co-reside with the "
    "live pool under the HBM limit — refused BEFORE booting it)",
    labels=("outcome",))

_version_lock = threading.Lock()
_current_version_label = None


def publish_model_version(version):
    """Point the ``serving_model_version`` gauge at ``version``
    (None -> 'unversioned'), removing the superseded series so the
    export never shows two live versions. Process-global, like every
    serving gauge: one server per process when the series must be
    attributable."""
    global _current_version_label
    label = version or "unversioned"
    with _version_lock:
        prev = _current_version_label
        _m_version.set(1, version=label)
        if prev is not None and prev != label:
            _m_version.remove(version=prev)
        _current_version_label = label


def clear_model_version(version):
    """Server close: drop the version series — a closed server serves
    nothing, and a lingering ``serving_model_version 1`` would read as
    a live deployment."""
    global _current_version_label
    label = version or "unversioned"
    with _version_lock:
        _m_version.remove(version=label)
        if _current_version_label == label:
            _current_version_label = None


def default_canary_feeds(bundle, ladder):
    """The default golden set when the caller supplies none: one
    all-zeros request at 1 row and one at the top bucket — enough to
    exercise the smallest and largest executable and catch a
    non-finite-on-neutral-input model. Callers with real invariants
    should pass representative ``canary_feeds`` (and parity bounds)
    instead; zeros are a smoke signal, not a quality bar."""
    out = []
    for rows in (1, ladder[-1]):
        out.append({
            n: np.zeros((rows,) + tuple(shape), dtype)
            for n, (shape, dtype) in bundle.sample_specs.items()})
    return out


class SwapController:
    """One server's hot-swap state machine. Owned lazily by
    :class:`~.server.InferenceServer` (``server.swap()`` /
    ``server.watch_dir()`` delegate here); at most one swap runs at a
    time — a concurrent ``swap()`` is refused at the gate rather than
    queued, because the second deploy's author must decide against the
    FIRST deploy's outcome, not race it."""

    def __init__(self, server):
        self._server = server
        self._swap_lock = threading.Lock()
        #: serializes the cutover flips against shutdown's _closed
        #: write: a swap that outlives a timed-out close() must abort
        #: BEFORE promoting a pool nothing would ever close
        self._state_lock = threading.Lock()
        self._closed = False
        self._watch_thread = None
        self._watch_stop = threading.Event()
        self._watch_failed_version = None
        self._drain_threads = []
        #: abandoned standby BUILD threads (timed-out warm boots):
        #: shutdown joins these too — a late-built pool must not boot
        #: replica threads after close() reported "fully stopped"
        self._standby_threads = []
        self._drain_lock = threading.Lock()

    # -- the staged pipeline ----------------------------------------------
    def swap(self, model_dir, canary_feeds=None, canary_check=None,
             parity_rtol=None, parity_atol=0.0,
             standby_timeout_ms=120_000.0, watchdog_ms=500.0,
             watchdog_max_errors=3, watchdog_latency_x=None):
        """Execute one staged swap to ``model_dir``; returns the
        report dict ``{"outcome": "ok", "model_version",
        "previous_version", "stage_ms": {...}}`` or raises
        :class:`SwapFailedError` (stage named, old version serving).

        - ``canary_feeds``: list of golden ``{feed: array}`` request
          dicts (leading batch dim); default
          :func:`default_canary_feeds`.
        - ``canary_check``: optional ``fn(feeds, outs) -> bool|None``
          run per canary request on the NEW version's sliced outputs;
          False or an exception fails the canary.
        - ``parity_rtol``/``parity_atol``: when ``parity_rtol`` is not
          None, the same canary batches also run through the LIVE
          version and every fetch must ``allclose`` within the bounds
          — for weight-identical refactor swaps, not retrained models.
        - ``standby_timeout_ms``: warm-boot budget before the swap is
          quarantined (stage ``standby``).
        - ``watchdog_ms`` / ``watchdog_max_errors`` /
          ``watchdog_latency_x``: the post-cutover
          :class:`~.resilience.SwapWatchdog` window; ``swap()`` blocks
          through it so the caller gets the typed verdict.
          ``watchdog_ms=0`` skips the window (cutover commits
          immediately)."""
        if not self._swap_lock.acquire(False):
            _m_swaps.inc(outcome="gate_failed")
            raise SwapFailedError(
                f"a swap is already in progress on this server; "
                f"refusing {model_dir!r} at the gate — decide against "
                f"the running deploy's outcome, don't race it",
                stage="gate", retryable=True)
        try:
            return self._swap_locked(
                model_dir, canary_feeds, canary_check, parity_rtol,
                parity_atol, standby_timeout_ms, watchdog_ms,
                watchdog_max_errors, watchdog_latency_x)
        finally:
            self._swap_lock.release()

    def _swap_locked(self, model_dir, canary_feeds, canary_check,
                     parity_rtol, parity_atol, standby_timeout_ms,
                     watchdog_ms, watchdog_max_errors,
                     watchdog_latency_x):
        stage_ms = {}
        t0 = time.perf_counter()
        if self._closed:
            _m_swaps.inc(outcome="gate_failed")
            raise SwapFailedError(
                "server is closing; swap refused at the gate",
                stage="gate", retryable=True)
        # cheap ARGUMENT validation before any stage spends work: a
        # caller error is an EnforceNotMet, never a swap outcome (it
        # judges the call, not the artifact — no outcome counted)
        enforce(canary_feeds is None or len(canary_feeds) >= 1,
                "canary_feeds must hold at least one golden request "
                "(pass None for the default zeros canary)")
        bundle = self._gate(model_dir)
        stage_ms["gate"] = round((time.perf_counter() - t0) * 1e3, 2)
        old_version = self._server.model_version
        _log(f"swap gate passed for "
             f"{bundle.version or 'unversioned'} (live: "
             f"{old_version or 'unversioned'}); warm-booting standby")

        ta = time.perf_counter()
        self._admit(bundle)
        stage_ms["admit"] = round((time.perf_counter() - ta) * 1e3, 2)

        t1 = time.perf_counter()
        standby = self._standby(bundle, standby_timeout_ms)
        stage_ms["standby"] = round((time.perf_counter() - t1) * 1e3, 2)

        t2 = time.perf_counter()
        try:
            self._canary(standby, bundle, canary_feeds, canary_check,
                         parity_rtol, parity_atol)
        except SwapFailedError:
            _m_swaps.inc(outcome="canary_failed")
            self._drain_background(standby)
            raise
        except EnforceNotMet:
            # argument validation inside the canary (e.g. a golden
            # request bigger than the ladder's top bucket): a CALLER
            # error, not a verdict against the artifact — propagate
            # raw (no outcome counted) so watch_dir can tell a broken
            # config from a broken publish; the standby still drains
            self._drain_background(standby)
            raise
        except Exception as e:
            _m_swaps.inc(outcome="canary_failed")
            self._drain_background(standby)
            raise SwapFailedError(
                f"canary execution failed on the standby version "
                f"({type(e).__name__}: {e}); the live version was "
                f"never touched", stage="canary") from e
        stage_ms["canary"] = round((time.perf_counter() - t2) * 1e3, 2)

        t3 = time.perf_counter()
        try:
            old_pool, old_bundle = self._cutover(standby, bundle)
        except SwapFailedError:
            # the closed-server abort inside _cutover: typed already
            _m_swaps.inc(outcome="rolled_back")
            self._drain_background(standby)
            raise
        except Exception as e:
            _m_swaps.inc(outcome="rolled_back")
            self._drain_background(standby)
            raise SwapFailedError(
                f"cutover failed ({type(e).__name__}: {e}); dispatch "
                f"was not committed to the new version",
                stage="cutover") from e
        stage_ms["cutover"] = round((time.perf_counter() - t3) * 1e3, 2)

        t4 = time.perf_counter()
        reason = self._watch_window(watchdog_ms, watchdog_max_errors,
                                    watchdog_latency_x, standby)
        stage_ms["watchdog"] = round((time.perf_counter() - t4) * 1e3,
                                     2)
        if reason is not None:
            self._rollback(old_pool, old_bundle, standby)
            _m_swaps.inc(outcome="rolled_back")
            _log(f"SWAP ROLLED BACK: {reason}; reverted to model "
                 f"version {old_bundle.version or 'unversioned'} "
                 f"(still resident — no reboot, no recompile)")
            raise SwapFailedError(
                f"post-cutover watchdog tripped: {reason}; traffic "
                f"was reverted to the previous version "
                f"{old_bundle.version or 'unversioned'} at a batch "
                f"boundary", stage="watchdog")

        # committed: the old pool drains its already-dispatched
        # batches in the background and releases its params — the end
        # of the ~2x-param-memory window
        self._drain_background(old_pool)
        with self._state_lock:
            # rotate the old series out, then honor a close() that
            # already gave up waiting on this swap: a closing server
            # serves nothing, whatever this swap just committed
            publish_model_version(bundle.version)
            if self._closed:
                clear_model_version(bundle.version)
        _m_swaps.inc(outcome="ok")
        _log(f"serving model version "
             f"{bundle.version or 'unversioned'} from "
             f"{bundle.model_dir} (cutover from "
             f"{old_version or 'unversioned'}, "
             f"{(time.perf_counter() - t0) * 1e3:.0f}ms total)")
        return {"outcome": "ok",
                "model_version": bundle.version,
                "previous_version": old_version,
                "model_dir": model_dir,
                # "int8"/"bf16" when the new version is a quantized
                # export (fp->quant and quant->fp swaps are ordinary
                # swaps; the gate/canary already ran the quantized
                # graph) — an operator reading the report can tell a
                # PTQ deploy from a retrain
                "quantized": bundle.quantized,
                "stage_ms": stage_ms}

    # -- stage 1: gate -----------------------------------------------------
    def _gate(self, model_dir):
        """Integrity + compatibility, committing nothing: re-runs the
        full ``verify_aot_dir`` CRC pass (the boot-time gate does not
        cover an artifact rewritten AFTER boot), loads the new
        program/params on the host, and refuses loudly on any drift
        from the live serving contract."""
        from paddle_tpu.serving.server import (
            _check_fetch_contract, _load_bundle,
        )
        server = self._server
        try:
            bundle = _load_bundle(model_dir, server.config.feed_specs,
                                  verify=True)
        except Exception as e:
            _m_swaps.inc(outcome="gate_failed")
            raise SwapFailedError(
                f"swap gate refused {model_dir!r}: "
                f"{type(e).__name__}: {e} — nothing was committed and "
                f"the live version keeps serving", stage="gate") from e
        live = server._bundle
        for what, new, cur in (
                ("feed names", bundle.feed_names, live.feed_names),
                ("fetch names", bundle.fetch_names, live.fetch_names),
                ("feed sample specs", bundle.sample_specs,
                 live.sample_specs)):
            if new != cur:
                _m_swaps.inc(outcome="gate_failed")
                raise SwapFailedError(
                    f"swap gate refused {model_dir!r}: {what} "
                    f"incompatible with the live config ({new!r} != "
                    f"{cur!r}) — in-flight and queued requests were "
                    f"validated against the live contract and must "
                    f"stay servable on either version through the "
                    f"cutover; deploy contract changes with a new "
                    f"server", stage="gate")
        try:
            _check_fetch_contract(bundle, server.pool.ladder)
        except Exception as e:
            _m_swaps.inc(outcome="gate_failed")
            raise SwapFailedError(
                f"swap gate refused {model_dir!r}: {e}",
                stage="gate") from e
        return bundle

    # -- stage 1.5: memory-aware admission --------------------------------
    def _admit(self, bundle):
        """Project whether the standby can CO-RESIDE with the live
        pool under the per-device HBM limit — and refuse with the
        projected numbers BEFORE the expensive warm boot, instead of
        discovering a mid-cutover OOM. Projection (per device): the
        live pool's worst-bucket compile-time peak (its params ride
        as arguments, so that covers the whole pool) + one copy of
        the standby's param bytes (its executables aren't compiled
        yet — params dominate, and the refusal errs permissive).
        Limit: ``ServingConfig.hbm_limit_bytes``, else the backend /
        PADDLE_TPU_HBM_LIMIT_BYTES fallback; no known limit means
        admission is advisory and always passes."""
        from paddle_tpu.monitor import memory as _memory
        limit = self._server.config.hbm_limit_bytes
        if limit is None:
            limit = _memory.hbm_limit_bytes()
            try:
                import jax
                devs = jax.local_devices()
                if devs:
                    limit = _memory.hbm_limit_bytes(devs[0]) or limit
            except Exception:
                pass
        if not limit:
            return
        live = int(self._server.pool.projected_bytes())
        standby_params = int(sum(np.asarray(p).nbytes
                                 for p in bundle.params_np))
        projected = live + standby_params
        if projected <= int(limit):
            return
        _m_swaps.inc(outcome="refused_memory")
        msg = (f"standby {bundle.version or 'unversioned'} projects "
               f"{projected} bytes per device (live pool {live} + "
               f"standby params {standby_params}) over the HBM limit "
               f"{int(limit)} — the two versions cannot co-reside "
               f"for the cutover window")
        _log(f"SWAP REFUSED at memory admission: {msg}")
        raise SwapFailedError(
            f"swap refused at memory admission: {msg}",
            stage="admission")

    # -- stage 2: standby warm boot ---------------------------------------
    def _build_standby_pool(self, bundle):
        """The expensive build (compile every bucket executable +
        ``device_put`` params) — a separate method so the chaos hooks
        (``PT_FAULT_SWAP_STANDBY_STALL``) can wedge exactly this."""
        from paddle_tpu.serving.server import _boot_pool
        return _boot_pool(bundle, self._server.config, role="standby")

    def _standby(self, bundle, timeout_ms):
        """Warm-boot the new version on a bounded worker thread. A
        build that wedges past ``timeout_ms`` or raises quarantines
        the SWAP (typed, stage ``standby``) while live traffic never
        notices — the abandoned thread's eventual pool, if any, is
        closed and released, never promoted."""
        state = {"pool": None, "err": None, "abandoned": False}
        lk = threading.Lock()

        def build():
            try:
                try:
                    pool = self._build_standby_pool(bundle)
                except BaseException as e:
                    with lk:
                        state["err"] = e
                    return
                with lk:
                    if not state["abandoned"]:
                        state["pool"] = pool
                        return
                # quarantined before we finished: dispose through the
                # TRACKED drain path — shutdown() joins it (close must
                # not report "fully stopped" over this pool's live
                # replica threads) and a drain that fails logs the
                # resident-params leak loudly, never `pass` silence
                self._drain_background(pool)
            finally:
                with self._drain_lock:
                    if t in self._standby_threads:
                        self._standby_threads.remove(t)

        t = threading.Thread(target=build, daemon=True,
                             name="serving-swap-standby")
        t.start()
        t.join(float(timeout_ms) / 1e3)
        with lk:
            pool, err = state["pool"], state["err"]
            if pool is None and err is None:
                state["abandoned"] = True
                # track the still-running build so shutdown can join
                # it: until it finishes (and its pool is disposed via
                # the drain path) the server is not "fully stopped"
                with self._drain_lock:
                    self._standby_threads.append(t)
        if pool is not None:
            return pool
        _m_swaps.inc(outcome="rolled_back")
        if err is not None:
            raise SwapFailedError(
                f"standby warm boot for "
                f"{bundle.version or 'unversioned'} failed "
                f"({type(err).__name__}: {err}); the swap was "
                f"quarantined and the live version keeps serving",
                stage="standby") from err
        raise SwapFailedError(
            f"standby warm boot wedged past {timeout_ms:g}ms; the "
            f"swap was quarantined (build thread abandoned — a pool "
            f"it eventually produces will be discarded) and the live "
            f"version keeps serving", stage="standby")

    # -- stage 3: canary ---------------------------------------------------
    def _canary(self, standby, bundle, canary_feeds, canary_check,
                parity_rtol, parity_atol):
        ladder = standby.ladder
        feeds_list = (canary_feeds if canary_feeds is not None
                      else default_canary_feeds(bundle, ladder))
        enforce(len(feeds_list) >= 1,
                "canary_feeds must hold at least one golden request")
        for ci, feeds in enumerate(feeds_list):
            # feed-presence/shape/rows problems judge the CALLER's
            # canary_feeds, not the artifact — the gate already
            # guaranteed the new version's specs equal the live ones,
            # so these would fail identically for EVERY publish.
            # Argument errors (EnforceNotMet), never a canary verdict:
            # watch_dir stops loudly on them instead of blacklisting
            # good deploys one by one.
            missing = [n for n in bundle.feed_names if n not in feeds]
            enforce(not missing,
                    f"canary request {ci} missing feeds {missing} — "
                    f"canary_feeds must carry every served feed")
            rows = None
            padded = {}
            for n in bundle.feed_names:
                shape, dtype = bundle.sample_specs[n]
                a = np.asarray(feeds[n], dtype=dtype)
                enforce(a.ndim >= 1
                        and tuple(a.shape[1:]) == tuple(shape),
                        f"canary request {ci} feed {n!r} sample "
                        f"shape {tuple(a.shape[1:]) if a.ndim else ()}"
                        f" != served {tuple(shape)}")
                rows = int(a.shape[0]) if rows is None else rows
                enforce(int(a.shape[0]) == rows,
                        f"canary request {ci} feed {n!r} rows "
                        f"{a.shape[0]} != {rows} (all feeds of one "
                        f"canary request share the batch dim)")
                buf = np.zeros((pick_bucket(rows, ladder),)
                               + tuple(shape), dtype)
                buf[:rows] = a
                padded[n] = buf
            bucket = pick_bucket(rows, ladder)
            outs = standby.replicas[0].run_batch(bucket, padded)
            sliced = [np.asarray(o)[:rows] for o in outs]
            for name, o in zip(bundle.fetch_names, sliced):
                if np.issubdtype(o.dtype, np.floating) and \
                        not np.all(np.isfinite(o)):
                    bad = int(np.size(o) - np.count_nonzero(
                        np.isfinite(o)))
                    raise SwapFailedError(
                        f"canary request {ci}: fetch {name!r} from "
                        f"the standby version has {bad} non-finite "
                        f"value(s) — the new version is broken on a "
                        f"golden input; live version untouched",
                        stage="canary")
            if parity_rtol is not None:
                live_outs = self._server.pool.replicas[0].run_batch(
                    bucket, padded)
                for name, a, b in zip(bundle.fetch_names, sliced,
                                      [np.asarray(o)[:rows]
                                       for o in live_outs]):
                    if not np.allclose(a, b, rtol=float(parity_rtol),
                                       atol=float(parity_atol)):
                        diff = float(np.max(np.abs(
                            a.astype(np.float64)
                            - b.astype(np.float64))))
                        raise SwapFailedError(
                            f"canary request {ci}: fetch {name!r} "
                            f"diverges from the live version beyond "
                            f"the parity bounds (max abs diff "
                            f"{diff:.3g}, rtol={parity_rtol}, "
                            f"atol={parity_atol})", stage="canary")
            if canary_check is not None:
                try:
                    ok = canary_check(feeds, sliced)
                except Exception as e:
                    raise SwapFailedError(
                        f"canary request {ci}: canary_check raised "
                        f"{type(e).__name__}: {e}",
                        stage="canary") from e
                if ok is False:
                    raise SwapFailedError(
                        f"canary request {ci}: canary_check returned "
                        f"False", stage="canary")

    # -- stage 4: cutover + rollback --------------------------------------
    def _cutover(self, standby, bundle):
        """Flip dispatch to the standby pool at a batch boundary.
        Batches already queued on the old pool drain THERE (every
        micro-batch executes wholly on one version); the old pool
        stays warm-resident until the watchdog window passes, so a
        rollback is two attribute flips, not a reboot. A chaos hook
        (``PT_FAULT_SWAP_ERROR_STORM``) patches this method to poison
        the new pool immediately after the flip."""
        server = self._server
        with self._state_lock:
            # atomic with shutdown()'s _closed write: a close() whose
            # bounded wait on this swap expired must not be outrun by
            # a later cutover that promotes a pool nothing will ever
            # close and republishes a series nothing will ever clear
            if self._closed:
                raise SwapFailedError(
                    "server closed while the swap was in flight; "
                    "aborted before cutover — nothing was committed "
                    "and the standby is being discarded",
                    stage="cutover", retryable=True)
            old_pool, old_bundle = server.pool, server._bundle
            try:
                server.pool = standby
                server._apply_bundle(bundle)
                server.scheduler.set_dispatch(standby.dispatch)
                old_pool.demote()
                standby.promote()
            except BaseException:
                # a flip raised partway (only reachable through
                # instrumented/chaos-wrapped methods — the flips are
                # plain attribute stores — but the generic handler
                # above us says "dispatch was not committed" and must
                # be telling the truth): put every already-applied
                # flip back before the standby is drained out
                server.scheduler.set_dispatch(old_pool.dispatch)
                server.pool = old_pool
                server._apply_bundle(old_bundle)
                standby.demote()
                old_pool.promote()
                raise
        return old_pool, old_bundle

    def _rollback(self, old_pool, old_bundle, standby):
        """Revert traffic to the still-resident old version — the
        mirror of ``_cutover``, plus background disposal of the failed
        new pool (its queued batches drain/fail typed there). Like
        ``_cutover``, the flips are atomic with shutdown's ``_closed``
        write: a rollback racing server.close() must NOT promote the
        old pool (republishing gauges close just zeroed) or leave its
        replica threads running past a True close — on a closing
        server the reverted-to pool drains out too, and close()'s
        swap-lock wait joins that drain before reporting stopped."""
        server = self._server
        with self._state_lock:
            closed = self._closed
            server.scheduler.set_dispatch(old_pool.dispatch)
            server.pool = old_pool
            server._apply_bundle(old_bundle)
            standby.demote()
            if not closed:
                old_pool.promote()
        self._drain_background(standby)
        if closed:
            self._drain_background(old_pool)

    def _watch_window(self, watchdog_ms, max_errors, latency_x,
                      new_pool):
        """Run the post-cutover watchdog window; returns a rollback
        reason or None. The error verdict counts the NEW pool's own
        ``batch_failures`` — the old pool's still-draining batches can
        fail (a wedged straggler) without tripping a rollback of a
        healthy new version. The baseline for the (opt-in) latency
        verdict is the process-lifetime mean request latency captured
        at the flip — crude but monotone-safe; error-storm detection
        is the primary signal."""
        if not watchdog_ms or watchdog_ms <= 0:
            return None
        baseline = None
        if latency_x is not None:
            s, c = SwapWatchdog._latency()
            baseline = (s / c) if c else None
            if baseline is None:
                # the caller opted into a latency verdict it cannot
                # get — degraded coverage must be visible, not silent
                _log("swap watchdog: watchdog_latency_x requested but "
                     "no request has completed before this swap, so "
                     "there is no latency baseline — the latency "
                     "verdict is DISABLED for this swap (the "
                     "error-storm verdict still runs)")
        wd = SwapWatchdog(window_ms=watchdog_ms,
                          max_errors=max_errors, latency_x=latency_x,
                          baseline_ms=baseline,
                          errors_fn=lambda: new_pool.batch_failures
                          ).start()
        while True:
            reason = wd.verdict()
            if reason is not None:
                return reason
            if wd.expired():
                # one terminal verdict above covers counts that landed
                # in the final poll gap
                return None
            time.sleep(min(0.02, wd.window_s / 4 or 0.001))

    # -- background drain of a retired pool -------------------------------
    def _drain_background(self, pool):
        """Close + release a demoted/rejected pool without blocking
        traffic: its replicas finish the batches already queued to it
        (completing or failing them typed), then the params and
        executable maps drop — ending the 2x-memory window. A pool
        that will not drain (a replica wedged longer than close's own
        loss-judging can absorb) leaves its params RESIDENT — that is
        a real leak and it is logged loudly, never swallowed."""

        def drain():
            try:
                # one bounded retry: close() keeps judging wedged
                # replicas itself, so a second pass is usually enough
                # for a straggler that outlived the first window
                ok = pool.close(timeout=120) or pool.close(timeout=120)
                if ok:
                    pool.release()
                else:
                    _log("retired pool failed to drain within 240s; "
                         "its params and executables remain RESIDENT "
                         "(the hot-swap 2x-param-memory window did "
                         "not end) — a replica is wedged past every "
                         "loss-judging window; restart the server to "
                         "reclaim the memory")
            except Exception as e:
                _log(f"retired pool drain failed "
                     f"({type(e).__name__}: {e}); its params remain "
                     f"RESIDENT — restart the server to reclaim the "
                     f"memory")
            with self._drain_lock:
                if t in self._drain_threads:
                    self._drain_threads.remove(t)

        t = threading.Thread(target=drain, daemon=True,
                             name="serving-swap-drain")
        with self._drain_lock:
            self._drain_threads.append(t)
        t.start()

    # -- watch-dir mode ----------------------------------------------------
    def watch_dir(self, model_dir=None, poll_ms=1000.0,
                  **swap_kwargs):
        """Continuous deploy: poll ``model_dir`` (default: the dir the
        server is currently serving from) for a NEW manifest
        ``model_version`` via the cheap index-only
        ``read_aot_version`` probe, and ``swap()`` to it when it
        changes. A version whose swap failed is remembered and skipped
        until the publisher writes a DIFFERENT version — one loud log
        line per bad artifact, no gate crash-loop, live version
        serving throughout. Unversioned dirs (no ``export_aot``
        manifest) are never auto-swapped: versioning is the publish
        signal."""
        enforce(self._watch_thread is None
                or not self._watch_thread.is_alive(),
                "watch_dir is already running on this server; "
                "stop_watch() first")
        enforce(not self._closed,
                "watch_dir refused: the server is closed")
        enforce(float(poll_ms) > 0,
                f"poll_ms must be positive, got {poll_ms!r}")
        target = model_dir or self._server.model_dir
        self._watch_stop.clear()

        def loop():
            from paddle_tpu.inference import read_aot_version
            while not self._watch_stop.wait(float(poll_ms) / 1e3):
                if self._closed:
                    return
                v = read_aot_version(target)
                if (v is None or v == self._server.model_version
                        or v == self._watch_failed_version):
                    continue
                _log(f"watch_dir: new model version {v} published in "
                     f"{target}; swapping")
                try:
                    self.swap(target, **swap_kwargs)
                    self._watch_failed_version = None
                except SwapFailedError as e:
                    if e.retryable:
                        # the TARGET was never judged (another swap
                        # held the lock / server closing): retry next
                        # poll — memoizing here would silently strand
                        # a good publish forever
                        _log(f"watch_dir: swap to {v} deferred "
                             f"({e}); will retry next poll")
                        continue
                    self._watch_failed_version = v
                    _log(f"watch_dir: swap to {v} failed at stage "
                         f"{e.stage!r} ({e}); live version keeps "
                         f"serving — will not retry until a new "
                         f"version is published")
                except EnforceNotMet as e:
                    # argument validation: the WATCHER's swap_kwargs
                    # are wrong, which says nothing about this (or
                    # any) artifact — every future attempt would fail
                    # identically, so stop loudly instead of either
                    # blacklisting a never-judged publish or retrying
                    # a config error forever
                    _log(f"watch_dir: swap arguments invalid ({e}); "
                         f"STOPPING the watcher — fix the watch_dir "
                         f"kwargs and re-arm (live version keeps "
                         f"serving, version {v} was NOT judged)")
                    return
                except Exception as e:  # never kill the watcher
                    self._watch_failed_version = v
                    _log(f"watch_dir: swap to {v} failed "
                         f"unexpectedly ({type(e).__name__}: {e}); "
                         f"live version keeps serving")

        self._watch_thread = threading.Thread(
            target=loop, daemon=True, name="serving-swap-watch")
        self._watch_thread.start()
        return self

    def stop_watch(self, timeout=5.0):
        """Stop the watch-dir poller (idempotent). Returns True when
        the thread exited within ``timeout``."""
        self._watch_stop.set()
        t = self._watch_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    # -- lifecycle ---------------------------------------------------------
    def begin_shutdown(self):
        """The FAST half of a server close, run BEFORE the scheduler
        stops admission: refuse new swaps (atomic with ``_cutover`` —
        an in-flight swap that has not yet flipped dispatch will abort
        instead of promoting a pool on a closing server) and stop the
        watch-dir poller so no swap can start mid-close."""
        with self._state_lock:
            self._closed = True
        self.stop_watch(timeout=5.0)

    def finish_shutdown(self, timeout=None):
        """The SLOW half, run after the scheduler and live pool have
        closed: wait for an in-flight swap to finish aborting/rolling
        back, join background pool drains and any abandoned standby
        build, so close() never reports "fully stopped" over live swap
        machinery. ``timeout=None`` blocks to completion (the close()
        contract) — except for a standby BUILD thread wedged inside a
        native compile, which cannot be interrupted: it is joined for
        a bounded grace, the leak is logged LOUDLY, and False is
        returned. One deadline is shared by every phase — a caller's
        close(T) bounds the whole wait near T, not T-per-phase."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout

        def left(default):
            if deadline is None:
                return default
            return max(deadline - time.monotonic(), 0.0)

        done = True
        # every swap stage is individually bounded (standby_timeout_ms,
        # the canary's finite batch set, watchdog_ms), so a blocking
        # acquire terminates; with a timeout, a miss means the swap is
        # still unwinding — not "fully stopped", so False propagates
        if deadline is None:
            self._swap_lock.acquire()
            self._swap_lock.release()
        elif self._swap_lock.acquire(timeout=left(0.0)):
            self._swap_lock.release()
        else:
            done = False
        with self._drain_lock:
            drains = list(self._drain_threads)
            builds = list(self._standby_threads)
        for t in drains:
            # drain threads are bounded by construction (two 120s
            # close windows + release), so a None timeout can safely
            # block on them
            t.join(left(None) if deadline is None else left(0.0))
            done = done and not t.is_alive()
        for t in builds:
            t.join(left(300.0))
            if t.is_alive():
                done = False
                _log("close: an abandoned standby build is still "
                     "wedged inside compilation; the pool it may "
                     "eventually produce will be discarded, but its "
                     "thread (and any params it allocates) cannot be "
                     "reclaimed — restart the process to be rid of it")
        return done

    def shutdown(self, timeout=None):
        """Both halves back to back — for callers outside the
        server's own close() sequencing."""
        self.begin_shutdown()
        return self.finish_shutdown(timeout)
