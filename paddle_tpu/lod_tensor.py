"""fluid.lod_tensor helpers.

Parity: python/paddle/fluid/lod_tensor.py (create_lod_tensor,
create_random_int_lodtensor). The TPU-native LoD form is
core.lod.RaggedBatch — dense padding + explicit lengths (SURVEY §7's
LoD translation) — so these constructors build RaggedBatch from the
reference's recursive_sequence_lengths format.
"""

import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.core.lod import RaggedBatch

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]


def _innermost_lengths(recursive_seq_lens):
    """Validate a multi-level recursive_sequence_lengths structure and
    return the innermost level's per-sequence row counts (outer levels
    group sequences; the rows live at the innermost level). Mirrors the
    reference's has_valid_recursive_sequence_lengths: each outer
    level's sum must equal the next level's sequence count."""
    if not recursive_seq_lens:
        raise EnforceNotMet("recursive_seq_lens must be non-empty")
    for lvl in recursive_seq_lens:
        if not isinstance(lvl, (list, tuple)) or not lvl:
            raise EnforceNotMet(
                "recursive_seq_lens must be a non-empty list of "
                "non-empty lists")
    for outer, inner in zip(recursive_seq_lens, recursive_seq_lens[1:]):
        if int(np.sum(outer)) != len(inner):
            raise EnforceNotMet(
                f"invalid recursive_seq_lens: outer level sums to "
                f"{int(np.sum(outer))} but the next level has "
                f"{len(inner)} sequences")
    return list(recursive_seq_lens[-1])


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """fluid.create_lod_tensor parity: build a ragged batch from flat
    row data + recursive sequence lengths.

    data: numpy array / jax array of shape [sum(lens), ...], or a list
    of per-sequence lists (each becoming a column vector row group,
    like the reference's list form).
    """
    lens = _innermost_lengths(recursive_seq_lens)
    if isinstance(data, list):
        # reference list form: list of per-sequence lists; each element
        # becomes a [len, 1] column. Validate lengths BEFORE reshaping
        # so mismatches report as EnforceNotMet, not numpy errors.
        if [len(s) for s in data] != lens:
            raise EnforceNotMet(
                f"recursive_seq_lens {lens} does not match data "
                f"lengths {[len(s) for s in data]}")
        width = max((np.asarray(s).reshape(len(s), -1).shape[1]
                     for s in data if len(s)), default=1)
        flat = np.concatenate(
            [np.asarray(s).reshape(len(s), -1) if len(s)
             else np.zeros((0, width)) for s in data], axis=0)
    else:
        flat = np.asarray(data)
        if flat.shape[0] != int(np.sum(lens)):
            raise EnforceNotMet(
                f"sum(recursive_seq_lens[-1])={int(np.sum(lens))} != "
                f"data rows {flat.shape[0]}")
    seqs, off = [], 0
    for n in lens:
        seqs.append(flat[off:off + n])
        off += n
    rb = RaggedBatch.from_list(seqs)
    rb.recursive_seq_lens = [list(l) for l in recursive_seq_lens]
    return rb


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=10, seed=None):
    """fluid.create_random_int_lodtensor parity: random ints in
    [low, high] with per-row shape base_shape."""
    lens = _innermost_lengths(recursive_seq_lens)
    total = int(np.sum(lens))
    rng = np.random.RandomState(seed)
    flat = rng.randint(low, high + 1,
                       size=[total] + list(base_shape)).astype(np.int64)
    return create_lod_tensor(flat, recursive_seq_lens, place)
