"""SelectedRows: sparse row-set tensors for embedding gradients.

Parity targets: framework/selected_rows.{h,cc} (rows + value block of a
conceptually [height, ...] tensor), operators/merge_selected_rows_op.cc
(sum duplicate rows), split/get ops (operators/split_selected_rows_op.cc,
get_tensor_from_selected_rows_op.cc), lookup_sparse_table
(operators/lookup_sparse_table_op.cc) and the sgd kernel's sparse branch
(operators/optimizers/sgd_op.cc SelectedRows path).

TPU-native shape: a (rows, values, height) triple of device arrays.
Embedding grads naturally arrive this way (grad of a gather IS a
row-set); `merge` uses segment_sum so it jits; scatter-apply uses
.at[].add — XLA lowers both to efficient scatter."""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "SelectedRows", "merge_selected_rows", "get_tensor_from_selected_rows",
    "split_selected_rows", "sparse_sgd_update", "lookup_sparse_table",
]


class SelectedRows(NamedTuple):
    rows: jnp.ndarray      # [n] int row indices (may repeat before merge)
    values: jnp.ndarray    # [n, ...] row payloads
    height: int            # logical dim-0 of the dense tensor


def _scatter_add(dense, rows, values):
    """dense[rows] += values, through the Pallas kernel registry when it
    selects the fused scatter-add body; the stock .at[].add otherwise
    (bit-identical flag-off path)."""
    from paddle_tpu.ops import pallas as _plk
    if jnp.ndim(values) == 2 and jnp.ndim(dense) == 2 \
            and _plk.use_pallas("embedding_scatter_add"):
        return _plk.dispatch("embedding_scatter_add", dense, rows, values)
    return dense.at[rows].add(values)


def merge_selected_rows(sr):
    """Sum duplicate rows (merge_selected_rows_op.cc). Jittable: the
    output keeps first-occurrence order of unique rows."""
    rows = jnp.asarray(sr.rows)
    uniq, inv = jnp.unique(rows, return_inverse=True,
                           size=rows.shape[0], fill_value=-1)
    summed = _scatter_add(
        jnp.zeros((rows.shape[0],) + tuple(sr.values.shape[1:]),
                  sr.values.dtype),
        inv.reshape(-1), sr.values)
    valid = uniq >= 0
    return SelectedRows(jnp.where(valid, uniq, 0), summed, sr.height), valid


def get_tensor_from_selected_rows(sr):
    """Densify (get_tensor_from_selected_rows_op.cc)."""
    dense = jnp.zeros((sr.height,) + tuple(sr.values.shape[1:]),
                      sr.values.dtype)
    return _scatter_add(dense, sr.rows, sr.values)


def split_selected_rows(sr, num_splits):
    """split_selected_rows_op.cc: shard rows by range over pservers —
    shard i owns rows [i*h/k, (i+1)*h/k)."""
    bounds = [sr.height * i // num_splits for i in range(num_splits + 1)]
    rows = np.asarray(sr.rows)
    vals = np.asarray(sr.values)
    out = []
    for i in range(num_splits):
        m = (rows >= bounds[i]) & (rows < bounds[i + 1])
        out.append(SelectedRows(jnp.asarray(rows[m] - bounds[i]),
                                jnp.asarray(vals[m]),
                                bounds[i + 1] - bounds[i]))
    return out


def sparse_sgd_update(param, sr_grad, lr):
    """sgd_op.cc SelectedRows branch: scatter-subtract only touched
    rows."""
    return _scatter_add(param, sr_grad.rows, -lr * sr_grad.values)


def lookup_sparse_table(table_dict, ids, dim, init_fn=None, seed=0):
    """lookup_sparse_table_op.cc: auto-growing host-side table lookup
    (python dict of id->row; the distributed twin lives in
    distributed/ps.py _SparseTable)."""
    rng = np.random.RandomState(seed)
    init_fn = init_fn or (
        lambda r: r.normal(0, 0.01, dim).astype(np.float32))
    out = np.empty((len(ids), dim), np.float32)
    for i, x in enumerate(np.asarray(ids).reshape(-1)):
        row = table_dict.get(int(x))
        if row is None:
            row = init_fn(rng)
            table_dict[int(x)] = row
        out[i] = row
    return jnp.asarray(out)
