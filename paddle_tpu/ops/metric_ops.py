"""Metric ops.

Parity targets: operators/metrics/ (accuracy_op.cc, auc_op.cc,
precision_recall_op.cc), chunk_eval_op.cc (python-side in metrics.py).
"""

import jax.numpy as jnp

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, name=None):
    """accuracy_op.cc parity: top-k accuracy; returns scalar [1]."""
    input = jnp.asarray(input)
    label = jnp.asarray(label)
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    if k == 1:
        pred = jnp.argmax(input, axis=-1)
        correct = (pred == label)
    else:
        idx = jnp.argsort(-input, axis=-1)[:, :k]
        correct = jnp.any(idx == label[:, None], axis=-1)
    return jnp.mean(correct.astype(jnp.float32))


def auc(predict, label, num_thresholds=4096, name=None):
    """auc_op.cc parity (batch AUC via threshold histogram)."""
    predict = jnp.asarray(predict)
    label = jnp.asarray(label).reshape(-1)
    pos_prob = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 \
        else predict.reshape(-1)
    bins = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32),
                    0, num_thresholds - 1)
    pos = jnp.zeros(num_thresholds).at[bins].add(label.astype(jnp.float32))
    neg = jnp.zeros(num_thresholds).at[bins].add(1.0 - label)
    # integrate from the top threshold down
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    tpr = tp / jnp.maximum(tot_pos, 1.0)
    fpr = fp / jnp.maximum(tot_neg, 1.0)
    return jnp.trapezoid(tpr, fpr)
