"""Metric ops.

Parity targets: operators/metrics/ (accuracy_op.cc, auc_op.cc,
precision_recall_op.cc), chunk_eval_op.cc (python-side in metrics.py).
"""

import jax.numpy as jnp

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, name=None):
    """accuracy_op.cc parity: top-k accuracy; returns scalar [1]."""
    input = jnp.asarray(input)
    label = jnp.asarray(label)
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    if k == 1:
        pred = jnp.argmax(input, axis=-1)
        correct = (pred == label)
    else:
        idx = jnp.argsort(-input, axis=-1)[:, :k]
        correct = jnp.any(idx == label[:, None], axis=-1)
    return jnp.mean(correct.astype(jnp.float32))


def auc(predict, label, num_thresholds=4096, name=None):
    """auc_op.cc parity (batch AUC via threshold histogram)."""
    predict = jnp.asarray(predict)
    label = jnp.asarray(label).reshape(-1)
    pos_prob = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 \
        else predict.reshape(-1)
    bins = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32),
                    0, num_thresholds - 1)
    pos = jnp.zeros(num_thresholds).at[bins].add(label.astype(jnp.float32))
    neg = jnp.zeros(num_thresholds).at[bins].add(1.0 - label)
    # integrate from the top threshold down
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    tpr = tp / jnp.maximum(tot_pos, 1.0)
    fpr = fp / jnp.maximum(tot_neg, 1.0)
    return jnp.trapezoid(tpr, fpr)


def precision_recall(predict, label, num_classes):
    """operators/metrics/precision_recall_op.cc: per-class and macro
    (precision, recall, f1). predict [B, C] scores, label [B]."""
    import numpy as np
    pred = np.asarray(jnp.argmax(predict, axis=-1)).reshape(-1)
    lab = np.asarray(label).reshape(-1)
    eps = 1e-12
    per = []
    for c in range(num_classes):
        tp = float(((pred == c) & (lab == c)).sum())
        fp = float(((pred == c) & (lab != c)).sum())
        fn = float(((pred != c) & (lab == c)).sum())
        p = tp / (tp + fp + eps)
        r = tp / (tp + fn + eps)
        f1 = 2 * p * r / (p + r + eps)
        per.append((p, r, f1))
    macro = tuple(sum(m[i] for m in per) / num_classes for i in range(3))
    return per, macro


def chunk_eval(inference, label, chunk_scheme="IOB", num_chunk_types=None,
               excluded_chunk_types=()):
    """operators/chunk_eval_op.cc: chunking F1 for sequence labeling.
    Tags encode (type, position) as tag = type * tag_num + pos with the
    scheme's position alphabet (IOB: B=0,I=1; IOE: I=0,E=1; IOBES:
    B,I,E,S=0..3; plain: single tag per type). Returns
    (precision, recall, f1, num_infer, num_label, num_correct)."""
    import numpy as np

    schemes = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}
    if chunk_scheme not in schemes:
        raise ValueError(f"unknown chunk_scheme {chunk_scheme!r}")
    width = schemes[chunk_scheme]

    def extract(tags):
        """tag sequence -> set of (start, end, type) chunks. Stray
        continuation tags start a chunk (CoNLL/ChunkEvaluator behavior)."""
        chunks = []
        state = {"start": None, "type": None}

        def close(i):
            if state["start"] is not None:
                chunks.append((state["start"], i - 1, state["type"]))
            state["start"] = state["type"] = None

        def open_(i, typ):
            close(i)
            state["start"], state["type"] = i, typ

        for i, t in enumerate(list(tags) + [-1]):
            if t < 0:
                close(i)
                continue
            typ, pos = divmod(int(t), width)
            outside = (num_chunk_types is not None
                       and typ >= num_chunk_types)
            if outside or typ in excluded_chunk_types:
                close(i)      # 'O' tag (tag >= types*width) ends chunks
                continue
            if chunk_scheme == "plain":
                if state["start"] is None or typ != state["type"]:
                    open_(i, typ)
            elif chunk_scheme == "IOB":          # B=0, I=1
                if pos == 0 or state["start"] is None \
                        or typ != state["type"]:
                    open_(i, typ)
            elif chunk_scheme == "IOE":          # I=0, E=1 (inclusive end)
                if state["start"] is None or typ != state["type"]:
                    open_(i, typ)
                if pos == 1:
                    chunks.append((state["start"], i, state["type"]))
                    state["start"] = state["type"] = None
            else:                                 # IOBES: B,I,E,S=0..3
                if pos == 3:
                    close(i)
                    chunks.append((i, i, typ))
                elif pos == 0:
                    open_(i, typ)
                else:                             # I or E
                    if state["start"] is None or typ != state["type"]:
                        open_(i, typ)
                    if pos == 2:
                        chunks.append((state["start"], i, state["type"]))
                        state["start"] = state["type"] = None
        return set(chunks)

    inf = np.asarray(inference).reshape(-1)
    lab = np.asarray(label).reshape(-1)
    ci = extract(inf)
    cl = extract(lab)
    correct = len(ci & cl)
    eps = 1e-12
    p = correct / (len(ci) + eps)
    r = correct / (len(cl) + eps)
    f1 = 2 * p * r / (p + r + eps)
    return p, r, f1, len(ci), len(cl), correct


def positive_negative_pair(score, label, query_ids):
    """operators/metrics/positive_negative_pair_op.cc: within each query,
    count ordered pairs where the higher-labeled doc scores higher
    (positive) vs lower (negative); ties are neutral."""
    import numpy as np
    s = np.asarray(score).reshape(-1)
    l = np.asarray(label).reshape(-1)
    q = np.asarray(query_ids).reshape(-1)
    pos = neg = neu = 0
    for qid in np.unique(q):
        idx = np.nonzero(q == qid)[0]
        for a in range(len(idx)):
            for b in range(a + 1, len(idx)):
                i, j = idx[a], idx[b]
                if l[i] == l[j]:
                    continue
                hi, lo = (i, j) if l[i] > l[j] else (j, i)
                if s[hi] > s[lo]:
                    pos += 1
                elif s[hi] < s[lo]:
                    neg += 1
                else:
                    neu += 1
    return pos, neg, neu


__all__ += ["precision_recall", "chunk_eval", "positive_negative_pair"]
