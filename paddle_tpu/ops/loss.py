"""Loss ops.

Parity targets: cross_entropy_op.cc, softmax_with_cross_entropy_op.cc,
sigmoid_cross_entropy_with_logits_op.cc, squared_l2_distance_op.cc,
smooth_l1_loss_op.cc, huber_loss_op.cc, log_loss_op.cc, hinge_loss_op.cc,
margin_rank_loss_op.cc, rank_loss_op.cc, kldiv_loss_op.cc, bpr_loss_op.cc,
cos_sim_op.cc, modified_huber_loss_op.cc, npair? (absent), mse (square_error),
teacher_student_sigmoid_loss_op.cc, center_loss (absent in this rev).
"""

import jax
import jax.numpy as jnp

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost",
    "smooth_l1", "huber_loss", "log_loss", "hinge_loss",
    "margin_rank_loss", "rank_loss", "kldiv_loss", "bpr_loss", "cos_sim",
    "modified_huber_loss", "mse_loss", "teacher_student_sigmoid_loss",
    "npair_loss", "dice_loss", "sampled_softmax_with_cross_entropy",
]


def _squeeze_label(label):
    label = jnp.asarray(label)
    if label.ndim and label.shape[-1] == 1:
        return label[..., 0]
    return label


def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  name=None):
    """cross_entropy_op.cc parity: input is a probability distribution
    (post-softmax). Returns [..., 1] like the reference."""
    eps = 1e-12
    if soft_label:
        loss = -jnp.sum(label * jnp.log(input + eps), axis=-1, keepdims=True)
        return loss
    lab = _squeeze_label(label)
    picked = jnp.take_along_axis(input, lab[..., None].astype(jnp.int32),
                                 axis=-1)
    loss = -jnp.log(picked + eps)
    if ignore_index >= 0:
        loss = jnp.where(lab[..., None] == ignore_index, 0.0, loss)
    return loss


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False,
                               axis=-1, name=None):
    """softmax_with_cross_entropy_op.cc parity — numerically-stable fused
    form (the op exists in the reference precisely because composing
    softmax+CE is unstable; here XLA fuses the stable logsumexp form)."""
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = jnp.asarray(label)
        # label is logits-shaped with the class axis of size 1, or has the
        # class axis dropped entirely; normalize to the former
        if lab.ndim != logp.ndim:
            lab = jnp.expand_dims(lab, axis)
        labx = lab.astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, labx, axis=axis)
        if ignore_index >= 0:
            loss = jnp.where(labx == ignore_index, 0.0, loss)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    """sigmoid_cross_entropy_with_logits_op.cc parity."""
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    valid = (label != ignore_index)
    loss = jnp.where(valid, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return loss


def square_error_cost(input, label, name=None):
    return jnp.square(input - label)


def mse_loss(input, label):
    return jnp.mean(jnp.square(input - label))


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0,
              name=None):
    """smooth_l1_loss_op.cc parity; returns [N, 1] summed over trailing dims."""
    sigma2 = sigma * sigma
    diff = x - y
    if inside_weight is not None:
        diff = diff * inside_weight
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * diff * diff,
                     ad - 0.5 / sigma2)
    if outside_weight is not None:
        loss = loss * outside_weight
    return jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)


def huber_loss(input, label, delta=1.0, name=None):
    d = label - input
    ad = jnp.abs(d)
    return jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))


def log_loss(input, label, epsilon=1e-4, name=None):
    return (-label * jnp.log(input + epsilon)
            - (1 - label) * jnp.log(1 - input + epsilon))


def hinge_loss(input, label, name=None):
    return jnp.maximum(0.0, 1.0 - input * (2 * label - 1))


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return jnp.maximum(0.0, -label * (left - right) + margin)


def rank_loss(label, left, right, name=None):
    d = left - right
    return jnp.log1p(jnp.exp(d)) - label * d


def kldiv_loss(x, target, reduction="mean", name=None):
    """kldiv_loss_op.cc parity: x is log-prob, target is prob."""
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    loss = jnp.where(target > 0, loss, 0.0)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return loss


def bpr_loss(input, label, name=None):
    """bpr_loss_op.cc parity: Bayesian personalized ranking over softmax
    correct-vs-others."""
    lab = _squeeze_label(label).astype(jnp.int32)
    pos = jnp.take_along_axis(input, lab[:, None], axis=1)
    diff = input - pos
    loss = jnp.log1p(jnp.exp(diff))
    n = input.shape[1]
    mask = jax.nn.one_hot(lab, n, dtype=loss.dtype)
    loss = jnp.sum(loss * (1 - mask), axis=1, keepdims=True) / (n - 1)
    return loss


def cos_sim(x, y, name=None):
    """cos_sim_op.cc parity: row-wise cosine similarity, y broadcastable."""
    x2 = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    y2 = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    xy = jnp.sum(x * y, axis=-1, keepdims=True)
    return xy / (x2 * y2 + 1e-12)


def modified_huber_loss(input, label, name=None):
    a = (2 * label - 1) * input
    return jnp.where(a < -1, -4.0 * a,
                     jnp.square(jnp.maximum(0.0, 1.0 - a)))


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0, name=None):
    x = jnp.clip(input, soft_max_lower_bound, soft_max_up_bound)
    z = jnp.asarray(label)
    # teacher (z<=0 means no teacher signal) + student parts, per the op
    sig = jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0.0)
    student = sig - x * (z > 0.5).astype(x.dtype)
    return student


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    sim = jnp.matmul(anchor, positive.T)
    lab = labels.reshape(-1)
    tgt = (lab[:, None] == lab[None, :]).astype(sim.dtype)
    tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
    ce = -jnp.sum(tgt * jax.nn.log_softmax(sim, axis=1), axis=1)
    l2 = jnp.mean(jnp.sum(jnp.square(anchor) + jnp.square(positive), axis=1))
    return jnp.mean(ce) + l2_reg * l2 * 0.25


def dice_loss(input, label, epsilon=1e-5, name=None):
    """fluid.layers.dice_loss parity (python/paddle/fluid/layers/nn.py
    dice_loss): input is per-class probabilities [..., C], label holds
    class indices [..., 1]; loss = 1 - 2*|X∩Y| / (|X|+|Y|)."""
    input = jnp.asarray(input)
    lab = _squeeze_label(label).astype(jnp.int32)
    one_hot = jax.nn.one_hot(lab, input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inse = jnp.sum(input * one_hot, axis=reduce_dims)
    dice_denom = (jnp.sum(input, axis=reduce_dims)
                  + jnp.sum(one_hot, axis=reduce_dims))
    dice = (2.0 * inse + epsilon) / (dice_denom + epsilon)
    return jnp.mean(1.0 - dice)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       remove_accidental_hits=True,
                                       seed=0, rng=None, name=None):
    """fluid.layers.sampled_softmax_with_cross_entropy parity
    (sample_logits_op.cc + softmax_with_cross_entropy): softmax CE
    evaluated over {true class} ∪ {num_samples uniform negatives}
    instead of the full vocabulary.

    TPU-first shape discipline: the sampled class set is a static
    [B, 1+num_samples] gather, so the op stays jit-compatible (no
    dynamic vocab-sized scatter). Sampling is uniform over the vocab
    (the reference's default sampler is log-uniform over *shuffled*
    ids, which is uniform in distribution).
    """
    from paddle_tpu.core import random as ptrandom
    logits = jnp.asarray(logits)
    lab = _squeeze_label(label).astype(jnp.int32)
    b, v = logits.shape
    if use_customized_samples:
        samples = jnp.asarray(customized_samples).astype(jnp.int32)
        if samples.ndim == 1:
            samples = jnp.broadcast_to(samples[None, :], (b, samples.shape[0]))
    else:
        if rng is None:
            rng = ptrandom.key_for(seed)
        samples = jax.random.randint(rng, (b, num_samples), 0, v)
    classes = jnp.concatenate([lab[:, None], samples], axis=1)  # [B, 1+S]
    picked = jnp.take_along_axis(logits, classes, axis=1)
    if remove_accidental_hits:
        # a sampled negative equal to the true class would cancel the
        # true logit; push it to -inf like the reference's kernel
        hit = classes[:, 1:] == lab[:, None]
        picked = picked.at[:, 1:].set(
            jnp.where(hit, jnp.finfo(picked.dtype).min, picked[:, 1:]))
    loss = -jax.nn.log_softmax(picked, axis=1)[:, 0]
    return loss[:, None]
