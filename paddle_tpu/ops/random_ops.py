"""Random-generation ops.

Parity targets: gaussian_random_op.cc, uniform_random_op.cc,
truncated_gaussian_random_op.cc, random_crop_op.cc, sampling_id_op.cc,
*_batch_size_like variants. Eager calls draw keys from the global RNG
(paddle_tpu.core.random); jitted code should pass `rng=` explicitly.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core import random as ptrandom
from paddle_tpu.core.dtypes import convert_dtype

__all__ = [
    "gaussian_random", "uniform_random", "truncated_gaussian_random",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
    "randint", "sampling_id", "random_crop", "shuffle_batch",
]


def _key(seed, rng):
    return rng if rng is not None else ptrandom.key_for(seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    rng=None, name=None):
    k = _key(seed, rng)
    return mean + std * jax.random.normal(k, tuple(shape),
                                          convert_dtype(dtype))


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   rng=None, name=None):
    k = _key(seed, rng)
    return jax.random.uniform(k, tuple(shape), convert_dtype(dtype),
                              minval=min, maxval=max)


def truncated_gaussian_random(shape, mean=0.0, std=1.0, seed=0,
                              dtype="float32", rng=None, name=None):
    k = _key(seed, rng)
    return mean + std * jax.random.truncated_normal(
        k, -2.0, 2.0, tuple(shape), convert_dtype(dtype))


def uniform_random_batch_size_like(input, shape, input_dim_idx=0,
                                   output_dim_idx=0, min=-1.0, max=1.0,
                                   seed=0, dtype="float32", rng=None,
                                   name=None):
    shape = list(shape)
    shape[output_dim_idx] = jnp.asarray(input).shape[input_dim_idx]
    return uniform_random(shape, dtype, min, max, seed, rng)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32", rng=None,
                                    name=None):
    shape = list(shape)
    shape[output_dim_idx] = jnp.asarray(input).shape[input_dim_idx]
    return gaussian_random(shape, mean, std, seed, dtype, rng)


def randint(low, high=None, shape=(1,), dtype="int64", seed=0, rng=None):
    if high is None:
        low, high = 0, low
    k = _key(seed, rng)
    return jax.random.randint(k, tuple(shape), low, high,
                              convert_dtype(dtype))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", rng=None,
                name=None):
    """sampling_id_op.cc parity: sample one category per row of prob
    matrix x."""
    k = _key(seed, rng)
    return jax.random.categorical(k, jnp.log(jnp.maximum(x, 1e-20)),
                                  axis=-1).astype(convert_dtype(dtype))


def random_crop(x, shape, seed=0, rng=None, name=None):
    """random_crop_op.cc parity: random spatial crop of trailing dims."""
    k = _key(seed, rng)
    x = jnp.asarray(x)
    nd, tail = x.ndim, len(shape)
    starts = []
    for i, s in enumerate(shape):
        k, sub = jax.random.split(k)
        hi = x.shape[nd - tail + i] - s + 1
        starts.append(jax.random.randint(sub, (), 0, hi))
    out = x
    for i, (st, sz) in enumerate(zip(starts, shape)):
        out = jax.lax.dynamic_slice_in_dim(out, st, sz, axis=nd - tail + i)
    return out


def shuffle_batch(x, seed=0, rng=None, name=None):
    k = _key(seed, rng)
    perm = jax.random.permutation(k, jnp.asarray(x).shape[0])
    return jnp.take(jnp.asarray(x), perm, axis=0)
