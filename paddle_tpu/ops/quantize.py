"""Quantization op family.

Parity: operators/fake_quantize_op.cc (fake_quantize_abs_max,
fake_quantize_range_abs_max, fake_quantize_moving_average_abs_max,
fake_quantize_dequantize_moving_average_abs_max,
fake_channel_wise_quantize_abs_max, moving_average_abs_max_scale),
operators/fake_dequantize_op.cc (fake_dequantize_max_abs,
fake_channel_wise_dequantize_max_abs), operators/quantize_op.cc /
dequantize_op.cc (int8 cast for inference backends).

TPU-native notes: the fake-quant ops carry a straight-through-estimator
gradient (custom_vjp on the rounding), so quantization-aware training
works under jax.grad out of the box — the reference relies on the
identity-grad registration in quantization_pass.py. Stateful running-scale
variants are functional: state in, state out.
"""

import functools

import jax
import jax.numpy as jnp

__all__ = [
    "fake_quantize_abs_max", "fake_quantize_dequantize_abs_max",
    "fake_channel_wise_quantize_abs_max",
    "fake_channel_wise_quantize_dequantize_abs_max",
    "fake_quantize_range_abs_max",
    "fake_quantize_moving_average_abs_max",
    "fake_quantize_dequantize_moving_average_abs_max",
    "moving_average_abs_max_scale",
    "fake_dequantize_max_abs", "fake_channel_wise_dequantize_max_abs",
    "quantize_linear", "dequantize_linear",
    "quantized_mul", "quantized_conv2d",
]


def _bin_cnt(bit_length):
    return (1 << (bit_length - 1)) - 1


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_round_fwd(x):
    return jnp.round(x), None


def _ste_round_bwd(_, g):
    return (g,)  # straight-through: d round(x)/dx := 1


_ste_round.defvjp(_ste_round_fwd, _ste_round_bwd)


def fake_quantize_abs_max(x, bit_length=8):
    """scale = max|x|; out = round(x / scale * bin_cnt) (a float tensor of
    integers, like the reference). Returns (out, scale)."""
    x = jnp.asarray(x)
    bins = _bin_cnt(bit_length)
    scale = jnp.max(jnp.abs(x))
    s = jnp.maximum(scale, 1e-12)
    out = _ste_round(x / s * bins)
    return out, scale


def fake_quantize_dequantize_abs_max(x, bit_length=8):
    """Quantize-dequantize roundtrip with STE — the QAT training op.
    Returns (out, scale)."""
    x = jnp.asarray(x)
    bins = _bin_cnt(bit_length)
    scale = jnp.max(jnp.abs(x))
    s = jnp.maximum(scale, 1e-12)
    out = _ste_round(x / s * bins) * s / bins
    return out, scale


def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0):
    """Per-channel abs-max quantization (conv weights). Returns
    (out, scales[channels])."""
    x = jnp.asarray(x)
    bins = _bin_cnt(bit_length)
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    s = jnp.maximum(scale, 1e-12).reshape(shape)
    out = _ste_round(x / s * bins)
    return out, scale


def fake_quantize_range_abs_max(x, in_scale, iteration, window_size=10000,
                                bit_length=8, is_test=False):
    """Windowed running-max scale. Returns (out, out_scale).
    The reference keeps a scale window buffer; functionally the window
    reduces to "reset the max at window boundaries"."""
    x = jnp.asarray(x)
    bins = _bin_cnt(bit_length)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale
    else:
        at_boundary = (iteration % window_size) == 0
        scale = jnp.where(at_boundary, cur, jnp.maximum(in_scale, cur))
    s = jnp.maximum(scale, 1e-12)
    out = _ste_round(jnp.clip(x, -s, s) / s * bins)
    return out, scale


def moving_average_abs_max_scale(x, accum, state, moving_rate=0.9):
    """EMA abs-max scale tracker (scale-only op). Returns
    (scale, accum', state')."""
    cur = jnp.max(jnp.abs(jnp.asarray(x)))
    accum = accum * moving_rate + cur * (1.0 - moving_rate)
    state = state * moving_rate + (1.0 - moving_rate)
    return accum / jnp.maximum(state, 1e-12), accum, state


def fake_quantize_moving_average_abs_max(x, accum, state, moving_rate=0.9,
                                         bit_length=8, is_test=False):
    """EMA-scaled quantization. Returns (out, scale, accum', state')."""
    x = jnp.asarray(x)
    bins = _bin_cnt(bit_length)
    if is_test:
        scale = accum / jnp.maximum(state, 1e-12)
    else:
        scale, accum, state = moving_average_abs_max_scale(
            x, accum, state, moving_rate)
    s = jnp.maximum(scale, 1e-12)
    out = _ste_round(jnp.clip(x, -s, s) / s * bins)
    return out, scale, accum, state


def fake_quantize_dequantize_moving_average_abs_max(
        x, accum, state, moving_rate=0.9, bit_length=8, is_test=False):
    """The QAT activation op: EMA scale + quant-dequant roundtrip.
    Returns (out, scale, accum', state')."""
    x = jnp.asarray(x)
    bins = _bin_cnt(bit_length)
    if is_test:
        scale = accum / jnp.maximum(state, 1e-12)
    else:
        scale, accum, state = moving_average_abs_max_scale(
            x, accum, state, moving_rate)
    s = jnp.maximum(scale, 1e-12)
    out = _ste_round(jnp.clip(x, -s, s) / s * bins) * s / bins
    return out, scale, accum, state


def fake_dequantize_max_abs(x, scale, max_range):
    """out = x * scale / max_range (fake_dequantize_op.cc)."""
    return jnp.asarray(x, jnp.float32) * scale / max_range


def fake_channel_wise_dequantize_max_abs(x, scales, quant_bits=(8,),
                                         quant_axis=0):
    """Per-channel dequantize; `scales` as in the reference's two-scale
    form (weight scales [, activation scale])."""
    x = jnp.asarray(x, jnp.float32)
    wscale = jnp.asarray(scales[0], jnp.float32)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    out = x * wscale.reshape(shape) / _bin_cnt(quant_bits[0])
    if len(scales) > 1 and scales[1] is not None:
        out = out * scales[1] / _bin_cnt(quant_bits[1])
    return out


def _storage_dtype(bit_length):
    if bit_length <= 8:
        return jnp.int8
    if bit_length <= 16:
        return jnp.int16
    return jnp.int32


def quantize_linear(x, scale, bit_length=8):
    """Real integer cast (inference): round+clip at the given scale
    (operators/quantize_op.cc); storage width follows bit_length."""
    bins = _bin_cnt(bit_length)
    q = jnp.round(jnp.asarray(x) / jnp.maximum(scale, 1e-12) * bins)
    return jnp.clip(q, -bins - 1, bins).astype(_storage_dtype(bit_length))


def dequantize_linear(q, scale, bit_length=8):
    """int → float at the given scale (operators/dequantize_op.cc)."""
    return q.astype(jnp.float32) * scale / _bin_cnt(bit_length)


def fake_channel_wise_quantize_dequantize_abs_max(x, bit_length=8,
                                                  quant_axis=0):
    """Per-channel quant-dequant roundtrip with STE (QAT for conv/fc
    weights). Returns (out, scales)."""
    x = jnp.asarray(x)
    bins = _bin_cnt(bit_length)
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    s = jnp.maximum(scale, 1e-12).reshape(shape)
    out = _ste_round(x / s * bins) * s / bins
    return out, scale


# -- int8 inference execution (the frozen-graph kernels) ------------------

def quantized_mul(x, w_q, x_scale, w_scale, x_num_col_dims=1,
                  bit_length=8, w_bit_length=None):
    """Int8 matmul with int32 accumulation — what a frozen QAT / PTQ
    'mul' executes (ref: the int8 kernels behind
    QuantizationFreezePass + trt int8 engine subgraphs). The activation
    quantizes on the fly at its calibrated scale; the weight arrives
    already integer. On TPU the int8xint8->int32 dot maps onto the MXU.
    """
    import math as _math
    x_bins = _bin_cnt(bit_length)
    w_bins = _bin_cnt(bit_length if w_bit_length is None
                      else w_bit_length)
    x = jnp.asarray(x)
    xs = x.reshape((_math.prod(x.shape[:x_num_col_dims]), -1))
    q_x = quantize_linear(xs, x_scale, bit_length=bit_length)
    acc = jax.lax.dot_general(
        q_x, jnp.asarray(w_q),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (
        jnp.float32(x_scale) * jnp.float32(w_scale) / (x_bins * w_bins))
    return out.reshape(x.shape[:x_num_col_dims] + (out.shape[-1],))


def quantized_conv2d(x, w_q, x_scale, w_scale, stride=1, padding=0,
                     dilation=1, groups=1, data_format="NCHW",
                     bit_length=8, w_bit_length=None):
    """Int8 conv with int32 accumulation (frozen conv2d). Weight layout
    OIHW like ops.nn.conv2d."""
    from paddle_tpu.ops.nn import _conv_padding, _pair
    x_bins = _bin_cnt(bit_length)
    w_bins = _bin_cnt(bit_length if w_bit_length is None
                      else w_bit_length)
    x = jnp.asarray(x)
    q_x = quantize_linear(x, x_scale, bit_length=bit_length)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w_q.shape, (data_format, "OIHW", data_format))
    acc = jax.lax.conv_general_dilated(
        q_x, jnp.asarray(w_q),
        window_strides=_pair(stride),
        padding=_conv_padding(padding, 2),
        rhs_dilation=_pair(dilation),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (
        jnp.float32(x_scale) * jnp.float32(w_scale) / (x_bins * w_bins))
