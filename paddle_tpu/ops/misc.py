"""Long-tail ops from the reference's root operator directory.

Parity targets (SURVEY §2.4 root-level op list — each function names its
reference file): add_position_encoding, affine_grid, grid_sampler,
bilinear_tensor_product, conv_shift, row_conv, im2sequence,
similarity_focus, spectral_norm, spp, temporal_shift, pool_with_index /
unpool, squared_l2_distance, fsp, hash, cvm, tree_conv, nce,
hierarchical_sigmoid, sample_logits, gru_unit, lstm_unit, shuffle
aliases (sum/top_k/arg_max/...). All pure jnp; layouts NCHW like the
rest of paddle_tpu.ops.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce

__all__ = [
    "add_position_encoding", "affine_grid", "grid_sampler",
    "bilinear_tensor_product", "conv_shift", "row_conv", "im2sequence",
    "similarity_focus", "spectral_norm", "spp", "temporal_shift",
    "max_pool2d_with_index", "unpool2d", "squared_l2_distance",
    "fsp_matrix", "hash_embedding_ids", "cvm", "tree_conv", "nce",
    "hierarchical_sigmoid", "sample_logits", "gru_unit", "lstm_unit",
    "sum", "top_k", "arg_max", "arg_min", "fill_any_like",
    "fill_zeros_like", "assign_value", "smooth_l1_loss", "lookup_table",
]


def add_position_encoding(x, alpha=1.0, beta=1.0):
    """operators/add_position_encoding_op.cc: out = alpha*x + beta*PE,
    PE the sin/cos transformer table. x: [B, T, C] (C even)."""
    b, t, c = x.shape
    enforce(c % 2 == 0, "channels must be even")
    pos = jnp.arange(t, dtype=x.dtype)[:, None]
    div = jnp.power(jnp.asarray(10000.0, x.dtype),
                    jnp.arange(c // 2, dtype=x.dtype) * 2.0 / c)
    ang = pos / div
    pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
    return alpha * x + beta * pe[None]


def affine_grid(theta, out_shape):
    """operators/affine_grid_op.cc: 2D sampling grid from batch of 2x3
    affine matrices. theta [N,2,3], out_shape (N,C,H,W) -> [N,H,W,2]
    (x,y) in [-1,1] source coords."""
    n, _, h, w = out_shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    base = jnp.broadcast_to(base, (n, h * w, 3)).astype(theta.dtype)
    out = jnp.einsum("nij,npj->npi", theta, base)    # [N,HW,2]
    return out.reshape(n, h, w, 2)


def grid_sampler(x, grid):
    """operators/grid_sampler_op.cc: bilinear sample NCHW ``x`` at
    ``grid`` [N,H,W,2] of (x,y) in [-1,1]; zero padding outside."""
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        valid = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        # vmap over batch: x[b,:,yc[b],xc[b]] -> [N,C,Ho,Wo]
        g = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, yc, xc)
        return g * valid[:, None].astype(x.dtype)

    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x0 + 1) * (wx * (1 - wy))[:, None]
           + gather(y0 + 1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y0 + 1, x0 + 1) * (wx * wy)[:, None])
    return out


def bilinear_tensor_product(x, y, weight, bias=None):
    """operators/bilinear_tensor_product_op.cc:
    out[:, k] = x @ W[k] @ y^T diag. x [B,M], y [B,N], W [K,M,N]."""
    out = jnp.einsum("bm,kmn,bn->bk", x, weight, y)
    if bias is not None:
        out = out + bias
    return out


def conv_shift(x, y):
    """operators/conv_shift_op.cc: circular convolution. x [B,M],
    y [B,N] (N odd, N<=M): out[i] = sum_j x[(i+j-N//2) mod M] * y[j]."""
    b, m = x.shape
    n = y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None] - half) % m
    return jnp.einsum("bmn,bn->bm", x[:, idx], y)


def row_conv(x, weight):
    """operators/row_conv_op.cc (lookahead conv): x [B,T,D],
    weight [future_ctx, D]: out[t] = sum_k x[t+k] * w[k]."""
    ctx = weight.shape[0]
    b, t, d = x.shape
    pad = jnp.pad(x, ((0, 0), (0, ctx - 1), (0, 0)))
    idx = jnp.arange(t)[:, None] + jnp.arange(ctx)[None]
    return jnp.einsum("btkd,kd->btd", pad[:, idx], weight)


def im2sequence(x, filter_size, stride=1, padding=0):
    """operators/im2sequence_op.cc: NCHW image -> sequence of flattened
    patches [B, L, C*kh*kw] (the reference emits LoD; dense here)."""
    kh, kw = ((filter_size, filter_size)
              if isinstance(filter_size, int) else filter_size)
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        xp, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * kh * kw, oh * ow).transpose(0, 2, 1)


def similarity_focus(x, axis, indexes):
    """operators/similarity_focus_op.cc: for each selected channel index
    along ``axis``, mark the argmax position per remaining-dim row; out
    is x's shape mask of 0/1."""
    enforce(x.ndim == 4 and axis in (1, 2, 3), "4-D input, axis in 1..3")
    mask = jnp.zeros_like(x)
    for ind in indexes:
        sl = jax.lax.index_in_dim(x, ind, axis, keepdims=True)
        for red in range(1, 4):
            if red == axis:
                continue
            am = jnp.argmax(sl, axis=red, keepdims=True)
            hit = (jnp.arange(x.shape[red])
                   .reshape([-1 if i == red else 1 for i in range(4)])
                   == am)
            mask = jnp.maximum(
                mask, jnp.broadcast_to(hit, x.shape).astype(x.dtype))
    return mask


def spectral_norm(weight, u=None, power_iters=1, eps=1e-12, dim=0):
    """operators/spectral_norm_op.cc: W / sigma(W) via power iteration.
    Returns (normalized_weight, new_u)."""
    w = jnp.moveaxis(weight, dim, 0)
    h = w.shape[0]
    mat = w.reshape(h, -1)
    if u is None:
        u = jax.random.normal(jax.random.PRNGKey(0), (h,), mat.dtype)
    v = None
    for _ in range(max(power_iters, 1)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    return weight / sigma, u


def spp(x, pyramid_height=3, pool_type="max"):
    """operators/spp_op.cc: spatial pyramid pooling NCHW ->
    [N, C * sum(4^l)] fixed-length descriptor."""
    n, c, h, w = x.shape
    outs = []
    for lvl in range(pyramid_height):
        bins = 2 ** lvl
        # adaptive pooling to bins x bins
        ys = [int(np.floor(i * h / bins)) for i in range(bins + 1)]
        xs = [int(np.floor(i * w / bins)) for i in range(bins + 1)]
        cells = []
        for i in range(bins):
            for j in range(bins):
                cell = x[:, :, ys[i]:max(ys[i + 1], ys[i] + 1),
                         xs[j]:max(xs[j + 1], xs[j] + 1)]
                if pool_type == "max":
                    cells.append(cell.max(axis=(2, 3)))
                else:
                    cells.append(cell.mean(axis=(2, 3)))
        outs.append(jnp.stack(cells, axis=-1).reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


def temporal_shift(x, seg_num, shift_ratio=0.25):
    """operators/temporal_shift_op.cc: shift 1/4 channels forward, 1/4
    backward along time. x [N*T, C, H, W]."""
    nt, c, h, w = x.shape
    t = seg_num
    n = nt // t
    xr = x.reshape(n, t, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    back = jnp.concatenate(
        [xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, c1:c2]), xr[:, :-1, c1:c2]], axis=1)
    return jnp.concatenate([back, fwd, xr[:, :, c2:]],
                           axis=2).reshape(nt, c, h, w)


def max_pool2d_with_index(x, pool_size, stride=None, padding=0):
    """operators/pool_with_index_op.cc: max pool + flat argmax indices
    (for unpool). NCHW."""
    k = (pool_size, pool_size) if isinstance(pool_size, int) else pool_size
    s = k if stride is None else (
        (stride, stride) if isinstance(stride, int) else stride)
    p = (padding, padding) if isinstance(padding, int) else padding
    n, c, h, w = x.shape
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
                 constant_values=neg)
    # single-channel index plane (broadcasting it to all C channels
    # before patch extraction made the 1-channel reshape below fail for
    # any C > 1)
    flat_idx = jnp.arange(xp.shape[2] * xp.shape[3]).reshape(
        1, 1, xp.shape[2], xp.shape[3])
    oh = (xp.shape[2] - k[0]) // s[0] + 1
    ow = (xp.shape[3] - k[1]) // s[1] + 1
    patches = jax.lax.conv_general_dilated_patches(
        xp, k, s, "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
    patches = patches.reshape(n, c, k[0] * k[1], oh, ow)
    ipatches = jax.lax.conv_general_dilated_patches(
        flat_idx.astype(jnp.float32), k, s, "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ipatches = ipatches.reshape(1, 1, k[0] * k[1], oh, ow)
    ipatches = jnp.broadcast_to(ipatches, patches.shape)
    am = jnp.argmax(patches, axis=2)
    out = jnp.take_along_axis(patches, am[:, :, None], axis=2)[:, :, 0]
    idx = jnp.take_along_axis(ipatches, am[:, :, None], axis=2)[:, :, 0]
    idx = idx.astype(jnp.int32)
    # translate padded-image flat coords back to the original image so
    # unpool scatters to the true argmax positions
    wp = xp.shape[3]
    orig = (idx // wp - p[0]) * w + (idx % wp - p[1])
    return out, orig


def unpool2d(x, indices, out_hw):
    """operators/unpool_op.cc: scatter pooled values back to their
    argmax positions; zeros elsewhere."""
    n, c, h, w = x.shape
    oh, ow = out_hw
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    idx = indices.reshape(n, c, -1)
    vals = x.reshape(n, c, -1)
    flat = jax.vmap(jax.vmap(
        lambda f, i, v: f.at[i].add(v)))(flat, idx, vals)
    return flat.reshape(n, c, oh, ow)


def squared_l2_distance(x, y):
    """operators/squared_l2_distance_op.cc: rowwise ||x-y||^2."""
    d = (x - y).reshape(x.shape[0], -1)
    return jnp.sum(d * d, axis=1, keepdims=True)


def fsp_matrix(a, b):
    """operators/fsp_op.cc (NCHW form): [N, Ca, Cb] Gram matrix."""
    n, ca, h, w = a.shape
    af = a.reshape(n, ca, h * w)
    bf = b.reshape(n, b.shape[1], h * w)
    return jnp.einsum("ncs,nds->ncd", af, bf) / (h * w)


def hash_embedding_ids(ids, mod, num_hash=1):
    """operators/hash_op.cc: xxhash-style id remap into [0, mod); we use
    splittable integer hashing (fmix) — stable across processes."""
    x = jnp.asarray(ids, jnp.uint32)
    outs = []
    for seed in range(num_hash):
        h = x ^ jnp.uint32(seed * 0x9E3779B9)
        h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        outs.append((h % jnp.uint32(mod)).astype(jnp.int64
                    if jax.config.jax_enable_x64 else jnp.int32))
    return outs[0] if num_hash == 1 else jnp.stack(outs, axis=-1)


def cvm(x, use_cvm=True):
    """operators/cvm_op.cc: CTR show/click feature. Input [B, D] whose
    first two columns are (show, click); with use_cvm the columns become
    log(show+1), log(click+1)-log(show+1); else they are dropped."""
    show = jnp.log(x[:, :1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - show
    if use_cvm:
        return jnp.concatenate([show, click, x[:, 2:]], axis=1)
    return x[:, 2:]


def tree_conv(nodes, edges, weight, max_depth=2):
    """operators/tree_conv_op.cc (tree-based convolution, simplified):
    nodes [B,N,D], edges [B,N,N] adjacency (0/1), weight [K,D,O] with K
    hops: out = sum_k A^k @ nodes @ W_k."""
    out = 0.0
    a = jnp.eye(nodes.shape[1], dtype=nodes.dtype)[None]
    a = jnp.broadcast_to(a, edges.shape)
    for k in range(min(weight.shape[0], max_depth + 1)):
        out = out + jnp.einsum("bnm,bmd,do->bno", a, nodes, weight[k])
        a = jnp.einsum("bnm,bmk->bnk", a, edges)
    return out


def nce(x, weight, bias, labels, sample_ids, num_total_classes):
    """operators/nce_op.cc: noise-contrastive estimation loss. x [B,D],
    weight [C,D], labels [B], sample_ids [S] negative class ids.
    Uniform noise distribution (the reference's default sampler)."""
    q = 1.0 / num_total_classes
    pos_logit = jnp.einsum("bd,bd->b", x, weight[labels]) + bias[labels]
    neg_logit = x @ weight[sample_ids].T + bias[sample_ids]  # [B,S]
    s = sample_ids.shape[0]
    pos = jax.nn.log_sigmoid(pos_logit - jnp.log(s * q))
    neg = jax.nn.log_sigmoid(-(neg_logit - jnp.log(s * q)))
    return -(pos + neg.sum(axis=1)) / (1 + s)


def hierarchical_sigmoid(x, weight, bias, labels, num_classes):
    """operators/hierarchical_sigmoid_op.cc with the default complete
    binary tree (math/matrix_bit_code.h): heap-numbered nodes, leaves
    are num_classes..2*num_classes-1, internal node k stores
    weight[k-1]; loss[b] = sum over the leaf→root walk of
    softplus((1-2*code) * (w . x_b + b)). Leaf depths differ when
    num_classes is not a power of two, so steps past the root are
    masked out."""
    depth = int(np.ceil(np.log2(2 * max(num_classes, 2))))
    node = jnp.asarray(labels, jnp.int32) + num_classes
    loss = 0.0
    for _ in range(depth):
        active = node > 1
        code = node % 2          # 0 = left, 1 = right
        parent = node // 2
        nid = jnp.maximum(parent - 1, 0)
        logit = jnp.einsum("bd,bd->b", x, weight[nid]) + bias[nid]
        sign = 1.0 - 2.0 * code.astype(x.dtype)
        loss = loss + active.astype(x.dtype) * jax.nn.softplus(sign * logit)
        node = jnp.where(active, parent, node)
    return loss


def sample_logits(logits, labels, sample_ids):
    """operators/sample_logits_op.cc: gather the label logit plus
    sampled-class logits, with the log-uniform correction left to the
    caller. Returns ([B, 1+S] logits, [B] new labels==0)."""
    pos = jnp.take_along_axis(logits, labels[:, None], axis=1)
    neg = logits[:, sample_ids]
    return jnp.concatenate([pos, neg], axis=1), jnp.zeros(
        logits.shape[0], jnp.int32)


def gru_unit(x, h_prev, w_gates, w_cand, b_gates=None, b_cand=None):
    """operators/gru_unit_op.cc: one GRU step. x [B, 3H] (pre-projected
    gates input), h_prev [B,H], w_gates [H,2H], w_cand [H,H]."""
    hdim = h_prev.shape[1]
    gi = x[:, :2 * hdim] + h_prev @ w_gates
    if b_gates is not None:
        gi = gi + b_gates
    u, r = jnp.split(jax.nn.sigmoid(gi), 2, axis=1)
    c = x[:, 2 * hdim:] + (r * h_prev) @ w_cand
    if b_cand is not None:
        c = c + b_cand
    c = jnp.tanh(c)
    return u * h_prev + (1 - u) * c


def lstm_unit(x, h_prev, c_prev):
    """operators/lstm_unit_op.cc: one LSTM step from pre-projected
    x [B, 4H] (i,f,c,o order), returns (h, c)."""
    hdim = h_prev.shape[1]
    i, f, g, o = jnp.split(x, 4, axis=1)
    c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


# ---------------------------------------------------------------------------
# aliases for reference op names whose functionality exists under another
# name (kept so the fluid surface matches §2.4 one-to-one)
# ---------------------------------------------------------------------------
def sum(xs):                                     # noqa: A001
    """operators/sum_op.cc: elementwise sum of a var list."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def top_k(x, k):
    """operators/top_k_op.cc."""
    return jax.lax.top_k(x, k)


def arg_max(x, axis=-1):
    return jnp.argmax(x, axis=axis)


def arg_min(x, axis=-1):
    return jnp.argmin(x, axis=axis)


def fill_any_like(x, value):
    return jnp.full_like(x, value)


def fill_zeros_like(x):
    return jnp.zeros_like(x)


def assign_value(shape, dtype, values):
    return jnp.asarray(np.asarray(values, dtype).reshape(shape))


def smooth_l1_loss(x, y, sigma=1.0):
    from paddle_tpu.ops.loss import smooth_l1
    return smooth_l1(x, y, sigma=sigma)


def lookup_table(ids, table, padding_idx=None):
    """operators/lookup_table_op.cc — alias of ops/nn.embedding (single
    implementation so padding_idx/shape semantics cannot diverge)."""
    from paddle_tpu.ops.nn import embedding
    return embedding(ids, table, padding_idx=padding_idx)


def deformable_conv(x, offset, weight, stride=1, padding=0,
                    deformable_groups=1, mask=None):
    """operators/deformable_conv_op.cc (v1; v2 when ``mask`` given —
    modulated). x [N,Cin,H,W], offset [N, 2*dg*kh*kw, Ho, Wo] in (dy,dx)
    interleave, weight [Cout,Cin,kh,kw]. Implemented as offset-shifted
    bilinear gathers + a dense matmul — gathers and the MXU matmul are
    both XLA-native, mirroring how the CUDA kernel splits im2col+gemm."""
    s = (stride, stride) if isinstance(stride, int) else stride
    p = (padding, padding) if isinstance(padding, int) else padding
    n, cin, h, w = x.shape
    cout, _, kh, kw = weight.shape
    oh = (h + 2 * p[0] - kh) // s[0] + 1
    ow = (w + 2 * p[1] - kw) // s[1] + 1
    enforce(offset.shape[1] == 2 * deformable_groups * kh * kw,
            "offset channel mismatch")
    off = offset.reshape(n, deformable_groups, kh * kw, 2, oh, ow)
    base_y = (jnp.arange(oh) * s[0] - p[0])[:, None]
    base_x = (jnp.arange(ow) * s[1] - p[1])[None]
    cols = []
    cg = cin // deformable_groups
    for g in range(deformable_groups):
        for k in range(kh * kw):
            ky, kx = divmod(k, kw)
            py = base_y + ky + off[:, g, k, 0]          # [N,Ho,Ow]
            px = base_x + kx + off[:, g, k, 1]
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy = py - y0
            wx = px - x0
            xs = x[:, g * cg:(g + 1) * cg]

            def gat(yy, xx):
                valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
                yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
                xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
                g_ = jax.vmap(lambda img, a, b: img[:, a, b])(xs, yc, xc)
                return g_ * valid[:, None].astype(x.dtype)

            v = (gat(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
                 + gat(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
                 + gat(y0 + 1, x0) * (wy * (1 - wx))[:, None]
                 + gat(y0 + 1, x0 + 1) * (wy * wx)[:, None])
            if mask is not None:
                v = v * mask[:, g * kh * kw + k][:, None]
            cols.append(v)                               # [N,cg,Ho,Ow]
    col = jnp.stack(cols, axis=2)    # [N, cg, dg*K, Ho, Ow], idx = g*K+k
    if deformable_groups == 1:
        col = col.reshape(n, cin * kh * kw, oh, ow)
    else:
        # weight flattens channel-major ((g*cg+cc)*K + k): bring dg
        # outside cg before flattening
        col = (col.reshape(n, cg, deformable_groups, kh * kw, oh, ow)
               .transpose(0, 2, 1, 3, 4, 5)
               .reshape(n, cin * kh * kw, oh, ow))
    wmat = weight.reshape(cout, cin * kh * kw)
    return jnp.einsum("ok,nkhw->nohw", wmat, col)


def average_accumulates(param, sum_1, sum_2, sum_3, num_accumulates,
                        old_num_accumulates, num_updates,
                        average_window=10000, max_average_window=10000,
                        min_average_window=10000):
    """operators/average_accumulates_op.cc: the ModelAverage optimizer's
    rolling accumulator update (sum_1 current window, sum_2 previous
    windows, sum_3 overflow staging)."""
    num_updates = num_updates + 1
    num_accumulates = num_accumulates + 1
    sum_1 = sum_1 + param
    roll = num_updates % average_window == 0
    window_full = num_accumulates >= max_average_window
    do_shift = jnp.logical_or(roll, window_full)

    sum_2_n = jnp.where(do_shift, sum_2 + sum_1, sum_2)
    sum_1_n = jnp.where(do_shift, jnp.zeros_like(sum_1), sum_1)
    old_n = jnp.where(do_shift, old_num_accumulates + num_accumulates,
                      old_num_accumulates)
    num_acc_n = jnp.where(do_shift, 0, num_accumulates)
    overflow = old_n > max_average_window
    sum_3_n = jnp.where(overflow, sum_2_n, sum_3)
    sum_2_f = jnp.where(overflow, jnp.zeros_like(sum_2_n), sum_2_n)
    old_f = jnp.where(overflow, num_acc_n, old_n)
    return sum_1_n, sum_2_f, sum_3_n, num_acc_n, old_f, num_updates


def beam_search(log_probs, pre_scores, pre_ids, beam_size,
                end_token=None, length_penalty=0.0, step=1):
    """operators/beam_search_op.cc as a batched functional step:
    log_probs [B*beam, V] for the current step, pre_scores [B*beam],
    pre_ids [B*beam, L] prefix. Returns (ids [B*beam, L+1],
    scores [B*beam], parent [B*beam]) after top-k over beam*V.
    Finished beams (prefix ends with end_token) keep their score and
    re-emit end_token."""
    bb, v = log_probs.shape
    b = bb // beam_size
    lp = log_probs
    if end_token is not None:
        done = pre_ids[:, -1] == end_token
        # finished: only end_token continuation at zero added cost
        neg = jnp.full_like(lp, -1e9)
        frozen = neg.at[:, end_token].set(0.0)
        lp = jnp.where(done[:, None], frozen, lp)
    total = pre_scores[:, None] + lp                       # [B*beam, V]
    if length_penalty:
        total = total / ((5.0 + step) / 6.0) ** length_penalty
    flat = total.reshape(b, beam_size * v)
    top_val, top_idx = jax.lax.top_k(flat, beam_size)      # [B, beam]
    parent_in_b = top_idx // v                             # [B, beam]
    token = top_idx % v
    parent = (parent_in_b
              + jnp.arange(b)[:, None] * beam_size).reshape(-1)
    ids = jnp.concatenate(
        [pre_ids[parent], token.reshape(-1, 1)], axis=1)
    return ids, top_val.reshape(-1), parent


__all__ += ["deformable_conv", "average_accumulates", "beam_search"]


def conv2d_fusion(x, weight, bias=None, residual=None, stride=1,
                  padding=0, dilation=1, groups=1, act="relu"):
    """operators/conv_fusion_op.cc: conv + bias + (optional residual
    add) + activation in one op. On TPU the fusion is XLA's job — this
    exists so fused-graph programs from the reference map one-to-one;
    the compiler emits the same fused kernel either way."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    out = jax.lax.conv_general_dilated(
        x, weight, s, [(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    if residual is not None:
        out = out + residual
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "identity" or act is None:
        pass
    else:
        out = getattr(jax.nn, act)(out)
    return out


def deformable_psroi_pooling(x, rois, trans, output_channels, group_size,
                             pooled_size, part_size=None, spatial_scale=1.0,
                             sample_per_part=4, trans_std=0.1,
                             roi_batch_indices=None):
    """operators/deformable_psroi_pooling_op.cc: position-sensitive RoI
    pooling with learned per-part offsets (Deformable R-FCN).

    x [N, C, H, W] with C = output_channels*group^2 laid out
    channel-major like the sibling detection.psroi_pool
    (channel = (ctop*g + gi)*g + gj); rois [R, 5] (batch_idx, x1, y1,
    x2, y2) or [R, 4] + roi_batch_indices; trans [R, 2, part, part]
    (dy, dx planes) or None for the plain PS-RoI case. Fully traceable
    (vmap over RoIs); samples are BILINEAR so gradients flow into the
    offsets, out-of-image samples are dropped like the reference kernel.
    """
    x = jnp.asarray(x, jnp.float32)
    rois = jnp.asarray(rois, jnp.float32)
    n, c, h, w = x.shape
    # rectangular pooled outputs supported (deformable_psroi_pooling_op
    # takes independent pooled_height/pooled_width)
    kh, kw = ((int(pooled_size[0]), int(pooled_size[1]))
              if isinstance(pooled_size, (list, tuple))
              else (int(pooled_size), int(pooled_size)))
    gh, gw = ((int(group_size[0]), int(group_size[1]))
              if isinstance(group_size, (list, tuple))
              else (int(group_size), int(group_size)))
    oc = int(output_channels)
    if part_size is None:
        part_h, part_w = kh, kw
    elif isinstance(part_size, (list, tuple)):
        part_h, part_w = int(part_size[0]), int(part_size[1])
    else:
        part_h = part_w = int(part_size)
    sp = int(sample_per_part)
    enforce(c == oc * gh * gw, "channel/group mismatch")
    if rois.shape[1] == 5:
        bidx = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    else:
        bidx = (jnp.zeros(rois.shape[0], jnp.int32)
                if roi_batch_indices is None
                else jnp.asarray(roi_batch_indices, jnp.int32))
        boxes = rois
    feat = x.reshape(n, oc, gh, gw, h, w)

    ii, jj = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    gi = jnp.clip(ii * gh // kh, 0, gh - 1)        # [kh,kw] channel group
    gj = jnp.clip(jj * gw // kw, 0, gw - 1)
    pi = jnp.clip(ii * part_h // kh, 0, part_h - 1)  # [kh,kw] offset part
    pj = jnp.clip(jj * part_w // kw, 0, part_w - 1)
    su = (jnp.arange(sp) + 0.5) / sp                # sub-bin sample frac

    def one(box, bi, tr):
        x1 = box[0] * spatial_scale
        y1 = box[1] * spatial_scale
        rw = jnp.maximum((box[2] - box[0]) * spatial_scale, 0.1)
        rh = jnp.maximum((box[3] - box[1]) * spatial_scale, 0.1)
        bin_h = rh / kh
        bin_w = rw / kw
        if tr is not None:
            dy = tr[0, pi, pj] * trans_std * rh     # [k,k]
            dx = tr[1, pi, pj] * trans_std * rw
        else:
            dy = dx = jnp.zeros((kh, kw), jnp.float32)
        # sample coords [k,k,sp,sp]
        ys = (y1 + dy)[..., None, None] \
            + (ii[..., None, None] + su[None, None, :, None]) \
            * bin_h
        xs = (x1 + dx)[..., None, None] \
            + (jj[..., None, None] + su[None, None, None, :]) \
            * bin_w
        inside = ((ys >= 0) & (ys <= h - 1) & (xs >= 0) & (xs <= w - 1))
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy = ys - y0
        wx = xs - x0
        fmap = feat[bi]                               # [oc,g,g,h,w]
        GI = gi[:, :, None, None]
        GJ = gj[:, :, None, None]

        def gat(yy, xx):
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            return fmap[:, GI, GJ, yc, xc]            # [oc,k,k,sp,sp]

        val = (gat(y0, x0) * ((1 - wy) * (1 - wx))
               + gat(y0, x0 + 1) * ((1 - wy) * wx)
               + gat(y0 + 1, x0) * (wy * (1 - wx))
               + gat(y0 + 1, x0 + 1) * (wy * wx))
        val = val * inside.astype(jnp.float32)
        cnt = jnp.maximum(inside.sum(axis=(-1, -2)), 1.0)  # [k,k]
        return val.sum(axis=(-1, -2)) / cnt               # [oc,k,k]

    if trans is None:
        return jax.vmap(lambda b, bi: one(b, bi, None))(boxes, bidx)
    tr = jnp.asarray(trans, jnp.float32).reshape(-1, 2, part_h, part_w)
    return jax.vmap(one)(boxes, bidx, tr)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=1,
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    """fluid.layers.deformable_roi_pooling parity (layers/nn.py
    deformable_roi_pooling over deformable_psroi_pooling_op.cc): the
    user-facing wrapper. position_sensitive=False pools each input
    channel (group 1); True is the R-FCN position-sensitive layout."""
    x = jnp.asarray(input)
    if isinstance(group_size, (list, tuple)):
        gh, gw = int(group_size[0]), int(group_size[1])
    else:
        gh = gw = int(group_size)
    if position_sensitive:
        oc = x.shape[1] // (gh * gw)
    else:
        gh = gw = 1
        oc = x.shape[1]
    return deformable_psroi_pooling(
        x, rois, None if no_trans else trans, oc, (gh, gw),
        (pooled_height, pooled_width), part_size=part_size,
        spatial_scale=spatial_scale, sample_per_part=sample_per_part,
        trans_std=trans_std)


__all__ += ["conv2d_fusion", "deformable_psroi_pooling",
            "deformable_roi_pooling"]
