"""Recurrent ops: LSTM / GRU / simple RNN over padded batches.

Parity targets: operators/lstm_op.cc, operators/gru_op.cc,
operators/lstmp_op.cc, operators/cudnn_lstm_op.cu.cc and the math kernels
operators/math/lstm_compute.cc / gru_compute.cc. The reference consumes
LoD-batched sequences (framework/lod_tensor.h:229); here sequences are
dense-padded [B, T, D] with an optional lengths vector (the LoD
replacement, SURVEY §5.7) and recurrence is a lax.scan over time — one
compiled loop instead of a per-step op chain (ref:
operators/recurrent_op.cc).

Gate layouts follow the reference: LSTM gate order i,f,c,o
(math/lstm_compute wiring), GRU gate order update,reset,candidate
(math/gru_compute).
"""

import jax
import jax.numpy as jnp

__all__ = ["lstm", "dynamic_lstm", "dynamic_lstmp", "gru", "dynamic_gru",
           "simple_rnn", "bidirectional_lstm"]


def _mask_from_lengths(lengths, T, B):
    if lengths is None:
        return None
    return (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.float32)


def lstm(x, w_ih, w_hh, b=None, h0=None, c0=None, lengths=None,
         reverse=False, peepholes=None):
    """Single-layer LSTM. x: [B,T,D]; w_ih: [D,4H] or None when x is
    already pre-projected [B,T,4H]; w_hh: [H,4H]; b: [4H]. Gate order
    i,f,c,o (ref: operators/math/lstm_compute.h). peepholes: optional
    [3H] (w_ic, w_fc, w_oc — elementwise cell→gate connections, the
    reference's use_peepholes=True default, ref: operators/lstm_op.cc:75-83).
    Returns (outputs [B,T,H], (h_T, c_T)). Padded steps (t >= lengths[b])
    carry state through unchanged and output 0."""
    B, T, D = x.shape
    H = w_hh.shape[0]
    dt = x.dtype
    h0 = h0 if h0 is not None else jnp.zeros((B, H), dt)
    c0 = c0 if c0 is not None else jnp.zeros((B, H), dt)
    mask = _mask_from_lengths(lengths, T, B)
    if peepholes is not None:
        w_ic, w_fc, w_oc = jnp.split(peepholes, 3)

    # hoist the input projection out of the scan: one big MXU matmul
    xp = x if w_ih is None else (x.reshape(B * T, D) @ w_ih)
    if b is not None:
        xp = xp + b
    xp = xp.reshape(B, T, 4 * H)
    if reverse:
        xp = xp[:, ::-1]
        mask = mask[:, ::-1] if mask is not None else None

    def step(carry, t):
        h, c = carry
        xt, mt = t
        gates = xt + h @ w_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if peepholes is not None:
            i = i + w_ic * c
            f = f + w_fc * c
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        if peepholes is not None:
            o = o + w_oc * c_new
        o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        if mt is not None:
            m = mt[:, None]
            c_new = m * c_new + (1 - m) * c
            h_new = m * h_new + (1 - m) * h
            out = h_new * m
        else:
            out = h_new
        return (h_new, c_new), out

    xs = (xp.transpose(1, 0, 2),
          mask.transpose(1, 0) if mask is not None else None)
    (hT, cT), outs = jax.lax.scan(step, (h0, c0), xs)
    outs = outs.transpose(1, 0, 2)
    if reverse:
        outs = outs[:, ::-1]
    return outs, (hT, cT)


def dynamic_lstm(input, w_hh, bias=None, h0=None, c0=None, lengths=None,
                 is_reverse=False, use_peepholes=True, name=None):
    """fluid.layers.dynamic_lstm parity (ref: operators/lstm_op.cc): input
    is the *pre-projected* x@W [B,T,4H]; w_hh [H,4H]. With
    use_peepholes=True (the reference default) bias is [7H]: 4H gate
    biases then 3H peephole weights w_ic,w_fc,w_oc."""
    H = w_hh.shape[0]
    peep = None
    b = bias
    if use_peepholes and bias is not None:
        bias = jnp.ravel(bias)
        if bias.shape[0] == 7 * H:
            b, peep = bias[:4 * H], bias[4 * H:]
        elif bias.shape[0] == 4 * H:
            b = bias          # gate biases only; no peephole weights given
        else:
            raise ValueError(
                f"dynamic_lstm bias must be [4H]={4*H} or (with "
                f"use_peepholes) [7H]={7*H}, got {bias.shape[0]}")
    return lstm(input, None, w_hh, b=b, h0=h0, c0=c0, lengths=lengths,
                reverse=is_reverse, peepholes=peep)


def dynamic_lstmp(input, w_hh, w_proj, bias=None, lengths=None,
                  is_reverse=False, name=None):
    """LSTM with recurrent projection (ref: operators/lstmp_op.cc):
    hidden H is projected to P each step; w_hh: [P,4H], w_proj: [H,P]."""
    B, T, fourH = input.shape
    H = fourH // 4
    P_ = w_proj.shape[1]
    dt = input.dtype
    mask = _mask_from_lengths(lengths, T, B)
    xp = input + (bias if bias is not None else 0.0)
    if is_reverse:
        xp = xp[:, ::-1]
        mask = mask[:, ::-1] if mask is not None else None

    def step(carry, t):
        r, c = carry            # r: projected hidden [B,P]
        xt, mt = t
        gates = xt + r @ w_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        r_new = h_new @ w_proj
        if mt is not None:
            m = mt[:, None]
            c_new = m * c_new + (1 - m) * c
            r_new = m * r_new + (1 - m) * r
            out = r_new * m
        else:
            out = r_new
        return (r_new, c_new), out

    xs = (xp.transpose(1, 0, 2),
          mask.transpose(1, 0) if mask is not None else None)
    (rT, cT), outs = jax.lax.scan(
        step, (jnp.zeros((B, P_), dt), jnp.zeros((B, H), dt)), xs)
    outs = outs.transpose(1, 0, 2)
    if is_reverse:
        outs = outs[:, ::-1]
    return outs, (rT, cT)


def gru(x, w_ih, w_hh, b=None, h0=None, lengths=None, reverse=False,
        origin_mode=False):
    """Single-layer GRU. x: [B,T,D]; w_ih: [D,3H] or None when x is
    pre-projected [B,T,3H]; w_hh: [H,3H], gate order
    update,reset,candidate (ref: operators/math/gru_compute.cc).
    origin_mode=False (the reference's dynamic_gru default, ref:
    python/paddle/fluid/layers/nn.py dynamic_gru): h = (1-u)*h + u*c;
    origin_mode=True: h = u*h + (1-u)*c. Returns (outputs [B,T,H], h_T)."""
    B, T, D = x.shape
    H = w_hh.shape[0]
    dt = x.dtype
    h0 = h0 if h0 is not None else jnp.zeros((B, H), dt)
    mask = _mask_from_lengths(lengths, T, B)

    xp = x if w_ih is None else (x.reshape(B * T, D) @ w_ih)
    if b is not None:
        xp = xp + b
    xp = xp.reshape(B, T, 3 * H)
    if reverse:
        xp = xp[:, ::-1]
        mask = mask[:, ::-1] if mask is not None else None
    w_uz, w_c = w_hh[:, :2 * H], w_hh[:, 2 * H:]

    def step(h, t):
        xt, mt = t
        xu, xr, xc = jnp.split(xt, 3, axis=-1)
        hz = h @ w_uz
        u = jax.nn.sigmoid(xu + hz[:, :H])
        r = jax.nn.sigmoid(xr + hz[:, H:])
        c = jnp.tanh(xc + (r * h) @ w_c)
        h_new = (u * h + (1 - u) * c) if origin_mode \
            else ((1 - u) * h + u * c)
        if mt is not None:
            m = mt[:, None]
            h_new = m * h_new + (1 - m) * h
            out = h_new * m
        else:
            out = h_new
        return h_new, out

    xs = (xp.transpose(1, 0, 2),
          mask.transpose(1, 0) if mask is not None else None)
    hT, outs = jax.lax.scan(step, h0, xs)
    outs = outs.transpose(1, 0, 2)
    if reverse:
        outs = outs[:, ::-1]
    return outs, hT


def dynamic_gru(input, w_hh, bias=None, h0=None, lengths=None,
                is_reverse=False, origin_mode=False, name=None):
    """fluid.layers.dynamic_gru parity (ref: operators/gru_op.cc): input
    pre-projected [B,T,3H]."""
    return gru(input, None, w_hh, b=bias, h0=h0, lengths=lengths,
               reverse=is_reverse, origin_mode=origin_mode)


def simple_rnn(x, w_ih, w_hh, b=None, h0=None, lengths=None, act=jnp.tanh):
    """Vanilla RNN (the StaticRNN building block,
    ref: layers/control_flow.py StaticRNN:280)."""
    B, T, D = x.shape
    H = w_hh.shape[0]
    dt = x.dtype
    h0 = h0 if h0 is not None else jnp.zeros((B, H), dt)
    mask = _mask_from_lengths(lengths, T, B)
    xp = x.reshape(B * T, D) @ w_ih
    if b is not None:
        xp = xp + b
    xp = xp.reshape(B, T, H)

    def step(h, t):
        xt, mt = t
        h_new = act(xt + h @ w_hh)
        if mt is not None:
            m = mt[:, None]
            h_new = m * h_new + (1 - m) * h
            return h_new, h_new * m
        return h_new, h_new

    xs = (xp.transpose(1, 0, 2),
          mask.transpose(1, 0) if mask is not None else None)
    hT, outs = jax.lax.scan(step, h0, xs)
    return outs.transpose(1, 0, 2), hT


def bidirectional_lstm(x, fwd_w_ih, fwd_w_hh, bwd_w_ih, bwd_w_hh,
                       fwd_b=None, bwd_b=None, lengths=None):
    """Concat of forward + reverse LSTM outputs (the cudnn_lstm
    bidirectional mode, ref: operators/cudnn_lstm_op.cu.cc)."""
    f, _ = lstm(x, fwd_w_ih, fwd_w_hh, b=fwd_b, lengths=lengths)
    b, _ = lstm(x, bwd_w_ih, bwd_w_hh, b=bwd_b, lengths=lengths,
                reverse=True)
    return jnp.concatenate([f, b], axis=-1)


def attention_lstm(x, c0, attn_w, lstm_w, attn_b=None, lstm_b=None,
                   h0=None, lengths=None):
    """Fused attention + LSTM (ref: operators/attention_lstm_op.cc):
    at each step additive (Bahdanau-style) attention scores every source
    position against the previous cell state —
    ``e_j = tanh(x_j . w_x + c . w_c + b)`` — and the attention-weighted
    context vector feeds one LSTM step. The tanh is essential: with a
    purely linear score the ``c`` term is a per-row constant and cancels
    in the softmax. x [B,T,M]; c0 [B,D]; attn_w [M+D,1]; lstm_w [M+D,4D]
    over concat(context, h), gate order i,f,c,o (the library convention,
    see lstm above). Returns (hidden [B,T,D], (h_T, c_T)); ``lengths``
    masks the attention softmax AND freezes each row's (h, c) past its
    end with zero output — the same padded-step contract as ``lstm``."""
    B, T, M = x.shape
    D = c0.shape[-1]
    dt = x.dtype
    h = h0 if h0 is not None else jnp.zeros((B, D), dt)
    c = c0.astype(dt)
    neg = jnp.asarray(-1e9, jnp.float32)
    amask = (None if lengths is None
             else (jnp.arange(T)[None, :] < lengths[:, None]))
    # hoist the step-invariant half of the score out of the scan: one
    # [B,T,M]x[M,1] matmul instead of a [B,T,M+D] concat+matmul per step
    x_score = (x @ attn_w[:M])[..., 0]                     # [B, T]
    if attn_b is not None:
        x_score = x_score + attn_b

    def step(carry, t):
        h, c = carry
        e = jnp.tanh(x_score + c @ attn_w[M:])             # [B, T]
        e32 = e.astype(jnp.float32)
        if amask is not None:
            e32 = jnp.where(amask, e32, neg)
        a = jax.nn.softmax(e32, axis=-1).astype(dt)
        ctx = jnp.einsum("bt,btm->bm", a, x)
        gates = jnp.concatenate([ctx, h], axis=-1) @ lstm_w
        if lstm_b is not None:
            gates = gates + lstm_b
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        if lengths is not None:
            live = (t < lengths)[:, None]
            h_new = jnp.where(live, h_new, h)
            c_new = jnp.where(live, c_new, c)
            out = jnp.where(live, h_new, jnp.zeros_like(h_new))
        else:
            out = h_new
        return (h_new, c_new), out

    (h, c), hs = jax.lax.scan(step, (h, c), jnp.arange(T))
    return hs.transpose(1, 0, 2), (h, c)


__all__.append("attention_lstm")
