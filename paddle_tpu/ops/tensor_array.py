"""LoDTensorArray + LoD structural ops.

Parity targets (SURVEY §2.4): array_to_lod_tensor / lod_tensor_to_array
(operators/array_to_lod_tensor_op.cc, lod_tensor_to_array_op.cc),
lod_array_length, lod_rank_table (operators/lod_rank_table_op.cc),
max_sequence_len, lod_reset, reorder_lod_tensor_by_rank,
split_lod_tensor / merge_lod_tensor (controlflow machinery),
tensor_array_to_tensor, shrink_rnn_memory — the machinery behind the
reference's DynamicRNN (layers/control_flow.py:1700).

TPU-native shape: the reference's LoDTensorArray is a runtime vector of
tensors mutated op-by-op inside While loops; here a TensorArray is an
immutable [T, ...] stacked array + integer length (scan-carry friendly,
static shapes), and LoD metadata travels as explicit `lengths` vectors
(see core/lod.RaggedBatch). DynamicRNN itself is ops/control_flow.scan —
these ops cover programs that manipulate the array/LoD structure
directly.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import RaggedBatch

__all__ = [
    "TensorArray", "create_array", "array_write", "array_read",
    "array_length", "tensor_array_to_tensor",
    "lod_tensor_to_array", "array_to_lod_tensor",
    "lod_rank_table", "max_sequence_len", "lod_reset",
    "reorder_lod_tensor_by_rank", "split_lod_tensor", "merge_lod_tensor",
    "shrink_rnn_memory",
]


class TensorArray:
    """LoDTensorArray parity, value-semantics: fixed-capacity [T, ...]
    buffer + current length. Writes return a NEW TensorArray (functional,
    so it can be a lax.scan/while_loop carry)."""

    def __init__(self, buffer, length):
        self.buffer = buffer
        self.length = length

    @classmethod
    def empty(cls, capacity, elem_shape, dtype=jnp.float32):
        return cls(jnp.zeros((capacity,) + tuple(elem_shape), dtype),
                   jnp.asarray(0, jnp.int32))

    def write(self, i, value):
        return TensorArray(self.buffer.at[i].set(value),
                           jnp.maximum(self.length, i + 1))

    def read(self, i):
        return self.buffer[i]

    def stack(self):
        return self.buffer[:int(self.length)] \
            if not isinstance(self.length, jax.core.Tracer) else self.buffer

    def __len__(self):
        return int(self.length)


jax.tree_util.register_pytree_node(
    TensorArray,
    lambda ta: ((ta.buffer, ta.length), None),
    lambda _, ch: TensorArray(*ch))


def create_array(capacity, elem_shape, dtype=jnp.float32):
    return TensorArray.empty(capacity, elem_shape, dtype)


def array_write(array, i, x):
    return array.write(i, x)


def array_read(array, i):
    return array.read(i)


def array_length(array):
    """operators/lod_array_length_op.cc."""
    return array.length


def tensor_array_to_tensor(array, axis=0):
    """operators/tensor_array_to_tensor_op.cc: concat/stack the array's
    valid prefix along ``axis``."""
    vals = array.stack()
    if axis == 0:
        return vals
    return jnp.moveaxis(vals, 0, axis)


def lod_tensor_to_array(ragged):
    """operators/lod_tensor_to_array_op.cc: split a ragged batch into a
    per-timestep array ordered by the rank table (longest first) —
    t-th entry holds step t of every sequence longer than t."""
    enforce(isinstance(ragged, RaggedBatch), "expects RaggedBatch")
    order = np.argsort(-np.asarray(ragged.lengths))
    data = jnp.asarray(ragged.data)[order]
    lens = np.asarray(ragged.lengths)[order]
    steps = []
    for t in range(int(lens.max()) if len(lens) else 0):
        steps.append(data[: int((lens > t).sum()), t])
    return steps, order, lens


def array_to_lod_tensor(steps, order, lens):
    """operators/array_to_lod_tensor_op.cc: inverse of the above."""
    n = len(lens)
    maxlen = len(steps)
    feat = steps[0].shape[1:] if steps else ()
    out = np.zeros((n, maxlen) + tuple(feat),
                   np.asarray(steps[0]).dtype if steps else np.float32)
    for t, s in enumerate(steps):
        out[: s.shape[0], t] = np.asarray(s)
    inv = np.argsort(order)
    return RaggedBatch(jnp.asarray(out[inv]),
                       jnp.asarray(np.asarray(lens)[inv]))


def lod_rank_table(ragged, level=0):
    """operators/lod_rank_table_op.cc: [(seq_index, length)] sorted by
    descending length (stable)."""
    lens = np.asarray(ragged.lengths)
    order = np.argsort(-lens, kind="stable")
    return [(int(i), int(lens[i])) for i in order]


def max_sequence_len(rank_table):
    """operators/max_sequence_len_op.cc."""
    return rank_table[0][1] if rank_table else 0


def lod_reset(ragged, target_lengths):
    """operators/lod_reset_op.cc: reinterpret the flat data under new
    sequence lengths."""
    flat, _ = ragged.to_lod()
    return RaggedBatch.from_lod(flat, _lengths_to_lod(target_lengths))


def _lengths_to_lod(lengths):
    off = [0]
    for n in lengths:
        off.append(off[-1] + int(n))
    return off


def reorder_lod_tensor_by_rank(ragged, rank_table):
    """operators/reorder_lod_tensor_by_rank_op.cc."""
    order = [i for i, _ in rank_table]
    return RaggedBatch(jnp.asarray(ragged.data)[jnp.asarray(order)],
                       jnp.asarray(ragged.lengths)[jnp.asarray(order)])


def split_lod_tensor(x, mask):
    """operators/split_lod_tensor_op.cc (IfElse machinery): partition
    rows by boolean mask -> (true_rows, false_rows, restore_index)."""
    mask = np.asarray(mask).astype(bool).reshape(-1)
    ti = np.nonzero(mask)[0]
    fi = np.nonzero(~mask)[0]
    restore = np.argsort(np.concatenate([ti, fi]))
    return (jnp.asarray(x)[jnp.asarray(ti, jnp.int32)] if len(ti) else
            jnp.zeros((0,) + x.shape[1:], x.dtype),
            jnp.asarray(x)[jnp.asarray(fi, jnp.int32)] if len(fi) else
            jnp.zeros((0,) + x.shape[1:], x.dtype),
            restore)


def merge_lod_tensor(true_rows, false_rows, restore_index):
    """operators/merge_lod_tensor_op.cc: inverse of split_lod_tensor."""
    allrows = jnp.concatenate([true_rows, false_rows], axis=0)
    return allrows[jnp.asarray(restore_index, jnp.int32)]


def shrink_rnn_memory(mem, rank_table, step):
    """operators/shrink_rnn_memory_op.cc: keep only the sequences still
    alive at timestep ``step`` (rank-table-ordered memory)."""
    alive = sum(1 for _, ln in rank_table if ln > step)
    return mem[:alive]


# reference op-name alias (lod_array_length_op.cc)
lod_array_length = array_length
__all__.append("lod_array_length")
