"""Linear-chain CRF ops.

TPU-native rebuild of the reference CRF operators
(ref: paddle/fluid/operators/linear_chain_crf_op.cc,
 paddle/fluid/operators/crf_decoding_op.cc). The reference consumes LoD
batches; here sequences are dense-padded [batch, time, num_tags] with an
explicit ``length`` vector (SURVEY §5.7 LoD→padding+mask mapping), and the
time recursions are `lax.scan` loops so the whole thing stays jittable.

Transition parameter layout matches the reference exactly so weights are
interchangeable: shape ``[num_tags + 2, num_tags]`` where row 0 holds start
weights, row 1 stop weights, and rows 2: the [num_tags, num_tags]
tag-to-tag transition matrix (ref: linear_chain_crf_op.cc OpMaker).
"""

import jax
import jax.numpy as jnp

__all__ = ["linear_chain_crf", "crf_decoding"]



def _split_transition(transition):
    start, stop, trans = transition[0], transition[1], transition[2:]
    return start, stop, trans


def linear_chain_crf(input, transition, label, length=None):
    """Negative log-likelihood of tag sequences under a linear-chain CRF.

    Args:
      input: emissions ``[batch, time, num_tags]`` (unnormalized).
      transition: ``[num_tags + 2, num_tags]`` (see module docstring).
      label: int tags ``[batch, time]`` (or ``[batch, time, 1]``).
      length: int ``[batch]`` valid lengths; None means full time axis.

    Returns:
      ``[batch]`` per-sequence negative log-likelihood
      (log_norm - path_score), the reference op's output semantics.
    """
    input = jnp.asarray(input)
    label = jnp.asarray(label)
    if label.ndim == 3:
        label = label[..., 0]
    b, t, d = input.shape
    if length is None:
        length = jnp.full((b,), t, jnp.int32)
    length = jnp.asarray(length, jnp.int32)
    start, stop, trans = _split_transition(jnp.asarray(transition))

    # mask[b, t] = 1 for valid steps
    steps = jnp.arange(t)
    mask = (steps[None, :] < length[:, None]).astype(input.dtype)

    # ---- log partition via forward algorithm ----
    alpha0 = input[:, 0, :] + start[None, :]

    def fwd(alpha, xs):
        em, m = xs  # em [b, d], m [b]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None, :, :],
                               axis=1) + em
        alpha = jnp.where(m[:, None] > 0, nxt, alpha)
        return alpha, None

    alpha, _ = jax.lax.scan(
        fwd, alpha0,
        (jnp.swapaxes(input, 0, 1)[1:], jnp.swapaxes(mask, 0, 1)[1:]))
    log_norm = jax.nn.logsumexp(alpha + stop[None, :], axis=1)

    # ---- score of the gold path ----
    em_score = jnp.sum(
        jnp.take_along_axis(input, label[..., None], axis=2)[..., 0] * mask,
        axis=1)
    pair_mask = mask[:, 1:]
    tr_score = jnp.sum(trans[label[:, :-1], label[:, 1:]] * pair_mask, axis=1)
    last_idx = jnp.maximum(length - 1, 0)
    last_tag = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = em_score + tr_score + start[label[:, 0]] + stop[last_tag]
    return log_norm - gold


def crf_decoding(input, transition, length=None):
    """Viterbi decode: most likely tag path per sequence.

    Returns int32 ``[batch, time]`` paths; steps past ``length`` are 0
    (the reference emits LoD-cut sequences; callers mask with ``length``).
    """
    input = jnp.asarray(input)
    b, t, d = input.shape
    if length is None:
        length = jnp.full((b,), t, jnp.int32)
    length = jnp.asarray(length, jnp.int32)
    start, stop, trans = _split_transition(jnp.asarray(transition))

    steps = jnp.arange(t)
    mask = steps[None, :] < length[:, None]

    score0 = input[:, 0, :] + start[None, :]

    def fwd(score, xs):
        em, m = xs
        cand = score[:, :, None] + trans[None, :, :]
        back = jnp.argmax(cand, axis=1)                       # [b, d]
        nxt = jnp.max(cand, axis=1) + em
        score = jnp.where(m[:, None], nxt, score)
        back = jnp.where(m[:, None], back, jnp.arange(d)[None, :])
        return score, back

    score, backs = jax.lax.scan(
        fwd, score0,
        (jnp.swapaxes(input, 0, 1)[1:], jnp.swapaxes(mask, 0, 1)[1:]))
    # backs: [t-1, b, d]
    last = jnp.argmax(score + stop[None, :], axis=1)          # [b]

    def bwd(tag, back):
        prev = jnp.take_along_axis(back, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first, tags = jax.lax.scan(bwd, last, backs, reverse=True)
    path = jnp.concatenate([first[None, :], tags], axis=0)    # [t, b]
    path = jnp.swapaxes(path, 0, 1).astype(jnp.int32)
    return jnp.where(mask, path, 0)
