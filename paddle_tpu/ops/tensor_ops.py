"""Tensor manipulation ops.

Parity targets: concat_op.cc, split_op.cc, stack_op.cc, unstack_op.cc,
squeeze_op.cc, unsqueeze_op.cc, reshape_op.cc, flatten_op.cc,
transpose_op.cc, slice_op.cc, strided_slice (absent), gather_op.cc,
scatter_op.cc, expand_op.cc, tile (absent, expand is the analog),
shape_op.cc, fill_constant_op.cc, fill_any_like_op.cc,
fill_zeros_like_op.cc, assign_op.cc, arg_max/arg_min/argsort_op.cc,
top_k_op.cc, where_op.cc, diag_op.cc, linspace_op.cc, range_op.cc,
reverse_op.cc, unique_op.cc, size_op.cc, is_empty_op.cc, multiplex_op.cc,
crop_op.cc, im2sequence via unfold, tensor_array_to_tensor_op.cc.
"""

import builtins

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import convert_dtype

builtins_slice = builtins.slice
builtins_list = builtins.list

__all__ = [
    "concat", "split", "stack", "unstack", "squeeze", "unsqueeze",
    "reshape", "flatten", "transpose", "slice", "strided_slice", "gather",
    "gather_nd", "scatter", "scatter_nd_add", "expand", "expand_as",
    "tile", "shape", "size", "fill_constant", "fill_constant_batch_size_like",
    "zeros", "ones", "zeros_like", "ones_like", "full_like", "assign",
    "argmax", "argmin", "argsort", "topk", "where", "where_index", "diag",
    "linspace", "arange", "reverse", "unique", "unique_with_counts",
    "is_empty", "has_inf", "has_nan", "rank", "create_tensor",
    "multiplex", "crop", "roll", "flip", "meshgrid", "eye",
]


def concat(input, axis=0, name=None):
    return jnp.concatenate([jnp.asarray(t) for t in input], axis=axis)


def split(input, num_or_sections, dim=-1, name=None):
    input = jnp.asarray(input)
    if isinstance(num_or_sections, int):
        return jnp.split(input, num_or_sections, axis=dim)
    idx = jnp.cumsum(jnp.array(num_or_sections[:-1])).tolist()
    return jnp.split(input, idx, axis=dim)


def stack(x, axis=0, name=None):
    return jnp.stack([jnp.asarray(t) for t in x], axis=axis)


def unstack(x, axis=0, num=None, name=None):
    x = jnp.asarray(x)
    return [jnp.squeeze(t, axis=axis)
            for t in jnp.split(x, x.shape[axis], axis=axis)]


def squeeze(input, axes=None, name=None):
    input = jnp.asarray(input)
    if not axes:
        return jnp.squeeze(input)
    axes = [a for a in axes if input.shape[a] == 1]
    return jnp.squeeze(input, axis=tuple(axes)) if axes else input


def unsqueeze(input, axes, name=None):
    input = jnp.asarray(input)
    if isinstance(axes, int):
        axes = [axes]
    for a in sorted(axes):
        input = jnp.expand_dims(input, a)
    return input


def reshape(x, shape, inplace=False, name=None):
    """reshape_op.cc parity incl. the 0-entry rule: a 0 in ``shape``
    copies the input's dim at that position (-1 infers as usual)."""
    x = jnp.asarray(x)
    shape = [x.shape[i] if s == 0 else s
             for i, s in enumerate(shape)]
    return jnp.reshape(x, shape)


def flatten(x, axis=1, name=None):
    """flatten_op.cc parity: collapse dims [0,axis) and [axis, ndim)."""
    x = jnp.asarray(x)
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return x.reshape(lead, -1)


def transpose(x, perm, name=None):
    return jnp.transpose(jnp.asarray(x), perm)


def slice(input, axes, starts, ends, name=None):
    """slice_op.cc parity."""
    input = jnp.asarray(input)
    idx = [builtins_slice(None)] * input.ndim
    for ax, st, en in zip(axes, starts, ends):
        dim = input.shape[ax]
        st2 = st + dim if st < 0 else min(st, dim)
        en2 = en + dim if en < 0 else min(en, dim)
        idx[ax] = builtins_slice(st2, en2)
    return input[tuple(idx)]


def strided_slice(input, axes, starts, ends, strides, name=None):
    input = jnp.asarray(input)
    idx = [builtins_slice(None)] * input.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins_slice(st, en, sd)
    return input[tuple(idx)]


def gather(input, index, overwrite=True, name=None):
    """gather_op.cc parity: select rows along axis 0."""
    index = jnp.asarray(index)
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    return jnp.take(jnp.asarray(input), index, axis=0)


def gather_nd(input, index, name=None):
    input, index = jnp.asarray(input), jnp.asarray(index)
    return input[tuple(jnp.moveaxis(index, -1, 0))]


def scatter(input, index, updates, overwrite=True, name=None):
    """scatter_op.cc parity: write (or add) update rows at index."""
    input = jnp.asarray(input)
    index = jnp.asarray(index)
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    if overwrite:
        return input.at[index].set(updates)
    return input.at[index].add(updates)


def scatter_nd_add(ref, index, updates, name=None):
    ref, index = jnp.asarray(ref), jnp.asarray(index)
    return ref.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def expand(x, expand_times, name=None):
    """expand_op.cc parity: tile each dim expand_times[i] times."""
    return jnp.tile(jnp.asarray(x), expand_times)


def expand_as(x, target_tensor, name=None):
    x = jnp.asarray(x)
    times = [t // s for s, t in zip(x.shape, target_tensor.shape)]
    return jnp.tile(x, times)


def tile(x, repeat_times, name=None):
    return jnp.tile(jnp.asarray(x), repeat_times)


def shape(input, name=None):
    return jnp.array(jnp.asarray(input).shape, dtype=jnp.int32)


def size(input, name=None):
    return jnp.array(jnp.asarray(input).size, dtype=jnp.int64)


def fill_constant(shape, dtype, value, name=None):
    return jnp.full(tuple(int(s) for s in shape), value,
                    dtype=convert_dtype(dtype))


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  name=None):
    shape = builtins_list(shape)
    shape[output_dim_idx] = jnp.asarray(input).shape[input_dim_idx]
    return jnp.full(tuple(shape), value, dtype=convert_dtype(dtype))


def zeros(shape, dtype="float32", name=None):
    return jnp.zeros(tuple(shape), convert_dtype(dtype))


def ones(shape, dtype="float32", name=None):
    return jnp.ones(tuple(shape), convert_dtype(dtype))


def zeros_like(x, out=None, name=None):
    return jnp.zeros_like(jnp.asarray(x))


def ones_like(x, out=None, name=None):
    return jnp.ones_like(jnp.asarray(x))


def full_like(x, fill_value, dtype=None, name=None):
    return jnp.full_like(jnp.asarray(x), fill_value,
                         dtype=convert_dtype(dtype) if dtype else None)


def assign(input, output=None, name=None):
    return jnp.asarray(input)


def argmax(x, axis=0, name=None):
    return jnp.argmax(jnp.asarray(x), axis=axis).astype(jnp.int64)


def argmin(x, axis=0, name=None):
    return jnp.argmin(jnp.asarray(x), axis=axis).astype(jnp.int64)


def argsort(input, axis=-1, descending=False, name=None):
    """argsort_op.cc parity: returns (sorted, indices)."""
    input = jnp.asarray(input)
    if descending:
        idx = jnp.argsort(-input, axis=axis)
    else:
        idx = jnp.argsort(input, axis=axis)
    out = jnp.take_along_axis(input, idx, axis=axis)
    return out, idx.astype(jnp.int64)


def topk(input, k, name=None):
    """top_k_op.cc parity over last axis: (values, indices)."""
    v, i = jax.lax.top_k(jnp.asarray(input), k)
    return v, i.astype(jnp.int64)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return where_index(condition)
    return jnp.where(condition, x, y)


def where_index(condition, name=None):
    """where_op.cc parity: indices of true elements. Dynamic-shaped; only
    usable eagerly (outside jit), like the reference's CPU-side usage."""
    import numpy as np
    return jnp.asarray(np.argwhere(np.asarray(condition)))


def diag(diagonal, name=None):
    return jnp.diag(jnp.asarray(diagonal))


def linspace(start, stop, num, dtype="float32", name=None):
    return jnp.linspace(start, stop, int(num), dtype=convert_dtype(dtype))


def arange(start, end=None, step=1, dtype="float32", name=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=convert_dtype(dtype))


def reverse(x, axis, name=None):
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(jnp.asarray(x), axis=tuple(axis))


def flip(x, axis, name=None):
    return reverse(x, axis)


def roll(x, shifts, axis=None, name=None):
    return jnp.roll(jnp.asarray(x), shifts, axis=axis)


def unique_with_counts(x, dtype="int32", name=None):
    """unique_op.cc parity (eager only: dynamic output shape)."""
    import numpy as np
    out, index, counts = np.unique(np.asarray(x), return_inverse=True,
                                   return_counts=True)
    return (jnp.asarray(out), jnp.asarray(index.astype(dtype)),
            jnp.asarray(counts.astype(dtype)))


def is_empty(x, name=None):
    return jnp.array(jnp.asarray(x).size == 0)


def multiplex(inputs, index, name=None):
    """multiplex_op.cc parity: per-row select among candidate tensors."""
    stacked = jnp.stack([jnp.asarray(t) for t in inputs], axis=0)
    idx = jnp.asarray(index).reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


def crop(x, shape=None, offsets=None, name=None):
    x = jnp.asarray(x)
    offsets = offsets or [0] * x.ndim
    idx = tuple(builtins_slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]


def meshgrid(*args):
    return jnp.meshgrid(*[jnp.asarray(a) for a in args], indexing="ij")


def eye(num_rows, num_columns=None, dtype="float32"):
    return jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype))


def unique(x, dtype="int32", name=None):
    """unique_op.cc / fluid.layers.unique parity: returns (out, index)
    where ``index`` maps each element of x to its position in ``out``
    (eager only: dynamic output shape, same constraint as the
    reference's LoD-producing form)."""
    out, index, _ = unique_with_counts(x, dtype=dtype)
    return out, index


def has_inf(x, name=None):
    """isfinite family (fluid.layers.has_inf): scalar "any inf"."""
    return jnp.any(jnp.isinf(jnp.asarray(x)))


def has_nan(x, name=None):
    """fluid.layers.has_nan: scalar "any nan"."""
    return jnp.any(jnp.isnan(jnp.asarray(x)))


def rank(input, name=None):
    """fluid.layers.rank: 0-D int tensor holding the number of
    dimensions. Static under jit (shape is trace-time constant)."""
    return jnp.asarray(jnp.asarray(input).ndim, jnp.int32)


def create_tensor(dtype="float32", name=None, persistable=False):
    """fluid.layers.tensor.create_tensor parity: an empty typed tensor
    to be filled by assign/fill ops later (eager: 0-size array)."""
    return jnp.zeros((0,), convert_dtype(dtype))
