"""Neural-net structural ops: conv / pool / norm / embedding / dropout.

Parity targets: operators/conv_op.cc(+cudnn), conv_transpose_op.cc,
pool_op.cc, batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
data_norm_op.cc, dropout_op.cc, lookup_table_op.cc, one_hot_op.cc,
label_smooth_op.cc, lrn_op.cc, pad_op.cc, pad2d_op.cc, interpolate_op.cc,
pixel_shuffle_op.cc, affine_channel_op.cc, unfold_op.cc,
space_to_depth_op.cc, shuffle_channel_op.cc, grid_sampler_op.cc.

Convs/matmuls are the MXU ops; layouts default to the reference's NCHW but
everything is expressed through lax.conv_general_dilated dimension numbers
so XLA picks TPU-optimal internal layouts.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import random as ptrandom

__all__ = [
    "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "depthwise_conv2d", "pool2d",
    "pool3d", "adaptive_pool2d", "adaptive_pool3d",
    "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "data_norm", "sync_batch_norm", "dropout",
    "embedding", "one_hot",
    "label_smooth", "lrn", "pad", "pad2d", "pad_constant_like",
    "interpolate", "resize_nearest", "resize_bilinear", "image_resize",
    "image_resize_short", "pixel_shuffle",
    "affine_channel", "unfold", "space_to_depth", "shuffle_channel",
    "fc_act",
]


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _conv_padding(padding, spatial):
    if isinstance(padding, str):
        return padding.upper()
    p = _pair(padding, spatial)
    return [(int(x), int(x)) for x in p]


def conv2d(x, weight, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """conv_op.cc parity. weight layout OIHW (out, in/groups, kh, kw)."""
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        (data_format, "OIHW", data_format))
    return lax.conv_general_dilated(
        x, weight,
        window_strides=_pair(stride),
        padding=_conv_padding(padding, 2),
        rhs_dilation=_pair(dilation),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None)


def depthwise_conv2d(x, weight, stride=1, padding=0, dilation=1,
                     data_format="NCHW", name=None):
    c = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    return conv2d(x, weight, stride, padding, dilation, groups=c,
                  data_format=data_format)


def conv3d(x, weight, stride=1, padding=0, dilation=1, groups=1, name=None):
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    return lax.conv_general_dilated(
        x, weight, window_strides=_pair(stride, 3),
        padding=_conv_padding(padding, 3), rhs_dilation=_pair(dilation, 3),
        dimension_numbers=dn, feature_group_count=groups)


def conv3d_transpose(x, weight, stride=1, padding=0, dilation=1, groups=1,
                     name=None):
    """conv_transpose_op.cc 3-D parity. Weight layout IODHW
    (in, out/groups, kd, kh, kw), same filter convention as
    conv2d_transpose; lowered as the gradient-of-conv formulation
    (lhs-dilation) so XLA maps it onto the MXU like a forward conv."""
    stride, dilation = _pair(stride, 3), _pair(dilation, 3)
    pads = _pair(padding, 3)
    kd, kh, kw = weight.shape[2], weight.shape[3], weight.shape[4]
    dn = lax.conv_dimension_numbers(
        x.shape,
        (weight.shape[1] * groups, weight.shape[0] // groups, kd, kh, kw),
        ("NCDHW", "OIDHW", "NCDHW"))
    w = jnp.flip(weight, axis=(2, 3, 4))
    cin, cog = weight.shape[0], weight.shape[1]
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        w = w.reshape(groups, cin // groups, cog, kd, kh, kw)
        w = jnp.swapaxes(w, 1, 2).reshape(
            groups * cog, cin // groups, kd, kh, kw)
    pad = [(dilation[i] * (k - 1) - pads[i],) * 2
           for i, k in enumerate((kd, kh, kw))]
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1, 1), padding=pad,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)


def conv2d_transpose(x, weight, stride=1, padding=0, dilation=1, groups=1,
                     data_format="NCHW", name=None):
    """conv_transpose_op.cc parity. weight layout IOHW (in, out/groups, kh, kw),
    matching the reference's transpose-conv filter layout."""
    stride, dilation = _pair(stride), _pair(dilation)
    pads = _pair(padding)
    kh, kw = weight.shape[2], weight.shape[3]
    # gradient-of-conv formulation: lhs-dilate input by stride
    dn = lax.conv_dimension_numbers(x.shape,
                                    (weight.shape[1] * groups, weight.shape[0] // groups, kh, kw),
                                    (data_format, "OIHW", data_format))
    # flip spatial dims and swap I/O to turn conv_transpose into conv;
    # grouped case: IOHW rows are group-major, so regroup to
    # (out, in/groups, kh, kw) for feature_group_count semantics
    w = jnp.flip(weight, axis=(2, 3))
    cin, cog = weight.shape[0], weight.shape[1]  # in, out/groups
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        w = w.reshape(groups, cin // groups, cog, kh, kw)
        w = jnp.swapaxes(w, 1, 2).reshape(groups * cog, cin // groups, kh, kw)
    pad_h = dilation[0] * (kh - 1) - pads[0]
    pad_w = dilation[1] * (kw - 1) - pads[1]
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1),
        padding=[(pad_h, pad_h), (pad_w, pad_w)],
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)


def pool2d(x, pool_size=2, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, exclusive=True,
           data_format="NCHW", name=None):
    """pool_op.cc parity (max/avg, exclusive avg-padding semantics,
    NCHW or NHWC layout — pool_op.cc handles both via data_format)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"pool2d: data_format must be NCHW|NHWC, "
                         f"got {data_format!r}")
    sp = (2, 3) if data_format == "NCHW" else (1, 2)
    if global_pooling:
        if pool_type == "max":
            return jnp.max(x, axis=sp, keepdims=True)
        return jnp.mean(x, axis=sp, keepdims=True)
    ks = _pair(pool_size)
    st = _pair(pool_stride)
    pd = _pair(pool_padding)

    def lay(h, w, one=1):
        # place the spatial entries at the layout's H/W positions
        out = [one, one, one, one]
        out[sp[0]], out[sp[1]] = h, w
        return tuple(out)

    window = lay(ks[0], ks[1])
    strides = lay(st[0], st[1])
    ph = (pd[0], pd[0] + (st[0] - 1 if ceil_mode else 0))
    pw = (pd[1], pd[1] + (st[1] - 1 if ceil_mode else 0))
    pads = lay(ph, pw, one=(0, 0))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if exclusive:
        # ones only over the spatial plane (singleton batch/channel):
        # the count is layout-independent and broadcasts in the divide
        ones = jnp.ones(lay(x.shape[sp[0]], x.shape[sp[1]]), x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    return s / (ks[0] * ks[1])


def pool3d(x, pool_size=2, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, name=None):
    if global_pooling:
        axis = (2, 3, 4)
        return (jnp.max if pool_type == "max" else jnp.mean)(x, axis=axis, keepdims=True)
    ks, st, pd = _pair(pool_size, 3), _pair(pool_stride, 3), _pair(pool_padding, 3)
    window, strides = (1, 1) + ks, (1, 1) + st
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
    if pool_type == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    return s / (ks[0] * ks[1] * ks[2])


def _adaptive_masks(size, out):
    """[out, size] 0/1 membership mask of pool_op.h's adaptive windows:
    cell i covers [floor(i*size/out), ceil((i+1)*size/out))
    (AdaptiveStartIndex/AdaptiveEndIndex). Shapes are static, so the
    mask is a compile-time-constant matrix — the avg reduction becomes
    a (normalized) matmul the MXU tiles, the max a masked reduce."""
    import numpy as _np
    idx = _np.arange(size)
    starts = _np.floor(_np.arange(out) * size / out).astype(int)
    ends = _np.ceil((_np.arange(out) + 1) * size / out).astype(int)
    return jnp.asarray(
        (idx[None, :] >= starts[:, None]) & (idx[None, :] < ends[:, None]),
        jnp.float32)


def _adaptive_reduce(x, axes, outs, pool_type):
    """Adaptive pooling over the given axes to the given output sizes
    via per-axis membership masks (axes reduced one at a time)."""
    for ax, out in zip(axes, outs):
        size = x.shape[ax]
        m = _adaptive_masks(size, out)                   # [out, size]
        xm = jnp.moveaxis(x, ax, -1)                     # [..., size]
        if pool_type == "max":
            big = jnp.finfo(x.dtype).min if jnp.issubdtype(
                x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            # [..., out, size] masked -> max over size
            r = jnp.max(jnp.where(m.astype(bool), xm[..., None, :], big),
                        axis=-1)
        else:
            # highest precision: the mask matmul must reproduce the
            # exact per-cell mean (the divisible reshape path is exact,
            # and pool parity tests compare at tight tolerances); the
            # f32 mask promotes the accumulation — cast back so bf16
            # inputs keep bf16 outputs like the sibling paths
            r = (jnp.einsum("...s,os->...o", xm, m,
                            precision=jax.lax.Precision.HIGHEST)
                 / m.sum(-1)).astype(x.dtype)
        x = jnp.moveaxis(r, -1, ax)
    return x


def adaptive_pool2d(x, pool_size, pool_type="avg", name=None):
    """Adaptive pooling (pool_op.cc adaptive=True): arbitrary output
    sizes via the reference's per-cell start/end windows
    (pool_op.h AdaptiveStartIndex/AdaptiveEndIndex); the divisible case
    keeps the cheap reshape-reduce."""
    n, c, h, w = x.shape
    oh, ow = _pair(pool_size)
    if h % oh == 0 and w % ow == 0:
        x = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return (jnp.max if pool_type == "max" else jnp.mean)(x, axis=(3, 5))
    return _adaptive_reduce(x, (2, 3), (oh, ow), pool_type)


def adaptive_pool3d(x, pool_size, pool_type="avg", name=None):
    """Adaptive 3-D pooling (pool_op.cc adaptive=True over NCDHW; ref
    python/paddle/fluid/layers/nn.py adaptive_pool3d). Arbitrary output
    sizes; divisible sizes keep the reshape-reduce fast path."""
    n, c, d, h, w = x.shape
    od, oh, ow = _pair(pool_size, 3)
    if d % od == 0 and h % oh == 0 and w % ow == 0:
        x = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        return (jnp.max if pool_type == "max" else jnp.mean)(
            x, axis=(3, 5, 7))
    return _adaptive_reduce(x, (2, 3, 4), (od, oh, ow), pool_type)


def batch_norm(x, scale, bias, mean, variance, epsilon=1e-5, momentum=0.9,
               is_test=False, data_layout="NCHW", use_global_stats=False,
               name=None):
    """batch_norm_op.cc parity.

    Returns (out, mean_out, variance_out, saved_mean, saved_variance) in
    training mode to mirror the reference's outputs; running stats use
    ``new = m*old + (1-m)*batch`` (batch_norm_op.cc momentum semantics).
    """
    axis = 1 if data_layout == "NCHW" else x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]

    if is_test or use_global_stats:
        m, v = mean, variance
        out = (x - m.reshape(bshape)) * (
            scale.reshape(bshape) * lax.rsqrt(v.reshape(bshape) + epsilon)
        ) + bias.reshape(bshape)
        return out, mean, variance, m, v

    m = jnp.mean(x, axis=red)
    v = jnp.var(x, axis=red)
    out = (x - m.reshape(bshape)) * (
        scale.reshape(bshape) * lax.rsqrt(v.reshape(bshape) + epsilon)
    ) + bias.reshape(bshape)
    mean_out = momentum * mean + (1 - momentum) * m
    var_out = momentum * variance + (1 - momentum) * v
    return out, mean_out, var_out, m, v


def sync_batch_norm(x, scale, bias, mean, variance, epsilon=1e-5,
                    momentum=0.9, is_test=False, data_layout="NCHW",
                    axis_name=None, name=None):
    """Cross-replica batch norm (sync_batch_norm_op.cu parity).

    Batch statistics are averaged across the ``axis_name`` mesh axis via
    XLA collectives (replacing the reference's hand-rolled two-pass NCCL
    allreduce of sum/sum-of-squares). Call inside shard_map/pmap with the
    data axis name; with axis_name=None it degrades to plain batch_norm
    (single-replica semantics).
    """
    if is_test or axis_name is None:
        return batch_norm(x, scale, bias, mean, variance, epsilon,
                          momentum, is_test=is_test,
                          data_layout=data_layout)
    axis = 1 if data_layout == "NCHW" else x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    # two-moment form so one pmean pair gives exact global stats
    m_local = jnp.mean(x, axis=red)
    sq_local = jnp.mean(jnp.square(x), axis=red)
    m = lax.pmean(m_local, axis_name)
    sq = lax.pmean(sq_local, axis_name)
    v = sq - jnp.square(m)
    out = (x - m.reshape(bshape)) * (
        scale.reshape(bshape) * lax.rsqrt(v.reshape(bshape) + epsilon)
    ) + bias.reshape(bshape)
    mean_out = momentum * mean + (1 - momentum) * m
    var_out = momentum * variance + (1 - momentum) * v
    return out, mean_out, var_out, m, v


def layer_norm(x, scale=None, bias=None, begin_norm_axis=1, epsilon=1e-5,
               name=None):
    """layer_norm_op.cc parity: normalize over dims [begin_norm_axis:)."""
    red = tuple(range(begin_norm_axis, x.ndim))
    m = jnp.mean(x, axis=red, keepdims=True)
    v = jnp.var(x, axis=red, keepdims=True)
    out = (x - m) * lax.rsqrt(v + epsilon)
    norm_shape = x.shape[begin_norm_axis:]
    if scale is not None:
        out = out * scale.reshape(norm_shape)
    if bias is not None:
        out = out + bias.reshape(norm_shape)
    return out


def group_norm(x, scale=None, bias=None, groups=32, epsilon=1e-5,
               data_layout="NCHW", name=None):
    """group_norm_op.cc parity (NCHW)."""
    n, c = x.shape[0], x.shape[1]
    g = groups
    xs = x.reshape((n, g, c // g) + x.shape[2:])
    red = tuple(range(2, xs.ndim))
    m = jnp.mean(xs, axis=red, keepdims=True)
    v = jnp.var(xs, axis=red, keepdims=True)
    xs = (xs - m) * lax.rsqrt(v + epsilon)
    out = xs.reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        out = out * scale.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    return out


def instance_norm(x, scale=None, bias=None, epsilon=1e-5, name=None):
    return group_norm(x, scale, bias, groups=x.shape[1], epsilon=epsilon)


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4,
              name=None):
    """data_norm_op.cc parity: normalize by accumulated batch statistics."""
    means = batch_sum / batch_size
    scales = jnp.sqrt(batch_size / (batch_square_sum - batch_size * jnp.square(means) + epsilon))
    return (x - means) * scales


def dropout(x, dropout_prob=0.5, is_test=False, seed=None,
            dropout_implementation="downgrade_in_infer", rng=None, name=None):
    """dropout_op.cc parity, both implementations:
    downgrade_in_infer (scale at inference) and upscale_in_train."""
    if dropout_prob == 0.0:
        return x
    if is_test:
        if dropout_implementation == "downgrade_in_infer":
            return x * (1.0 - dropout_prob)
        return x
    if rng is None:
        rng = ptrandom.key_for(seed)
    keep = jax.random.bernoulli(rng, 1.0 - dropout_prob, x.shape)
    if dropout_implementation == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - dropout_prob), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def embedding(ids, weight, padding_idx=None, name=None):
    """lookup_table_op.cc parity: gather rows; padding_idx rows → 0.

    On TPU this is a gather from an HBM-resident table; the distributed
    large-table path lives in paddle_tpu/distributed/sparse.py.
    """
    ids = jnp.asarray(ids)
    squeeze = False
    if ids.ndim and ids.shape[-1] == 1:
        ids, squeeze = ids[..., 0], True
    from paddle_tpu.ops import pallas as _plk
    weight = jnp.asarray(weight)
    if weight.ndim == 2 and _plk.use_pallas("embedding_gather"):
        out = _plk.dispatch("embedding_gather", weight, ids)
    else:
        out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:  # fluid convention: -1 means last row
            padding_idx = weight.shape[0] + padding_idx
        mask = (ids != padding_idx)[..., None]
        out = jnp.where(mask, out, 0.0)
    return out


def one_hot(x, depth, dtype=jnp.float32, name=None):
    x = jnp.asarray(x)
    if x.ndim and x.shape[-1] == 1:
        x = x[..., 0]
    return jax.nn.one_hot(x, depth, dtype=dtype)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


def lrn(x, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    """lrn_op.cc parity: local response norm across channels (NCHW)."""
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i: i + x.shape[1]] for i in range(n))
    return x / jnp.power(k + alpha * acc, beta)


def pad(x, paddings, pad_value=0.0, name=None):
    """pad_op.cc parity: flat [before0, after0, before1, after1, ...]."""
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return jnp.pad(x, cfg, constant_values=pad_value)


def pad2d(x, paddings, mode="constant", pad_value=0.0, data_format="NCHW",
          name=None):
    t, b, l, r = paddings
    cfg = ((0, 0), (0, 0), (t, b), (l, r)) if data_format == "NCHW" \
        else ((0, 0), (t, b), (l, r), (0, 0))
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, constant_values=pad_value)
    return jnp.pad(x, cfg, mode=jmode)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    cfg = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, cfg, constant_values=pad_value)


def interpolate(x, out_shape=None, scale=None, resample="BILINEAR",
                align_corners=True, data_format="NCHW", name=None):
    """interpolate_op.cc parity (nearest / bilinear over NCHW)."""
    n, c, h, w = x.shape
    if out_shape is None:
        out_shape = (int(h * scale), int(w * scale))
    oh, ow = out_shape
    method = "nearest" if resample.upper() == "NEAREST" else "bilinear"
    if method == "nearest" or not align_corners:
        return jax.image.resize(x, (n, c, oh, ow), method=method)
    # align_corners bilinear via explicit gather-interpolation
    ys = jnp.linspace(0, h - 1, oh)
    xs = jnp.linspace(0, w - 1, ow)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]
    g = lambda yi, xi: x[:, :, yi][:, :, :, xi]
    top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
    bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
    return top * (1 - wy) + bot * wy


def resize_nearest(x, out_shape=None, scale=None, align_corners=True, name=None):
    return interpolate(x, out_shape, scale, "NEAREST", align_corners)


def resize_bilinear(x, out_shape=None, scale=None, align_corners=True, name=None):
    return interpolate(x, out_shape, scale, "BILINEAR", align_corners)


def image_resize(x, out_shape=None, scale=None, resample="BILINEAR",
                 align_corners=True, name=None):
    """fluid.layers.image_resize parity (layers/nn.py image_resize):
    the user-facing dispatcher over interpolate_op.cc."""
    if resample.upper() not in ("BILINEAR", "NEAREST"):
        raise ValueError(
            f"image_resize: resample must be BILINEAR or NEAREST, "
            f"got {resample}")
    return interpolate(x, out_shape, scale, resample.upper(), align_corners)


def image_resize_short(x, out_short_len, resample="BILINEAR", name=None):
    """fluid.layers.image_resize_short parity: resize so the SHORT edge
    becomes out_short_len, keeping aspect ratio."""
    n, c, h, w = x.shape
    short = min(h, w)
    oh = int(round(h * out_short_len / short))
    ow = int(round(w * out_short_len / short))
    return image_resize(x, (oh, ow), None, resample)  # shares validation


def pixel_shuffle(x, upscale_factor, name=None):
    """pixel_shuffle_op.cc parity (NCHW)."""
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(n, c // (r * r), h * r, w * r)


def affine_channel(x, scale, bias, data_layout="NCHW", name=None):
    bshape = (1, -1) + (1,) * (x.ndim - 2) if data_layout == "NCHW" else (-1,)
    return x * scale.reshape(bshape) + bias.reshape(bshape)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """unfold_op.cc (im2col) parity: [N,C,H,W] → [N, C*kh*kw, L]."""
    kh, kw = _pair(kernel_sizes)
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), _pair(strides),
        [(p, p) for p in _pair(paddings)],
        rhs_dilation=_pair(dilations),
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, x.shape[1], kh, kw), ("NCHW", "OIHW", "NCHW")))
    n, ckk = patches.shape[0], patches.shape[1]
    return patches.reshape(n, ckk, -1)


def space_to_depth(x, blocksize, name=None):
    n, c, h, w = x.shape
    b = blocksize
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


def shuffle_channel(x, group, name=None):
    n, c, h, w = x.shape
    x = x.reshape(n, group, c // group, h, w)
    return x.swapaxes(1, 2).reshape(n, c, h, w)


def fc_act(x, act):
    """Apply a named activation (the reference's `act` attr pattern)."""
    if act is None:
        return x
    from paddle_tpu.ops import activation as A
    return getattr(A, act)(x)
