"""Detection op family.

Parity targets: paddle/fluid/operators/detection/ (30+ ops, ~15k LoC — prior
boxes, box coding, NMS, YOLO, RoI ops, FPN proposal machinery) plus root ops
detection_map_op.cc, roi_align_op.cc, roi_pool_op.cc, psroi_pool_op.cc.

TPU-first redesign, not a translation:
- every jittable op uses static shapes and fixed-size padded outputs with a
  sentinel (label/score = -1) instead of the reference's LoDTensor ragged
  outputs (ref: detection/multiclass_nms_op.cc:70-75 sets a dynamic -1 dim);
- greedy NMS is a `lax.fori_loop` over a fixed candidate count with a
  vectorised suppression mask — O(K) sequential steps, O(K) vector work per
  step, no data-dependent shapes;
- batch is `jax.vmap`, never a Python loop;
- the sampling/label-assignment ops that the reference runs on CPU inside
  the graph (rpn_target_assign, generate_proposal_labels, detection_map)
  are host/numpy functions here — on TPU they belong in the input pipeline,
  not the compiled step.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "iou_similarity", "box_coder", "prior_box", "density_prior_box",
    "anchor_generator", "bipartite_match", "target_assign",
    "multiclass_nms", "detection_output", "ssd_loss",
    "yolo_box", "yolov3_loss", "box_clip", "polygon_box_transform",
    "sigmoid_focal_loss", "roi_align", "roi_pool", "psroi_pool",
    "generate_proposals", "distribute_fpn_proposals",
    "collect_fpn_proposals", "box_decoder_and_assign",
    "retinanet_detection_output", "rpn_target_assign",
    "generate_proposal_labels", "detection_map",
    "retinanet_target_assign", "roi_perspective_transform",
    "generate_mask_labels", "mine_hard_examples",
]


# ---------------------------------------------------------------------------
# IoU / box utilities
# ---------------------------------------------------------------------------

def _box_area(boxes, normalized=True):
    off = 0.0 if normalized else 1.0
    w = jnp.maximum(boxes[..., 2] - boxes[..., 0] + off, 0.0)
    h = jnp.maximum(boxes[..., 3] - boxes[..., 1] + off, 0.0)
    return w * h


def _pairwise_iou(a, b, normalized=True):
    """IoU matrix [N, M] for corner-form boxes a [N,4], b [M,4]."""
    off = 0.0 if normalized else 1.0
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = _box_area(a, normalized)[:, None] + \
        _box_area(b, normalized)[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def iou_similarity(x, y, box_normalized=True):
    """IoU between every box pair; x [N,4] (or [B,N,4]), y [M,4] → [N,M].

    Parity: detection/iou_similarity_op.{cc,h}.
    """
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if x.ndim == 3:
        return jax.vmap(lambda xx: _pairwise_iou(xx, y, box_normalized))(x)
    return _pairwise_iou(x, y, box_normalized)


def box_clip(input, im_info):
    """Clip boxes to image bounds. input [..., 4]; im_info [B, 3] (h, w,
    scale) or [3]. Parity: detection/box_clip_op.{cc,h} (clips to
    im_info/scale - 1)."""
    boxes = jnp.asarray(input, jnp.float32)
    info = jnp.asarray(im_info, jnp.float32)
    if info.ndim == 1:
        info = info[None]
    h = info[:, 0] / info[:, 2] - 1.0
    w = info[:, 1] / info[:, 2] - 1.0
    if boxes.ndim == 2:
        h, w = h[0], w[0]
        return jnp.stack([
            jnp.clip(boxes[:, 0], 0, w), jnp.clip(boxes[:, 1], 0, h),
            jnp.clip(boxes[:, 2], 0, w), jnp.clip(boxes[:, 3], 0, h)],
            axis=-1)
    shape = (-1,) + (1,) * (boxes.ndim - 2)
    h = h.reshape(shape)
    w = w.reshape(shape)
    return jnp.stack([
        jnp.clip(boxes[..., 0], 0, w), jnp.clip(boxes[..., 1], 0, h),
        jnp.clip(boxes[..., 2], 0, w), jnp.clip(boxes[..., 3], 0, h)],
        axis=-1)


def polygon_box_transform(input):
    """Quad-point offsets → absolute coords (EAST-style text detection).
    input [N, 8k, H, W]; even channels are x offsets (added to col index*4),
    odd channels y offsets (row index*4).
    Parity: detection/polygon_box_transform_op.cc."""
    x = jnp.asarray(input, jnp.float32)
    n, c, h, w = x.shape
    ys = jnp.arange(h, dtype=jnp.float32)[:, None] * 4.0
    xs = jnp.arange(w, dtype=jnp.float32)[None, :] * 4.0
    even = jnp.arange(c) % 2 == 0
    base = jnp.where(even[:, None, None], xs[None], ys[None])
    return base[None] - x


# ---------------------------------------------------------------------------
# box_coder (encode/decode center-size)
# ---------------------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, variance=None):
    """Encode/decode boxes against priors in center-size form.

    Parity: detection/box_coder_op.{cc,h,cu}. prior_box [M,4];
    prior_box_var [M,4] or None (then `variance` list or 1.0);
    encode: target [N,4] → [N,M,4]; decode: target [N,M,4] (or [N,4] w/
    axis broadcast) → [N,M,4].
    """
    prior = jnp.asarray(prior_box, jnp.float32)
    target = jnp.asarray(target_box, jnp.float32)
    off = 0.0 if box_normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph

    if prior_box_var is not None:
        var = jnp.asarray(prior_box_var, jnp.float32)
    elif variance is not None:
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               prior.shape)
    else:
        var = jnp.ones_like(prior)

    if code_type.lower() in ("encode_center_size", "encode"):
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + 0.5 * tw
        tcy = target[:, 1] + 0.5 * th
        # output [N, M, 4]
        ex = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        ey = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ew = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        eh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ex, ey, ew, eh], axis=-1)
        return out / var[None, :, :]
    # decode
    if target.ndim == 2:
        target = target[:, None, :]
    if axis == 0:
        pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :],
                                pcx[None, :], pcy[None, :])
        var_ = var[None, :, :]
    else:
        pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None],
                                pcx[:, None], pcy[:, None])
        var_ = var[:, None, :]
    t = target * var_
    dcx = t[..., 0] * pw_ + pcx_
    dcy = t[..., 1] * ph_ + pcy_
    dw = jnp.exp(t[..., 2]) * pw_
    dh = jnp.exp(t[..., 3]) * ph_
    return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                      dcx + dw * 0.5 - off, dcy + dh * 0.5 - off], axis=-1)


# ---------------------------------------------------------------------------
# prior boxes / anchors
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes for one feature map.

    input [N,C,H,W] feature map, image [N,C,IH,IW]. Returns
    (boxes [H,W,P,4], variances [H,W,P,4]), normalized corner form.
    Parity: detection/prior_box_op.{cc,h} (aspect-ratio expansion w/ flip
    matches ExpandAspectRatios in bbox_util).
    """
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] \
        if max_sizes is not None else []
    ars = [1.0]
    for ar in np.atleast_1d(aspect_ratios):
        ar = float(ar)
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    # per-cell (w, h) list, matching the reference's ordering
    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if k < len(max_sizes):
                d = float(np.sqrt(ms * max_sizes[k]))
                whs.append((d, d))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if k < len(max_sizes):
                d = float(np.sqrt(ms * max_sizes[k]))
                whs.append((d, d))
    wh = jnp.asarray(whs, jnp.float32)  # [P, 2]
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]      # [H,W,1,2]
    half = wh[None, None, :, :] / 2.0                  # [1,1,P,2]
    scale = jnp.asarray([iw, ih], jnp.float32)
    mins = (c - half) / scale
    maxs = (c + half) / scale
    boxes = jnp.concatenate([mins, maxs], axis=-1)     # [H,W,P,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return boxes, var


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False):
    """Densified prior boxes (face-detection style).

    For each (density d, fixed_size s), a d×d grid of shifted centers per
    cell, one box per fixed_ratio. Parity: detection/density_prior_box_op.h.
    """
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh
    whs, shifts = [], []
    for d, s in zip(densities, fixed_sizes):
        d = int(d)
        for ar in fixed_ratios:
            bw = s * float(np.sqrt(ar))
            bh = s / float(np.sqrt(ar))
            shift = 1.0 / d
            for r in range(d):
                for c_ in range(d):
                    whs.append((bw, bh))
                    shifts.append(((c_ + 0.5) * shift - 0.5,
                                   (r + 0.5) * shift - 0.5))
    wh = jnp.asarray(whs, jnp.float32)           # [P,2]
    sh = jnp.asarray(shifts, jnp.float32)        # [P,2] in cell units
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]
    step = jnp.asarray([step_w, step_h], jnp.float32)
    centers = c + sh[None, None] * step
    half = wh[None, None] / 2.0
    scale = jnp.asarray([iw, ih], jnp.float32)
    boxes = jnp.concatenate([(centers - half) / scale,
                             (centers + half) / scale], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return boxes, var


def anchor_generator(input, anchor_sizes=(64., 128., 256., 512.),
                     aspect_ratios=(0.5, 1.0, 2.0),
                     variance=(0.1, 0.1, 0.2, 0.2),
                     stride=(16.0, 16.0), offset=0.5):
    """RPN anchors for one level. input [N,C,H,W] → (anchors [H,W,A,4],
    variances [H,W,A,4]), absolute pixel corner form.
    Parity: detection/anchor_generator_op.{cc,h}.
    """
    fh, fw = input.shape[2], input.shape[3]
    sw, sh = float(stride[0]), float(stride[1])
    whs = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            area = sw * sh
            w0 = float(np.sqrt(area / ar))
            h0 = w0 * ar
            scale_w = s / sw
            scale_h = s / sh
            whs.append((scale_w * w0, scale_h * h0))
    wh = jnp.asarray(whs, jnp.float32)
    cx = jnp.arange(fw, dtype=jnp.float32) * sw + offset * sw
    cy = jnp.arange(fh, dtype=jnp.float32) * sh + offset * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]
    half = wh[None, None] / 2.0
    anchors = jnp.concatenate([c - half, c + half], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), anchors.shape)
    return anchors, var


# ---------------------------------------------------------------------------
# matching / target assignment
# ---------------------------------------------------------------------------

def _bipartite_match_one(dist):
    """Greedy global-max matching. dist [R, C] → (col→row indices [C],
    matched dist [C]); -1 where unmatched.
    Parity: detection/bipartite_match_op.cc BipartiteMatch (greedy
    max-first), incl. the dist>0 requirement.
    """
    r, c = dist.shape
    n = min(r, c)

    def body(_, carry):
        d, idx, md = carry
        flat = jnp.argmax(d)
        i, j = flat // c, flat % c
        best = d[i, j]
        ok = best > 0
        idx = jnp.where(ok, idx.at[j].set(i), idx)
        md = jnp.where(ok, md.at[j].set(best), md)
        # retire matched row and column
        d = jnp.where(ok, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return d, idx, md

    idx0 = jnp.full((c,), -1, jnp.int32)
    md0 = jnp.zeros((c,), jnp.float32)
    _, idx, md = lax.fori_loop(0, n, body, (dist, idx0, md0))
    return idx, md


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=None):
    """Match columns (priors) to rows (ground truth) by greedy max-first
    bipartite matching; 'per_prediction' additionally matches any remaining
    column whose best row-distance exceeds dist_threshold.

    dist_matrix [R, C] or [B, R, C]. Returns (match_indices, match_dist)
    shaped like the column axis. Parity: detection/bipartite_match_op.cc.
    """
    dist = jnp.asarray(dist_matrix, jnp.float32)
    squeeze = dist.ndim == 2
    if squeeze:
        dist = dist[None]

    def one(d):
        idx, md = _bipartite_match_one(d)
        if match_type == "per_prediction":
            thr = 0.5 if dist_threshold is None else float(dist_threshold)
            best_row = jnp.argmax(d, axis=0).astype(jnp.int32)
            best_d = jnp.max(d, axis=0)
            extra = (idx < 0) & (best_d > thr)
            idx = jnp.where(extra, best_row, idx)
            md = jnp.where(extra, best_d, md)
        return idx, md

    idx, md = jax.vmap(one)(dist)
    if squeeze:
        return idx[0], md[0]
    return idx, md


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0):
    """Gather rows of `input` by match index; mismatch (-1) slots get
    `mismatch_value` and weight 0. input [B, R, K] (per-batch rows),
    matched_indices [B, C] → (out [B, C, K], weight [B, C, 1]).
    Parity: detection/target_assign_op.{cc,h}.
    """
    x = jnp.asarray(input)
    idx = jnp.asarray(matched_indices, jnp.int32)
    if x.ndim == 2:
        x = jnp.broadcast_to(x[None], (idx.shape[0],) + x.shape)
    safe = jnp.maximum(idx, 0)
    out = jnp.take_along_axis(x, safe[:, :, None], axis=1)
    matched = (idx >= 0)
    out = jnp.where(matched[:, :, None], out,
                    jnp.asarray(mismatch_value, x.dtype))
    w = matched.astype(jnp.float32)[:, :, None]
    if negative_indices is not None:
        # negative_indices: [B, C] 0/1 mask of sampled negatives (dense
        # stand-in for the reference's ragged NegIndices LoD input)
        neg = jnp.asarray(negative_indices).astype(jnp.float32)
        w = jnp.maximum(w, neg[:, :, None])
    return out, w


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------

def _greedy_nms_mask(boxes, scores, iou_threshold, normalized=True,
                     eta=1.0):
    """Greedy NMS over candidates sorted by score (desc). Returns a keep
    mask aligned to the sorted order plus the sort indices.

    TPU-native scheme: K-step `fori_loop`, each step commits the highest
    unsuppressed candidate and vector-suppresses the rest — the sequential
    dependency the reference resolves with a dynamic output
    (detection/multiclass_nms_op.cc NMSFast) becomes a fixed-shape loop.
    """
    k = scores.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    iou = _pairwise_iou(b, b, normalized)

    def body(i, carry):
        keep, sup, thr = carry
        valid = (~sup) & (s > -jnp.inf)
        # first unsuppressed candidate in sorted order
        nxt = jnp.argmax(valid)
        has = jnp.any(valid)
        keep = jnp.where(has, keep.at[nxt].set(True), keep)
        sup = jnp.where(has, sup | (iou[nxt] > thr), sup)
        sup = jnp.where(has, sup.at[nxt].set(True), sup)
        thr = jnp.where((eta < 1.0) & (thr > 0.5), thr * eta, thr)
        return keep, sup, thr

    keep0 = jnp.zeros((k,), bool)
    sup0 = s <= -jnp.inf
    keep, _, _ = lax.fori_loop(
        0, k, body, (keep0, sup0, jnp.float32(iou_threshold)))
    return keep, order


def multiclass_nms(bboxes, scores, background_label=0, score_threshold=0.05,
                   nms_top_k=400, nms_threshold=0.3, keep_top_k=100,
                   normalized=True, nms_eta=1.0):
    """Per-class NMS + cross-class top-k.

    bboxes [B, M, 4]; scores [B, C, M]. Returns [B, keep_top_k, 6]
    (label, score, x1, y1, x2, y2) padded with -1 rows — fixed shape
    instead of the reference's ragged LoD output
    (detection/multiclass_nms_op.cc:70-75).
    """
    bboxes = jnp.asarray(bboxes, jnp.float32)
    scores = jnp.asarray(scores, jnp.float32)
    bsz, ncls, m = scores.shape
    # drop the background class BEFORE the per-class vmap — its lane
    # would otherwise pay a full sort + KxK IoU + K-step NMS per image
    if 0 <= background_label < ncls:
        fg_cls = np.asarray([c for c in range(ncls)
                             if c != background_label])
        scores = scores[:, fg_cls, :]
    else:
        fg_cls = np.arange(ncls)
    cls_ids = jnp.asarray(fg_cls, jnp.int32)
    nfg = len(fg_cls)
    k = min(int(nms_top_k) if nms_top_k > 0 else m, m)
    keep_k = int(keep_top_k) if keep_top_k > 0 else nfg * k

    def per_class(cls_scores, boxes):
        s = jnp.where(cls_scores > score_threshold, cls_scores, -jnp.inf)
        topv, topi = lax.top_k(s, k)
        cand = boxes[topi]
        keep, order = _greedy_nms_mask(cand, topv, nms_threshold,
                                       normalized, nms_eta)
        kept_scores = jnp.where(keep, topv[order], -jnp.inf)
        return kept_scores, cand[order]

    def per_image(boxes, img_scores):
        ks, kb = jax.vmap(lambda cs: per_class(cs, boxes))(img_scores)
        labels = jnp.broadcast_to(cls_ids[:, None], (nfg, k))
        flat_s = ks.reshape(-1)
        flat_b = kb.reshape(-1, 4)
        flat_l = labels.reshape(-1)
        kk = min(keep_k, flat_s.shape[0])
        topv, topi = lax.top_k(flat_s, kk)
        valid = topv > -jnp.inf
        out = jnp.concatenate([
            jnp.where(valid, flat_l[topi], -1).astype(jnp.float32)[:, None],
            jnp.where(valid, topv, -1.0)[:, None],
            jnp.where(valid[:, None], flat_b[topi], -1.0)], axis=-1)
        if kk < keep_k:
            out = jnp.concatenate(
                [out, jnp.full((keep_k - kk, 6), -1.0)], axis=0)
        return out

    return jax.vmap(per_image)(bboxes, scores)


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """SSD head post-processing: decode loc against priors, then
    multiclass_nms. loc [B, M, 4], scores [B, M, C] (softmax-ed),
    priors [M, 4]. Parity: fluid.layers.detection_output
    (python/paddle/fluid/layers/detection.py)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")  # [B, M, 4]
    scores_t = jnp.transpose(jnp.asarray(scores, jnp.float32), (0, 2, 1))
    return multiclass_nms(decoded, scores_t,
                          background_label=background_label,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, nms_threshold=nms_threshold,
                          keep_top_k=keep_top_k, nms_eta=nms_eta)


def _mine_negatives(loss, matched, dist, neg_pos_ratio, neg_dist_threshold,
                    sample_size, mining_type):
    """Shared negative-mining core (mine_hard_examples_op.cc): rank
    unmatched low-overlap priors by loss. max_negative keeps
    neg_pos_ratio * num_pos per image; hard_example keeps
    min(sample_size, candidates) regardless of the positive count.
    loss/matched/dist: [N, P]. Returns bool neg_sel [N, P]."""
    neg_cand = (~matched) & (dist < neg_dist_threshold)
    score = jnp.where(neg_cand, loss, -jnp.inf)
    order = jnp.argsort(-score, axis=1)
    rank = jnp.argsort(order, axis=1)
    avail = jnp.sum(neg_cand, axis=1)
    if mining_type == "hard_example":
        num_neg = avail if sample_size is None else \
            jnp.minimum(avail, int(sample_size))
    else:
        num_pos = jnp.sum(matched, axis=1)
        num_neg = jnp.minimum((neg_pos_ratio * num_pos).astype(jnp.int32),
                              avail)
        if sample_size is not None:
            num_neg = jnp.minimum(num_neg, int(sample_size))
    return neg_cand & (rank < num_neg[:, None])


# ---------------------------------------------------------------------------
# SSD loss (match + hard negative mining)
# ---------------------------------------------------------------------------

def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             normalize=True, sample_size=None):
    """SSD multibox loss with per-prediction matching and max-negative
    hard mining.

    Dense-padded ground truth replaces the reference's LoD ragged input:
    gt_box [B, G, 4], gt_label [B, G] with label < 0 marking padding.
    location [B, M, 4], confidence [B, M, C], prior_box [M, 4].
    Parity: fluid.layers.ssd_loss (layers/detection.py) =
    iou_similarity → bipartite_match → target_assign → smooth_l1 +
    softmax cross-entropy → mine_hard_examples
    (detection/mine_hard_examples_op.cc, max_negative mining).
    """
    loc = jnp.asarray(location, jnp.float32)
    conf = jnp.asarray(confidence, jnp.float32)
    gtb = jnp.asarray(gt_box, jnp.float32)
    gtl = jnp.asarray(gt_label, jnp.int32)
    if gtl.ndim == 3:
        gtl = gtl[..., 0]
    prior = jnp.asarray(prior_box, jnp.float32)
    bsz, m, ncls = conf.shape

    gt_valid = gtl >= 0
    # IoU gt-rows × prior-cols, padded gt rows forced to 0 similarity
    sim = iou_similarity(gtb, prior)                       # [B, G, M]
    sim = jnp.where(gt_valid[:, :, None], sim, 0.0)
    match_idx, match_dist = bipartite_match(
        sim, match_type, overlap_threshold)                # [B, M]

    matched = match_idx >= 0
    safe = jnp.maximum(match_idx, 0)
    tgt_box = jnp.take_along_axis(gtb, safe[:, :, None], axis=1)
    tgt_label = jnp.take_along_axis(gtl, safe, axis=1)
    tgt_label = jnp.where(matched, tgt_label, background_label)

    # localization targets: encode matched gt elementwise against its own
    # prior (the reference materializes the full [N, M] encode then
    # gathers; elementwise avoids the O(M^2) intermediate)
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    var = (jnp.asarray(prior_box_var, jnp.float32)
           if prior_box_var is not None else jnp.ones((m, 4)))
    tw = tgt_box[..., 2] - tgt_box[..., 0]
    th = tgt_box[..., 3] - tgt_box[..., 1]
    tcx = tgt_box[..., 0] + 0.5 * tw
    tcy = tgt_box[..., 1] + 0.5 * th
    loc_tgt = jnp.stack([
        (tcx - pcx) / jnp.maximum(pw, 1e-9),
        (tcy - pcy) / jnp.maximum(ph, 1e-9),
        jnp.log(jnp.maximum(jnp.abs(tw / jnp.maximum(pw, 1e-9)), 1e-9)),
        jnp.log(jnp.maximum(jnp.abs(th / jnp.maximum(ph, 1e-9)), 1e-9))],
        axis=-1) / var[None]                               # [B, M, 4]
    diff = loc - loc_tgt
    adiff = jnp.abs(diff)
    smooth_l1 = jnp.where(adiff < 1.0, 0.5 * diff * diff, adiff - 0.5)
    loc_loss = jnp.sum(smooth_l1, -1) * matched.astype(jnp.float32)

    logp = jax.nn.log_softmax(conf, axis=-1)
    conf_all = -jnp.take_along_axis(logp, tgt_label[:, :, None],
                                    axis=-1)[..., 0]       # [B, M]

    # max_negative mining: rank negatives by conf loss, keep
    # neg_pos_ratio * num_pos per image
    num_pos = jnp.sum(matched, axis=1)                     # [B]
    neg_sel = _mine_negatives(conf_all, matched, match_dist,
                              neg_pos_ratio, neg_overlap, sample_size,
                              "max_negative")

    conf_loss = conf_all * (matched | neg_sel).astype(jnp.float32)
    total = conf_loss_weight * jnp.sum(conf_loss, 1) + \
        loc_loss_weight * jnp.sum(loc_loss, 1)
    if normalize:
        total = total / jnp.maximum(num_pos.astype(jnp.float32), 1.0)
    return total  # [B]


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio):
    """Decode YOLOv3 head output into boxes + per-class scores.

    x [B, A*(5+C), H, W]; img_size [B, 2] (h, w). Returns
    (boxes [B, A*H*W, 4] absolute corner form, scores [B, A*H*W, C]).
    Parity: detection/yolo_box_op.{cc,h} (incl. zeroing boxes whose
    objectness < conf_thresh).
    """
    x = jnp.asarray(x, jnp.float32)
    b, c, h, w = x.shape
    na = len(anchors) // 2
    anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(b, na, 5 + class_num, h, w)
    tx, ty, tw, th = x[:, :, 0], x[:, :, 1], x[:, :, 2], x[:, :, 3]
    obj = jax.nn.sigmoid(x[:, :, 4])
    cls = jax.nn.sigmoid(x[:, :, 5:])

    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    cx = (jax.nn.sigmoid(tx) + gx) / w
    cy = (jax.nn.sigmoid(ty) + gy) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(tw) * anc[None, :, 0, None, None] / input_w
    bh = jnp.exp(th) * anc[None, :, 1, None, None] / input_h

    imgh = jnp.asarray(img_size, jnp.float32)[:, 0]
    imgw = jnp.asarray(img_size, jnp.float32)[:, 1]
    sh = imgh[:, None, None, None]
    sw = imgw[:, None, None, None]
    x1 = (cx - bw / 2) * sw
    y1 = (cy - bh / 2) * sh
    x2 = (cx + bw / 2) * sw
    y2 = (cy + bh / 2) * sh
    # clip to image, zero out low-objectness boxes
    x1 = jnp.clip(x1, 0, sw - 1)
    y1 = jnp.clip(y1, 0, sh - 1)
    x2 = jnp.clip(x2, 0, sw - 1)
    y2 = jnp.clip(y2, 0, sh - 1)
    keep = obj > conf_thresh
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    scores = obj[..., None] * jnp.moveaxis(cls, 2, -1)
    scores = jnp.where(keep[..., None], scores, 0.0)
    return (boxes.reshape(b, -1, 4), scores.reshape(b, -1, class_num))


def _bce(logit, label):
    # sigmoid cross-entropy matching yolov3_loss_op.h:35 SigmoidCrossEntropy
    return jnp.maximum(logit, 0.0) - logit * label + \
        jnp.log1p(jnp.exp(-jnp.abs(logit)))


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True):
    """YOLOv3 training loss (per image).

    x [B, A*(5+C), H, W]; gt_box [B, G, 4] normalized (cx, cy, w, h) with
    all-zero rows as padding; gt_label [B, G]. Loss terms follow
    detection/yolov3_loss_op.h: sigmoid-CE for x, y; L1 for w, h (scaled by
    2 - w*h); sigmoid-CE objectness with >ignore_thresh IoU slots ignored;
    per-class sigmoid-CE with optional label smoothing.
    """
    x = jnp.asarray(x, jnp.float32)
    gtb = jnp.asarray(gt_box, jnp.float32)
    gtl = jnp.asarray(gt_label, jnp.int32)
    if gtl.ndim == 3:
        gtl = gtl[..., 0]
    b, c, h, w = x.shape
    mask = np.asarray(anchor_mask, np.int32)
    na = len(mask)
    n_total = len(anchors) // 2
    anc = jnp.asarray(anchors, jnp.float32).reshape(n_total, 2)
    anc_m = anc[mask]                                    # [A, 2]
    x = x.reshape(b, na, 5 + class_num, h, w)
    input_h = float(downsample_ratio * h)
    input_w = float(downsample_ratio * w)
    gt_valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)     # [B, G]
    if gt_score is None:
        gscore = gt_valid.astype(jnp.float32)
    else:
        gscore = jnp.asarray(gt_score, jnp.float32) * gt_valid

    pos, neg = 1.0, 0.0
    if use_label_smooth:
        delta = jnp.minimum(1.0 / class_num, 1.0 / 40)
        pos, neg = 1.0 - delta, delta

    # --- anchor responsibility: best shape-IoU over ALL anchors ---
    gw = gtb[..., 2] * input_w                           # [B, G]
    gh = gtb[..., 3] * input_h
    aw = anc[None, None, :, 0]
    ah = anc[None, None, :, 1]
    inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah)
    union = gw[..., None] * gh[..., None] + aw * ah - inter
    shape_iou = inter / jnp.maximum(union, 1e-10)        # [B, G, Atot]
    best_anchor = jnp.argmax(shape_iou, axis=-1)         # [B, G]

    gi = jnp.clip((gtb[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gtb[..., 1] * h).astype(jnp.int32), 0, h - 1)

    # scatter positive targets into [B, A, H, W] maps
    def scatter_img(best_a, gi_, gj_, gtb_, gtl_, gsc_, valid):
        tgt_obj = jnp.zeros((na, h, w))
        tgt_box_m = jnp.zeros((na, h, w, 4))
        tgt_cls = jnp.zeros((na, h, w), jnp.int32)
        tgt_w = jnp.zeros((na, h, w))
        for k, a_full in enumerate(mask):
            sel = valid & (best_a == a_full)
            weight = jnp.where(sel, gsc_, 0.0)
            tgt_obj = tgt_obj.at[k, gj_, gi_].max(
                jnp.where(sel, weight, 0.0), mode="drop")
            # last-writer-wins for box/class targets at a cell
            tgt_box_m = tgt_box_m.at[k, gj_, gi_].set(
                jnp.where(sel[:, None], gtb_, tgt_box_m[k, gj_, gi_]),
                mode="drop")
            tgt_cls = tgt_cls.at[k, gj_, gi_].set(
                jnp.where(sel, gtl_, tgt_cls[k, gj_, gi_]), mode="drop")
            tgt_w = tgt_w.at[k, gj_, gi_].max(weight, mode="drop")
        return tgt_obj, tgt_box_m, tgt_cls, tgt_w

    tgt_obj, tgt_box, tgt_cls, tgt_wt = jax.vmap(scatter_img)(
        best_anchor, gi, gj, gtb, gtl, gscore, gt_valid)

    # --- location loss at positive cells ---
    gxs = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gys = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    tx_tgt = tgt_box[..., 0] * w - jnp.floor(tgt_box[..., 0] * w)
    ty_tgt = tgt_box[..., 1] * h - jnp.floor(tgt_box[..., 1] * h)
    tw_tgt = jnp.log(jnp.maximum(
        tgt_box[..., 2] * input_w / anc_m[None, :, 0, None, None], 1e-9))
    th_tgt = jnp.log(jnp.maximum(
        tgt_box[..., 3] * input_h / anc_m[None, :, 1, None, None], 1e-9))
    scale = tgt_wt * (2.0 - tgt_box[..., 2] * tgt_box[..., 3])
    loc = (_bce(x[:, :, 0], tx_tgt) + _bce(x[:, :, 1], ty_tgt) +
           jnp.abs(x[:, :, 2] - tw_tgt) + jnp.abs(x[:, :, 3] - th_tgt))
    pos_mask = tgt_wt > 0
    loc_loss = jnp.sum(jnp.where(pos_mask, loc * scale, 0.0), (1, 2, 3))

    # --- objectness: ignore predictions with IoU > ignore_thresh ---
    cxp = (jax.nn.sigmoid(x[:, :, 0]) + gxs) / w
    cyp = (jax.nn.sigmoid(x[:, :, 1]) + gys) / h
    bwp = jnp.exp(x[:, :, 2]) * anc_m[None, :, 0, None, None] / input_w
    bhp = jnp.exp(x[:, :, 3]) * anc_m[None, :, 1, None, None] / input_h
    pred = jnp.stack([cxp - bwp / 2, cyp - bhp / 2,
                      cxp + bwp / 2, cyp + bhp / 2], -1)  # [B,A,H,W,4]
    gcorner = jnp.stack([
        gtb[..., 0] - gtb[..., 2] / 2, gtb[..., 1] - gtb[..., 3] / 2,
        gtb[..., 0] + gtb[..., 2] / 2, gtb[..., 1] + gtb[..., 3] / 2], -1)

    def img_iou(p, g, valid):
        iou = _pairwise_iou(p.reshape(-1, 4), g)          # [AHW, G]
        iou = jnp.where(valid[None, :], iou, 0.0)
        return jnp.max(iou, -1).reshape(na, h, w)

    best_iou = jax.vmap(img_iou)(pred, gcorner, gt_valid)
    objness = jnp.where(pos_mask, tgt_wt,
                        jnp.where(best_iou > ignore_thresh, -1.0, 0.0))
    obj_logit = x[:, :, 4]
    obj_loss = jnp.where(
        objness > 0, _bce(obj_logit, 1.0) * objness,
        jnp.where(objness == 0, _bce(obj_logit, 0.0), 0.0))
    obj_loss = jnp.sum(obj_loss, (1, 2, 3))

    # --- classification at positive cells ---
    cls_logit = jnp.moveaxis(x[:, :, 5:], 2, -1)          # [B,A,H,W,C]
    onehot = jax.nn.one_hot(tgt_cls, class_num)
    cls_tgt = onehot * pos + (1 - onehot) * neg
    cls_loss = jnp.sum(_bce(cls_logit, cls_tgt), -1) * tgt_wt
    cls_loss = jnp.sum(jnp.where(pos_mask, cls_loss, 0.0), (1, 2, 3))

    return loc_loss + obj_loss + cls_loss  # [B]


# ---------------------------------------------------------------------------
# focal loss
# ---------------------------------------------------------------------------

def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """RetinaNet focal loss. x [N, C] logits; label [N] int (0 =
    background, 1..C = class id); fg_num scalar normalizer.
    Parity: detection/sigmoid_focal_loss_op.{cc,h,cu}.
    """
    x = jnp.asarray(x, jnp.float32)
    label = jnp.asarray(label, jnp.int32).reshape(-1)
    n, c = x.shape
    fg = jnp.maximum(jnp.asarray(fg_num, jnp.float32).reshape(()), 1.0)
    cls_ids = jnp.arange(1, c + 1)[None, :]
    tgt = (label[:, None] == cls_ids).astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = _bce(x, tgt)
    p_t = p * tgt + (1 - p) * (1 - tgt)
    alpha_t = alpha * tgt + (1 - alpha) * (1 - tgt)
    return alpha_t * jnp.power(1 - p_t, gamma) * ce / fg


# ---------------------------------------------------------------------------
# RoI ops
# ---------------------------------------------------------------------------

def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, roi_batch_indices=None):
    """RoIAlign with bilinear sampling.

    input [N, C, H, W]; rois [R, 4] (x1, y1, x2, y2) in input-image
    coords; roi_batch_indices [R] maps each roi to its batch image (dense
    replacement for the reference's LoD roi batching,
    roi_align_op.cc). Parity: roi_align_op.{cc,h,cu}.
    """
    x = jnp.asarray(input, jnp.float32)
    rois = jnp.asarray(rois, jnp.float32)
    n, c, h, w = x.shape
    r = rois.shape[0]
    bidx = (jnp.zeros((r,), jnp.int32) if roi_batch_indices is None
            else jnp.asarray(roi_batch_indices, jnp.int32))
    ph, pw = int(pooled_height), int(pooled_width)
    sr = int(sampling_ratio)

    def one_roi(roi, bi):
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sampling grid per bin (static count; reference's adaptive
        # ceil(roi/pooled) needs dynamic shapes — fixed 2x2 when sr<0,
        # the common detectron configuration)
        s = sr if sr > 0 else 2
        iy = (jnp.arange(s) + 0.5) / s
        ix = (jnp.arange(s) + 0.5) / s
        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        yy = y1 + (py[:, None] + iy[None, :]) * bin_h     # [ph, s]
        xx = x1 + (px[:, None] + ix[None, :]) * bin_w     # [pw, s]
        yf = yy.reshape(-1)                               # [ph*s]
        xf = xx.reshape(-1)                               # [pw*s]
        y0 = jnp.clip(jnp.floor(yf), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xf), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        ly = jnp.clip(yf - y0, 0.0, 1.0)
        lx = jnp.clip(xf - x0, 0.0, 1.0)
        feat = x[bi]                                      # [C, H, W]
        # gather 4 corners: [C, ph*s, pw*s]
        v00 = feat[:, y0i[:, None], x0i[None, :]]
        v01 = feat[:, y0i[:, None], x1i[None, :]]
        v10 = feat[:, y1i[:, None], x0i[None, :]]
        v11 = feat[:, y1i[:, None], x1i[None, :]]
        wy = ly[:, None]
        wx = lx[None, :]
        val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
               v10 * wy * (1 - wx) + v11 * wy * wx)
        val = val.reshape(c, ph, s, pw, s)
        return jnp.mean(val, axis=(2, 4))                 # [C, ph, pw]

    return jax.vmap(one_roi)(rois, bidx)                  # [R, C, ph, pw]


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, roi_batch_indices=None):
    """RoI max pooling (Fast R-CNN). Same I/O convention as roi_align.
    Parity: roi_pool_op.{cc,h,cu}."""
    x = jnp.asarray(input, jnp.float32)
    rois = jnp.asarray(rois, jnp.float32)
    n, c, h, w = x.shape
    r = rois.shape[0]
    bidx = (jnp.zeros((r,), jnp.int32) if roi_batch_indices is None
            else jnp.asarray(roi_batch_indices, jnp.int32))
    ph, pw = int(pooled_height), int(pooled_width)

    ygrid = jnp.arange(h, dtype=jnp.float32)
    xgrid = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi, bi):
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bh = rh / ph
        bw = rw / pw
        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        ys = jnp.clip(jnp.floor(y1 + py * bh), 0, h)       # [ph]
        ye = jnp.clip(jnp.ceil(y1 + (py + 1) * bh), 0, h)
        xs = jnp.clip(jnp.floor(x1 + px * bw), 0, w)
        xe = jnp.clip(jnp.ceil(x1 + (px + 1) * bw), 0, w)
        # membership masks avoid dynamic slicing: [ph, H], [pw, W]
        my = (ygrid[None, :] >= ys[:, None]) & (ygrid[None, :] < ye[:, None])
        mx = (xgrid[None, :] >= xs[:, None]) & (xgrid[None, :] < xe[:, None])
        feat = x[bi]                                       # [C, H, W]
        m = my[:, None, :, None] & mx[None, :, None, :]    # [ph, pw, H, W]
        masked = jnp.where(m[None], feat[:, None, None, :, :], -jnp.inf)
        out = jnp.max(masked, axis=(3, 4))                 # [C, ph, pw]
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois, bidx)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, roi_batch_indices=None):
    """Position-sensitive RoI pooling (R-FCN): input channels laid out as
    [output_channels * ph * pw]; bin (i, j) averages its own channel group.
    Parity: psroi_pool_op.{cc,h,cu}."""
    x = jnp.asarray(input, jnp.float32)
    rois = jnp.asarray(rois, jnp.float32)
    n, c, h, w = x.shape
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)
    r = rois.shape[0]
    bidx = (jnp.zeros((r,), jnp.int32) if roi_batch_indices is None
            else jnp.asarray(roi_batch_indices, jnp.int32))
    ygrid = jnp.arange(h, dtype=jnp.float32)
    xgrid = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi, bi):
        x1 = jnp.round(roi[0]) * spatial_scale
        y1 = jnp.round(roi[1]) * spatial_scale
        x2 = jnp.round(roi[2] + 1.0) * spatial_scale
        y2 = jnp.round(roi[3] + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh = rh / ph
        bw = rw / pw
        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        ys = jnp.clip(jnp.floor(y1 + py * bh), 0, h)
        ye = jnp.clip(jnp.ceil(y1 + (py + 1) * bh), 0, h)
        xs = jnp.clip(jnp.floor(x1 + px * bw), 0, w)
        xe = jnp.clip(jnp.ceil(x1 + (px + 1) * bw), 0, w)
        my = (ygrid[None, :] >= ys[:, None]) & (ygrid[None, :] < ye[:, None])
        mx = (xgrid[None, :] >= xs[:, None]) & (xgrid[None, :] < xe[:, None])
        feat = x[bi].reshape(oc, ph, pw, h, w)
        m = (my[:, None, :, None] & mx[None, :, None, :]).astype(jnp.float32)
        # bin (i,j) uses channel group [:, i, j]
        num = jnp.einsum("cijhw,ijhw->cij", feat[:, :, :, :, :],
                         m)
        cnt = jnp.maximum(jnp.sum(m, axis=(2, 3)), 1.0)
        return num / cnt[None]                             # [oc, ph, pw]

    return jax.vmap(one_roi)(rois, bidx)


# ---------------------------------------------------------------------------
# RPN proposals / FPN routing
# ---------------------------------------------------------------------------

def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0):
    """RPN proposal generation.

    scores [B, A, H, W]; bbox_deltas [B, A*4, H, W]; anchors [H, W, A, 4];
    variances like anchors; im_info [B, 3]. Returns
    (rois [B, post_nms_top_n, 4], roi_probs [B, post_nms_top_n, 1],
    valid counts [B]) — fixed shapes; invalid rows are zero.
    Parity: detection/generate_proposals_op.cc (decode → clip → filter
    min_size → top-k → NMS → top-k).
    """
    scores = jnp.asarray(scores, jnp.float32)
    deltas = jnp.asarray(bbox_deltas, jnp.float32)
    info = jnp.asarray(im_info, jnp.float32)
    b, na, h, w = scores.shape
    anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 4)
    variances = jnp.asarray(variances, jnp.float32).reshape(-1, 4)
    total = na * h * w
    pre_k = min(int(pre_nms_top_n), total)
    post_k = min(int(post_nms_top_n), pre_k)

    def per_image(sc, dl, im):
        # layout: anchors generated [H, W, A, 4] → flatten hwA to match
        # score transpose [H, W, A]
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)            # [HWA]
        d = dl.reshape(na, 4, h, w)
        d = jnp.transpose(d, (2, 3, 0, 1)).reshape(-1, 4)       # [HWA, 4]
        topv, topi = lax.top_k(s, pre_k)
        anc = anchors[topi]
        var = variances[topi]
        # decode (variance-scaled center-size, like box_coder decode)
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        t = jnp.take(d, topi, axis=0) * var
        cx = t[:, 0] * aw + acx
        cy = t[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(t[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(t[:, 3], 10.0)) * ah
        props = jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                           cx + bw * 0.5 - 1.0, cy + bh * 0.5 - 1.0], -1)
        # clip to the RESIZED image bounds (im_info h, w directly —
        # the reference's ClipTiledBoxes with is_scale=false; box_clip
        # would divide by scale and truncate half the image for scale>1)
        props = jnp.stack([
            jnp.clip(props[:, 0], 0, im[1] - 1.0),
            jnp.clip(props[:, 1], 0, im[0] - 1.0),
            jnp.clip(props[:, 2], 0, im[1] - 1.0),
            jnp.clip(props[:, 3], 0, im[0] - 1.0)], axis=-1)
        # min_size filter in original-image scale
        ms = jnp.maximum(min_size, 1.0) * im[2]
        pw = props[:, 2] - props[:, 0] + 1.0
        phh = props[:, 3] - props[:, 1] + 1.0
        valid = (pw >= ms) & (phh >= ms)
        sc_f = jnp.where(valid, topv, -jnp.inf)
        keep, order = _greedy_nms_mask(props, sc_f, nms_thresh,
                                       normalized=False, eta=eta)
        kept_s = jnp.where(keep, sc_f[order], -jnp.inf)
        fv, fi = lax.top_k(kept_s, post_k)
        ok = fv > -jnp.inf
        rois = jnp.where(ok[:, None], props[order][fi], 0.0)
        probs = jnp.where(ok, fv, 0.0)[:, None]
        return rois, probs, jnp.sum(ok.astype(jnp.int32))

    return jax.vmap(per_image)(scores, deltas, info)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """Route RoIs to FPN levels by scale: level = floor(refer_level +
    log2(sqrt(area) / refer_scale)).

    fpn_rois [R, 4]. Returns (multi_rois: list of [R, 4] per level,
    level_masks: list of [R] bool, restore_index [R]) — each level keeps
    the full fixed R rows with a validity mask (TPU-static replacement for
    the reference's per-level ragged outputs,
    detection/distribute_fpn_proposals_op.h).
    """
    rois = jnp.asarray(fpn_rois, jnp.float32)
    r = rois.shape[0]
    area = jnp.maximum(rois[:, 2] - rois[:, 0] + 1.0, 0.0) * \
        jnp.maximum(rois[:, 3] - rois[:, 1] + 1.0, 0.0)
    scale = jnp.sqrt(area)
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    multi_rois, masks = [], []
    for l in range(int(min_level), int(max_level) + 1):
        m = lvl == l
        masks.append(m)
        multi_rois.append(jnp.where(m[:, None], rois, 0.0))
    # restore index: position of each original roi in the level-sorted
    # concatenation (stable by level then original order)
    key = lvl * r + jnp.arange(r)
    sorted_pos = jnp.argsort(key)
    restore = jnp.argsort(sorted_pos).astype(jnp.int32)
    return multi_rois, masks, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, valid_masks=None):
    """Concat per-level RoIs and keep global top-k by score.

    multi_rois: list of [Ri, 4]; multi_scores: list of [Ri]. Returns
    (rois [post_nms_top_n, 4], scores [post_nms_top_n]) zero-padded.
    Parity: detection/collect_fpn_proposals_op.{cc,h}.
    """
    rois = jnp.concatenate([jnp.asarray(x, jnp.float32)
                            for x in multi_rois], axis=0)
    scores = jnp.concatenate(
        [jnp.asarray(s, jnp.float32).reshape(-1) for s in multi_scores])
    if valid_masks is not None:
        vm = jnp.concatenate([jnp.asarray(m).reshape(-1)
                              for m in valid_masks])
        scores = jnp.where(vm, scores, -jnp.inf)
    k = min(int(post_nms_top_n), scores.shape[0])
    topv, topi = lax.top_k(scores, k)
    ok = topv > -jnp.inf
    out_r = jnp.where(ok[:, None], rois[topi], 0.0)
    out_s = jnp.where(ok, topv, 0.0)
    if k < post_nms_top_n:
        pad = post_nms_top_n - k
        out_r = jnp.concatenate([out_r, jnp.zeros((pad, 4))])
        out_s = jnp.concatenate([out_s, jnp.zeros((pad,))])
    return out_r, out_s


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip_value=4.135):
    """Decode per-class boxes then pick each roi's best-scoring class box.
    prior_box [R, 4]; target_box [R, C*4]; box_score [R, C].
    Parity: detection/box_decoder_and_assign_op.{cc,h}.
    """
    prior = jnp.asarray(prior_box, jnp.float32)
    var = jnp.asarray(prior_box_var, jnp.float32)
    tgt = jnp.asarray(target_box, jnp.float32)
    score = jnp.asarray(box_score, jnp.float32)
    r, c4 = tgt.shape
    c = c4 // 4
    t = tgt.reshape(r, c, 4) * var[:, None, :]
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    clip = float(box_clip_value)
    dcx = t[..., 0] * pw[:, None] + pcx[:, None]
    dcy = t[..., 1] * ph[:, None] + pcy[:, None]
    dw = jnp.exp(jnp.minimum(t[..., 2], clip)) * pw[:, None]
    dh = jnp.exp(jnp.minimum(t[..., 3], clip)) * ph[:, None]
    decoded = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                         dcx + dw * 0.5 - 1.0, dcy + dh * 0.5 - 1.0], -1)
    best = jnp.argmax(score[:, 1:], axis=-1) + 1   # skip background col 0
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
    return decoded.reshape(r, c4), assigned


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet decode-across-levels + class-wise NMS.

    bboxes/scores/anchors: lists per FPN level — bboxes[i] [B, Ai, 4]
    deltas, scores[i] [B, Ai, C] sigmoid scores, anchors[i] [Ai, 4].
    Parity: detection/retinanet_detection_output_op.cc.
    """
    infos = jnp.asarray(im_info, jnp.float32)
    decoded, all_scores = [], []
    for d, s, a in zip(bboxes, scores, anchors):
        dec = box_coder(a, None, jnp.asarray(d, jnp.float32),
                        code_type="decode_center_size", box_normalized=False,
                        axis=0, variance=[1.0, 1.0, 1.0, 1.0])
        decoded.append(dec)
        all_scores.append(jnp.asarray(s, jnp.float32))
    boxes = jnp.concatenate(decoded, axis=1)               # [B, A, 4]
    sc = jnp.concatenate(all_scores, axis=1)               # [B, A, C]
    boxes = box_clip(boxes, infos)
    sc_t = jnp.transpose(sc, (0, 2, 1))                    # [B, C, A]
    return multiclass_nms(boxes, sc_t, background_label=-1,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, nms_threshold=nms_threshold,
                          keep_top_k=keep_top_k, normalized=False,
                          nms_eta=nms_eta)


# ---------------------------------------------------------------------------
# host-side (numpy) label-assignment + metric ops — input-pipeline stage on
# TPU, matching the reference's CPU-only kernels
# ---------------------------------------------------------------------------

def _np_iou_matrix(a, b, normalized=False):
    """Vectorized numpy IoU matrix [N, M] (host-op helper)."""
    off = 0.0 if normalized else 1.0
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    aa = np.maximum(a[:, 2] - a[:, 0] + off, 0.0) * \
        np.maximum(a[:, 3] - a[:, 1] + off, 0.0)
    ab = np.maximum(b[:, 2] - b[:, 0] + off, 0.0) * \
        np.maximum(b[:, 3] - b[:, 1] + off, 0.0)
    union = aa[:, None] + ab[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-10), 0.0)


def _np_encode_boxes(priors, targets, normalized=False):
    """Elementwise center-size encode of targets[i] against priors[i]
    (numpy, host-op helper — avoids the O(N^2) pairwise encode)."""
    off = 0.0 if normalized else 1.0
    priors = np.asarray(priors, np.float32)
    targets = np.asarray(targets, np.float32)
    pw = priors[:, 2] - priors[:, 0] + off
    ph = priors[:, 3] - priors[:, 1] + off
    pcx = priors[:, 0] + 0.5 * pw
    pcy = priors[:, 1] + 0.5 * ph
    tw = targets[:, 2] - targets[:, 0] + off
    th = targets[:, 3] - targets[:, 1] + off
    tcx = targets[:, 0] + 0.5 * tw
    tcy = targets[:, 1] + 0.5 * th
    return np.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                     np.log(np.abs(tw / pw)), np.log(np.abs(th / ph))],
                    axis=-1)

def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=False,
                      seed=0):
    """Sample anchors for RPN training (host/numpy; CPU-only kernel in the
    reference too — detection/rpn_target_assign_op.cc).

    anchor_box [A, 4]; gt_boxes [G, 4]; im_info [3]. Returns
    (loc_index, score_index, tgt_label, tgt_bbox, bbox_inside_weight) as
    numpy arrays (ragged — meant for the input pipeline).
    """
    anchors = np.asarray(anchor_box, np.float32).reshape(-1, 4)
    gts = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
    if is_crowd is not None:
        crowd = np.asarray(is_crowd).reshape(-1).astype(bool)
        gts = gts[~crowd]  # crowd gt never produce positives (parity:
        # rpn_target_assign_op.cc FilterCrowdGtBoxes)
    info = np.asarray(im_info, np.float32).reshape(-1)[:3]
    a = anchors.shape[0]
    rng = np.random.RandomState(seed)

    if rpn_straddle_thresh >= 0:
        t = rpn_straddle_thresh
        inside = ((anchors[:, 0] >= -t) & (anchors[:, 1] >= -t) &
                  (anchors[:, 2] < info[1] + t) &
                  (anchors[:, 3] < info[0] + t))
    else:
        inside = np.ones((a,), bool)
    idx_inside = np.nonzero(inside)[0]
    if gts.shape[0] == 0 or idx_inside.size == 0:
        empty = np.zeros((0,), np.int64)
        return (empty, empty, np.zeros((0, 1), np.int32),
                np.zeros((0, 4), np.float32), np.zeros((0, 4), np.float32))
    iou = _np_iou_matrix(anchors[idx_inside], gts)
    best_gt = iou.argmax(1)
    best_iou = iou.max(1)
    labels = np.full((idx_inside.size,), -1, np.int32)
    labels[best_iou >= rpn_positive_overlap] = 1
    # anchors that are the best for some gt are positive too
    for g in range(gts.shape[0]):
        m = iou[:, g] == iou[:, g].max()
        labels[m & (iou[:, g] > 0)] = 1
    labels[(best_iou < rpn_negative_overlap) & (labels != 1)] = 0

    num_fg = int(rpn_fg_fraction * rpn_batch_size_per_im)
    fg = np.nonzero(labels == 1)[0]
    if fg.size > num_fg:
        drop = (rng.choice(fg, fg.size - num_fg, replace=False)
                if use_random else fg[num_fg:])
        labels[drop] = -1
        fg = np.nonzero(labels == 1)[0]
    num_bg = rpn_batch_size_per_im - fg.size
    bg = np.nonzero(labels == 0)[0]
    if bg.size > num_bg:
        drop = (rng.choice(bg, bg.size - num_bg, replace=False)
                if use_random else bg[num_bg:])
        labels[drop] = -1
        bg = np.nonzero(labels == 0)[0]

    loc_index = idx_inside[fg].astype(np.int64)
    score_index = idx_inside[np.concatenate([fg, bg])].astype(np.int64)
    tgt_label = np.concatenate([np.ones_like(fg), np.zeros_like(bg)]) \
        .astype(np.int32).reshape(-1, 1)
    tgt_bbox = _np_encode_boxes(anchors[loc_index], gts[best_gt[fg]])
    inw = np.ones_like(tgt_bbox, np.float32)
    return loc_index, score_index, tgt_label, tgt_bbox, inw


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=False, seed=0):
    """Sample RoIs + regression targets for Fast R-CNN head training
    (host/numpy, like the reference's CPU kernel —
    detection/generate_proposal_labels_op.cc). One image at a time.

    Returns (rois, labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights).
    """
    rois = np.asarray(rpn_rois, np.float32).reshape(-1, 4)
    gts = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
    gtc = np.asarray(gt_classes, np.int32).reshape(-1)
    if is_crowd is not None:
        crowd = np.asarray(is_crowd).reshape(-1).astype(bool)
        gts = gts[~crowd]
        gtc = gtc[~crowd]
    rng = np.random.RandomState(seed)
    # gt boxes participate as candidate rois
    cand = np.concatenate([rois, gts], 0) if gts.size else rois
    if gts.size:
        iou = _np_iou_matrix(cand, gts)
        best_gt = iou.argmax(1)
        best_iou = iou.max(1)
    else:
        best_gt = np.zeros((cand.shape[0],), np.int64)
        best_iou = np.zeros((cand.shape[0],), np.float32)
    fg = np.nonzero(best_iou >= fg_thresh)[0]
    bg = np.nonzero((best_iou < bg_thresh_hi) &
                    (best_iou >= bg_thresh_lo))[0]
    num_fg = min(int(fg_fraction * batch_size_per_im), fg.size)
    if fg.size > num_fg:
        fg = (rng.choice(fg, num_fg, replace=False)
              if use_random else fg[:num_fg])
    num_bg = min(batch_size_per_im - num_fg, bg.size)
    if bg.size > num_bg:
        bg = (rng.choice(bg, num_bg, replace=False)
              if use_random else bg[:num_bg])
    keep = np.concatenate([fg, bg])
    out_rois = cand[keep]
    labels = gtc[best_gt[keep]].copy() if gts.size else \
        np.zeros((keep.size,), np.int32)
    labels[num_fg:] = 0
    tgt = np.zeros((keep.size, 4 * class_nums), np.float32)
    inw = np.zeros_like(tgt)
    if num_fg and gts.size:
        matched = gts[best_gt[fg]]
        w = np.asarray(bbox_reg_weights, np.float32)
        enc = _np_encode_boxes(out_rois[:num_fg], matched) / w
        for i in range(num_fg):
            c = labels[i]
            tgt[i, 4 * c:4 * c + 4] = enc[i]
            inw[i, 4 * c:4 * c + 4] = 1.0
    outw = (inw > 0).astype(np.float32)
    return out_rois, labels.reshape(-1, 1), tgt, inw, outw


def detection_map(detect_res, gt_label, gt_box, class_num,
                  background_label=0, overlap_threshold=0.5,
                  evaluate_difficult=True, ap_type="integral"):
    """Mean average precision over one batch (host/numpy metric, parity:
    operators/detection_map_op.cc).

    detect_res: [D, 6] rows (label, score, x1, y1, x2, y2) — the padded
    multiclass_nms output is accepted (label -1 rows skipped); a leading
    batch axis is allowed and flattened with per-image gt lists.
    gt_label: [G] labels, gt_box [G, 4]; lists per image allowed.
    """
    def listify(x):
        if isinstance(x, (list, tuple)):
            return [np.asarray(v) for v in x]
        x = np.asarray(x)
        return [x] if x.ndim == 2 or (x.ndim == 1) else list(x)

    dets = listify(detect_res)
    gls = listify(gt_label)
    gbs = listify(gt_box)
    scores = {c: [] for c in range(class_num)}
    tps = {c: [] for c in range(class_num)}
    npos = {c: 0 for c in range(class_num)}
    for det, gl, gb in zip(dets, gls, gbs):
        det = det[det[:, 0] >= 0]
        gl = gl.reshape(-1).astype(int)
        gb = gb.reshape(-1, 4)
        for c in set(gl.tolist()):
            npos[c] += int((gl == c).sum())
        taken = np.zeros(len(gl), bool)
        det_sorted = det[np.argsort(-det[:, 1])]
        iou_all = (_np_iou_matrix(det_sorted[:, 2:6], gb, normalized=True)
                   if len(gb) and len(det_sorted) else
                   np.zeros((len(det_sorted), len(gb)), np.float32))
        for k, row in enumerate(det_sorted):
            c = int(row[0])
            if c == background_label or c >= class_num:
                continue
            ious = iou_all[k]
            cmask = (gl == c) & ~taken
            ious = np.where(cmask, ious, 0.0)
            j = ious.argmax() if ious.size else -1
            tp = bool(ious.size and ious[j] >= overlap_threshold)
            if tp:
                taken[j] = True
            scores[c].append(row[1])
            tps[c].append(1.0 if tp else 0.0)
    aps = []
    for c in range(class_num):
        if c == background_label or npos[c] == 0:
            continue
        s = np.asarray(scores[c])
        t = np.asarray(tps[c])
        order = np.argsort(-s)
        t = t[order]
        tp_cum = np.cumsum(t)
        fp_cum = np.cumsum(1.0 - t)
        rec = tp_cum / npos[c]
        prec = tp_cum / np.maximum(tp_cum + fp_cum, 1e-9)
        if ap_type == "11point":
            ap = np.mean([prec[rec >= r].max() if (rec >= r).any() else 0.0
                          for r in np.linspace(0, 1, 11)])
        else:
            ap = 0.0
            prev_r = 0.0
            for p_, r_ in zip(prec, rec):
                ap += p_ * (r_ - prev_r)
                prev_r = r_
        aps.append(ap)
    return float(np.mean(aps)) if aps else 0.0


# ---------------------------------------------------------------------------
# r3 tail ops (VERDICT-r2 Missing #3)
# ---------------------------------------------------------------------------
def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    """RetinaNet target assignment (host/numpy, CPU-only kernel in the
    reference too — detection/retinanet_target_assign_op.cc; python
    surface layers/detection.py:63).

    Unlike RPN there is NO fg/bg sampling: every anchor with IoU >=
    positive_overlap (or that is some gt's argmax) is foreground with
    its gt's class label, every anchor with max-IoU < negative_overlap
    is background (label 0), the rest are ignored. When no anchor is
    foreground, one fake foreground (anchor 0) with zero
    bbox_inside_weight keeps the focal-loss normalizer valid.

    bbox_pred [N=1, A, 4]; cls_logits [N=1, A, C]; anchor_box [A, 4];
    gt_boxes [G, 4]; gt_labels [G] (1..num_classes). Returns
    (predicted_scores [F+B, C], predicted_location [F, 4],
    target_label [F+B, 1], target_bbox [F, 4],
    bbox_inside_weight [F, 4], fg_num [1]) — numpy, ragged (input
    pipeline use, like rpn_target_assign).
    """
    anchors = np.asarray(anchor_box, np.float32).reshape(-1, 4)
    gts = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
    glab = np.asarray(gt_labels, np.int32).reshape(-1)
    if is_crowd is not None:
        crowd = np.asarray(is_crowd).reshape(-1).astype(bool)
        gts, glab = gts[~crowd], glab[~crowd]
    a = anchors.shape[0]
    loc = np.asarray(bbox_pred, np.float32).reshape(-1, 4)
    scores = np.asarray(cls_logits, np.float32)
    scores = scores.reshape(-1, scores.shape[-1])

    labels = np.full((a,), -1, np.int32)
    best_gt = np.zeros((a,), np.int64)
    if gts.shape[0]:
        iou = _np_iou_matrix(anchors, gts)
        best_gt = iou.argmax(1)
        best_iou = iou.max(1)
        labels[best_iou >= positive_overlap] = 1
        for g in range(gts.shape[0]):      # gt argmax anchors -> fg
            m = iou[:, g] == iou[:, g].max()
            labels[m & (iou[:, g] > 0)] = 1
        labels[(best_iou < negative_overlap) & (labels != 1)] = 0
    else:
        labels[:] = 0

    fg = np.nonzero(labels == 1)[0]
    bg = np.nonzero(labels == 0)[0]
    fake = fg.size == 0
    if fake:                                # keep focal-loss denominator
        fg = np.array([0], np.int64)
    loc_index = fg.astype(np.int64)
    # the fake fg pads ONLY the location rows (zero inside weight); the
    # score rows use real fg + bg, else anchor 0 would be double-counted
    # in the cls loss when no real foreground exists
    score_fg = fg if not fake else np.zeros((0,), np.int64)
    score_index = np.concatenate([score_fg, bg]).astype(np.int64)
    tgt_label = np.concatenate([
        glab[best_gt[score_fg]] if gts.shape[0]
        else np.zeros((score_fg.size,), np.int32),
        np.zeros((bg.size,), np.int32)]).astype(np.int32).reshape(-1, 1)
    if gts.shape[0]:
        tgt_bbox = _np_encode_boxes(anchors[fg], gts[best_gt[fg]])
    else:
        tgt_bbox = np.zeros((fg.size, 4), np.float32)
    inw = np.zeros_like(tgt_bbox) if fake else np.ones_like(tgt_bbox)
    fg_num = np.array([fg.size], np.int32)
    return (scores[score_index], loc[loc_index], tgt_label, tgt_bbox,
            inw, fg_num)


def _perspective_matrix(xs, ys, th, tw):
    """Exact port of get_transform_matrix
    (detection/roi_perspective_transform_op.cc:110-161): maps output
    pixel (ow, oh) to source coords via a 3x3 homography."""
    x0, x1, x2, x3 = xs[0], xs[1], xs[2], xs[3]
    y0, y1, y2, y3 = ys[0], ys[1], ys[2], ys[3]
    len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
    len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
    len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
    len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
    est_h = (len2 + len4) / 2.0
    est_w = (len1 + len3) / 2.0
    nh = jnp.asarray(th, jnp.float32)
    nw = jnp.minimum(
        jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-6)) + 1.0,
        float(tw))
    dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
    dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
    den = dx1 * dy2 - dx2 * dy1
    den = jnp.where(jnp.abs(den) < 1e-12, 1e-12, den)
    m6 = (dx3 * dy2 - dx2 * dy3) / den / jnp.maximum(nw - 1, 1e-6)
    m7 = (dx1 * dy3 - dx3 * dy1) / den / jnp.maximum(nh - 1, 1e-6)
    m8 = jnp.asarray(1.0, jnp.float32)
    m3 = (y1 - y0 + m6 * (nw - 1) * y1) / jnp.maximum(nw - 1, 1e-6)
    m4 = (y3 - y0 + m7 * (nh - 1) * y3) / jnp.maximum(nh - 1, 1e-6)
    m5 = y0
    m0 = (x1 - x0 + m6 * (nw - 1) * x1) / jnp.maximum(nw - 1, 1e-6)
    m1 = (x3 - x0 + m7 * (nh - 1) * x3) / jnp.maximum(nh - 1, 1e-6)
    m2 = x0
    return jnp.stack([m0, m1, m2, m3, m4, m5, m6, m7, m8])


def _in_quad(px, py, xs, ys):
    """Even-odd point-in-quadrilateral test, vectorized over a grid.
    px/py [...]; xs/ys [4]. Mirrors in_quad
    (roi_perspective_transform_op.cc)."""
    x1, y1 = xs, ys
    x2, y2 = jnp.roll(xs, -1), jnp.roll(ys, -1)
    px = px[..., None]
    py = py[..., None]
    dy = y2 - y1
    t = (py - y1) / jnp.where(jnp.abs(dy) < 1e-12, 1e-12, dy)
    crosses = ((y1 > py) != (y2 > py)) & (px < x1 + t * (x2 - x1))
    return jnp.sum(crosses.astype(jnp.int32), axis=-1) % 2 == 1


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              roi_batch_indices=None):
    """ROI perspective transform (parity:
    detection/roi_perspective_transform_op.cc; python surface
    layers/detection.py:2078). TPU-first: the per-pixel C++ loops become
    one vmapped dense gather — homography per quad ROI, bilinear
    sampling, zero outside the quad or feature bounds.

    input [N, C, H, W]; rois [R, 8] quads (x1..y4, clockwise from top
    left) in input-image coords; roi_batch_indices [R] (dense
    replacement for LoD batching, as in roi_align). Returns
    (out [R, C, th, tw], mask [R, 1, th, tw] int32,
    transform_matrix [R, 9]).
    """
    x = jnp.asarray(input, jnp.float32)
    rois = jnp.asarray(rois, jnp.float32).reshape(-1, 8)
    n, c, h, w = x.shape
    r = rois.shape[0]
    th, tw = int(transformed_height), int(transformed_width)
    bidx = (jnp.zeros((r,), jnp.int32) if roi_batch_indices is None
            else jnp.asarray(roi_batch_indices, jnp.int32))

    def one_roi(quad, bi):
        xs = quad[0::2] * spatial_scale
        ys = quad[1::2] * spatial_scale
        m = _perspective_matrix(xs, ys, th, tw)
        ow = jnp.arange(tw, dtype=jnp.float32)[None, :]    # [1, tw]
        oh = jnp.arange(th, dtype=jnp.float32)[:, None]    # [th, 1]
        u = m[0] * ow + m[1] * oh + m[2]
        v = m[3] * ow + m[4] * oh + m[5]
        ww = m[6] * ow + m[7] * oh + m[8]
        ww = jnp.where(jnp.abs(ww) < 1e-12, 1e-12, ww)
        in_w = u / ww                                      # [th, tw]
        in_h = v / ww
        valid = (_in_quad(in_w, in_h, xs, ys)
                 & (in_w >= -0.5) & (in_w <= w - 0.5)
                 & (in_h >= -0.5) & (in_h <= h - 0.5))
        y0 = jnp.clip(jnp.floor(in_h), 0, h - 1)
        x0 = jnp.clip(jnp.floor(in_w), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        ly = jnp.clip(in_h - y0, 0.0, 1.0)
        lx = jnp.clip(in_w - x0, 0.0, 1.0)
        feat = x[bi]                                       # [C, H, W]
        v00 = feat[:, y0i, x0i]
        v01 = feat[:, y0i, x1i]
        v10 = feat[:, y1i, x0i]
        v11 = feat[:, y1i, x1i]
        val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
               v10 * ly * (1 - lx) + v11 * ly * lx)
        out = jnp.where(valid[None], val, 0.0)             # [C, th, tw]
        return out, valid.astype(jnp.int32)[None], m

    out, mask, mats = jax.vmap(one_roi)(rois, bidx)
    return out, mask, mats


def _np_rasterize_polys(polys, box, resolution):
    """Rasterize a union of polygons (each [P, 2], image coords) over a
    resolution x resolution grid of ``box`` centers — even-odd rule per
    polygon, union across polygons (host/numpy; the reference delegates
    to its poly2mask helper)."""
    x1, y1, x2, y2 = [float(v) for v in box]
    gx = x1 + (np.arange(resolution) + 0.5) * max(x2 - x1, 1e-6) \
        / resolution
    gy = y1 + (np.arange(resolution) + 0.5) * max(y2 - y1, 1e-6) \
        / resolution
    px = np.broadcast_to(gx[None, :], (resolution, resolution))
    py = np.broadcast_to(gy[:, None], (resolution, resolution))
    mask = np.zeros((resolution, resolution), bool)
    for poly in polys:
        p = np.asarray(poly, np.float32).reshape(-1, 2)
        if p.shape[0] < 3:
            continue
        xa, ya = p[:, 0], p[:, 1]
        xb, yb = np.roll(xa, -1), np.roll(ya, -1)
        dy = yb - ya
        dy = np.where(np.abs(dy) < 1e-12, 1e-12, dy)
        t = (py[..., None] - ya) / dy
        crosses = ((ya > py[..., None]) != (yb > py[..., None])) \
            & (px[..., None] < xa + t * (xb - xa))
        mask |= (crosses.sum(-1) % 2 == 1)
    return mask.astype(np.int32)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    """Mask R-CNN mask-target generation (host/numpy, CPU-only kernel in
    the reference too — detection/generate_mask_labels_op.cc; python
    surface layers/detection.py:2270). One image at a time.

    gt_segms: per-gt list of polygons (each a flat [x1,y1,x2,y2,...] or
    [P,2] array) in ORIGINAL image coords (scaled by im_info[2], as the
    reference does); rois [R, 4] in scaled-image coords;
    labels_int32 [R] class per roi (0 = background).

    Returns (mask_rois [F, 4], roi_has_mask_int32 [F, 1] — indices into
    ``rois``, mask_int32 [F, num_classes * resolution^2] with the
    matched class's slice in {0, 1} and every other class -1, the
    reference's ExpandMaskTarget layout). With no foreground rois, the
    first roi gets an all -1 mask (ignore) — reference line 228.
    """
    info = np.asarray(im_info, np.float32).reshape(-1)
    scale = float(info[2]) if info.size >= 3 else 1.0
    rois = np.asarray(rois, np.float32).reshape(-1, 4)
    labels = np.asarray(labels_int32, np.int32).reshape(-1)
    segs = list(gt_segms)
    if is_crowd is not None:
        crowd = np.asarray(is_crowd).reshape(-1).astype(bool)
        segs = [s for s, k in zip(segs, crowd) if not k]

    def seg_polys(seg):
        if isinstance(seg, (list, tuple)) and seg and \
                not np.isscalar(seg[0]):
            return [np.asarray(p, np.float32).reshape(-1, 2) * scale
                    for p in seg]
        return [np.asarray(seg, np.float32).reshape(-1, 2) * scale]

    polys_per_gt = [seg_polys(s) for s in segs]
    gt_bounds = []
    for polys in polys_per_gt:
        allp = np.concatenate(polys, 0) if polys else \
            np.zeros((1, 2), np.float32)
        gt_bounds.append([allp[:, 0].min(), allp[:, 1].min(),
                          allp[:, 0].max(), allp[:, 1].max()])
    gt_bounds = np.asarray(gt_bounds, np.float32).reshape(-1, 4)

    fg = np.nonzero(labels > 0)[0]
    msize = num_classes * resolution * resolution
    if fg.size == 0 or gt_bounds.shape[0] == 0:
        sel = np.array([0], np.int64) if rois.shape[0] else \
            np.zeros((0,), np.int64)
        masks = np.full((sel.size, msize), -1, np.int32)
        return (rois[sel], sel.astype(np.int32).reshape(-1, 1), masks)

    iou = _np_iou_matrix(rois[fg], gt_bounds)
    best = iou.argmax(1)
    masks = np.full((fg.size, msize), -1, np.int32)
    for i, (ri, gi) in enumerate(zip(fg, best)):
        cls = int(labels[ri])
        m = _np_rasterize_polys(polys_per_gt[gi], rois[ri], resolution)
        s = cls * resolution * resolution
        masks[i, s:s + resolution * resolution] = m.reshape(-1)
    return (rois[fg], fg.astype(np.int32).reshape(-1, 1), masks)


def mine_hard_examples(cls_loss, loc_loss, match_indices, match_dist,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=None, mining_type="max_negative"):
    """Standalone hard-example mining (parity:
    detection/mine_hard_examples_op.cc; ssd_loss fuses the same logic
    inline). TPU-first output shape: instead of the reference's ragged
    NegIndices LoD, returns (neg_mask [N, P] 0/1 of selected negatives,
    match_indices passed through as the UpdatedMatchIndices slot —
    unmatched entries are already -1 by the input contract).

    cls_loss/loc_loss [N, P]; match_indices [N, P] (-1 = unmatched);
    match_dist [N, P].
    """
    cls_loss = jnp.asarray(cls_loss, jnp.float32)
    loss = cls_loss if mining_type == "max_negative" or loc_loss is None \
        else cls_loss + jnp.asarray(loc_loss, jnp.float32)
    mi = jnp.asarray(match_indices, jnp.int32)
    dist = jnp.asarray(match_dist, jnp.float32)
    matched = mi >= 0
    neg_sel = _mine_negatives(loss, matched, dist, neg_pos_ratio,
                              neg_dist_threshold, sample_size,
                              mining_type)
    return neg_sel.astype(jnp.int32), mi
