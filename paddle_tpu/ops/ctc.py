"""CTC ops: loss, greedy alignment, edit distance.

TPU-native rebuild of the reference's warpctc / ctc_align / edit_distance
operators (ref: paddle/fluid/operators/warpctc_op.cc — wraps the external
warp-ctc library; operators/ctc_align_op.cc; operators/edit_distance_op.cc).
Here the CTC forward recursion is written directly as a log-space `lax.scan`
so it runs on TPU inside the jitted step and differentiates through JAX
autodiff (the reference needed a hand-written CUDA gradient).
"""

import jax
import jax.numpy as jnp

__all__ = ["ctc_loss", "warpctc", "ctc_align", "ctc_greedy_decoder",
           "edit_distance"]

_NEG = -1e30


def ctc_loss(logits, labels, logit_lengths=None, label_lengths=None,
             blank=0, norm_by_times=False):
    """Connectionist Temporal Classification loss.

    Args:
      logits: ``[batch, time, num_classes]`` unnormalized activations.
      labels: int ``[batch, max_label_len]`` target label ids (no blanks).
      logit_lengths / label_lengths: int ``[batch]``; None = full.
      blank: blank class id.
      norm_by_times: divide each loss by its logit length
        (ref warpctc_op.cc attr ``norm_by_times``).

    Returns:
      ``[batch]`` negative log-likelihoods.
    """
    logits = jnp.asarray(logits)
    labels = jnp.asarray(labels, jnp.int32)
    b, t, c = logits.shape
    l = labels.shape[1]
    if logit_lengths is None:
        logit_lengths = jnp.full((b,), t, jnp.int32)
    if label_lengths is None:
        label_lengths = jnp.full((b,), l, jnp.int32)
    logit_lengths = jnp.asarray(logit_lengths, jnp.int32)
    label_lengths = jnp.asarray(label_lengths, jnp.int32)

    logp = jax.nn.log_softmax(logits, axis=-1)

    # extended label sequence with interleaved blanks: length s = 2l+1
    s = 2 * l + 1
    ext = jnp.full((b, s), blank, jnp.int32).at[:, 1::2].set(labels)
    # skip-transition allowed at s when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :s]
    can_skip = (ext != blank) & (ext != ext_m2)

    pos = jnp.arange(s)[None, :]
    valid_s = pos < (2 * label_lengths[:, None] + 1)

    # alpha[0]
    a0 = jnp.full((b, s), _NEG)
    a0 = a0.at[:, 0].set(jnp.take_along_axis(
        logp[:, 0, :], ext[:, :1], axis=1)[:, 0])
    has_label = (label_lengths > 0)
    a0 = a0.at[:, 1].set(jnp.where(
        has_label,
        jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0],
        _NEG))
    a0 = jnp.where(valid_s, a0, _NEG)

    def step(alpha, xs):
        lp, live = xs  # lp [b, c], live [b] bool
        a_prev = alpha
        a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :s]
        a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :s]
        a_m2 = jnp.where(can_skip, a_m2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_m1), a_m2)
        em = jnp.take_along_axis(lp, ext, axis=1)
        nxt = jnp.where(valid_s, merged + em, _NEG)
        alpha = jnp.where(live[:, None], nxt, alpha)
        return alpha, None

    tmask = jnp.arange(t)[None, :] < logit_lengths[:, None]
    alpha, _ = jax.lax.scan(
        step, a0,
        (jnp.swapaxes(logp, 0, 1)[1:], jnp.swapaxes(tmask, 0, 1)[1:]))

    end = 2 * label_lengths  # index of final blank
    a_end = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
    a_end1 = jnp.where(
        has_label,
        jnp.take_along_axis(alpha, jnp.maximum(end - 1, 0)[:, None],
                            axis=1)[:, 0],
        _NEG)
    ll = jnp.logaddexp(a_end, a_end1)
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(logit_lengths, 1).astype(loss.dtype)
    return loss


def warpctc(input, label, input_length=None, label_length=None,
            blank=0, norm_by_times=False):
    """Reference-name alias of :func:`ctc_loss` (ref: warpctc_op.cc)."""
    return ctc_loss(input, label, input_length, label_length, blank,
                    norm_by_times)


def ctc_align(input, input_length=None, blank=0, padding_value=0):
    """Greedy CTC decode: merge repeats, drop blanks
    (ref: ctc_align_op.cc).

    Args:
      input: int frame-wise predictions ``[batch, time]`` (e.g. argmax of
        logits) or float logits ``[batch, time, classes]``.

    Returns:
      (aligned ``[batch, time]`` padded with ``padding_value``,
       lengths ``[batch]``).
    """
    input = jnp.asarray(input)
    if input.ndim == 3:
        input = jnp.argmax(input, axis=-1)
    input = input.astype(jnp.int32)
    b, t = input.shape
    if input_length is None:
        input_length = jnp.full((b,), t, jnp.int32)
    tmask = jnp.arange(t)[None, :] < jnp.asarray(input_length)[:, None]

    prev = jnp.pad(input, ((0, 0), (1, 0)), constant_values=-1)[:, :t]
    keep = (input != blank) & (input != prev) & tmask
    # stable compaction: target position of each kept token
    idx = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full((b, t), padding_value, jnp.int32)
    rows = jnp.arange(b)[:, None] * jnp.ones((1, t), jnp.int32)
    scatter_idx = jnp.where(keep, idx, t)  # dumped past the end when dropped
    out = jnp.pad(out, ((0, 0), (0, 1)))
    out = out.at[rows, scatter_idx].set(jnp.where(keep, input, padding_value))
    out = out[:, :t]
    lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
    return out, lengths


def edit_distance(input, label, input_length=None, label_length=None,
                  normalized=True):
    """Levenshtein distance between hypothesis and reference sequences
    (ref: edit_distance_op.cc). Jittable DP over a `lax.scan`.

    Returns (distances ``[batch]`` float32, sequence_num scalar).
    """
    hyp = jnp.asarray(input, jnp.int32)
    ref = jnp.asarray(label, jnp.int32)
    b, n = hyp.shape
    m = ref.shape[1]
    if input_length is None:
        input_length = jnp.full((b,), n, jnp.int32)
    if label_length is None:
        label_length = jnp.full((b,), m, jnp.int32)
    hlen = jnp.asarray(input_length, jnp.int32)
    rlen = jnp.asarray(label_length, jnp.int32)

    def one(h, r, hl, rl):
        row0 = jnp.arange(m + 1, dtype=jnp.float32)

        def row_step(prev_row, xs):
            i, hi = xs  # 1-based row index, hyp token
            sub = prev_row[:-1] + (hi != r).astype(jnp.float32)
            dele = prev_row[1:] + 1.0

            def cell(left, trip):
                s, d = trip
                val = jnp.minimum(jnp.minimum(s, d), left + 1.0)
                return val, val

            _, rest = jax.lax.scan(cell, i.astype(jnp.float32), (sub, dele))
            row = jnp.concatenate([i.astype(jnp.float32)[None], rest])
            row = jnp.where(i <= hl, row, prev_row)
            return row, None

        final, _ = jax.lax.scan(
            row_step, row0, (jnp.arange(1, n + 1), h))
        dist = final[rl]
        # empty-reference convention of the reference op
        dist = jnp.where(rl == 0, hl.astype(jnp.float32), dist)
        if normalized:
            dist = jnp.where(rl > 0, dist / rlen_safe(rl), dist)
        return dist

    def rlen_safe(rl):
        return jnp.maximum(rl, 1).astype(jnp.float32)

    dists = jax.vmap(one)(hyp, ref, hlen, rlen)
    return dists, jnp.asarray(b, jnp.int32)


def ctc_greedy_decoder(input, blank=None, input_length=None,
                       padding_value=0, name=None):
    """fluid.layers.ctc_greedy_decoder parity (layers/nn.py
    ctc_greedy_decoder): argmax over classes per frame, then the
    merge-repeats/drop-blanks collapse — i.e. ctc_align over the argmax
    path. ``blank`` defaults to num_classes-1 like the reference.

    Returns (decoded [B, T] padded with ``padding_value``, lengths [B]).
    """
    input = jnp.asarray(input)
    if input.ndim != 3:
        raise ValueError("ctc_greedy_decoder expects [batch, time, classes]")
    if blank is None:
        blank = input.shape[-1] - 1
    path = jnp.argmax(input, axis=-1)
    return ctc_align(path, input_length=input_length, blank=blank,
                     padding_value=padding_value)
