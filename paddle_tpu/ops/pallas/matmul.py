"""Pallas bodies for the static-graph ``fused_matmul`` op.

Two registered kernels back ``_fused_matmul_compute``
(static/opt_passes.py):

- ``fused_matmul`` — fp path: x @ w (+ bias) (+ act) as one blocked MXU
  kernel, fp32 accumulation, bias/act fused into the epilogue of the
  last K step. Differentiable via custom_vjp (backward = the two stock
  matmuls; act grads from saved residuals).
- ``fused_matmul_int8`` — the weight-only PTQ serving variant: the int8
  weight block is dequantized INSIDE the tile loop (convert + per-channel
  scale ride the K-stream in VMEM), so the fp32 sidecar copy of the
  weight the stock body materializes never exists in HBM. Forward-only:
  serving never differentiates a quantized program.

The reference bodies are the exact stock-jnp composition the fused op
has always lowered (pinned by the 220-program equivalence fuzz with the
registry forced on, tests/test_opt_passes.py)."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import registry as _registry

try:  # pltpu import fails on some CPU-only builds; interpret mode works
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["try_fused_matmul"]

#: mirrors static/opt_passes.QUANT_BINS (int8 per-channel abs-max:
#: q = round(w / scale * 127)); duplicated to keep this leaf module free
#: of the static-graph import graph
_QUANT_BINS = 127.0

# the epilogue activations, fp32 — identical math to ops/activation.py
# (relu/sigmoid/tanh/gelu with approximate=False)
_ACTS = {
    "relu": lambda v: jnp.maximum(v, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": lambda v: jax.nn.gelu(v, approximate=False),
}


def _vmem_spec(*args, **kwargs):
    if _HAS_PLTPU:
        kwargs.setdefault("memory_space", pltpu.VMEM)
    return pl.BlockSpec(*args, **kwargs)


def _round_up(v, m):
    return -(-v // m) * m


def _fmm_kernel(*refs, nk, act, dequant, has_bias):
    """One (m-block, n-block) output tile, K innermost: accumulate fp32
    partial products across the K grid axis, dequantize int8 weight
    blocks in-tile, apply bias+act in the last K step's epilogue."""
    x_ref, w_ref = refs[0], refs[1]
    i = 2
    scale_ref = bias_ref = None
    if dequant:
        scale_ref = refs[i]
        i += 1
    if has_bias:
        bias_ref = refs[i]
        i += 1
    o_ref = refs[i]
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    xb = x_ref[...].astype(jnp.float32)
    if dequant:
        wb = w_ref[...].astype(jnp.float32) \
            * (scale_ref[...].astype(jnp.float32) / _QUANT_BINS)
    else:
        wb = w_ref[...].astype(jnp.float32)
    o_ref[...] += jax.lax.dot_general(
        xb, wb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _epilogue():
        r = o_ref[...]
        if has_bias:
            r = r + bias_ref[...].astype(jnp.float32)
        if act is not None:
            r = _ACTS[act](r)
        o_ref[...] = r


def _fmm_call(x2, w, scale, bias, act, interpret):
    """Blocked pallas_call over padded [M,K]@[K,N]; returns fp32 [M,N]."""
    m, kdim = x2.shape
    n = w.shape[1]
    bm = min(128, _round_up(m, 8))
    bn = min(512, _round_up(n, 128))
    # 256 is sublane-safe for every weight dtype (fp32 8, bf16 16, int8 32)
    bk = min(512, _round_up(kdim, 256))
    mp, kp, np_ = _round_up(m, bm), _round_up(kdim, bk), _round_up(n, bn)
    if mp != m or kp != kdim:
        x2 = jnp.pad(x2, ((0, mp - m), (0, kp - kdim)))
    if kp != kdim or np_ != n:
        w = jnp.pad(w, ((0, kp - kdim), (0, np_ - n)))
    grid = (mp // bm, np_ // bn, kp // bk)
    in_specs = [
        _vmem_spec((bm, bk), lambda im, in_, ik: (im, ik)),
        _vmem_spec((bk, bn), lambda im, in_, ik: (ik, in_)),
    ]
    args = [x2, w]
    if scale is not None:
        s1 = jnp.asarray(scale, jnp.float32).reshape(1, -1)
        if np_ != n:
            s1 = jnp.pad(s1, ((0, 0), (0, np_ - n)))
        in_specs.append(_vmem_spec((1, bn), lambda im, in_, ik: (0, in_)))
        args.append(s1)
    if bias is not None:
        b1 = jnp.asarray(bias).reshape(1, -1)
        if np_ != n:
            b1 = jnp.pad(b1, ((0, 0), (0, np_ - n)))
        in_specs.append(_vmem_spec((1, bn), lambda im, in_, ik: (0, in_)))
        args.append(b1)
    kernel = functools.partial(
        _fmm_kernel, nk=grid[2], act=act, dequant=scale is not None,
        has_bias=bias is not None)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=_vmem_spec((bm, bn), lambda im, in_, ik: (im, in_)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
    )(*args)
    if mp != m or np_ != n:
        out = out[:m, :n]
    return out


# -- fp body (differentiable) ----------------------------------------------

def _fmm_fwd_impl(x2, w, bias, act, interpret):
    """Returns (fp32 out, fp32 act-residual). gelu keeps its epilogue
    OUTSIDE the kernel: its grad needs the pre-activation z, and saving z
    from inside would cost a second HBM output for every fused matmul."""
    kernel_act = None if act == "gelu" else act
    z = _fmm_call(x2, w, None, bias, kernel_act, interpret)
    if act == "gelu":
        return _ACTS["gelu"](z), z
    return z, z


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fmm_fp(x2, w, bias, act, out_dtype, interpret):
    out, _ = _fmm_fwd_impl(x2, w, bias, act, interpret)
    return out.astype(out_dtype)


def _fmm_fp_fwd(x2, w, bias, act, out_dtype, interpret):
    out, res = _fmm_fwd_impl(x2, w, bias, act, interpret)
    return out.astype(out_dtype), (x2, w, bias, res)


def _fmm_fp_bwd(act, out_dtype, interpret, saved, dy):
    x2, w, bias, res = saved
    dy32 = dy.astype(jnp.float32)
    if act == "relu":
        dz = dy32 * (res > 0)           # res = post-act out
    elif act == "sigmoid":
        dz = dy32 * res * (1.0 - res)
    elif act == "tanh":
        dz = dy32 * (1.0 - res * res)
    elif act == "gelu":
        _, vjpf = jax.vjp(_ACTS["gelu"], res)   # res = pre-act z
        dz = vjpf(dy32)[0]
    else:
        dz = dy32
    # backward = the two stock matmuls (XLA's MXU path; the forward win
    # is the fused epilogue/dequant, not the dot itself)
    dx = jax.lax.dot_general(
        dz, w.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x2.dtype)
    dw = jax.lax.dot_general(
        x2.astype(jnp.float32), dz, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    db = None if bias is None else \
        jnp.sum(dz, axis=0).astype(jnp.asarray(bias).dtype)
    return dx, dw, db


_fmm_fp.defvjp(_fmm_fp_fwd, _fmm_fp_bwd)


def fused_matmul_pallas(x, w, bias=None, act=None, out_dtype=None,
                        interpret=False):
    """Pallas fp body: x [..., K] @ w [K, N] (+ bias [N]) (+ act)."""
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    if out_dtype is None:
        out_dtype = jnp.result_type(x.dtype, w.dtype)
    out = _fmm_fp(x2, w, bias, act, jnp.dtype(out_dtype), bool(interpret))
    return out.reshape(lead + (w.shape[1],))


def fused_matmul_reference(x, w, bias=None, act=None, out_dtype=None,
                           interpret=None):
    """Stock composition: exactly what _fused_matmul_compute lowers for
    the eligible operand pattern (2-D weight, trailing-axis bias)."""
    out = jnp.matmul(jnp.asarray(x), jnp.asarray(w))
    if out_dtype is not None:
        out = out.astype(out_dtype)
    if bias is not None:
        out = out + jnp.asarray(bias)
    if act is not None:
        out = _ACTS[act](out)
    return out


# -- int8 body (forward-only, serving) -------------------------------------

def fused_matmul_int8_pallas(x, w, scale, bias=None, act=None,
                             interpret=False):
    """x [..., K] @ dequant(w int8 [K, N], scale [N]) (+ bias) (+ act).
    Dequant runs inside the tile loop; forward-only (PTQ serving)."""
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    kernel_act = None if act == "gelu" else act
    out = _fmm_call(x2, w, scale, bias, kernel_act, bool(interpret))
    if act == "gelu":
        out = _ACTS["gelu"](out)
    out_dtype = jnp.result_type(x.dtype, jnp.float32)
    return out.astype(out_dtype).reshape(lead + (w.shape[1],))


def fused_matmul_int8_reference(x, w, scale, bias=None, act=None,
                                interpret=None):
    """The existing sidecar-dequant composition (opt_passes PTQ path):
    materialize the fp32 weight, then the stock matmul chain."""
    wd = jnp.asarray(w).astype(jnp.float32) \
        * (jnp.asarray(scale) / _QUANT_BINS)
    out = jnp.matmul(jnp.asarray(x), wd)
    if bias is not None:
        out = out + jnp.asarray(bias)
    if act is not None:
        out = _ACTS[act](out)
    return out


_registry.register_kernel(
    "fused_matmul", fused_matmul_reference, fused_matmul_pallas,
    doc="x @ w (+bias) (+act), fp32 accumulation, fused epilogue")
_registry.register_kernel(
    "fused_matmul_int8", fused_matmul_int8_reference,
    fused_matmul_int8_pallas,
    doc="x @ dequant(w_int8, scale) (+bias) (+act); dequant in-tile")


# -- static-graph dispatch helper ------------------------------------------

def try_fused_matmul(ins, attrs):
    """Pallas fast path for the static ``fused_matmul`` op. Returns the
    op output, or None when the registry selects the stock body or the
    operand pattern is outside the kernels' contract — the caller
    (static/opt_passes._fused_matmul_compute) then runs the stock
    composition, keeping the flag-off path bit-identical."""
    quant = attrs.get("quant")
    name = "fused_matmul_int8" if quant == "int8" else "fused_matmul"
    if not _registry.use_pallas(name):
        return None
    xs = list(ins["X"])
    x, w = jnp.asarray(xs[0]), jnp.asarray(xs[1])
    i = 2
    scale = None
    if quant == "int8":
        scale = xs[i]
        i += 1
        if w.dtype != jnp.int8:
            return None
    elif quant == "bf16":
        # stock path casts the bf16-stored weight to fp32 before the
        # matmul; mirror that so out dtype matches, then ride the fp body
        pass
    elif quant is not None:
        return None
    if w.ndim != 2 or x.ndim < 2 or x.shape[-1] != w.shape[0]:
        return None
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return None
    if quant != "int8" and not (jnp.issubdtype(w.dtype, jnp.floating)):
        return None
    mm_attrs = attrs.get("mm_attrs", {})
    if attrs["mm_type"] == "matmul":
        if mm_attrs.get("transpose_x") or mm_attrs.get("transpose_y") \
                or mm_attrs.get("alpha", 1.0) != 1.0:
            return None
        x_eff = x
        out_shape = x.shape[:-1] + (w.shape[1],)
    elif attrs["mm_type"] == "mul":
        if mm_attrs.get("x_num_col_dims", 1) != 1 \
                or mm_attrs.get("y_num_col_dims", 1) != 1:
            return None
        x_eff = x.reshape((x.shape[0], -1))
        if x_eff.shape[1] != w.shape[0]:
            return None
        out_shape = (x.shape[0], w.shape[1])
    else:
        return None
    bias = None
    if attrs.get("has_bias"):
        b = jnp.asarray(xs[i])
        axis = attrs.get("bias_axis", -1)
        if b.ndim != 1 or b.shape[0] != w.shape[1] \
                or axis not in (-1, len(out_shape) - 1):
            return None
        bias = b
    act = attrs.get("act")
    if act is not None and act not in _ACTS:
        return None
    if quant == "int8":
        out = _registry.dispatch("fused_matmul_int8", x_eff, w, scale,
                                 bias=bias, act=act)
    else:
        out_dtype = jnp.result_type(x.dtype, jnp.float32) \
            if quant == "bf16" else None
        out = _registry.dispatch("fused_matmul", x_eff, w,
                                 bias=bias, act=act, out_dtype=out_dtype)
    return out.reshape(out_shape)
