"""Fused optimizer-update kernels (SGD / momentum / Adam).

The stock apply path lowers each update rule as a chain of elementwise
jnp ops — every intermediate (momentum*v, (1-b1)*g, sqrt(m2)+eps, ...)
is a separate HBM round trip. The Pallas bodies stream param + grad +
slots through VMEM once per 256x128 block and write param + slots back
in the same pass.

Reference bodies mirror the exact ``Optimizer._update`` math in
optimizer.py (sgd_op.cc / momentum_op.cc / adam_op.cc rules); the
wrappers in optimizer.py pin output dtypes to the stock ones via
``jax.eval_shape`` over the reference, so mixed-precision params (bf16
p, f32 lr) keep their historical promotion behavior bit-for-bit."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import registry as _registry

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = []

_LANES = 128


def _vmem_spec(*args, **kwargs):
    if _HAS_PLTPU:
        kwargs.setdefault("memory_space", pltpu.VMEM)
    return pl.BlockSpec(*args, **kwargs)


def _round_up(v, m):
    return -(-v // m) * m


def _ew_call(kernel, arrays, scalars, n_out, interpret):
    """Run an elementwise kernel over same-size tensors: flatten to
    [rows, 128] f32 blocks, ride the scalars in as one (1, ns) block,
    return n_out f32 arrays of the original flat size."""
    size = arrays[0].size
    rows = -(-size // _LANES)
    br = min(256, _round_up(rows, 8))
    rows_p = _round_up(rows, br)
    pad = rows_p * _LANES - size
    padded = [
        jnp.pad(jnp.asarray(a).reshape(-1).astype(jnp.float32), (0, pad))
        .reshape(rows_p, _LANES) for a in arrays
    ]
    sc = jnp.stack([jnp.asarray(s, jnp.float32).reshape(()) for s in
                    scalars]).reshape(1, -1)
    ns = sc.shape[1]
    outs = pl.pallas_call(
        kernel,
        grid=(rows_p // br,),
        in_specs=[_vmem_spec((br, _LANES), lambda i: (i, 0))
                  for _ in padded]
        + [_vmem_spec((1, ns), lambda i: (0, 0))],
        out_specs=[_vmem_spec((br, _LANES), lambda i: (i, 0))] * n_out,
        out_shape=[jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32)]
        * n_out,
        interpret=interpret,
    )(*padded, sc)
    if n_out == 1:
        outs = [outs] if not isinstance(outs, (list, tuple)) else outs
    return [o.reshape(-1)[:size] for o in outs]


# -- SGD -------------------------------------------------------------------

def fused_sgd_reference(p, g, lr, interpret=None):
    return p - lr * g


def _sgd_kernel(p_ref, g_ref, sc_ref, o_ref):
    o_ref[...] = p_ref[...] - sc_ref[0, 0] * g_ref[...]


def fused_sgd_pallas(p, g, lr, interpret=False):
    shape = jnp.shape(p)
    (out,) = _ew_call(_sgd_kernel, [p, g], [lr], 1, bool(interpret))
    return out.reshape(shape)


# -- momentum --------------------------------------------------------------

def fused_momentum_reference(p, g, v, lr, momentum=0.9,
                             use_nesterov=False, interpret=None):
    v_new = momentum * v + g
    if use_nesterov:
        new_p = p - lr * (g + momentum * v_new)
    else:
        new_p = p - lr * v_new
    return new_p, v_new


def _momentum_kernel(p_ref, g_ref, v_ref, sc_ref, po_ref, vo_ref, *,
                     momentum, nesterov):
    lr = sc_ref[0, 0]
    g = g_ref[...]
    v = momentum * v_ref[...] + g
    if nesterov:
        po_ref[...] = p_ref[...] - lr * (g + momentum * v)
    else:
        po_ref[...] = p_ref[...] - lr * v
    vo_ref[...] = v


def fused_momentum_pallas(p, g, v, lr, momentum=0.9, use_nesterov=False,
                          interpret=False):
    shape = jnp.shape(p)
    kernel = functools.partial(_momentum_kernel, momentum=float(momentum),
                               nesterov=bool(use_nesterov))
    new_p, new_v = _ew_call(kernel, [p, g, v], [lr], 2, bool(interpret))
    return new_p.reshape(shape), new_v.reshape(shape)


# -- Adam ------------------------------------------------------------------

def fused_adam_reference(p, g, m1, m2, lr, t, beta1=0.9, beta2=0.999,
                         epsilon=1e-8, interpret=None):
    t = jnp.asarray(t).astype(jnp.float32)
    m1n = beta1 * m1 + (1 - beta1) * g
    m2n = beta2 * m2 + (1 - beta2) * jnp.square(g)
    bc = jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    new_p = p - lr * bc * m1n / (jnp.sqrt(m2n) + epsilon)
    return new_p, m1n, m2n


def _adam_kernel(p_ref, g_ref, m1_ref, m2_ref, sc_ref, po_ref, m1o_ref,
                 m2o_ref, *, beta1, beta2, epsilon):
    lr_bc = sc_ref[0, 0]  # lr * bias-correction, folded outside (scalars)
    g = g_ref[...]
    m1 = beta1 * m1_ref[...] + (1 - beta1) * g
    m2 = beta2 * m2_ref[...] + (1 - beta2) * g * g
    po_ref[...] = p_ref[...] - lr_bc * m1 / (jnp.sqrt(m2) + epsilon)
    m1o_ref[...] = m1
    m2o_ref[...] = m2


def fused_adam_pallas(p, g, m1, m2, lr, t, beta1=0.9, beta2=0.999,
                      epsilon=1e-8, interpret=False):
    shape = jnp.shape(p)
    t32 = jnp.asarray(t).astype(jnp.float32)
    # bias correction is pure scalar work — fold into lr on the host side
    bc = jnp.sqrt(1 - beta2 ** t32) / (1 - beta1 ** t32)
    kernel = functools.partial(_adam_kernel, beta1=float(beta1),
                               beta2=float(beta2), epsilon=float(epsilon))
    new_p, m1n, m2n = _ew_call(kernel, [p, g, m1, m2], [lr * bc], 3,
                               bool(interpret))
    return new_p.reshape(shape), m1n.reshape(shape), m2n.reshape(shape)


_registry.register_kernel(
    "fused_sgd", fused_sgd_reference, fused_sgd_pallas,
    doc="p - lr*g, one VMEM pass")
_registry.register_kernel(
    "fused_momentum", fused_momentum_reference, fused_momentum_pallas,
    doc="momentum/nesterov update + velocity slot, one VMEM pass")
_registry.register_kernel(
    "fused_adam", fused_adam_reference, fused_adam_pallas,
    doc="bias-corrected Adam update + both moment slots, one VMEM pass")
