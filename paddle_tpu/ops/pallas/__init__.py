"""Pallas kernel layer: registry + kernel modules (docs/PERFORMANCE.md
"Pallas kernel layer").

Importing this package registers every built-in kernel:
fused_matmul / fused_matmul_int8 (matmul.py), embedding_gather /
embedding_scatter_add (embedding.py), fused_sgd / fused_momentum /
fused_adam (optimizer.py), and — via ops/pallas_kernels.py —
flash_attention / fused_layer_norm / softmax_cross_entropy."""

from paddle_tpu.ops.pallas.registry import (  # noqa: F401
    DEFAULT_VMEM_BUDGET, register_kernel, get_kernel, list_kernels,
    dispatch, get_body, selected_body, use_pallas, selection_mode,
    override, platform, within_vmem_budget,
)
from paddle_tpu.ops.pallas import matmul as _matmul  # noqa: F401
from paddle_tpu.ops.pallas import embedding as _embedding  # noqa: F401
from paddle_tpu.ops.pallas import optimizer as _optimizer  # noqa: F401
from paddle_tpu.ops.pallas.matmul import try_fused_matmul  # noqa: F401

# the three legacy entry points register themselves when
# ops/pallas_kernels.py executes; import it so `import paddle_tpu.ops.pallas`
# alone yields the complete registry. Guarded: pallas_kernels imports this
# package for the platform probe, so during ops/__init__'s own import of
# pallas_kernels this is a benign partially-initialized no-op.
try:
    from paddle_tpu.ops import pallas_kernels as _legacy  # noqa: F401
except ImportError:  # pragma: no cover - circular during package init
    pass

__all__ = [
    "register_kernel", "get_kernel", "list_kernels", "dispatch",
    "get_body", "selected_body", "use_pallas", "selection_mode",
    "override", "platform", "try_fused_matmul",
    "within_vmem_budget", "DEFAULT_VMEM_BUDGET",
]
