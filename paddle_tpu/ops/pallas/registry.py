"""Pallas kernel registry: ONE selection/fallback/flag home.

Mirrors the op-registry pattern (``register_op`` in
``static/opt_passes.py``): each registered kernel declares a stock-jnp
**reference** body and an optional **Pallas** body. Selection happens at
trace/compile time:

- ``auto`` (default): Pallas body on an accelerator, stock reference on
  CPU — tier-1 stays on the exact jnp semantics it always had.
- ``on``: force the Pallas body everywhere; on CPU it runs in Pallas
  interpreter mode (the same kernel code path the TPU compiles).
- ``off``: force the stock reference everywhere.

Override via ``FLAGS_use_pallas_kernels=auto|on|off`` (core/flags.py),
the short env ``PADDLE_TPU_PALLAS=0|1``, or the :func:`override` context
manager for in-process A/B (bench.py kernels mode, parity tests).

Every selection change is published through the
``pallas_kernels_selected{kernel,body}`` gauge so a running job's kernel
selection is inspectable from the metrics snapshot
(docs/OBSERVABILITY.md).
"""

import contextlib
import functools
import os
import threading

from paddle_tpu.core.flags import define_flag, get_flag

__all__ = [
    "register_kernel", "get_kernel", "list_kernels", "dispatch",
    "get_body", "selected_body", "use_pallas", "selection_mode",
    "override", "platform", "within_vmem_budget",
    "DEFAULT_VMEM_BUDGET",
]

#: fp32 elements a kernel body may hold whole in VMEM (~16 MB of a
#: v5e core's ~16 MB/core VMEM at 4 B/element) — the shared default
#: every budget-guarded kernel falls back past
DEFAULT_VMEM_BUDGET = 4 << 20

_REGISTRY = {}
_lock = threading.Lock()
_tls = threading.local()

# PADDLE_TPU_PALLAS=0|1 is the short A/B switch; FLAGS_use_pallas_kernels
# (read by define_flag from the env) wins when both are set, matching the
# flag system's precedence for every other flag.
_env_short = os.environ.get("PADDLE_TPU_PALLAS")
define_flag(
    "use_pallas_kernels",
    {"0": "off", "1": "on"}.get(_env_short, "auto"),
    "Pallas kernel registry selection: 'auto' = Pallas bodies on an "
    "accelerator, stock jnp reference on CPU; 'on' = force Pallas "
    "(interpreter mode on CPU); 'off' = force the stock reference. "
    "Short env form: PADDLE_TPU_PALLAS=0|1 (ops/pallas/registry.py)")

_MODE_ALIASES = {
    "auto": "auto", "": "auto", "default": "auto",
    "on": "on", "1": "on", "true": "on", "yes": "on",
    "off": "off", "0": "off", "false": "off", "no": "off",
}


class Kernel:
    """One registered kernel: a stock-jnp reference body and an optional
    Pallas body. Both bodies share one signature; the Pallas body must
    additionally accept ``interpret=`` (bool) — the registry injects it
    from the platform probe."""

    __slots__ = ("name", "reference", "pallas", "doc")

    def __init__(self, name, reference, pallas=None, doc=""):
        self.name = name
        self.reference = reference
        self.pallas = pallas
        self.doc = doc

    def __repr__(self):
        bodies = "reference+pallas" if self.pallas else "reference"
        return f"Kernel({self.name!r}, {bodies})"


def register_kernel(name, reference, pallas=None, doc=""):
    """Register (or re-register) a kernel. Mirrors ``register_op``:
    last registration wins, so tests can shadow a body."""
    k = Kernel(name, reference, pallas, doc)
    with _lock:
        _REGISTRY[name] = k
    return k


def get_kernel(name):
    return _REGISTRY[name]


def list_kernels():
    return sorted(_REGISTRY)


@functools.lru_cache(maxsize=None)
def platform():
    """Per-process cached device-platform probe. jax.devices() walks the
    backend registry on every call — on the per-step hot path (every
    kernel invocation) the probe must be paid exactly once."""
    import jax
    try:
        return jax.devices()[0].platform
    except Exception:  # pragma: no cover - no backend at all
        return "cpu"


def selection_mode():
    """Effective mode: an :func:`override` beats the flag."""
    ov = getattr(_tls, "override", None)
    if ov:
        return ov[-1]
    return _MODE_ALIASES.get(str(get_flag("use_pallas_kernels")).lower(),
                             "auto")


@contextlib.contextmanager
def override(mode):
    """Force selection for the current thread: 'on' | 'off' | 'auto'.
    Nestable; used by the bench kernels mode and the parity tests."""
    mode = _MODE_ALIASES[str(mode).lower()]
    stack = getattr(_tls, "override", None)
    if stack is None:
        stack = _tls.override = []
    stack.append(mode)
    try:
        yield
    finally:
        stack.pop()


def selected_body(name):
    """Which body a dispatch of ``name`` would run right now:
    'pallas' (compiled), 'pallas_interpret' (CPU interpreter mode), or
    'reference'."""
    k = _REGISTRY[name]
    if k.pallas is None:
        return "reference"
    mode = selection_mode()
    if mode == "off":
        return "reference"
    cpu = platform() == "cpu"
    if mode == "on":
        return "pallas_interpret" if cpu else "pallas"
    return "reference" if cpu else "pallas"


def use_pallas(name):
    """True when dispatch would run the Pallas body — call sites that
    keep their stock code inline (bit-identical flag-off path) gate on
    this instead of always routing through :func:`dispatch`."""
    return selected_body(name) != "reference"


_last_selection = {}


def _note_selection(name, body):
    """Publish selection changes to the pallas_kernels_selected gauge.
    Only on change: dispatch sits on the hot path."""
    if _last_selection.get(name) == body:
        return
    prev = _last_selection.get(name)
    _last_selection[name] = body
    try:
        from paddle_tpu.monitor.registry import gauge
        g = gauge("pallas_kernels_selected",
                  "Which body the Pallas kernel registry selected "
                  "(1 = active), per kernel",
                  labels=("kernel", "body"))
        if prev is not None:
            g.set(0, kernel=name, body=prev)
        g.set(1, kernel=name, body=body)
    except Exception:  # pragma: no cover - telemetry must never fail a step
        pass


def within_vmem_budget(kernel, elements, budget=None):
    """True when a kernel body planning to hold ``elements`` fp32
    elements whole in VMEM fits under ``budget`` (default
    :data:`DEFAULT_VMEM_BUDGET`). The shared guard every Pallas body
    calls BEFORE committing to its VMEM-resident strategy: a False
    means "fall back to the reference body", and every such rejection
    counts in ``pallas_vmem_budget_rejections_total{kernel}`` so
    budget fallbacks are visible per kernel instead of silently
    vanishing into the reference path."""
    if budget is None:
        budget = DEFAULT_VMEM_BUDGET
    if int(elements) <= int(budget):
        return True
    try:
        from paddle_tpu.monitor.registry import counter
        counter("pallas_vmem_budget_rejections_total",
                "Pallas kernel dispatches that fell back to the "
                "stock reference body because the planned "
                "VMEM-resident working set exceeded the budget "
                "(fp32 elements, ops/pallas/registry.py "
                "within_vmem_budget)",
                labels=("kernel",)).inc(kernel=str(kernel))
    except Exception:  # pragma: no cover - telemetry must never fail a step
        pass
    return False


def get_body(name, which):
    """Raw body access for A/B harnesses: which = 'reference'|'pallas'."""
    k = _REGISTRY[name]
    return k.reference if which == "reference" else k.pallas


def dispatch(name, *args, **kwargs):
    """Run the selected body. The Pallas body receives ``interpret=``
    resolved from the platform probe (unless the caller already forced
    it)."""
    k = _REGISTRY[name]
    body = selected_body(name)
    _note_selection(name, body)
    if body == "reference":
        return k.reference(*args, **kwargs)
    kwargs.setdefault("interpret", body == "pallas_interpret")
    return k.pallas(*args, **kwargs)
