"""Pallas bodies for the sparse embedding hot pair.

- ``embedding_gather`` — rows = table[ids]: scalar-prefetched index map
  (PrefetchScalarGridSpec) so each grid step DMAs exactly the one table
  row it emits; stock body is ``jnp.take(..., mode="clip")``.
- ``embedding_scatter_add`` — dst[ids] += updates, the segment-sum /
  ``.at[].add`` pattern behind merge_selected_rows, sparse SGD and the
  NativeSparseTable apply path. The Pallas body reduces each
  destination-row block with a one-hot [rows_block, n] @ [n, d] matmul —
  duplicate indices are summed by the dot itself, so the result is
  deterministic by construction (same property the stock segment_sum
  gives, unlike loop-carried float adds).

Both bodies are differentiable via custom_vjp (the backward of gather is
scatter-add and vice versa — stock-jnp, not nested kernels)."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import registry as _registry

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = []


def _round_up(v, m):
    return -(-v // m) * m


# -- gather ----------------------------------------------------------------

def embedding_gather_reference(table, ids, interpret=None):
    """Stock lookup (jnp.take default semantics: out-of-bounds rows fill
    with NaN for float tables)."""
    return jnp.take(jnp.asarray(table), jnp.asarray(ids), axis=0)


def _gather_kernel(ids_ref, tbl_ref, o_ref):
    del ids_ref  # consumed by the index map
    o_ref[...] = tbl_ref[...]


def _gather_call(table, ids, interpret):
    n = ids.shape[0]
    h, d = table.shape
    dp = _round_up(d, 128)
    if dp != d:
        table = jnp.pad(table, ((0, 0), (0, dp - d)))
    # clip to match jnp.take's default OOB mode
    ids32 = jnp.clip(ids.astype(jnp.int32), 0, h - 1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, dp), lambda i, idref: (idref[i], 0))],
        out_specs=pl.BlockSpec((1, dp), lambda i, idref: (i, 0)),
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, dp), table.dtype),
        interpret=interpret,
    )(ids32, table)
    return out[:, :d] if dp != d else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _gather(table, ids, shape, dtype_name, interpret):
    return _gather_call(table, ids, interpret)


def _gather_fwd(table, ids, shape, dtype_name, interpret):
    return _gather_call(table, ids, interpret), ids


def _gather_bwd(shape, dtype_name, interpret, ids, dy):
    ids32 = jnp.clip(ids.astype(jnp.int32), 0, shape[0] - 1)
    d_table = jnp.zeros(shape, jnp.float32).at[ids32].add(
        dy.astype(jnp.float32))
    return d_table.astype(dtype_name), None


_gather.defvjp(_gather_fwd, _gather_bwd)


def embedding_gather_pallas(table, ids, interpret=False):
    """rows = table[ids] via a scalar-prefetched row-DMA kernel."""
    table = jnp.asarray(table)
    ids = jnp.asarray(ids)
    lead = ids.shape
    flat = ids.reshape(-1)
    if flat.shape[0] == 0:
        return jnp.zeros(lead + (table.shape[1],), table.dtype)
    if not _HAS_PLTPU:  # pragma: no cover - interpret still needs pltpu spec
        return embedding_gather_reference(table, ids)
    out = _gather(table, flat, tuple(table.shape), table.dtype.name,
                  bool(interpret))
    if jnp.issubdtype(table.dtype, jnp.inexact):
        # the kernel clips OOB ids to a real row; stock jnp.take fills
        # them with NaN — mask outside the kernel so forward AND (via
        # where's vjp zeroing the cotangent) backward match exactly
        valid = (flat >= 0) & (flat < table.shape[0])
        out = jnp.where(valid[:, None], out, jnp.nan)
    return out.reshape(lead + (table.shape[1],))


# -- scatter-add -----------------------------------------------------------

def embedding_scatter_add_reference(dst, ids, updates, interpret=None):
    """Stock body: .at[].add — drops out-of-range ids (JAX default)."""
    return jnp.asarray(dst).at[jnp.asarray(ids)].add(jnp.asarray(updates))


def _scatter_kernel(dst_ref, ids_ref, upd_ref, o_ref, *, bh):
    i = pl.program_id(0)
    rows = i * bh + jax.lax.broadcasted_iota(jnp.int32, (bh, 1), 0)
    # [bh, n_pad] one-hot; padded ids are -1 so their column stays zero,
    # and (matching .at[].add semantics) out-of-range ids contribute nowhere
    onehot = (rows == ids_ref[...]).astype(jnp.float32)
    acc = dst_ref[...].astype(jnp.float32) + jax.lax.dot_general(
        onehot, upd_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    o_ref[...] = acc.astype(o_ref.dtype)


def _scatter_call(dst, ids, updates, interpret):
    h, d = dst.shape
    n = ids.shape[0]
    dp = _round_up(d, 128)
    n_pad = _round_up(max(n, 1), 128)
    bh = min(256, _round_up(h, 8))
    hp = _round_up(h, bh)
    if hp != h or dp != d:
        dst = jnp.pad(dst, ((0, hp - h), (0, dp - d)))
    ids32 = ids.astype(jnp.int32)
    if n_pad != n:
        ids32 = jnp.pad(ids32, (0, n_pad - n), constant_values=-1)
        updates = jnp.pad(updates, ((0, n_pad - n), (0, 0)))
    if dp != d:
        updates = jnp.pad(updates, ((0, 0), (0, dp - d)))
    out = pl.pallas_call(
        functools.partial(_scatter_kernel, bh=bh),
        grid=(hp // bh,),
        in_specs=[
            pl.BlockSpec((bh, dp), lambda i: (i, 0)),
            pl.BlockSpec((1, n_pad), lambda i: (0, 0)),
            pl.BlockSpec((n_pad, dp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bh, dp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((hp, dp), dst.dtype),
        interpret=interpret,
    )(dst, ids32.reshape(1, -1), updates)
    if hp != h or dp != d:
        out = out[:h, :d]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _scatter_add(dst, ids, updates, interpret):
    return _scatter_call(dst, ids, updates, interpret)


def _scatter_fwd(dst, ids, updates, interpret):
    return _scatter_call(dst, ids, updates, interpret), ids


def _scatter_bwd(interpret, ids, dy):
    return dy, None, jnp.take(dy, jnp.asarray(ids), axis=0)


_scatter_add.defvjp(_scatter_fwd, _scatter_bwd)

def embedding_scatter_add_pallas(dst, ids, updates, interpret=False):
    """dst[ids] += updates via per-row-block one-hot matmul reduction."""
    dst = jnp.asarray(dst)
    ids = jnp.asarray(ids).reshape(-1)
    updates = jnp.asarray(updates)
    if ids.shape[0] == 0 or dst.ndim != 2 or updates.ndim != 2:
        return embedding_scatter_add_reference(dst, ids, updates)
    # the one-hot body holds the padded updates block whole in VMEM —
    # the shared registry budget guard decides (and counts) fallback
    n_pad = _round_up(ids.shape[0], 128)
    dp = _round_up(dst.shape[1], 128)
    if not _registry.within_vmem_budget("embedding_scatter_add",
                                        n_pad * dp):
        return embedding_scatter_add_reference(dst, ids, updates)
    return _scatter_add(dst, ids, updates, bool(interpret))


_registry.register_kernel(
    "embedding_gather", embedding_gather_reference, embedding_gather_pallas,
    doc="rows = table[ids] (scalar-prefetched row DMA)")
_registry.register_kernel(
    "embedding_scatter_add", embedding_scatter_add_reference,
    embedding_scatter_add_pallas,
    doc="dst[ids] += updates (one-hot matmul; duplicate-safe)")
