"""Activation ops.

Parity target: operators/activation_op.cc (sigmoid, logsigmoid, relu,
gelu, tanh, tanh_shrink, softplus, softsign, brelu, leaky_relu, soft_relu,
elu, relu6, stanh, hard_sigmoid, swish, thresholded_relu, hard_shrink…)
plus softmax_op.cc, maxout_op.cc, prelu_op.cc, selu_op.cc.

All are VPU-friendly elementwise maps; XLA fuses them into adjacent
matmuls/convs, which is the TPU answer to the reference's
fused_elemwise_activation op (operators/fused/).
"""

import jax
import jax.numpy as jnp

__all__ = [
    "relu", "relu6", "leaky_relu", "prelu", "elu", "selu", "gelu",
    "sigmoid", "logsigmoid", "hard_sigmoid", "tanh", "tanh_shrink",
    "softplus", "softsign", "softshrink", "hard_shrink", "brelu",
    "soft_relu", "stanh", "swish", "hard_swish", "thresholded_relu",
    "maxout", "softmax", "log_softmax", "mish",
]


def relu(x, name=None):
    return jnp.maximum(jnp.asarray(x), 0)


def relu6(x, threshold=6.0, name=None):
    return jnp.clip(jnp.asarray(x), 0, threshold)


def leaky_relu(x, alpha=0.02, name=None):
    x = jnp.asarray(x)
    return jnp.where(x > 0, x, alpha * x)


def prelu(x, weight, mode="all", name=None):
    """prelu_op.cc parity; mode all|channel|element."""
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    if mode == "channel" and w.ndim == 1:
        w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x > 0, x, w * x)


def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(jnp.asarray(x), alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    x = jnp.asarray(x)
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(jnp.asarray(x), approximate=approximate)


def sigmoid(x, name=None):
    return jax.nn.sigmoid(jnp.asarray(x))


def logsigmoid(x, name=None):
    return jax.nn.log_sigmoid(jnp.asarray(x))


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return jnp.clip(slope * jnp.asarray(x) + offset, 0.0, 1.0)


def tanh(x, name=None):
    return jnp.tanh(jnp.asarray(x))


def tanh_shrink(x, name=None):
    x = jnp.asarray(x)
    return x - jnp.tanh(x)


def softplus(x, name=None):
    return jax.nn.softplus(jnp.asarray(x))


def softsign(x, name=None):
    x = jnp.asarray(x)
    return x / (1 + jnp.abs(x))


def softshrink(x, alpha=0.5, name=None):
    x = jnp.asarray(x)
    return jnp.where(x > alpha, x - alpha, jnp.where(x < -alpha, x + alpha, 0.0))


def hard_shrink(x, threshold=0.5, name=None):
    x = jnp.asarray(x)
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return jnp.clip(jnp.asarray(x), t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    x = jnp.clip(jnp.asarray(x), -threshold, threshold)
    return jnp.log1p(jnp.exp(x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * jnp.asarray(x))


def swish(x, beta=1.0, name=None):
    x = jnp.asarray(x)
    return x * jax.nn.sigmoid(beta * x)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    x = jnp.asarray(x)
    return x * jnp.clip(x + offset, 0, threshold) / scale


def thresholded_relu(x, threshold=1.0, name=None):
    x = jnp.asarray(x)
    return jnp.where(x > threshold, x, 0.0)


def mish(x, name=None):
    x = jnp.asarray(x)
    return x * jnp.tanh(jax.nn.softplus(x))


def maxout(x, groups, axis=1, name=None):
    """maxout_op.cc parity: channel axis split into groups, max over group."""
    x = jnp.asarray(x)
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def softmax(x, axis=-1, name=None):
    return jax.nn.softmax(jnp.asarray(x), axis=axis)


def log_softmax(x, axis=-1, name=None):
    return jax.nn.log_softmax(jnp.asarray(x), axis=axis)
