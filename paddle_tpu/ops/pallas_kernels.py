"""Pallas TPU kernels for the hot ops.

The reference hand-writes CUDA for its hot paths (operators/math/,
operators/jit/ xbyak codegen, fused_* ops — SURVEY §2.4); the TPU-native
equivalent is Pallas (Mosaic) kernels sitting behind the same functional op
surface. XLA already fuses the easy elementwise chains; these kernels cover
what fusion can't express:

- flash_attention — blockwise online-softmax attention; the [S, S] score
  matrix never exists in HBM (the reference materialises scores in
  operators/math/ softmax + matmul calls). Forward is a Pallas kernel;
  backward is the standard blockwise recompute formulated for XLA.
- fused_layer_norm — one VMEM pass for mean/var/normalise/affine.
- softmax_cross_entropy — fused max/logsumexp/pick in one pass over the
  vocab axis (the reference's softmax_with_cross_entropy fused op,
  operators/softmax_with_cross_entropy_op.cc).

All three are registered in the Pallas kernel registry
(ops/pallas/registry.py) — selection between the Pallas body and a
stock-jnp reference is the registry's job (`FLAGS_use_pallas_kernels`,
`PADDLE_TPU_PALLAS`). The public entry points keep their historical
`interpret=` escape hatch: passing an explicit bool bypasses the
registry and forces the Pallas body with that interpreter setting
(tests pin kernel behavior this way); `interpret=None` defers to the
registry's platform-based selection.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import registry as _registry

try:  # pltpu import fails on some CPU-only builds; interpret mode works
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["flash_attention", "fused_layer_norm", "softmax_cross_entropy"]

_NEG_INF = -1e30


def _auto_interpret(interpret):
    if interpret is not None:
        return interpret
    # registry.platform() is the per-process cached probe — jax.devices()
    # must not be re-walked on every kernel invocation (hot path)
    return _registry.platform() == "cpu"


def _vmem_spec(*args, **kwargs):
    if _HAS_PLTPU:
        kwargs.setdefault("memory_space", pltpu.VMEM)
    return pl.BlockSpec(*args, **kwargs)


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

def _masked_scores(qs, k_blk, b_blk, q0, k0, causal):
    """Scaled scores for one (q-block, k-block) tile: qs is pre-scaled
    [bq, d], k_blk [bk, d], b_blk [bk] additive key bias; q0/k0 are the
    tile's absolute row/col offsets for the causal mask. Shared by the
    forward and both backward kernels so masking/bias can never drift
    between them."""
    s = jax.lax.dot_general(
        qs, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [bq, bk]
    s = s + b_blk[None, :]
    if causal:
        bq, bk = s.shape
        qi = q0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        ki = k0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(ki <= qi, s, _NEG_INF)
    return s


def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *,
                      sm_scale, block_k, causal, seq_len, block_q):
    """One (batch, head, q-block) cell: stream K/V blocks, keep running
    (max, sum, acc) — the online-softmax recurrence."""
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale        # [bq, d]
    bq, d = q.shape
    nk = seq_len // block_k
    iq = pl.program_id(2)

    def body(jk, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, 0, pl.ds(jk * block_k, block_k), :] \
            .astype(jnp.float32)                           # [bk, d]
        v_blk = v_ref[0, 0, pl.ds(jk * block_k, block_k), :] \
            .astype(jnp.float32)
        b_blk = bias_ref[0, 0, pl.ds(jk * block_k, block_k)] \
            .astype(jnp.float32)                           # [bk]
        s = _masked_scores(q, k_blk, b_blk, iq * block_q, jk * block_k,
                           causal)
        m_cur = jnp.max(s, axis=-1)                        # [bq]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])                    # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # stop at the diagonal: K blocks entirely above it are fully
        # masked — skipping them halves causal attention FLOPs
        nk_eff = jnp.minimum(
            nk, ((iq + 1) * block_q + block_k - 1) // block_k)
    else:
        nk_eff = nk
    m, l, acc = lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, :, 0] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k,
               interpret):
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b, h, s // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, block_k=block_k,
        causal=causal, seq_len=s, block_q=block_q)
    # Mosaic tiling constraint: a block's last two dims must be
    # (8k, 128k)-divisible or equal to the array's — so the per-batch
    # bias rides as [B, 1, S] (block (1, 1, S)) and lse as [B, H, S, 1]
    # (block (1, 1, bq, 1)), both satisfying the "equal dimension" rule.
    o, lse4 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((1, 1, block_q, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            _vmem_spec((1, 1, s, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, s, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, s), lambda ib, ih, iq: (ib, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((1, 1, block_q, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            _vmem_spec((1, 1, block_q, 1),
                       lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, bias[:, None, :])
    return o, lse4[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention(q, k, v, bias, sm_scale, causal, block_q, block_k,
                     interpret):
    o, _ = _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k,
                      interpret)
    return o


def _flash_attention_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k,
                         interpret):
    o, lse = _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k,
                        interpret)
    return o, (q, k, v, bias, o, lse)


def _flash_bwd_dkdv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                           bias_ref, dk_ref, dv_ref, dbh_ref, *,
                           sm_scale, block_q, block_k, causal, seq_len):
    """One (batch, head, k-block) cell: stream Q/dO blocks, recompute the
    probabilities from the saved logsumexp, accumulate dK/dV (and the
    per-head key-bias grad) in VMEM — scores never touch HBM."""
    k_blk = k_ref[0, 0].astype(jnp.float32)                # [bk, d]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    b_blk = bias_ref[0, 0].astype(jnp.float32)             # [bk]
    bk, d = k_blk.shape
    ik = pl.program_id(2)
    nq = seq_len // block_q

    def body(jq, carry):
        dk_acc, dv_acc, db_acc = carry
        qs = q_ref[0, 0, pl.ds(jq * block_q, block_q), :] \
            .astype(jnp.float32) * sm_scale                # [bq, d]
        do_blk = do_ref[0, 0, pl.ds(jq * block_q, block_q), :] \
            .astype(jnp.float32)
        lse_blk = lse_ref[0, 0, pl.ds(jq * block_q, block_q), 0]
        d_blk = delta_ref[0, 0, pl.ds(jq * block_q, block_q), 0]
        s = _masked_scores(qs, k_blk, b_blk, jq * block_q, ik * block_k,
                           causal)
        p = jnp.exp(s - lse_blk[:, None])                  # [bq, bk]
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        dz = p * (dp - d_blk[:, None])
        dk_acc = dk_acc + jax.lax.dot_general(
            dz, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        db_acc = db_acc + jnp.sum(dz, axis=0)              # [bk]
        return dk_acc, dv_acc, db_acc

    # causal: q-blocks strictly above the diagonal see only masked scores
    jq0 = (ik * block_k) // block_q if causal else 0
    dk, dv, db = lax.fori_loop(
        jq0, nq, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32),
         jnp.zeros((bk,), jnp.float32)))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)
    dbh_ref[0, 0, :, 0] = db


def _flash_bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                         bias_ref, dq_ref, *,
                         sm_scale, block_q, block_k, causal, seq_len):
    """One (batch, head, q-block) cell: stream K/V blocks, accumulate dQ."""
    qs = q_ref[0, 0].astype(jnp.float32) * sm_scale        # [bq, d]
    do_blk = do_ref[0, 0].astype(jnp.float32)
    lse_blk = lse_ref[0, 0, :, 0]                          # [bq]
    d_blk = delta_ref[0, 0, :, 0]
    bq, d = qs.shape
    iq = pl.program_id(2)
    nk = seq_len // block_k

    def body(jk, dq_acc):
        k_blk = k_ref[0, 0, pl.ds(jk * block_k, block_k), :] \
            .astype(jnp.float32)                           # [bk, d]
        v_blk = v_ref[0, 0, pl.ds(jk * block_k, block_k), :] \
            .astype(jnp.float32)
        b_blk = bias_ref[0, 0, pl.ds(jk * block_k, block_k)] \
            .astype(jnp.float32)
        s = _masked_scores(qs, k_blk, b_blk, iq * block_q, jk * block_k,
                           causal)
        p = jnp.exp(s - lse_blk[:, None])
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dz = p * (dp - d_blk[:, None])
        return dq_acc + jax.lax.dot_general(
            dz, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        nk_eff = jnp.minimum(
            nk, ((iq + 1) * block_q + block_k - 1) // block_k)
    else:
        nk_eff = nk
    dq = lax.fori_loop(0, nk_eff, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_attention_bwd(sm_scale, causal, block_q, block_k, interpret,
                         res, do):
    """Blockwise recompute backward as two Pallas kernels (the standard
    flash split): dK/dV gridded over key blocks, dQ over query blocks.
    Live memory stays O(block · S); the [S, S] score matrix never exists."""
    q, k, v, bias, o, lse = res
    b, h, s, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1,
                    keepdims=True)                         # [B,H,S,1]
    lse4 = lse[..., None]                                  # [B,H,S,1]
    bias3 = bias[:, None, :]                               # [B,1,S]
    kernel_kv = functools.partial(
        _flash_bwd_dkdv_kernel, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, causal=causal, seq_len=s)
    dk, dv, dbh = pl.pallas_call(
        kernel_kv,
        grid=(b, h, s // block_k),
        in_specs=[
            _vmem_spec((1, 1, s, d), lambda ib, ih, ik: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, s, d), lambda ib, ih, ik: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, s, 1), lambda ib, ih, ik: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, s, 1), lambda ib, ih, ik: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, block_k, d),
                       lambda ib, ih, ik: (ib, ih, ik, 0)),
            _vmem_spec((1, 1, block_k, d),
                       lambda ib, ih, ik: (ib, ih, ik, 0)),
            _vmem_spec((1, 1, block_k), lambda ib, ih, ik: (ib, 0, ik)),
        ],
        out_specs=[
            _vmem_spec((1, 1, block_k, d),
                       lambda ib, ih, ik: (ib, ih, ik, 0)),
            _vmem_spec((1, 1, block_k, d),
                       lambda ib, ih, ik: (ib, ih, ik, 0)),
            _vmem_spec((1, 1, block_k, 1),
                       lambda ib, ih, ik: (ib, ih, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, do, lse4, delta, k, v, bias3)
    kernel_q = functools.partial(
        _flash_bwd_dq_kernel, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, causal=causal, seq_len=s)
    dq = pl.pallas_call(
        kernel_q,
        grid=(b, h, s // block_q),
        in_specs=[
            _vmem_spec((1, 1, block_q, d),
                       lambda ib, ih, iq: (ib, ih, iq, 0)),
            _vmem_spec((1, 1, block_q, d),
                       lambda ib, ih, iq: (ib, ih, iq, 0)),
            _vmem_spec((1, 1, block_q, 1),
                       lambda ib, ih, iq: (ib, ih, iq, 0)),
            _vmem_spec((1, 1, block_q, 1),
                       lambda ib, ih, iq: (ib, ih, iq, 0)),
            _vmem_spec((1, 1, s, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, s, d), lambda ib, ih, iq: (ib, ih, 0, 0)),
            _vmem_spec((1, 1, s), lambda ib, ih, iq: (ib, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((1, 1, block_q, d),
                       lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        interpret=interpret,
    )(q, do, lse4, delta, k, v, bias3)[0]
    dbias = jnp.sum(dbh[..., 0], axis=1)                   # [B,S]
    return dq, dk, dv, dbias.astype(bias.dtype)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def _dense_attention_reference(q, k, v, bias=None, causal=False,
                               sm_scale=None, block_q=512, block_k=512,
                               interpret=None):
    """Stock-jnp attention (scores materialized): the semantic reference
    the flash kernel is pinned against. block_q/block_k/interpret are
    accepted (and ignored) so both bodies share one signature."""
    q = jnp.asarray(q)
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    b, h, s, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    qs = q.astype(jnp.float32) * sm_scale
    scores = jnp.einsum("bhqd,bhkd->bhqk", qs, k.astype(jnp.float32))
    if bias is not None:
        bias = jnp.asarray(bias, jnp.float32).reshape(b, s)
        scores = scores + bias[:, None, None, :]
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, (s, s), 0)
        ki = lax.broadcasted_iota(jnp.int32, (s, s), 1)
        scores = jnp.where(ki <= qi, scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_attention_pallas(q, k, v, bias=None, causal=False,
                            sm_scale=None, block_q=512, block_k=512,
                            interpret=False):
    """Pallas body: block-size resolution, 128-lane padding, kernel call."""
    q = jnp.asarray(q)
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    b, h, s, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if bias is None:
        bias = jnp.zeros((b, s), jnp.float32)
    bias = jnp.asarray(bias, jnp.float32).reshape(b, s)
    if s <= max(block_q, block_k):
        # short sequences: one block each way — but still pad to the
        # 128-lane grain so Mosaic never gets an unaligned whole-array
        # block (e.g. S=300 bf16 must not reach the kernel unpadded)
        pad = (-s) % 128
        block_q = block_k = s + pad
    else:
        # pad only to the 128-lane grain, then shrink each block to the
        # largest power-of-two (>=128) dividing the padded length — a
        # S=640 input runs at block 128 with zero pad instead of paying
        # ~60% masked pad work at block 512
        pad = (-s) % 128
        sp = s + pad
        while block_q > 128 and sp % block_q:
            block_q //= 2
        while block_k > 128 and sp % block_k:
            block_k //= 2
        if sp % block_q or sp % block_k:
            # non-power-of-two caller blocks: fall back to lcm padding
            # (the grid floors by block_q and the kv loops by block_k —
            # S must be a multiple of BOTH or trailing keys are dropped)
            pad = (-s) % math.lcm(block_q, block_k)
    if pad:
        zf = ((0, 0), (0, 0), (0, pad), (0, 0))
        q = jnp.pad(q, zf)
        k = jnp.pad(k, zf)
        v = jnp.pad(v, zf)
        bias = jnp.pad(bias, ((0, 0), (0, pad)),
                       constant_values=_NEG_INF)
    out = _flash_attention(q, k, v, bias, float(sm_scale), bool(causal),
                           int(block_q), int(block_k), bool(interpret))
    if pad:
        out = out[:, :, :s, :]
    return out


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=None,
                    block_q=512, block_k=512, interpret=None):
    """Blockwise (flash) attention.

    q, k, v: [B, H, S, D]. bias: optional [B, S] additive key bias
    (e.g. key-padding mask as 0 / -inf). Returns [B, H, S, D] in q.dtype.
    Sequence is padded to the block size internally (padded keys masked).

    Body selection is the registry's (`FLAGS_use_pallas_kernels`); an
    explicit ``interpret=`` bool forces the Pallas body.
    """
    kw = dict(bias=bias, causal=causal, sm_scale=sm_scale,
              block_q=block_q, block_k=block_k)
    if interpret is not None:
        return _flash_attention_pallas(q, k, v, interpret=bool(interpret),
                                       **kw)
    return _registry.dispatch("flash_attention", q, k, v, **kw)


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + b_ref[:].astype(
        jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:, 0] = mu[:, 0]
    rstd_ref[:, 0] = rstd[:, 0]


def _ln_fwd(x2, g, b, eps, block_n, interpret):
    n, hdim = x2.shape
    block_n = min(block_n, n)
    grid = (pl.cdiv(n, block_n),)
    y, mu, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            _vmem_spec((block_n, hdim), lambda i: (i, 0)),
            _vmem_spec((hdim,), lambda i: (0,)),
            _vmem_spec((hdim,), lambda i: (0,)),
        ],
        out_specs=[
            _vmem_spec((block_n, hdim), lambda i: (i, 0)),
            # stats ride as [n, 1] (bn, 1) blocks: Mosaic's layout for a
            # bare f32[n] is lane-tiled T(1024) and rejects (bn,) blocks
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2.shape, x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, g, b)
    return y, mu[:, 0], rstd[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_layer_norm(x2, g, b, eps, block_n, interpret):
    y, _, _ = _ln_fwd(x2, g, b, eps, block_n, interpret)
    return y


def _fused_ln_fwd(x2, g, b, eps, block_n, interpret):
    y, mu, rstd = _ln_fwd(x2, g, b, eps, block_n, interpret)
    return y, (x2, g, mu, rstd)


def _fused_ln_bwd(eps, block_n, interpret, res, dy):
    x2, g, mu, rstd = res
    x32 = x2.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = (x32 - mu[:, None]) * rstd[:, None]
    gf = g.astype(jnp.float32)
    dg = jnp.sum(dy32 * xhat, axis=0)
    db = jnp.sum(dy32, axis=0)
    wdy = dy32 * gf
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd[:, None]
    return dx.astype(x2.dtype), dg.astype(g.dtype), db.astype(g.dtype)


_fused_layer_norm.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def _layer_norm_reference(x, gamma, beta, eps=1e-12, block_n=256,
                          interpret=None):
    """Stock-jnp layer norm, bit-identical to models/bert._layer_norm's
    historical inline math (fp32 stats, x.dtype out)."""
    x = jnp.asarray(x)
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps) \
        * jnp.asarray(gamma).astype(jnp.float32) \
        + jnp.asarray(beta).astype(jnp.float32)
    return y.astype(x.dtype)


def _fused_layer_norm_pallas(x, gamma, beta, eps=1e-12, block_n=256,
                             interpret=False):
    x = jnp.asarray(x)
    shape = x.shape
    hdim = shape[-1]
    x2 = x.reshape(-1, hdim)
    n = x2.shape[0]
    block_n = min(block_n, n)
    pad = (-n) % block_n
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    y = _fused_layer_norm(x2, jnp.asarray(gamma), jnp.asarray(beta),
                          float(eps), int(block_n), bool(interpret))
    if pad:
        y = y[:n]
    return y.reshape(shape)


def fused_layer_norm(x, gamma, beta, eps=1e-12, block_n=256,
                     interpret=None):
    """LayerNorm over the last axis in a single VMEM pass.

    x: [..., H]; gamma/beta: [H]. Stats in fp32, output in x.dtype
    (parity: operators/layer_norm_op.cc; jit/ layernorm kernel).
    Body selection is the registry's; explicit ``interpret=`` forces the
    Pallas body.
    """
    if interpret is not None:
        return _fused_layer_norm_pallas(x, gamma, beta, eps=eps,
                                        block_n=block_n,
                                        interpret=bool(interpret))
    return _registry.dispatch("fused_layer_norm", x, gamma, beta, eps=eps,
                              block_n=block_n)


# ---------------------------------------------------------------------------
# fused softmax cross-entropy
# ---------------------------------------------------------------------------

def _xent_kernel(logits_ref, labels_ref, loss_ref, lse_ref):
    x = logits_ref[:].astype(jnp.float32)                  # [bn, V]
    lab = labels_ref[:, 0]                                 # [bn]
    m = jnp.max(x, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m[:, None]), axis=-1))
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.sum(jnp.where(cols == lab[:, None], x, 0.0), axis=-1)
    loss_ref[:, 0] = lse - picked
    lse_ref[:, 0] = lse


def _xent_fwd_call(logits2, labels1, block_n, interpret):
    n, v = logits2.shape
    block_n = min(block_n, n)
    grid = (pl.cdiv(n, block_n),)
    # 1-D vectors ride as [n, 1] blocks (bn, 1): Mosaic's layout for a
    # bare s32/f32[n] is lane-tiled T(1024) and rejects (bn,) blocks
    loss, lse = pl.pallas_call(
        _xent_kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((block_n, v), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits2, labels1[:, None])
    return loss[:, 0], lse[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _softmax_xent(logits2, labels1, block_n, interpret):
    loss, _ = _xent_fwd_call(logits2, labels1, block_n, interpret)
    return loss


def _softmax_xent_fwd(logits2, labels1, block_n, interpret):
    loss, lse = _xent_fwd_call(logits2, labels1, block_n, interpret)
    return loss, (logits2, labels1, lse)


def _softmax_xent_bwd(block_n, interpret, res, dloss):
    logits2, labels1, lse = res
    x = logits2.astype(jnp.float32)
    p = jnp.exp(x - lse[:, None])
    onehot = jax.nn.one_hot(labels1, x.shape[-1], dtype=jnp.float32)
    dx = (p - onehot) * dloss[:, None]
    return dx.astype(logits2.dtype), None


_softmax_xent.defvjp(_softmax_xent_fwd, _softmax_xent_bwd)


def _xent_reference(logits, labels, block_n=128, interpret=None):
    """Stock-jnp softmax cross-entropy (fp32 max/logsumexp/pick)."""
    logits = jnp.asarray(logits)
    labels = jnp.asarray(labels, jnp.int32)
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m[..., None]), axis=-1))
    picked = jnp.take_along_axis(x, labels[..., None],
                                 axis=-1)[..., 0]
    return lse - picked


def _softmax_xent_pallas(logits, labels, block_n=128, interpret=False):
    logits = jnp.asarray(logits)
    labels = jnp.asarray(labels, jnp.int32)
    v = logits.shape[-1]
    lead = logits.shape[:-1]
    logits2 = logits.reshape(-1, v)
    labels1 = labels.reshape(-1)
    n = logits2.shape[0]
    # cap the row block so one (block_n, V) fp32 tile (double-buffered)
    # stays well under the ~16MB VMEM budget even at LM vocab sizes
    vmem_rows = max(8, (4 << 20) // max(4 * v, 1) // 8 * 8)
    block_n = min(block_n, vmem_rows, n)
    pad = (-n) % block_n
    if pad:
        logits2 = jnp.pad(logits2, ((0, pad), (0, 0)))
        labels1 = jnp.pad(labels1, (0, pad))
    loss = _softmax_xent(logits2, labels1, int(block_n), bool(interpret))
    if pad:
        loss = loss[:n]
    return loss.reshape(lead)


def softmax_cross_entropy(logits, labels, block_n=128, interpret=None):
    """Fused per-example softmax cross-entropy.

    logits: [..., V]; labels: [...] int. Returns [...] fp32 losses.
    One pass computes max, logsumexp, and the label pick (parity:
    operators/softmax_with_cross_entropy_op.cc fused op). Body selection
    is the registry's; explicit ``interpret=`` forces the Pallas body.
    """
    if interpret is not None:
        return _softmax_xent_pallas(logits, labels, block_n=block_n,
                                    interpret=bool(interpret))
    return _registry.dispatch("softmax_cross_entropy", logits, labels,
                              block_n=block_n)


_registry.register_kernel(
    "flash_attention", _dense_attention_reference, _flash_attention_pallas,
    doc="blockwise online-softmax attention; [S,S] scores never in HBM")
_registry.register_kernel(
    "fused_layer_norm", _layer_norm_reference, _fused_layer_norm_pallas,
    doc="one-VMEM-pass layer norm (fp32 stats)")
_registry.register_kernel(
    "softmax_cross_entropy", _xent_reference, _softmax_xent_pallas,
    doc="fused max/logsumexp/pick over the vocab axis")
