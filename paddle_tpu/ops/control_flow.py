"""Structured control flow.

Parity targets: operators/controlflow/ (while_op.cc,
conditional_block_op.cc), layers/control_flow.py (While:630, IfElse:1564,
Switch:1436, StaticRNN:280, DynamicRNN:1700).

The reference interprets sub-blocks op-by-op under While/cond; under XLA
control flow must be structured primitives traced once
(lax.while_loop/cond/scan — no data-dependent Python control flow inside
jit). DynamicRNN/StaticRNN map onto `scan` with masking for ragged
sequences.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.lod import RaggedBatch

__all__ = [
    "cond", "case", "switch_case", "while_loop", "scan", "static_rnn",
    "dynamic_rnn",
]


def cond(pred, true_fn, false_fn, operands=()):
    """conditional_block / layers.cond parity."""
    return lax.cond(pred, lambda ops: true_fn(*ops),
                    lambda ops: false_fn(*ops), operands)


def case(pred_fn_pairs, default=None):
    """layers.case parity: first true predicate wins."""
    preds = [p for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is None:
        default = fns[-1]
        preds, fns = preds[:-1], fns[:-1]

    def build(i):
        if i == len(preds):
            return default()
        return lax.cond(preds[i], fns[i], lambda: build(i + 1))

    return build(0)


def switch_case(branch_index, branch_fns, default=None):
    """layers.switch_case parity."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map branch_index onto dense positions
        idx = jnp.sum(jnp.stack(
            [jnp.where(branch_index == k, i, 0) for i, k in enumerate(keys)]))
        matched = jnp.any(jnp.stack(
            [branch_index == k for k in keys]))
        if default is not None:
            fns = fns + [default]
            idx = jnp.where(matched, idx, len(fns) - 1)
        return lax.switch(idx, fns)
    fns = list(branch_fns)
    if default is not None:
        fns.append(default)
        branch_index = jnp.clip(branch_index, 0, len(fns) - 1)
    return lax.switch(branch_index, fns)


def while_loop(cond_fn, body_fn, loop_vars):
    """layers.while_loop parity over lax.while_loop."""
    single = not isinstance(loop_vars, (tuple, list))
    vars_ = (loop_vars,) if single else tuple(loop_vars)

    def body(vs):
        out = body_fn(*vs)
        return (out,) if single else tuple(out)

    out = lax.while_loop(lambda vs: cond_fn(*vs), body, vars_)
    return out[0] if single else list(out)


def scan(f, init, xs, reverse=False):
    return lax.scan(f, init, xs, reverse=reverse)


def static_rnn(step_fn, inputs, initial_state):
    """StaticRNN parity: inputs [B, T, ...] unrolled via scan (time major
    internally). step_fn(state, x_t) -> (new_state, out_t)."""
    xs = jnp.swapaxes(inputs, 0, 1)  # [T, B, ...]
    final, outs = lax.scan(step_fn, initial_state, xs)
    return final, jnp.swapaxes(outs, 0, 1)


def dynamic_rnn(step_fn, inputs, initial_state):
    """DynamicRNN parity over ragged input: state freezes past each row's
    length (so final state == state at the last valid step, matching the
    reference's shrink-memory semantics,
    ref: operators/shrink_rnn_memory_op.cc)."""
    if not isinstance(inputs, RaggedBatch):
        raise TypeError("dynamic_rnn expects a RaggedBatch")
    data, lengths = inputs.data, inputs.lengths
    xs = jnp.swapaxes(data, 0, 1)  # [T, B, ...]
    tsteps = data.shape[1]

    def body(carry, inp):
        t, state = carry
        x_t = inp
        new_state, out_t = step_fn(state, x_t)
        alive = (t < lengths)

        def sel(new, old):
            m = alive.reshape((-1,) + (1,) * (new.ndim - 1))
            return jnp.where(m, new, old)

        state = jax.tree.map(sel, new_state, state)
        out_t = jax.tree.map(
            lambda o: jnp.where(
                alive.reshape((-1,) + (1,) * (o.ndim - 1)), o, 0), out_t)
        return (t + 1, state), out_t

    (_, final), outs = lax.scan(body, (jnp.int32(0), initial_state), xs)
    outs = jax.tree.map(lambda o: jnp.swapaxes(o, 0, 1), outs)
    return final, RaggedBatch(outs, lengths)
