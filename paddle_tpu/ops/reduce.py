"""Reduction ops.

Parity targets: operators/reduce_ops/ (reduce_sum/mean/max/min/prod/all/
any), mean_op.cc, squared_l2_norm_op.cc, l1_norm_op.cc, norm_op.cc,
mean_iou_op.cc, frobenius (absent).
"""

import jax.numpy as jnp
from jax import lax

__all__ = [
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_all", "reduce_any", "mean", "squared_l2_norm", "l1_norm",
    "l2_normalize", "norm", "mean_iou",
]


def _axes(dim, keep_dim):
    if dim is None:
        return None, keep_dim
    if isinstance(dim, int):
        dim = (dim,)
    return tuple(dim), keep_dim


def _reduce(fn):
    def op(input, dim=None, keep_dim=False, name=None):
        axes, keep = _axes(dim, keep_dim)
        return fn(jnp.asarray(input), axis=axes, keepdims=keep)
    return op


reduce_sum = _reduce(jnp.sum)
reduce_mean = _reduce(jnp.mean)
reduce_max = _reduce(jnp.max)
reduce_min = _reduce(jnp.min)
reduce_prod = _reduce(jnp.prod)
reduce_all = _reduce(jnp.all)
reduce_any = _reduce(jnp.any)


def mean(x, name=None):
    """mean_op.cc parity: scalar mean of all elements."""
    return jnp.mean(jnp.asarray(x))


def squared_l2_norm(x, name=None):
    return jnp.sum(jnp.square(jnp.asarray(x)))


def l1_norm(x, name=None):
    return jnp.sum(jnp.abs(jnp.asarray(x)))


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    x = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return x / jnp.maximum(norm, epsilon)


def norm(x, axis=-1, epsilon=1e-10, name=None):
    """norm_op.cc parity: returns normalized x (out) like the op's Out."""
    x = jnp.asarray(x)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + epsilon)
    return x / n


def mean_iou(input, label, num_classes):
    """mean_iou_op.cc parity: (miou, out_wrong, out_correct)."""
    pred = jnp.asarray(input).reshape(-1)
    lab = jnp.asarray(label).reshape(-1)
    cm = jnp.zeros((num_classes, num_classes), jnp.int64)
    idx = lab * num_classes + pred
    cm = cm.reshape(-1).at[idx].add(1).reshape(num_classes, num_classes)
    inter = jnp.diag(cm).astype(jnp.float32)
    union = (jnp.sum(cm, 0) + jnp.sum(cm, 1)).astype(jnp.float32) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    wrong = jnp.sum(cm, 1).astype(jnp.int64) - jnp.diag(cm)
    correct = jnp.diag(cm)
    return miou, wrong, correct
