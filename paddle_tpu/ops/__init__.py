"""Functional op library.

TPU-native analog of paddle/fluid/operators (475 REGISTER_OPERATOR sites,
ref: SURVEY §2.4). Ops here are pure functions over jax arrays that lower
to XLA HLO; there is no (place × dtype × layout) kernel registry — XLA's
compiler plays that role (ref: framework/operator.cc:986 ChooseKernel).
Gradients come from JAX autodiff instead of per-op GradOpDescMakers
(ref: framework/grad_op_desc_maker.h).

Naming follows the reference op names so `fluid.layers.*` parity is a thin
re-export (see paddle_tpu/layers.py).
"""

from paddle_tpu.ops.math import *            # noqa: F401,F403
from paddle_tpu.ops.activation import *      # noqa: F401,F403
from paddle_tpu.ops.nn import *              # noqa: F401,F403
from paddle_tpu.ops.loss import *            # noqa: F401,F403
from paddle_tpu.ops.reduce import *          # noqa: F401,F403
from paddle_tpu.ops.tensor_ops import *      # noqa: F401,F403
from paddle_tpu.ops.sequence import *        # noqa: F401,F403
from paddle_tpu.ops.random_ops import *      # noqa: F401,F403
from paddle_tpu.ops.control_flow import *    # noqa: F401,F403
from paddle_tpu.ops.metric_ops import *      # noqa: F401,F403
from paddle_tpu.ops.rnn import *             # noqa: F401,F403
from paddle_tpu.ops.crf import *             # noqa: F401,F403
from paddle_tpu.ops.ctc import *             # noqa: F401,F403
from paddle_tpu.ops.detection import *       # noqa: F401,F403
from paddle_tpu.ops.quantize import *        # noqa: F401,F403
from paddle_tpu.ops.misc import *            # noqa: F401,F403
from paddle_tpu.ops.aliases import *         # noqa: F401,F403
from paddle_tpu.ops.tensor_array import *    # noqa: F401,F403
from paddle_tpu.ops.selected_rows import *   # noqa: F401,F403
from paddle_tpu.ops import pallas_kernels    # noqa: F401  (module: perf
# primitives — flash_attention, fused_layer_norm, softmax_cross_entropy —
# not part of the fluid.layers parity surface)
