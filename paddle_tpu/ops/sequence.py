"""Sequence ops over RaggedBatch (dense padding + lengths).

Parity targets: operators/sequence_ops/ (sequence_pool, sequence_softmax,
sequence_expand, sequence_pad/unpad, sequence_concat, sequence_reverse,
sequence_mask, sequence_slice, sequence_erase, sequence_enumerate,
sequence_first/last_step) — the reference implements these over
offset-based LoD (ref: lod_tensor.h:229); here every op is a masked dense
computation with static shapes, which is what XLA needs to tile onto the
VPU/MXU (ref: SURVEY §5.7 design note).

Sequence inputs are `RaggedBatch` (data [B, T, ...], lengths [B]) or a
(data, lengths) pair.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import RaggedBatch, sequence_mask

__all__ = [
    "sequence_mask", "sequence_pool", "sequence_softmax", "sequence_expand",
    "sequence_pad", "sequence_unpad", "sequence_concat", "sequence_reverse",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_scatter", "sequence_expand_as", "sequence_conv",
    "sequence_reshape", "sequence_enumerate", "sequence_erase",
]


def _unpack(x):
    if isinstance(x, RaggedBatch):
        return x.data, x.lengths
    if isinstance(x, (tuple, list)) and len(x) == 2:
        return jnp.asarray(x[0]), jnp.asarray(x[1])
    raise TypeError("sequence op needs RaggedBatch or (data, lengths)")


def _mask(data, lengths):
    m = sequence_mask(lengths, maxlen=data.shape[1], dtype=data.dtype)
    return m.reshape(m.shape + (1,) * (data.ndim - 2))


def sequence_pool(input, pool_type="sum", name=None):
    """sequence_pool_op parity: reduce each sequence over time.
    Returns [B, ...]."""
    data, lengths = _unpack(input)
    m = _mask(data, lengths)
    pt = pool_type.lower()
    denom = jnp.maximum(lengths, 1).astype(data.dtype)
    denom = denom.reshape((-1,) + (1,) * (data.ndim - 2))
    if pt == "sum":
        return jnp.sum(data * m, axis=1)
    if pt == "average" or pt == "mean":
        return jnp.sum(data * m, axis=1) / denom
    if pt == "sqrt":
        return jnp.sum(data * m, axis=1) / jnp.sqrt(denom)
    if pt == "max":
        neg = jnp.finfo(data.dtype).min if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return jnp.max(jnp.where(m > 0, data, neg), axis=1)
    if pt == "first":
        return data[:, 0]
    if pt == "last":
        return sequence_last_step(input)
    raise ValueError(f"unknown pool_type {pool_type}")


def sequence_first_step(input, name=None):
    data, _ = _unpack(input)
    return data[:, 0]


def sequence_last_step(input, name=None):
    data, lengths = _unpack(input)
    idx = jnp.maximum(lengths - 1, 0)
    return jnp.take_along_axis(
        data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)).astype(jnp.int32),
        axis=1)[:, 0]


def sequence_softmax(input, name=None):
    """sequence_softmax_op parity: softmax within each sequence, padding
    excluded."""
    data, lengths = _unpack(input)
    m = _mask(data, lengths)
    neg = jnp.finfo(data.dtype).min
    logits = jnp.where(m > 0, data, neg)
    out = jax.nn.softmax(logits, axis=1)
    return RaggedBatch(out * m, lengths)


def sequence_expand(x, y, ref_level=-1, name=None):
    """sequence_expand_op parity, dense form: repeat each row of x to match
    y's per-sequence lengths. x: [B, ...] (one entry per sequence),
    y: RaggedBatch giving the target lengths. Returns RaggedBatch
    [B, T, ...] with x broadcast across time."""
    ydata, ylen = _unpack(y)
    xb = jnp.asarray(x)
    out = jnp.broadcast_to(xb[:, None],
                           (xb.shape[0], ydata.shape[1]) + xb.shape[1:])
    return RaggedBatch(out * _mask(out, ylen), ylen)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value=0.0, maxlen=None, name=None):
    """sequence_pad_op parity: RaggedBatch is already padded; re-pad to
    maxlen and return (data, lengths) like the reference's (Out, Length)."""
    data, lengths = _unpack(x)
    if maxlen is not None and maxlen != data.shape[1]:
        if maxlen > data.shape[1]:
            cfg = [(0, 0), (0, maxlen - data.shape[1])] + [(0, 0)] * (data.ndim - 2)
            data = jnp.pad(data, cfg, constant_values=pad_value)
        else:
            data = data[:, :maxlen]
    m = _mask(data, lengths)
    data = jnp.where(m > 0, data, pad_value)
    return data, lengths


def sequence_unpad(x, length, name=None):
    """sequence_unpad_op parity: wrap dense (x, length) as RaggedBatch."""
    return RaggedBatch(jnp.asarray(x), jnp.asarray(length))


def sequence_concat(input, name=None):
    """sequence_concat_op parity: concat along time per batch row."""
    datas, lens = zip(*[_unpack(t) for t in input])
    total = sum(d.shape[1] for d in datas)
    b = datas[0].shape[0]
    tail = datas[0].shape[2:]
    out = jnp.zeros((b, total) + tail, datas[0].dtype)
    out_len = sum(lens)
    # place each segment at the running offset per row via scatter of
    # time indices
    offs = jnp.zeros((b,), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(total, dtype=jnp.int32), (b, total))
    for d, l in zip(datas, lens):
        t = d.shape[1]
        tpos = jnp.arange(t, dtype=jnp.int32)[None, :] + offs[:, None]
        valid = jnp.arange(t, dtype=jnp.int32)[None, :] < l[:, None]
        onehot = (pos[:, :, None] == tpos[:, None, :]) & valid[:, None, :]
        upd = jnp.einsum("bts,bs...->bt...", onehot.astype(d.dtype), d)
        out = out + upd
        offs = offs + l
    return RaggedBatch(out, out_len)


def sequence_reverse(x, name=None):
    """sequence_reverse_op parity: reverse valid prefix of each row."""
    data, lengths = _unpack(x)
    t = data.shape[1]
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    src = lengths[:, None] - 1 - pos
    src = jnp.where(src >= 0, src, pos)  # padding stays in place
    return RaggedBatch(
        jnp.take_along_axis(
            data, src.reshape(src.shape + (1,) * (data.ndim - 2)), axis=1),
        lengths)


def sequence_slice(input, offset, length, name=None):
    """sequence_slice_op parity: per-sequence [offset, offset+length)."""
    data, _ = _unpack(input)
    offset = jnp.asarray(offset).reshape(-1)
    length = jnp.asarray(length).reshape(-1)
    maxl = data.shape[1]
    pos = jnp.arange(maxl, dtype=jnp.int32)[None, :]
    src = pos + offset[:, None]
    src = jnp.clip(src, 0, maxl - 1)
    out = jnp.take_along_axis(
        data, src.reshape(src.shape + (1,) * (data.ndim - 2)), axis=1)
    return RaggedBatch(out, length.astype(jnp.int32))


def sequence_scatter(x, index, updates, name=None):
    """sequence_scatter_op parity (dense): add updates at given positions."""
    x = jnp.asarray(x)
    idx = jnp.asarray(index)
    return x.at[jnp.arange(x.shape[0])[:, None], idx].add(updates)


def sequence_conv(input, filter, context_length, context_start=None,
                  name=None):
    """sequence_conv_op parity (ref: operators/sequence_ops/
    sequence_conv_op.cc): context-window convolution over time.

    ``input`` is RaggedBatch/(data [B, T, H], lengths) or a dense [B, T, H]
    array; ``filter`` is [context_length * H, num_filters] (the reference's
    im2col-then-matmul layout, operators/math/context_project.h). Padded
    steps are zeroed before the window gather so results match the
    reference's LoD behavior at sequence boundaries.
    """
    if isinstance(input, (RaggedBatch, tuple, list)):
        data, lengths = _unpack(input)
        data = data * _mask(data, lengths)
    else:
        data, lengths = jnp.asarray(input), None
    b, t, h = data.shape
    if context_start is None:
        context_start = -((context_length - 1) // 2)
    cols = []
    for k in range(context_length):
        off = context_start + k
        shifted = jnp.roll(data, -off, axis=1)
        if off < 0:
            m = jnp.arange(t) >= -off
        else:
            m = jnp.arange(t) < t - off
        cols.append(shifted * m[None, :, None].astype(data.dtype))
    ctx = jnp.concatenate(cols, axis=-1)             # [B, T, cl*H]
    out = ctx @ jnp.asarray(filter)                  # [B, T, F]
    if lengths is not None:
        return RaggedBatch(out * _mask(out, lengths), lengths)
    return out


def sequence_reshape(input, new_dim, name=None):
    """sequence_reshape_op parity (ref
    sequence_ops/sequence_reshape_op.cc): re-chunk each sequence's
    flattened (length_i * M) elements into rows of ``new_dim``. Ragged
    form: data [B, T, M] -> [B, T*M/new_dim, new_dim] with
    lengths' = lengths * M / new_dim — valid because each row's payload
    is a row-major prefix of the flattened [T*M] buffer, so the reshape
    moves padding only at the tail. Requires (T*M) % new_dim == 0
    statically; each length_i * M must be divisible by new_dim for
    exact parity (the reference enforces it at runtime).
    """
    data, lengths = _unpack(input)
    enforce(data.ndim == 3,
            "sequence_reshape expects ragged [B, T, M] input")
    b, t, m = data.shape
    nd = int(new_dim)
    # the reference enforces (length_i * M) % new_dim == 0 per sequence
    # at runtime; do the same whenever lengths are concrete (trace-time
    # lengths can't raise — indivisible payloads would silently
    # truncate, so refuse only what we can see)
    if not isinstance(lengths, jax.core.Tracer):
        ln = np.asarray(lengths)
        bad = ln[(ln * m) % nd != 0]
        enforce(bad.size == 0,
                f"sequence payloads {bad.tolist()[:4]} * M={m} not "
                f"divisible by new_dim={nd} "
                f"(sequence_reshape_op.cc contract)")
    # PADDED T*M need not divide new_dim: pad the flat buffer so the
    # reshape always exists; valid payloads are row-major prefixes, so
    # only tail padding moves
    total = t * m
    pad = (-total) % nd
    flat = data.reshape(b, total)
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    out = flat.reshape(b, (total + pad) // nd, nd)
    new_len = (lengths * m) // nd
    return RaggedBatch(out, new_len.astype(jnp.int32))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """sequence_enumerate_op parity: every position emits the window of
    ``win_size`` consecutive token ids starting there; positions past a
    sequence's end (including window overhang) read ``pad_value``.
    Ragged [B, T] int -> RaggedBatch([B, T, win_size], lengths)."""
    data, lengths = _unpack(input)
    enforce(data.ndim == 2, "sequence_enumerate expects ragged [B, T]")
    b, t = data.shape
    idx = jnp.arange(t)[:, None] + jnp.arange(int(win_size))[None, :]
    gathered = jnp.take(data, jnp.minimum(idx, t - 1), axis=1)  # [B,T,W]
    valid = idx[None] < lengths[:, None, None]                  # [B,T,W]
    out = jnp.where(valid, gathered, pad_value).astype(data.dtype)
    return RaggedBatch(out, lengths)


def sequence_erase(input, tokens, name=None):
    """sequence_erase_op parity: delete every occurrence of ``tokens``
    from each sequence, compacting survivors to the front (padding keeps
    the dense [B, T] shape; lengths shrink). TPU-first: the compaction
    is a stable argsort on the keep mask — no dynamic shapes."""
    data, lengths = _unpack(input)
    enforce(data.ndim == 2, "sequence_erase expects ragged [B, T]")
    b, t = data.shape
    toks = jnp.asarray(list(tokens), data.dtype).reshape(-1)
    in_range = jnp.arange(t)[None, :] < lengths[:, None]
    erase = jnp.any(data[:, :, None] == toks[None, None, :], axis=-1)
    keep = in_range & ~erase
    # stable order: kept tokens (0) before dropped/padding (1)
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    out = jnp.take_along_axis(data, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    mask = jnp.arange(t)[None, :] < new_len[:, None]
    return RaggedBatch(jnp.where(mask, out, 0).astype(data.dtype),
                       new_len)
