"""Long-tail reference op names that are thin TPU-native primitives.

Each function cites the reference op it covers. These live in their own
module (not misc.py) because `range` shadows the Python builtin.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["range", "alloc_continuous_space", "rnn_memory_helper",
           "delete_var", "beam_search_decode"]


def range(start, end=None, step=1, dtype="int64"):  # noqa: A001
    """operators/range_op.cc (fluid.layers.range): arithmetic sequence
    [start, end) with stride ``step``."""
    if end is None:
        start, end = 0, start
    dt = np.dtype(dtype)
    if not jax.config.jax_enable_x64:   # canonicalize like the rest of jnp
        dt = {np.dtype(np.int64): np.dtype(np.int32),
              np.dtype(np.float64): np.dtype(np.float32)}.get(dt, dt)
    return jnp.arange(start, end, step).astype(dt)


def alloc_continuous_space(inputs, set_constant=None):
    """operators/alloc_continuous_space_op.cc: coalesce a tensor list
    into ONE flat buffer and return (flat, views) where views alias the
    buffer's segments with the originals' shapes. This is the
    fused-allreduce bucketing primitive (SURVEY §2.5 "Fused allreduce"
    row); on TPU the flat buffer is what a bucketed collective reduces in
    one shot, and XLA aliases the views back for free."""
    shapes = [x.shape for x in inputs]
    sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
    if set_constant is not None:
        flat = jnp.full((sum(sizes),), set_constant, inputs[0].dtype)
    else:
        flat = jnp.concatenate([jnp.ravel(x) for x in inputs])
    views, off = [], 0
    for s, sz in zip(shapes, sizes):
        views.append(flat[off:off + sz].reshape(s))
        off += sz
    return flat, views


def rnn_memory_helper(x):
    """operators/rnn_memory_helper_op.cc: identity marker the reference
    inserts so RNN memory vars get gradient plumbing across recurrent
    step boundaries. Under functional `lax.scan` the carry IS the memory
    and autodiff flows through it, so this is the identity."""
    return jnp.asarray(x)


def delete_var(scope, *names):
    """operators/delete_var_op.cc: drop variables from a Scope. Device
    buffer lifetime is XLA's job (liveness/DCE + donation — SURVEY §7 GC
    row); this host op releases the host-side references so a long-lived
    Scope cannot pin dead arrays."""
    for n in names:
        scope.drop_var(n)


def beam_search_decode(step_ids, step_parents, end_token=None):
    """operators/beam_search_decode_op.cc: backtrack per-step beam
    selections into full sequences. step_ids/step_parents: [T, B*beam]
    (token chosen at each step, and which beam slot it extended — the
    outputs of ops.misc.beam_search stacked over steps). Returns
    [B*beam, T] token sequences, best beam first within each batch
    group; with ``end_token`` set, every position after a sequence's
    first end_token is overwritten with end_token (the reference op's
    truncation, kept static-shape). Jittable: the backtrack is a
    reverse `lax.scan` of gathers."""
    step_ids = jnp.asarray(step_ids)
    step_parents = jnp.asarray(step_parents)
    t_steps, bb = step_ids.shape

    def back(beam, t):
        tok = step_ids[t][beam]
        return step_parents[t][beam], tok

    _, toks = lax.scan(back, jnp.arange(bb),
                       jnp.arange(t_steps - 1, -1, -1))
    seqs = toks[::-1].T                                    # [BB, T]
    if end_token is not None:
        ended = jnp.cumsum(
            (seqs == end_token).astype(jnp.int32), axis=1) > 0
        after_end = jnp.concatenate(
            [jnp.zeros((bb, 1), bool), ended[:, :-1]], axis=1)
        seqs = jnp.where(after_end, jnp.asarray(end_token, seqs.dtype),
                         seqs)
    return seqs
