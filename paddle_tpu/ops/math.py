"""Elementwise & linear-algebra math ops.

Parity targets: operators/elementwise/* (broadcast machinery
ref: operators/elementwise/elementwise_op_function.h), matmul_op.cc,
mul_op.cc, scale_op.cc, sum_op.cc, cumsum_op.cc, clip_op.cc,
clip_by_norm_op.cc, cast_op.cc, isfinite_op.cc, increment_op.cc.

The reference's elementwise ops take an ``axis`` attr to align a
lower-rank Y against X's dims (elementwise_op_function.h trim/expand);
here that is reproduced by reshaping Y before the broadcast, and XLA fuses
the rest.
"""

import math

import jax.numpy as jnp
from jax import lax

__all__ = [
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_min", "elementwise_max",
    "elementwise_pow", "elementwise_mod", "elementwise_floordiv",
    "matmul", "mul", "bmm", "dot", "scale", "sums", "cumsum",
    "clip", "clip_by_norm", "cast", "increment", "isfinite",
    "abs", "ceil", "floor", "round", "exp", "log", "sqrt", "rsqrt",
    "square", "reciprocal", "sign", "cos", "sin", "atan", "acos",
    "asin", "pow",
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "minus",
]


def _align(x, y, axis=-1):
    """Reference broadcast rule: align y's dims starting at `axis` of x."""
    x, y = jnp.asarray(x), jnp.asarray(y)
    if x.ndim == y.ndim or y.ndim == 0:
        return x, y
    if axis == -1:
        axis = x.ndim - y.ndim
    shape = [1] * x.ndim
    shape[axis: axis + y.ndim] = y.shape
    return x, y.reshape(shape)


def _binary(fn):
    def op(x, y, axis=-1, name=None):
        x, y = _align(x, y, axis)
        return fn(x, y)
    return op


elementwise_add = _binary(jnp.add)
elementwise_sub = _binary(jnp.subtract)
elementwise_mul = _binary(jnp.multiply)
elementwise_div = _binary(jnp.divide)
elementwise_min = _binary(jnp.minimum)
elementwise_max = _binary(jnp.maximum)
elementwise_pow = _binary(jnp.power)
elementwise_mod = _binary(jnp.mod)
elementwise_floordiv = _binary(jnp.floor_divide)


def minus(x, y):
    return jnp.subtract(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    """matmul_op.cc parity: batched matmul with optional transposes.

    Feeds the MXU; keep operands >=2D and let XLA batch. 1-D operands get
    the reference's vec-mat promotion.
    """
    x, y = jnp.asarray(x), jnp.asarray(y)
    squeeze_l = squeeze_r = False
    if x.ndim == 1:
        x, squeeze_l = x[None, :], True
    if y.ndim == 1:
        y, squeeze_r = y[:, None], True
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    if squeeze_l:
        out = out[..., 0, :]
    if squeeze_r:
        out = out[..., 0]
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """mul_op.cc parity: flatten x to 2-D at x_num_col_dims, y likewise,
    then 2-D matmul."""
    x, y = jnp.asarray(x), jnp.asarray(y)
    # shapes are static under tracing: compute flatten sizes in Python so
    # mul stays jit/eval_shape-traceable (no data-dependent shapes on TPU)
    xs = x.reshape((math.prod(x.shape[:x_num_col_dims]), -1)) \
        if x.ndim > 2 or x_num_col_dims != 1 else x.reshape((x.shape[0], -1))
    ys = y.reshape((math.prod(y.shape[:y_num_col_dims]), -1))
    out = jnp.matmul(xs, ys)
    return out.reshape(x.shape[:x_num_col_dims] + (ys.shape[-1],))


def bmm(x, y):
    return jnp.matmul(x, y)


def dot(x, y):
    return jnp.sum(x * y, axis=-1, keepdims=True)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    """scale_op.cc parity."""
    x = jnp.asarray(x)
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def sums(inputs, name=None):
    """sum_op.cc parity: add a list of tensors."""
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


def cumsum(x, axis=None, exclusive=False, reverse=False, name=None):
    x = jnp.asarray(x)
    if axis is None:
        x, axis = x.ravel(), 0
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


def clip(x, min, max, name=None):
    return jnp.clip(jnp.asarray(x), min, max)


def clip_by_norm(x, max_norm, name=None):
    """clip_by_norm_op.cc parity: x * max_norm / max(norm, max_norm)."""
    x = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return x * (max_norm / jnp.maximum(norm, max_norm))


def cast(x, dtype):
    from paddle_tpu.core.dtypes import convert_dtype
    return jnp.asarray(x).astype(convert_dtype(dtype))


def increment(x, value=1.0, name=None):
    return jnp.asarray(x) + value


def isfinite(x, name=None):
    """isfinite_op.cc parity: reduce-all finite check."""
    return jnp.all(jnp.isfinite(jnp.asarray(x)))


# -- simple unary (activation_op.cc registers several of these too) --------
def abs(x, name=None): return jnp.abs(jnp.asarray(x))            # noqa: E704
def ceil(x, name=None): return jnp.ceil(jnp.asarray(x))          # noqa: E704
def floor(x, name=None): return jnp.floor(jnp.asarray(x))        # noqa: E704
def round(x, name=None): return jnp.round(jnp.asarray(x))        # noqa: E704
def exp(x, name=None): return jnp.exp(jnp.asarray(x))            # noqa: E704
def log(x, name=None): return jnp.log(jnp.asarray(x))            # noqa: E704
def sqrt(x, name=None): return jnp.sqrt(jnp.asarray(x))          # noqa: E704
def rsqrt(x, name=None): return lax.rsqrt(jnp.asarray(x))        # noqa: E704
def square(x, name=None): return jnp.square(jnp.asarray(x))      # noqa: E704
def reciprocal(x, name=None): return 1.0 / jnp.asarray(x)        # noqa: E704
def sign(x, name=None): return jnp.sign(jnp.asarray(x))          # noqa: E704
def cos(x, name=None): return jnp.cos(jnp.asarray(x))            # noqa: E704
def sin(x, name=None): return jnp.sin(jnp.asarray(x))            # noqa: E704
def atan(x, name=None): return jnp.arctan(jnp.asarray(x))        # noqa: E704
def acos(x, name=None): return jnp.arccos(jnp.asarray(x))        # noqa: E704
def asin(x, name=None): return jnp.arcsin(jnp.asarray(x))        # noqa: E704


def pow(x, factor=1.0, name=None):
    return jnp.power(jnp.asarray(x), factor)


# -- logical / compare (operators/controlflow/{logical,compare}_op.cc) -----
def logical_and(x, y, name=None): return jnp.logical_and(x, y)   # noqa: E704
def logical_or(x, y, name=None): return jnp.logical_or(x, y)     # noqa: E704
def logical_xor(x, y, name=None): return jnp.logical_xor(x, y)   # noqa: E704
def logical_not(x, name=None): return jnp.logical_not(x)         # noqa: E704
def equal(x, y, name=None): return jnp.equal(x, y)               # noqa: E704
def not_equal(x, y, name=None): return jnp.not_equal(x, y)       # noqa: E704
def less_than(x, y, name=None): return jnp.less(x, y)            # noqa: E704
def less_equal(x, y, name=None): return jnp.less_equal(x, y)     # noqa: E704
def greater_than(x, y, name=None): return jnp.greater(x, y)      # noqa: E704
def greater_equal(x, y, name=None): return jnp.greater_equal(x, y)  # noqa: E704
