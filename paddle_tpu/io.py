"""fluid.io parity: checkpoint save/load + inference model export.

Parity: python/paddle/fluid/io.py (save_params:242, save_persistables:475,
load_params:527, load_persistables:714, save_inference_model:921,
load_inference_model:1109). Sharded/async checkpoint for SPMD training
lives in paddle_tpu.io_checkpoint (orbax-style per-host shards).
"""

import os
import pickle

import jax
import numpy as np

from paddle_tpu.static.io import (
    save_inference_model, load_inference_model, save_params, load_params,
    save_persistables, load_persistables,
)

__all__ = [
    "save_inference_model", "load_inference_model", "save_params",
    "load_params", "save_persistables", "load_persistables",
    "save_pytree", "load_pytree",
]


def save_pytree(tree, path):
    """Save a params/state pytree (eager path checkpointing — the analog
    of dygraph/checkpoint.py save_dygraph)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    with open(path, "wb") as f:
        pickle.dump({"treedef": pickle.dumps(treedef),
                     "leaves": [np.asarray(l) for l in leaves]}, f)


def load_pytree(path):
    import jax.numpy as jnp
    with open(path, "rb") as f:
        blob = pickle.load(f)
    treedef = pickle.loads(blob["treedef"])
    return jax.tree.unflatten(treedef, [jnp.asarray(l)
                                        for l in blob["leaves"]])
