"""fluid.io parity: checkpoint save/load + inference model export.

Parity: python/paddle/fluid/io.py (save_params:242, save_persistables:475,
load_params:527, load_persistables:714, save_inference_model:921,
load_inference_model:1109). Sharded/async checkpoint for SPMD training
lives in paddle_tpu.io_checkpoint (orbax-style per-host shards).
"""

import json
import os

import jax
import numpy as np

from paddle_tpu.static.io import (
    save_inference_model, load_inference_model, save_params, load_params,
    save_persistables, load_persistables, save_vars, load_vars,
)
from paddle_tpu.dataio.pyreader import DataLoader, PyReader

__all__ = [
    "save_inference_model", "load_inference_model", "save_params",
    "load_params", "save_persistables", "load_persistables",
    "save_vars", "load_vars", "batch",
    "save_pytree", "load_pytree", "save_dygraph", "load_dygraph",
    "DataLoader", "PyReader",
]


def save_pytree(tree, path):
    """Save a params/state pytree (eager path checkpointing — the analog
    of dygraph/checkpoint.py save_dygraph). Format: one .npz with a
    structural JSON manifest — no pickle (loading never executes code;
    trees are dicts/lists/tuples of arrays or scalars)."""
    from paddle_tpu.static.serialize import tree_manifest
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    manifest, arrays = tree_manifest(tree)
    mblob = np.frombuffer(json.dumps(manifest).encode("utf-8"),
                          dtype=np.uint8)
    tmp = path + ".tmp.npz"
    np.savez(tmp, __manifest__=mblob,
             **{k: np.asarray(v) for k, v in arrays.items()})
    os.replace(tmp, path)


def load_pytree(path):
    import jax.numpy as jnp
    from paddle_tpu.static.serialize import tree_from_manifest
    with np.load(path, allow_pickle=False) as blob:
        manifest = json.loads(
            bytes(blob["__manifest__"].tobytes()).decode("utf-8"))
        arrays = {k: jnp.asarray(blob[k]) for k in blob.files
                  if k != "__manifest__"}
    return tree_from_manifest(manifest, arrays)


# dygraph/checkpoint.py name parity (save_dygraph/load_dygraph)
def save_dygraph(state_dict, model_path):
    save_pytree(state_dict, model_path + ".pdparams"
                if not model_path.endswith(".pdparams") else model_path)


def load_dygraph(model_path):
    p = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    return load_pytree(p), None      # (param_dict, optimizer_dict)


def batch(reader, batch_size, drop_last=False):
    """fluid.io.batch parity: sample reader -> reader of sample lists
    (delegates to the shared dataio batching decorator)."""
    from paddle_tpu.dataio.feeder import batch_reader
    return batch_reader(reader, batch_size, drop_last)
