"""fluid.io parity: checkpoint save/load + inference model export.

Parity: python/paddle/fluid/io.py (save_params:242, save_persistables:475,
load_params:527, load_persistables:714, save_inference_model:921,
load_inference_model:1109). Sharded/async checkpoint for SPMD training
lives in paddle_tpu.io_checkpoint (orbax-style per-host shards).
"""

import os
import pickle

import jax
import numpy as np

from paddle_tpu.static.io import (
    save_inference_model, load_inference_model, save_params, load_params,
    save_persistables, load_persistables,
)
from paddle_tpu.dataio.pyreader import DataLoader, PyReader

__all__ = [
    "save_inference_model", "load_inference_model", "save_params",
    "load_params", "save_persistables", "load_persistables",
    "save_pytree", "load_pytree", "save_dygraph", "load_dygraph",
    "DataLoader", "PyReader",
]


def save_pytree(tree, path):
    """Save a params/state pytree (eager path checkpointing — the analog
    of dygraph/checkpoint.py save_dygraph)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    with open(path, "wb") as f:
        pickle.dump({"treedef": pickle.dumps(treedef),
                     "leaves": [np.asarray(l) for l in leaves]}, f)


def load_pytree(path):
    import jax.numpy as jnp
    with open(path, "rb") as f:
        blob = pickle.load(f)
    treedef = pickle.loads(blob["treedef"])
    return jax.tree.unflatten(treedef, [jnp.asarray(l)
                                        for l in blob["leaves"]])


# dygraph/checkpoint.py name parity (save_dygraph/load_dygraph)
def save_dygraph(state_dict, model_path):
    save_pytree(state_dict, model_path + ".pdparams"
                if not model_path.endswith(".pdparams") else model_path)


def load_dygraph(model_path):
    p = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    return load_pytree(p), None      # (param_dict, optimizer_dict)
