"""Cross-trainer sample exchange for global shuffle.

Parity: Dataset::GlobalShuffle's trainer-to-trainer redistribution
(ref: paddle/fluid/framework/data_set.h:82-92 + data_set.cc
GlobalShuffle — each sample is hashed to an owning trainer and SENT
there over the fleet's RPC substrate). Here the transport is the
framed binary wire protocol (distributed/wire.py — fixed schemas, no
pickle): every trainer listens on its own endpoint, ships each
non-owned sample batch to its owner as SHUFFLE_PUSH frames (npz-packed
sample blobs), finishes with SHUFFLE_DONE carrying the sent count, and
collects until every peer's DONE arrived.
"""

import io
import os
import socket
import threading
import time

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.distributed import wire

__all__ = ["exchange_samples", "sample_hash",
           "resolve_exchange_endpoints"]

_CHUNK = 512            # samples per SHUFFLE_PUSH frame


def sample_hash(sample):
    """Deterministic content hash shared by all trainers: ownership
    keys on sample CONTENT, never load position, so every trainer
    agrees regardless of per-trainer filelist partitioning (and of
    reader implementation — the native loader's order is deterministic
    nowadays, but trainers legitimately load different file sets)."""
    import hashlib
    key = b"|".join(np.asarray(a).tobytes() for a in sample)
    return int(hashlib.md5(key).hexdigest(), 16)


def _pack(samples):
    from paddle_tpu.dataio.common import _npz_dump
    buf = io.BytesIO()
    _npz_dump(samples, buf)
    return np.frombuffer(buf.getvalue(), np.uint8)


def _unpack(blob):
    from paddle_tpu.dataio.common import _npz_load
    return _npz_load(io.BytesIO(np.asarray(blob, np.uint8).tobytes()))


def _send_frame(sock, kind, fields):
    wire.send_frame(sock, kind, fields)


def _recv_frame(sock):
    kind, _, _, fields = wire.recv_frame(sock)
    return kind, fields


def resolve_exchange_endpoints(worker_endpoints):
    """The endpoints the sample exchange should BIND. In collective
    mode the trainer endpoints double as the jax.distributed
    rendezvous (rank 0's is the coordinator — a long-lived bound
    port), so binding them again would EADDRINUSE; the launcher wires
    dedicated exchange ports as PADDLE_EXCHANGE_ENDPOINTS (launch.py,
    both modes eventually — PS mode's worker endpoints are already
    dedicated). Falls back to the worker endpoints when the env is
    absent or inconsistent."""
    env = os.environ.get("PADDLE_EXCHANGE_ENDPOINTS", "")
    eps = [e for e in env.split(",") if e]
    if len(eps) == len(worker_endpoints):
        return eps
    return list(worker_endpoints)


class _Listener:
    """Accept SHUFFLE_PUSH/DONE frames from peer trainers until every
    expected peer trainer id has delivered SHUFFLE_DONE.

    Completion is counted by DISTINCT trainer ids that sent DONE — not
    by raw accepted connections: a stray connection (port scanner,
    health check) or a peer reconnecting after a transient drop must
    not consume a peer slot and stall the exchange."""

    def __init__(self, endpoint, n_peers, timeout=120.0):
        host, port = endpoint.rsplit(":", 1)
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind((host, int(port)))
        self.srv.listen(max(n_peers, 1))
        # short accept timeout: the accept loop re-checks completion
        # between accepts instead of blocking the full deadline
        self.srv.settimeout(0.25)
        self.n_peers = n_peers
        self.timeout = timeout
        self.received = []
        self.counts = {}            # from_trainer -> received count
        self.done_ids = set()       # trainer ids that sent DONE
        self.errors = []            # fatal: integrity violations
        self.conn_errors = []       # soft: per-connection transport
        self._lock = threading.Lock()
        self._active_conns = 0      # serve threads currently running
        # INACTIVITY deadline, not absolute: steady frame traffic (a
        # large exchange legitimately outlasting `timeout` wall-clock)
        # keeps the listener alive; only `timeout`s of silence ends it
        self._last_activity = time.time()
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    def _touch(self):
        self._last_activity = time.time()

    def _finished(self):
        """Accept loop exit condition: all peers DONE, or a fatal
        integrity error (no point waiting out the timeout on those)."""
        with self._lock:
            return (len(self.done_ids) >= self.n_peers
                    or bool(self.errors))

    def _accept(self):
        while not self._finished():
            # the inactivity clock only advances per COMPLETED frame,
            # so a single large frame mid-transfer must not trip it:
            # while any serve thread runs, its socket's own timeout
            # (recv raises after `timeout` of zero bytes) is the
            # liveness bound, and the thread's exit re-checks here
            with self._lock:
                quiet = (self._active_conns == 0
                         and time.time() - self._last_activity
                         > self.timeout)
            if quiet:
                return
            try:
                conn, _ = self.srv.accept()
            except socket.timeout:
                continue
            except OSError:         # pragma: no cover - closed socket
                return
            self._touch()
            conn.settimeout(self.timeout)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        staged = []                 # pushes buffered until DONE
        with self._lock:
            self._active_conns += 1
        try:
            with conn:
                while True:
                    kind, fields = _recv_frame(conn)
                    self._touch()
                    if kind == wire.SHUFFLE_PUSH:
                        staged.extend(_unpack(fields[1]))
                    elif kind == wire.SHUFFLE_DONE:
                        tid, total = int(fields[0]), int(fields[1])
                        with self._lock:
                            got = self.counts.get(tid, 0) + len(staged)
                            if got != total:
                                self.errors.append(RuntimeError(
                                    f"trainer {tid} claimed {total} "
                                    f"samples, received {got}"))
                                return
                            self.received.extend(staged)
                            self.counts[tid] = got
                            self.done_ids.add(tid)
                        return
                    else:
                        self.errors.append(RuntimeError(
                            f"unexpected frame kind {kind}"))
                        return
        except Exception as e:
            # a dropped/garbled connection is only fatal if its peer
            # never completes (it may reconnect and resend the whole
            # bucket); its staged pushes die with this frame, so a
            # resend cannot double-count
            with self._lock:
                self.conn_errors.append(e)
        finally:
            with self._lock:
                self._active_conns -= 1
            self._touch()       # thread exit restarts the quiet clock

    def wait(self):
        # the accept thread exits on completion, fatal error, or
        # `timeout` of inactivity — join without a cap of our own so
        # an active transfer extends the wait (progress, not wall
        # clock, is the liveness signal)
        while self._accept_thread.is_alive():
            self._accept_thread.join(1.0)
        self.srv.close()
        with self._lock:
            if self.errors:
                raise self.errors[0]
            complete = len(self.done_ids) >= self.n_peers
        if not complete:
            err = (f"; first transport error: {self.conn_errors[0]!r}"
                   if self.conn_errors else "")
            raise TimeoutError(
                f"sample exchange incomplete after {self.timeout}s of "
                f"inactivity: {len(self.done_ids)}/{self.n_peers} "
                f"peers finished (done ids {sorted(self.done_ids)})"
                f"{err}")
        # all peers DONE: their serve threads have returned (DONE is
        # the last frame on the connection); stray connections hold
        # staged samples only in their own frames, so the set is final
        return self.received


def exchange_samples(samples, endpoints, trainer_id, hash_fn=None,
                     timeout=120.0):
    """Redistribute ``samples`` across the trainers at ``endpoints``:
    returns the samples OWNED by ``trainer_id`` (own retained + all
    received), where ownership is hash(sample) % n_trainers. Blocking
    collective: every trainer must call this with the same endpoint
    list."""
    n = len(endpoints)
    enforce(0 <= trainer_id < n, "trainer_id out of range")
    if n == 1:
        return list(samples)
    hash_fn = hash_fn or sample_hash
    by_owner = [[] for _ in range(n)]
    for s in samples:
        by_owner[hash_fn(s) % n].append(s)

    listener = _Listener(endpoints[trainer_id], n_peers=n - 1,
                         timeout=timeout)
    # ship every non-owned bucket to its owner; peers bring their
    # listeners up at slightly different times, so connects retry
    import time as _time

    def connect(ep):
        host, port = ep.rsplit(":", 1)
        t0 = _time.time()
        while True:
            try:
                return socket.create_connection((host, int(port)),
                                                timeout=timeout)
            except OSError:
                if _time.time() - t0 > timeout:
                    raise
                _time.sleep(0.1)

    for owner in range(n):
        if owner == trainer_id:
            continue
        sock = connect(endpoints[owner])
        try:
            bucket = by_owner[owner]
            for lo in range(0, len(bucket), _CHUNK):
                _send_frame(sock, wire.SHUFFLE_PUSH,
                            (trainer_id, _pack(bucket[lo:lo + _CHUNK])))
            _send_frame(sock, wire.SHUFFLE_DONE,
                        (trainer_id, len(bucket)))
        finally:
            sock.close()
    received = listener.wait()
    return by_owner[trainer_id] + received
