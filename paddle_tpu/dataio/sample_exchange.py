"""Cross-trainer sample exchange for global shuffle.

Parity: Dataset::GlobalShuffle's trainer-to-trainer redistribution
(ref: paddle/fluid/framework/data_set.h:82-92 + data_set.cc
GlobalShuffle — each sample is hashed to an owning trainer and SENT
there over the fleet's RPC substrate). Here the transport is the
framed binary wire protocol (distributed/wire.py — fixed schemas, no
pickle): every trainer listens on its own endpoint, ships each
non-owned sample batch to its owner as SHUFFLE_PUSH frames (npz-packed
sample blobs), finishes with SHUFFLE_DONE carrying the sent count, and
collects until every peer's DONE arrived.
"""

import io
import socket
import threading

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.distributed import wire

__all__ = ["exchange_samples", "sample_hash"]

_CHUNK = 512            # samples per SHUFFLE_PUSH frame


def sample_hash(sample):
    """Deterministic content hash shared by all trainers (load order is
    nondeterministic under the threaded reader, so ownership must key
    on sample CONTENT)."""
    import hashlib
    key = b"|".join(np.asarray(a).tobytes() for a in sample)
    return int(hashlib.md5(key).hexdigest(), 16)


def _pack(samples):
    from paddle_tpu.dataio.common import _npz_dump
    buf = io.BytesIO()
    _npz_dump(samples, buf)
    return np.frombuffer(buf.getvalue(), np.uint8)


def _unpack(blob):
    from paddle_tpu.dataio.common import _npz_load
    return _npz_load(io.BytesIO(np.asarray(blob, np.uint8).tobytes()))


def _send_frame(sock, kind, fields):
    wire.send_frame(sock, kind, fields)


def _recv_frame(sock):
    kind, _, _, fields = wire.recv_frame(sock)
    return kind, fields


class _Listener:
    """Accept SHUFFLE_PUSH/DONE frames from peer trainers until every
    expected peer has sent DONE."""

    def __init__(self, endpoint, n_peers, timeout=120.0):
        host, port = endpoint.rsplit(":", 1)
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind((host, int(port)))
        self.srv.listen(max(n_peers, 1))
        self.srv.settimeout(timeout)
        self.n_peers = n_peers
        self.timeout = timeout
        self.received = []
        self.counts = {}            # from_trainer -> claimed count
        self.errors = []
        self._threads = []
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    def _accept(self):
        done = 0
        try:
            while done < self.n_peers:
                conn, _ = self.srv.accept()
                conn.settimeout(self.timeout)
                t = threading.Thread(target=self._serve_conn,
                                     args=(conn,), daemon=True)
                t.start()
                self._threads.append(t)
                done += 1
        except Exception as e:      # pragma: no cover - timeout path
            self.errors.append(e)

    def _serve_conn(self, conn):
        try:
            with conn:
                while True:
                    kind, fields = _recv_frame(conn)
                    if kind == wire.SHUFFLE_PUSH:
                        _, blob = fields
                        samples = _unpack(blob)
                        with self._lock:
                            self.received.extend(samples)
                            tid = int(fields[0])
                            self.counts[tid] = self.counts.get(tid, 0) \
                                + len(samples)
                    elif kind == wire.SHUFFLE_DONE:
                        tid, total = int(fields[0]), int(fields[1])
                        with self._lock:
                            got = self.counts.get(tid, 0)
                            if got != total:
                                self.errors.append(RuntimeError(
                                    f"trainer {tid} claimed {total} "
                                    f"samples, received {got}"))
                            self.counts.setdefault(tid, 0)
                        return
                    else:
                        self.errors.append(RuntimeError(
                            f"unexpected frame kind {kind}"))
                        return
        except Exception as e:
            self.errors.append(e)

    def wait(self):
        self._accept_thread.join(self.timeout)
        stuck = self._accept_thread.is_alive()
        for t in self._threads:
            t.join(self.timeout)
            stuck = stuck or t.is_alive()
        self.srv.close()
        if stuck:
            # a join timing out means a peer is still mid-transfer —
            # returning now would hand back a partial (and still
            # mutating) sample set
            raise TimeoutError(
                f"sample exchange incomplete after {self.timeout}s: "
                f"a peer transfer is still in flight")
        if self.errors:
            raise self.errors[0]
        return self.received


def exchange_samples(samples, endpoints, trainer_id, hash_fn=None,
                     timeout=120.0):
    """Redistribute ``samples`` across the trainers at ``endpoints``:
    returns the samples OWNED by ``trainer_id`` (own retained + all
    received), where ownership is hash(sample) % n_trainers. Blocking
    collective: every trainer must call this with the same endpoint
    list."""
    n = len(endpoints)
    enforce(0 <= trainer_id < n, "trainer_id out of range")
    if n == 1:
        return list(samples)
    hash_fn = hash_fn or sample_hash
    by_owner = [[] for _ in range(n)]
    for s in samples:
        by_owner[hash_fn(s) % n].append(s)

    listener = _Listener(endpoints[trainer_id], n_peers=n - 1,
                         timeout=timeout)
    # ship every non-owned bucket to its owner; peers bring their
    # listeners up at slightly different times, so connects retry
    import time as _time

    def connect(ep):
        host, port = ep.rsplit(":", 1)
        t0 = _time.time()
        while True:
            try:
                return socket.create_connection((host, int(port)),
                                                timeout=timeout)
            except OSError:
                if _time.time() - t0 > timeout:
                    raise
                _time.sleep(0.1)

    for owner in range(n):
        if owner == trainer_id:
            continue
        sock = connect(endpoints[owner])
        try:
            bucket = by_owner[owner]
            for lo in range(0, len(bucket), _CHUNK):
                _send_frame(sock, wire.SHUFFLE_PUSH,
                            (trainer_id, _pack(bucket[lo:lo + _CHUNK])))
            _send_frame(sock, wire.SHUFFLE_DONE,
                        (trainer_id, len(bucket)))
        finally:
            sock.close()
    received = listener.wait()
    return by_owner[trainer_id] + received
