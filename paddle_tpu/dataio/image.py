"""Image preprocessing utilities.

Parity: python/paddle/dataset/image.py (resize_short, to_chw,
center_crop, random_crop, left_right_flip, simple_transform,
load_and_transform, load_image, load_image_bytes,
batch_images_from_tar).

The reference shells out to cv2 for everything; on a TPU host the
per-image work is numpy (the heavy path belongs in the native pipeline
— data_pipeline.cc — or on-device via ops.nn.interpolate). Geometry ops
here are pure numpy so they run everywhere; JPEG/PNG *decoding* needs
cv2 or PIL and raises a clear error when neither is present.

Images are HWC uint8/float arrays like the reference's cv2 convention.
"""

import os
import pickle
import tarfile

import numpy as np

__all__ = [
    "batch_images_from_tar", "load_image_bytes", "load_image",
    "resize_short", "to_chw", "center_crop", "random_crop",
    "left_right_flip", "simple_transform", "load_and_transform",
]


def _decode(data, is_color=True):
    try:
        import cv2
        flag = cv2.IMREAD_COLOR if is_color else cv2.IMREAD_GRAYSCALE
        img = cv2.imdecode(np.frombuffer(data, np.uint8), flag)
        if img is None:
            raise ValueError("cv2 could not decode image bytes")
        return img
    except ImportError:
        pass
    try:
        import io as _io
        from PIL import Image
        img = Image.open(_io.BytesIO(data))
        img = img.convert("RGB" if is_color else "L")
        return np.asarray(img)
    except ImportError:
        raise RuntimeError(
            "decoding images needs cv2 or PIL; neither is installed "
            "(geometry-only helpers — resize/crop/flip — work without)")


def load_image_bytes(data, is_color=True):
    """Decode an encoded image from a bytes object."""
    return _decode(data, is_color)


def load_image(file, is_color=True):
    """Decode an encoded image file."""
    with open(file, "rb") as f:
        return _decode(f.read(), is_color)


def _resize_bilinear_np(img, oh, ow):
    """Pure-numpy bilinear resize over HWC (half-pixel centers)."""
    img = np.asarray(img)
    squeeze = img.ndim == 2
    if squeeze:
        img = img[:, :, None]
    h, w, c = img.shape
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    f = img.astype(np.float32)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if img.dtype == np.uint8:
        out = np.clip(np.rint(out), 0, 255).astype(np.uint8)
    else:
        out = out.astype(img.dtype)
    return out[:, :, 0] if squeeze else out


def resize_short(im, size):
    """Resize so the SHORT edge becomes ``size``, keeping aspect."""
    h, w = im.shape[:2]
    short = min(h, w)
    oh = int(round(h * size / short))
    ow = int(round(w * size / short))
    return _resize_bilinear_np(im, oh, ow)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (the training layout; ref image.py to_chw)."""
    return np.asarray(im).transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    hs = max((h - size) // 2, 0)
    ws = max((w - size) // 2, 0)
    return im[hs:hs + size, ws:ws + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    hs = rng.randint(0, max(h - size, 0) + 1)
    ws = rng.randint(0, max(w - size, 0) + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> crop(+flip when training) -> CHW -> mean-subtract
    (ref image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im = im - mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pack a tar of images into pickled batch files (ref image.py
    batch_images_from_tar: {'data': [bytes...], 'label': [...]} per
    batch, plus a batch-name manifest). Stores ENCODED bytes like the
    reference — decoding stays in the consumer."""
    out_path = f"{data_file}_{dataset_name}_batch"
    os.makedirs(out_path, exist_ok=True)
    data, labels, file_id, names = [], [], 0, []
    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name not in img2label:
                continue
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                name = os.path.join(out_path, f"batch_{file_id}")
                with open(name, "wb") as f:
                    pickle.dump({"data": data, "label": labels}, f,
                                protocol=2)
                names.append(name)
                file_id += 1
                data, labels = [], []
    if data:
        name = os.path.join(out_path, f"batch_{file_id}")
        with open(name, "wb") as f:
            pickle.dump({"data": data, "label": labels}, f, protocol=2)
        names.append(name)
    with open(os.path.join(out_path, "batch_names.txt"), "w") as f:
        f.write("\n".join(names))
    return out_path
