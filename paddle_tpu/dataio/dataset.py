"""Builtin datasets (synthetic, reference-shaped).

Parity: python/paddle/dataset/{mnist,cifar,uci_housing,imdb,imikolov,
movielens,…}.py — same reader contract (`train()`/`test()` return
zero-arg callables yielding tuples), same sample shapes/ranges, but
deterministic synthetic data so tests are hermetic (the reference
downloads with md5 caching, dataset/common.py).
"""

import numpy as np

__all__ = ["mnist", "cifar10", "uci_housing", "imdb", "imikolov"]


class _Synthetic:
    def __init__(self, make_sample, n_train, n_test, seed=7):
        self._make = make_sample
        self.n_train = n_train
        self.n_test = n_test
        self.seed = seed

    def train(self):
        def reader():
            rng = np.random.RandomState(self.seed)
            for _ in range(self.n_train):
                yield self._make(rng)
        return reader

    def test(self):
        def reader():
            rng = np.random.RandomState(self.seed + 1)
            for _ in range(self.n_test):
                yield self._make(rng)
        return reader


def _mnist_sample(rng):
    img = rng.uniform(-1, 1, size=(784,)).astype(np.float32)
    label = rng.randint(0, 10)
    return img, label


mnist = _Synthetic(_mnist_sample, n_train=1024, n_test=256)


def _cifar_sample(rng):
    img = rng.uniform(0, 1, size=(3, 32, 32)).astype(np.float32)
    label = rng.randint(0, 10)
    return img.reshape(-1), label


cifar10 = _Synthetic(_cifar_sample, n_train=1024, n_test=256)


def _housing_sample(rng):
    x = rng.uniform(-1, 1, size=(13,)).astype(np.float32)
    w = np.linspace(-0.5, 0.5, 13).astype(np.float32)
    y = np.array([float(x @ w) + 0.1 * rng.randn()], np.float32)
    return x, y


uci_housing = _Synthetic(_housing_sample, n_train=512, n_test=128)

IMDB_VOCAB = 5147  # matches paddle.dataset.imdb word_dict size order


def _imdb_sample(rng):
    n = rng.randint(8, 100)
    words = rng.randint(0, IMDB_VOCAB, size=(n,)).astype(np.int64)
    label = rng.randint(0, 2)
    return words, label


imdb = _Synthetic(_imdb_sample, n_train=512, n_test=128)

IMIKOLOV_VOCAB = 2074


def _imikolov_sample(rng):
    return tuple(rng.randint(0, IMIKOLOV_VOCAB) for _ in range(5))


imikolov = _Synthetic(_imikolov_sample, n_train=512, n_test=128)
