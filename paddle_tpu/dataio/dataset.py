"""Builtin datasets (synthetic by default, reference-shaped).

Parity: python/paddle/dataset/{mnist,cifar,uci_housing,imdb,imikolov,
movielens,…}.py — same reader contract (`train()`/`test()` return
zero-arg callables yielding tuples), same sample shapes/ranges, but
deterministic synthetic data so tests are hermetic (the reference
downloads with md5 caching, dataset/common.py).

Real corpora are OPT-IN: set ``PT_DATASET_REAL=1`` (or pass
``source="real"``) and mnist/cifar10 route through
paddle_tpu.dataio.common's download+md5 cache (the reference's
dataset/common.py contract, same md5 pins).
"""

import numpy as np

__all__ = ["mnist", "cifar10", "uci_housing", "imdb", "imikolov"]


class _Synthetic:
    def __init__(self, make_sample, n_train, n_test, seed=7):
        self._make = make_sample
        self.n_train = n_train
        self.n_test = n_test
        self.seed = seed

    def train(self):
        def reader():
            rng = np.random.RandomState(self.seed)
            for _ in range(self.n_train):
                yield self._make(rng)
        return reader

    def test(self):
        def reader():
            rng = np.random.RandomState(self.seed + 1)
            for _ in range(self.n_test):
                yield self._make(rng)
        return reader


class _MaybeReal(_Synthetic):
    """Synthetic by default; ``source="real"`` (or PT_DATASET_REAL=1)
    switches to the downloaded corpus via ``real_factory(split)``."""

    def __init__(self, make_sample, n_train, n_test, real_factory,
                 seed=7):
        super().__init__(make_sample, n_train, n_test, seed)
        self._real_factory = real_factory

    def _use_real(self, source):
        if source is None:
            from paddle_tpu.dataio.common import real_data_enabled
            return real_data_enabled()
        if source not in ("synthetic", "real"):
            raise ValueError(f"source must be synthetic|real, "
                             f"got {source!r}")
        return source == "real"

    def train(self, source=None):
        if self._use_real(source):
            return self._real_factory("train")
        return super().train()

    def test(self, source=None):
        if self._use_real(source):
            return self._real_factory("test")
        return super().test()


def _mnist_sample(rng):
    img = rng.uniform(-1, 1, size=(784,)).astype(np.float32)
    label = rng.randint(0, 10)
    return img, label


def _mnist_real(split):
    from paddle_tpu.dataio import common
    return common.mnist_reader(split)


mnist = _MaybeReal(_mnist_sample, n_train=1024, n_test=256,
                   real_factory=_mnist_real)


def _cifar_sample(rng):
    img = rng.uniform(0, 1, size=(3, 32, 32)).astype(np.float32)
    label = rng.randint(0, 10)
    return img.reshape(-1), label


def _cifar_real(split):
    from paddle_tpu.dataio import common
    return common.cifar10_reader(split)


cifar10 = _MaybeReal(_cifar_sample, n_train=1024, n_test=256,
                     real_factory=_cifar_real)


def _housing_sample(rng):
    x = rng.uniform(-1, 1, size=(13,)).astype(np.float32)
    w = np.linspace(-0.5, 0.5, 13).astype(np.float32)
    y = np.array([float(x @ w) + 0.1 * rng.randn()], np.float32)
    return x, y


uci_housing = _Synthetic(_housing_sample, n_train=512, n_test=128)

IMDB_VOCAB = 5147  # matches paddle.dataset.imdb word_dict size order


def _imdb_sample(rng):
    n = rng.randint(8, 100)
    words = rng.randint(0, IMDB_VOCAB, size=(n,)).astype(np.int64)
    label = rng.randint(0, 2)
    return words, label


imdb = _Synthetic(_imdb_sample, n_train=512, n_test=128)

IMIKOLOV_VOCAB = 2074


def _imikolov_sample(rng):
    return tuple(rng.randint(0, IMIKOLOV_VOCAB) for _ in range(5))


imikolov = _Synthetic(_imikolov_sample, n_train=512, n_test=128)


# -- remaining reference dataset family (python/paddle/dataset/) ----------
MOVIELENS_USERS, MOVIELENS_MOVIES, MOVIELENS_CATEGORIES = 6040, 3952, 18


def _movielens_sample(rng):
    """movielens.py: (user_id, gender, age, job, movie_id,
    category-id list, title words, rating)."""
    user = rng.randint(1, MOVIELENS_USERS + 1)
    gender = rng.randint(0, 2)
    age = rng.randint(0, 7)
    job = rng.randint(0, 21)
    movie = rng.randint(1, MOVIELENS_MOVIES + 1)
    # variable-length category-id list (CATEGORIES_DICT indices), like
    # MovieInfo.value() — NOT a one-hot
    cats = rng.choice(MOVIELENS_CATEGORIES, size=rng.randint(1, 4),
                      replace=False).astype(np.int64)
    title = rng.randint(0, 5175, size=(rng.randint(1, 6),)).astype(np.int64)
    rating = float(rng.randint(1, 6))
    return user, gender, age, job, movie, cats, title, rating


movielens = _Synthetic(_movielens_sample, n_train=1024, n_test=256)

WMT14_DICT_SIZE = 30000
WMT16_DICT_SIZE = 10000


def _wmt_sample(vocab):
    def make(rng):
        """(src ids, tgt ids, tgt-next ids) — the seq2seq triple
        wmt14/wmt16.py yield (with <s>/<e> at ids 0/1)."""
        ns = rng.randint(4, 30)
        nt = rng.randint(4, 30)
        # src wrapped in <s>=0 ... <e>=1 like the reference
        src = np.concatenate(
            [[0], rng.randint(2, vocab, size=(ns,)), [1]]).astype(np.int64)
        tgt = np.concatenate([[0], rng.randint(2, vocab, size=(nt,))]) \
            .astype(np.int64)
        tgt_next = np.concatenate([tgt[1:], [1]]).astype(np.int64)
        return src, tgt, tgt_next
    return make


wmt14 = _Synthetic(_wmt_sample(WMT14_DICT_SIZE), n_train=512, n_test=128)
wmt16 = _Synthetic(_wmt_sample(WMT16_DICT_SIZE), n_train=512, n_test=128)

CONLL05_WORD_VOCAB, CONLL05_LABELS = 44068, 59


CONLL05_PRED_VOCAB = 3162


def _conll05_sample(rng):
    """conll05.py SRL 9-tuple: (words, ctx_n2, ctx_n1, ctx_0, ctx_p1,
    ctx_p2, predicate, mark, labels) — length-aligned id sequences."""
    n = rng.randint(5, 40)
    seq = lambda hi: rng.randint(0, hi, size=(n,)).astype(np.int64)
    return (seq(CONLL05_WORD_VOCAB),) \
        + tuple(seq(CONLL05_WORD_VOCAB) for _ in range(5)) \
        + (seq(CONLL05_PRED_VOCAB), seq(2), seq(CONLL05_LABELS))


conll05 = _Synthetic(_conll05_sample, n_train=512, n_test=128)


SENTIMENT_VOCAB = 39768   # NLTK movie_reviews word-dict size order


def _sentiment_sample(rng):
    n = rng.randint(8, 60)
    return (rng.randint(0, SENTIMENT_VOCAB, size=(n,)).astype(np.int64),
            rng.randint(0, 2))


sentiment = _Synthetic(_sentiment_sample, n_train=512, n_test=128)


def _voc2012_sample(rng):
    """voc2012.py: (image CHW float, segmentation label HW int32)."""
    img = rng.uniform(0, 1, size=(3, 64, 64)).astype(np.float32)
    seg = rng.randint(0, 21, size=(64, 64)).astype(np.int32)
    return img, seg


voc2012 = _Synthetic(_voc2012_sample, n_train=128, n_test=32)


def _mq2007_sample(rng):
    """mq2007.py pairwise form: (label, query-doc features a,
    features b) — label FIRST, like the reference's yield."""
    fa = rng.uniform(0, 1, size=(46,)).astype(np.float32)
    fb = rng.uniform(0, 1, size=(46,)).astype(np.float32)
    return float(rng.randint(0, 2)), fa, fb


mq2007 = _Synthetic(_mq2007_sample, n_train=512, n_test=128)


def _flowers_sample(rng):
    img = rng.uniform(0, 1, size=(3, 224, 224)).astype(np.float32)
    return img, rng.randint(0, 102)


flowers = _Synthetic(_flowers_sample, n_train=256, n_test=64)

__all__ += ["movielens", "wmt14", "wmt16", "conll05", "sentiment",
            "voc2012", "mq2007", "flowers"]


class _RealOnly:
    """Dataset whose train()/test() always serve a REAL local corpus
    (no network, no synthetic fallback needed)."""

    def __init__(self, factory):
        self._factory = factory

    def train(self):
        return self._factory("train")

    def test(self):
        return self._factory("test")


def _digits_factory(split):
    from paddle_tpu.dataio.common import digits_reader
    return digits_reader(split)


# real handwritten digits, available offline (sklearn bundle) — the
# zero-egress stand-in for dataset.mnist in convergence runs
# (BASELINE.md "Real-data convergence")
digits = _RealOnly(_digits_factory)

__all__ += ["digits"]


# fluid namespace parity: paddle.dataset.common (download cache +
# split/cluster_files_reader/convert file sharding)
from paddle_tpu.dataio import common  # noqa: E402,F401
