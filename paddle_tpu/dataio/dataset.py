"""Builtin datasets (synthetic by default, reference-shaped).

Parity: python/paddle/dataset/{mnist,cifar,uci_housing,imdb,imikolov,
movielens,…}.py — same reader contract (`train()`/`test()` return
zero-arg callables yielding tuples), same sample shapes/ranges, but
deterministic synthetic data so tests are hermetic (the reference
downloads with md5 caching, dataset/common.py).

Real corpora are OPT-IN: set ``PT_DATASET_REAL=1`` (or pass
``source="real"``) and mnist/cifar10 route through
paddle_tpu.dataio.common's download+md5 cache (the reference's
dataset/common.py contract, same md5 pins).
"""

import numpy as np

__all__ = ["mnist", "cifar10", "uci_housing", "imdb", "imikolov"]


class _Synthetic:
    def __init__(self, make_sample, n_train, n_test, seed=7):
        self._make = make_sample
        self.n_train = n_train
        self.n_test = n_test
        self.seed = seed

    def train(self):
        def reader():
            rng = np.random.RandomState(self.seed)
            for _ in range(self.n_train):
                yield self._make(rng)
        return reader

    def test(self):
        def reader():
            rng = np.random.RandomState(self.seed + 1)
            for _ in range(self.n_test):
                yield self._make(rng)
        return reader


class _MaybeReal(_Synthetic):
    """Synthetic by default; ``source="real"`` (or PT_DATASET_REAL=1)
    switches to the downloaded corpus via ``real_factory(split)``."""

    def __init__(self, make_sample, n_train, n_test, real_factory,
                 seed=7):
        super().__init__(make_sample, n_train, n_test, seed)
        self._real_factory = real_factory

    def _use_real(self, source):
        if source is None:
            from paddle_tpu.dataio.common import real_data_enabled
            return real_data_enabled()
        if source not in ("synthetic", "real"):
            raise ValueError(f"source must be synthetic|real, "
                             f"got {source!r}")
        return source == "real"

    def train(self, source=None):
        if self._use_real(source):
            return self._real_factory("train")
        return super().train()

    def test(self, source=None):
        if self._use_real(source):
            return self._real_factory("test")
        return super().test()


def _mnist_sample(rng):
    img = rng.uniform(-1, 1, size=(784,)).astype(np.float32)
    label = rng.randint(0, 10)
    return img, label


def _mnist_real(split):
    from paddle_tpu.dataio import common
    return common.mnist_reader(split)


mnist = _MaybeReal(_mnist_sample, n_train=1024, n_test=256,
                   real_factory=_mnist_real)


def _cifar_sample(rng):
    img = rng.uniform(0, 1, size=(3, 32, 32)).astype(np.float32)
    label = rng.randint(0, 10)
    return img.reshape(-1), label


def _cifar_real(split):
    from paddle_tpu.dataio import common
    return common.cifar10_reader(split)


cifar10 = _MaybeReal(_cifar_sample, n_train=1024, n_test=256,
                     real_factory=_cifar_real)


def _housing_sample(rng):
    x = rng.uniform(-1, 1, size=(13,)).astype(np.float32)
    w = np.linspace(-0.5, 0.5, 13).astype(np.float32)
    y = np.array([float(x @ w) + 0.1 * rng.randn()], np.float32)
    return x, y


uci_housing = _Synthetic(_housing_sample, n_train=512, n_test=128)

IMDB_VOCAB = 5147  # matches paddle.dataset.imdb word_dict size order


def _imdb_sample(rng):
    n = rng.randint(8, 100)
    words = rng.randint(0, IMDB_VOCAB, size=(n,)).astype(np.int64)
    label = rng.randint(0, 2)
    return words, label


class _Downloadable:
    """Shared download tier for the real-corpus datasets: subclasses
    pin URL/MD5/MODULE (the reference's per-module constants) and
    ``path`` overrides the download — that is how CI proves the
    parsers on in-tree fixtures in zero-egress environments."""

    URL = MD5 = MODULE = None

    def _archive(self, path):
        if path is not None:
            return path
        from paddle_tpu.dataio.common import download
        return download(self.URL, self.MODULE, self.MD5)


class _Imdb(_Downloadable, _Synthetic):
    """paddle.dataset.imdb parity: no-arg train()/test() serve the
    synthetic tier; passing ``word_idx`` (and optionally ``path`` to a
    local aclImdb-format tarball) runs the REAL parser
    (ref: dataset/imdb.py:96-138). Downloads stay network-gated."""

    URL = ("http://ai.stanford.edu/%7Eamaas/data/sentiment/"
           "aclImdb_v1.tar.gz")
    MD5 = "7c2ac02c03563afcf9b574c7e56c153a"
    MODULE = "imdb"

    def build_dict(self, pattern, cutoff, path=None):
        from paddle_tpu.dataio import parsers
        return parsers.imdb_build_dict(self._archive(path), pattern,
                                       cutoff)

    def word_dict(self, path=None, cutoff=150):
        return self.build_dict(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$",
            cutoff, path)

    def train(self, word_idx=None, path=None):
        if word_idx is None:
            return super().train()
        from paddle_tpu.dataio import parsers
        return parsers.imdb_reader(
            self._archive(path), r"aclImdb/train/pos/.*\.txt$",
            r"aclImdb/train/neg/.*\.txt$", word_idx)

    def test(self, word_idx=None, path=None):
        if word_idx is None:
            return super().test()
        from paddle_tpu.dataio import parsers
        return parsers.imdb_reader(
            self._archive(path), r"aclImdb/test/pos/.*\.txt$",
            r"aclImdb/test/neg/.*\.txt$", word_idx)


imdb = _Imdb(_imdb_sample, n_train=512, n_test=128)

IMIKOLOV_VOCAB = 2074


def _imikolov_sample(rng):
    return tuple(rng.randint(0, IMIKOLOV_VOCAB) for _ in range(5))


class _Imikolov(_Downloadable, _Synthetic):
    """paddle.dataset.imikolov parity (ref: dataset/imikolov.py): real
    PTB n-gram/seq readers when ``word_idx`` is given."""

    URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"
    MD5 = "30177ea32e27c525793142b6bf2c8e2d"
    MODULE = "imikolov"
    NGRAM, SEQ = "ngram", "seq"

    def build_dict(self, min_word_freq=50, path=None):
        from paddle_tpu.dataio import parsers
        return parsers.imikolov_build_dict(self._archive(path),
                                           min_word_freq)

    def train(self, word_idx=None, n=5, data_type="ngram", path=None):
        if word_idx is None:
            return super().train()
        from paddle_tpu.dataio import parsers
        return parsers.imikolov_reader(
            self._archive(path), parsers.IMIKOLOV_TRAIN, word_idx, n,
            data_type)

    def test(self, word_idx=None, n=5, data_type="ngram", path=None):
        if word_idx is None:
            return super().test()
        from paddle_tpu.dataio import parsers
        return parsers.imikolov_reader(
            self._archive(path), parsers.IMIKOLOV_VALID, word_idx, n,
            data_type)


imikolov = _Imikolov(_imikolov_sample, n_train=512, n_test=128)


# -- remaining reference dataset family (python/paddle/dataset/) ----------
MOVIELENS_USERS, MOVIELENS_MOVIES, MOVIELENS_CATEGORIES = 6040, 3952, 18


def _movielens_sample(rng):
    """movielens.py: (user_id, gender, age, job, movie_id,
    category-id list, title words, rating)."""
    user = rng.randint(1, MOVIELENS_USERS + 1)
    gender = rng.randint(0, 2)
    age = rng.randint(0, 7)
    job = rng.randint(0, 21)
    movie = rng.randint(1, MOVIELENS_MOVIES + 1)
    # variable-length category-id list (CATEGORIES_DICT indices), like
    # MovieInfo.value() — NOT a one-hot
    cats = rng.choice(MOVIELENS_CATEGORIES, size=rng.randint(1, 4),
                      replace=False).astype(np.int64)
    title = rng.randint(0, 5175, size=(rng.randint(1, 6),)).astype(np.int64)
    rating = float(rng.randint(1, 6))
    return user, gender, age, job, movie, cats, title, rating


class _Movielens(_Downloadable, _Synthetic):
    """paddle.dataset.movielens parity (ref: dataset/movielens.py):
    ``path`` to an ml-1m.zip-format archive enables the real parser;
    meta queries (max ids, dicts) come from one cached parse."""

    URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
    MD5 = "c4d9eecfca2ab87c1945afe126590906"
    MODULE = "movielens"
    _meta_cache = None

    @property
    def age_table(self):
        from paddle_tpu.dataio import parsers
        return tuple(parsers.MOVIELENS_AGE_TABLE)

    def _meta(self, path):
        archive = self._archive(path)
        if self._meta_cache is None or self._meta_cache[0] != archive:
            from paddle_tpu.dataio import parsers
            self._meta_cache = (archive,
                                parsers.movielens_meta(archive))
        return self._meta_cache[1]

    def _real_reader(self, path, is_test):
        from paddle_tpu.dataio import parsers
        archive = self._archive(path)
        return parsers.movielens_reader(archive, is_test=is_test,
                                        meta=self._meta(path))

    def train(self, path=None):
        if path is None:
            return super().train()
        return self._real_reader(path, is_test=False)

    def test(self, path=None):
        if path is None:
            return super().test()
        return self._real_reader(path, is_test=True)

    def get_movie_title_dict(self, path=None):
        return self._meta(path)[3]

    def movie_categories(self, path=None):
        return self._meta(path)[2]

    def max_movie_id(self, path=None):
        return max(self._meta(path)[0])

    def max_user_id(self, path=None):
        return max(self._meta(path)[1])

    def max_job_id(self, path=None):
        return max(u[3] for u in self._meta(path)[1].values())

    def movie_info(self, path=None):
        return self._meta(path)[0]

    def user_info(self, path=None):
        return self._meta(path)[1]


movielens = _Movielens(_movielens_sample, n_train=1024, n_test=256)

WMT14_DICT_SIZE = 30000
WMT16_DICT_SIZE = 10000


def _wmt_sample(vocab):
    def make(rng):
        """(src ids, tgt ids, tgt-next ids) — the seq2seq triple
        wmt14/wmt16.py yield (with <s>/<e> at ids 0/1)."""
        ns = rng.randint(4, 30)
        nt = rng.randint(4, 30)
        # src wrapped in <s>=0 ... <e>=1 like the reference
        src = np.concatenate(
            [[0], rng.randint(2, vocab, size=(ns,)), [1]]).astype(np.int64)
        tgt = np.concatenate([[0], rng.randint(2, vocab, size=(nt,))]) \
            .astype(np.int64)
        tgt_next = np.concatenate([tgt[1:], [1]]).astype(np.int64)
        return src, tgt, tgt_next
    return make


class _Wmt14(_Downloadable, _Synthetic):
    """paddle.dataset.wmt14 parity (ref: dataset/wmt14.py): real
    parallel-corpus reader when ``dict_size`` is given."""

    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"
    MD5 = "0791583d57d5beb693b9414c5b36798c"
    MODULE = "wmt14"

    def train(self, dict_size=None, path=None):
        if dict_size is None:
            return super().train()
        from paddle_tpu.dataio import parsers
        return parsers.wmt14_reader(self._archive(path), "train/train",
                                    dict_size)

    def test(self, dict_size=None, path=None):
        if dict_size is None:
            return super().test()
        from paddle_tpu.dataio import parsers
        return parsers.wmt14_reader(self._archive(path), "test/test",
                                    dict_size)

    def get_dict(self, dict_size, reverse=False, path=None):
        from paddle_tpu.dataio import parsers
        src, trg = parsers.wmt14_dicts(self._archive(path), dict_size)
        if reverse:
            src = {v: k for k, v in src.items()}
            trg = {v: k for k, v in trg.items()}
        return src, trg


class _Wmt16(_Downloadable, _Synthetic):
    """paddle.dataset.wmt16 parity (ref: dataset/wmt16.py): dicts built
    from the train split with <s>/<e>/<unk> pinned at 0/1/2."""

    URL = ("http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz")
    MD5 = "0c38be43600334966403524a40dcd81e"
    MODULE = "wmt16"

    def _reader(self, split, src_dict_size, trg_dict_size, src_lang,
                path):
        from paddle_tpu.dataio import parsers
        # an omitted trg size mirrors src (never None: a None size
        # would silently build the full target vocab and hand a model
        # sized to src_dict_size out-of-range ids)
        if trg_dict_size is None:
            trg_dict_size = src_dict_size
        return parsers.wmt16_reader(self._archive(path),
                                    f"wmt16/{split}", src_dict_size,
                                    trg_dict_size, src_lang)

    def train(self, src_dict_size=None, trg_dict_size=None,
              src_lang="en", path=None):
        if src_dict_size is None:
            return super().train()
        return self._reader("train", src_dict_size, trg_dict_size,
                            src_lang, path)

    def test(self, src_dict_size=None, trg_dict_size=None,
             src_lang="en", path=None):
        if src_dict_size is None:
            return super().test()
        return self._reader("test", src_dict_size, trg_dict_size,
                            src_lang, path)

    def validation(self, src_dict_size, trg_dict_size, src_lang="en",
                   path=None):
        return self._reader("val", src_dict_size, trg_dict_size,
                            src_lang, path)

    def get_dict(self, lang, dict_size, reverse=False, path=None):
        from paddle_tpu.dataio import parsers
        d = parsers.wmt16_build_dict(self._archive(path), dict_size,
                                     lang)
        return {v: k for k, v in d.items()} if reverse else d


wmt14 = _Wmt14(_wmt_sample(WMT14_DICT_SIZE), n_train=512, n_test=128)
wmt16 = _Wmt16(_wmt_sample(WMT16_DICT_SIZE), n_train=512, n_test=128)

CONLL05_WORD_VOCAB, CONLL05_LABELS = 44068, 59


CONLL05_PRED_VOCAB = 3162


def _conll05_sample(rng):
    """conll05.py SRL 9-tuple: (words, ctx_n2, ctx_n1, ctx_0, ctx_p1,
    ctx_p2, predicate, mark, labels) — length-aligned id sequences."""
    n = rng.randint(5, 40)
    seq = lambda hi: rng.randint(0, hi, size=(n,)).astype(np.int64)
    return (seq(CONLL05_WORD_VOCAB),) \
        + tuple(seq(CONLL05_WORD_VOCAB) for _ in range(5)) \
        + (seq(CONLL05_PRED_VOCAB), seq(2), seq(CONLL05_LABELS))


class _Conll05(_Synthetic):
    """paddle.dataset.conll05 parity (ref: dataset/conll05.py): real
    SRL readers over a conll05st tarball + dict files."""

    def get_dict(self, word_dict_path, verb_dict_path,
                 label_dict_path):
        from paddle_tpu.dataio import parsers
        return (parsers.conll05_load_dict(word_dict_path),
                parsers.conll05_load_dict(verb_dict_path),
                parsers.conll05_load_label_dict(label_dict_path))

    def reader(self, tar_path, words_name, props_name, word_dict,
               verb_dict, label_dict):
        from paddle_tpu.dataio import parsers
        corpus = parsers.conll05_corpus_reader(tar_path, words_name,
                                               props_name)
        return parsers.conll05_reader(corpus, word_dict, verb_dict,
                                      label_dict)

    def test(self, tar_path=None, word_dict=None, verb_dict=None,
             label_dict=None, words_name=("conll05st-release/test.wsj/"
                                          "words/test.wsj.words.gz"),
             props_name=("conll05st-release/test.wsj/props/"
                         "test.wsj.props.gz")):
        if tar_path is None:
            return super().test()
        return self.reader(tar_path, words_name, props_name,
                           word_dict, verb_dict, label_dict)


conll05 = _Conll05(_conll05_sample, n_train=512, n_test=128)


SENTIMENT_VOCAB = 39768   # NLTK movie_reviews word-dict size order


def _sentiment_sample(rng):
    n = rng.randint(8, 60)
    return (rng.randint(0, SENTIMENT_VOCAB, size=(n,)).astype(np.int64),
            rng.randint(0, 2))


class _Sentiment(_Synthetic):
    """paddle.dataset.sentiment parity (ref: dataset/sentiment.py):
    real NLTK movie_reviews-layout readers when ``root`` is given."""

    def get_word_dict(self, root):
        from paddle_tpu.dataio import parsers
        return parsers.sentiment_word_dict(root)

    def train(self, root=None):
        if root is None:
            return super().train()
        from paddle_tpu.dataio import parsers
        return parsers.sentiment_reader(root, "train")

    def test(self, root=None):
        if root is None:
            return super().test()
        from paddle_tpu.dataio import parsers
        return parsers.sentiment_reader(root, "test")


sentiment = _Sentiment(_sentiment_sample, n_train=512, n_test=128)


def _voc2012_sample(rng):
    """voc2012.py: (image CHW float, segmentation label HW int32)."""
    img = rng.uniform(0, 1, size=(3, 64, 64)).astype(np.float32)
    seg = rng.randint(0, 21, size=(64, 64)).astype(np.int32)
    return img, seg


class _Voc2012(_Synthetic):
    """paddle.dataset.voc2012 parity (ref: dataset/voc2012.py): real
    VOC-tar segmentation readers when ``path`` is given; same
    split->set-file mapping (train:trainval, test:train, val:val)."""

    def train(self, path=None):
        if path is None:
            return super().train()
        from paddle_tpu.dataio import parsers
        return parsers.voc2012_reader(path, "trainval")

    def test(self, path=None):
        if path is None:
            return super().test()
        from paddle_tpu.dataio import parsers
        return parsers.voc2012_reader(path, "train")

    def val(self, path):
        from paddle_tpu.dataio import parsers
        return parsers.voc2012_reader(path, "val")


voc2012 = _Voc2012(_voc2012_sample, n_train=128, n_test=32)


def _mq2007_sample(rng):
    """mq2007.py pairwise form: (label, query-doc features a,
    features b) — label FIRST, like the reference's yield."""
    fa = rng.uniform(0, 1, size=(46,)).astype(np.float32)
    fb = rng.uniform(0, 1, size=(46,)).astype(np.float32)
    return float(rng.randint(0, 2)), fa, fb


class _Mq2007(_Synthetic):
    """paddle.dataset.mq2007 parity (ref: dataset/mq2007.py): real
    LETOR readers (pointwise/pairwise/listwise) when ``path`` is
    given."""

    def train(self, path=None, fmt="pairwise"):
        if path is None:
            return super().train()
        from paddle_tpu.dataio import parsers
        return parsers.mq2007_reader(path, fmt)

    def test(self, path=None, fmt="pairwise"):
        if path is None:
            return super().test()
        from paddle_tpu.dataio import parsers
        return parsers.mq2007_reader(path, fmt)


mq2007 = _Mq2007(_mq2007_sample, n_train=512, n_test=128)


def _flowers_sample(rng):
    img = rng.uniform(0, 1, size=(3, 224, 224)).astype(np.float32)
    return img, rng.randint(0, 102)


class _Flowers(_Synthetic):
    """paddle.dataset.flowers parity (ref: dataset/flowers.py): real
    102flowers readers when the three archive paths are given."""

    def _reader(self, data_tar, label_mat, setid_mat, split, mapper):
        from paddle_tpu.dataio import parsers
        return parsers.flowers_reader(data_tar, label_mat, setid_mat,
                                      split, mapper)

    def train(self, data_tar=None, label_mat=None, setid_mat=None,
              mapper=None):
        if data_tar is None:
            return super().train()
        return self._reader(data_tar, label_mat, setid_mat, "trnid",
                            mapper)

    def test(self, data_tar=None, label_mat=None, setid_mat=None,
             mapper=None):
        if data_tar is None:
            return super().test()
        return self._reader(data_tar, label_mat, setid_mat, "tstid",
                            mapper)

    def valid(self, data_tar, label_mat, setid_mat, mapper=None):
        return self._reader(data_tar, label_mat, setid_mat, "valid",
                            mapper)


flowers = _Flowers(_flowers_sample, n_train=256, n_test=64)

__all__ += ["movielens", "wmt14", "wmt16", "conll05", "sentiment",
            "voc2012", "mq2007", "flowers"]


class _RealOnly:
    """Dataset whose train()/test() always serve a REAL local corpus
    (no network, no synthetic fallback needed)."""

    def __init__(self, factory):
        self._factory = factory

    def train(self):
        return self._factory("train")

    def test(self):
        return self._factory("test")


def _digits_factory(split):
    from paddle_tpu.dataio.common import digits_reader
    return digits_reader(split)


# real handwritten digits, available offline (sklearn bundle) — the
# zero-egress stand-in for dataset.mnist in convergence runs
# (BASELINE.md "Real-data convergence")
digits = _RealOnly(_digits_factory)

__all__ += ["digits"]


# fluid namespace parity: paddle.dataset.common (download cache +
# split/cluster_files_reader/convert file sharding)
from paddle_tpu.dataio import common  # noqa: E402,F401
