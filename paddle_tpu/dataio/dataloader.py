"""High-throughput file DataLoader: native threaded readers -> parse ->
batch -> async device prefetch.

The end-to-end role of the reference's Dataset + DataFeed + buffered
reader chain (ref: framework/data_set.h:40, framework/data_feed.h:62,
operators/reader/buffered_reader.cc — threaded file reading, queueing,
and async device transfer double-buffering). Record ingest + shuffle +
queueing run in C++ (paddle_tpu.native); parsing/batching run in a
Python worker thread (records are user-format); device puts are
prefetched one batch ahead so the accelerator never waits on feed.

Falls back to a pure-Python file reader when the native toolchain is
unavailable (same iterator contract).
"""

import numpy as np

from paddle_tpu.monitor.registry import counter as _counter

__all__ = ["FileDataLoader"]

_m_batches = _counter("dataio_batches_total",
                      "Batches parsed and stacked by FileDataLoader")


def _py_record_iter(files, epochs, mode, shuffle_buffer=0, seed=0):
    """Fallback reader: same contract as NativeLoader incl. the
    reservoir-style shuffle buffer (single-threaded)."""
    import random
    rng = random.Random(seed)
    buf = []

    def raw():
        ep = 0
        while epochs < 0 or ep < epochs:  # epochs=-1: cycle forever
            ep += 1
            for f in files:
                with open(f, "rb") as fh:
                    for line in fh:
                        yield line.rstrip(b"\n")

    if shuffle_buffer <= 0:
        yield from raw()
        return
    for rec in raw():
        if len(buf) < shuffle_buffer:
            buf.append(rec)
            continue
        j = rng.randrange(len(buf))
        out, buf[j] = buf[j], rec
        yield out
    rng.shuffle(buf)
    yield from buf


class FileDataLoader:
    """Iterate device-ready batches parsed from files.

    parse_fn(record: bytes) -> tuple/np.ndarray sample;
    samples are stacked per-field into numpy batches. With
    device_put=True (default) batches are transferred to the default
    device one step ahead of consumption. ``prefetch`` bounds the
    read-ahead queue; ``prefetch <= 0`` means UNBOUNDED read-ahead (the
    worker may buffer the whole dataset — only use when that fits in
    host memory).
    """

    def __init__(self, files, parse_fn, batch_size, nthreads=2,
                 shuffle_buffer=0, seed=0, epochs=1, mode="lines",
                 drop_last=True, device_put=True, prefetch=2):
        self.files = list(files)
        self.parse_fn = parse_fn
        self.batch_size = batch_size
        self.nthreads = nthreads
        self.shuffle_buffer = shuffle_buffer
        self.seed = seed
        self.epochs = epochs
        self.mode = mode
        self.drop_last = drop_last
        self.device_put = device_put
        self.prefetch = prefetch

    def _records(self):
        if self.mode not in ("lines", "recordio"):
            raise ValueError(f"mode must be 'lines' or 'recordio', "
                             f"got {self.mode!r}")
        from paddle_tpu import native
        if self.mode == "recordio" and not native.available():
            raise RuntimeError(
                "mode='recordio' needs the native library (no pure-Python "
                "RecordIO scanner); the native build failed or no C++ "
                "toolchain is present")
        if native.available():
            return native.NativeLoader(
                self.files, nthreads=self.nthreads,
                shuffle_buffer=self.shuffle_buffer, seed=self.seed,
                epochs=self.epochs, mode=self.mode)
        # no toolchain: single-threaded Python reader, same contract
        return _py_record_iter(self.files, self.epochs, self.mode,
                               self.shuffle_buffer, self.seed)

    def _batches(self):
        buf = []
        records = self._records()
        try:
            for rec in records:
                buf.append(self.parse_fn(rec))
                if len(buf) == self.batch_size:
                    _m_batches.inc()
                    yield self._stack(buf)
                    buf = []
            if buf and not self.drop_last:
                _m_batches.inc()
                yield self._stack(buf)
        finally:
            if hasattr(records, "close"):
                records.close()

    @staticmethod
    def _stack(samples):
        if isinstance(samples[0], (tuple, list)):
            return tuple(np.stack([s[i] for s in samples])
                         for i in range(len(samples[0])))
        return np.stack(samples)

    def __iter__(self):
        """Async prefetch pipeline: a worker thread parses/batches/
        device-puts ahead of the consumer (buffered_reader.cc's
        double-buffering). The thread/queue machinery is the shared
        background_prefetch helper (static.executor): a parse_fn
        exception re-raises HERE with the worker's traceback intact,
        and abandoning the iterator early (break / close) shuts the
        worker down."""
        from paddle_tpu.static.executor import background_prefetch

        if self.device_put:
            import jax
            put = jax.device_put
        else:
            def put(batch):
                return batch

        return background_prefetch(self._batches(), put, self.prefetch)
