"""High-throughput file DataLoader: native threaded readers -> parse ->
batch -> async device prefetch.

The end-to-end role of the reference's Dataset + DataFeed + buffered
reader chain (ref: framework/data_set.h:40, framework/data_feed.h:62,
operators/reader/buffered_reader.cc — threaded file reading, queueing,
and async device transfer double-buffering). Record ingest + shuffle +
queueing run in C++ (paddle_tpu.native); parsing/batching run in a
Python worker thread (records are user-format); device puts are
prefetched one batch ahead so the accelerator never waits on feed.

Falls back to a pure-Python file reader when the native toolchain is
unavailable (same iterator contract).

The sharded-cursor contract (record order)
------------------------------------------
Both readers — the multi-threaded native loader and the single-threaded
Python oracle — produce ONE deterministic record order, a pure function
of (files, seed, shuffle_buffer, epochs) and independent of thread
count:

* shard = file. Within a shard, records flow in file byte order,
  optionally decorrelated by a per-shard reservoir of
  ``shuffle_buffer`` records driven by a splitmix64 RNG re-derived per
  ``(seed, shard, epoch)`` (``_ShardRng`` — implemented identically in
  C++).
* the merged stream interleaves shards round-robin (one record per
  live shard per cycle) with an epoch barrier: a shard that finished
  the current epoch parks until every shard has, then the global epoch
  advances and the round-robin resets to shard 0.

``nthreads`` is therefore a pure throughput knob: the native loader's
worker threads own fixed shard sets and feed per-shard ordered queues;
the consumer-side merge is where the deterministic order (and the
cursor) lives. ``_PyRecordReader`` is the conformance oracle — the
native loader must produce bit-identical streams and cursors
(tests/test_data_plane.py pins it).

Exactly-once resume (``stateful=True``): the cursor (state version 2)
is a vector of per-file byte offsets + per-shard emitted counts (the
shuffle-buffer snapshot — a reservoir is replayable from
``(seed, shard, epoch, count)``) plus the global epoch, round-robin
position and consumed total, exposed as ``state()`` / ``set_state()``.
A state snapshot rides with every batch through the prefetch queue and
is committed only when the *consumer* receives that batch, so
read-ahead the process never consumed is not counted; saving
``state()`` in a checkpoint (``auto_checkpoint(data_state=loader)``)
and resuming yields bit-identical batches to an uninterrupted run.
Iterators are cursors into ONE stream: a second ``__iter__`` continues
after the last delivered batch rather than replaying from the restored
snapshot (re-consuming records would break exactly-once silently).
Stateful mode keeps NATIVE throughput when the library is present —
the deterministic merge made the multi-threaded loader resumable;
version-1 cursors (the pre-sharded sequential order) migrate where the
two orders provably coincide (epoch boundaries, or single-file
unshuffled streams) and refuse loudly otherwise.

Data-parallel slicing and topology-elastic resume (``world_size=`` /
``rank=``): every rank runs the SAME deterministic job-level stream
(same files, seed, shuffle) in global batches of ``batch_size`` and
keeps its contiguous row slice of each batch. Because the job-level
record order is a pure function of the data — not of the rank count or
the reader implementation — the per-step global batch is identical at
any world size, the per-rank cursors are positions in one shared
stream, and a restart at a different rank count resumes exactly:
``merge_rank_states`` folds the saved per-rank cursors into one
job-level frontier (refusing loudly if they diverge), and
``set_state`` on the new topology's loaders re-partitions it — no
record dropped, none double-consumed. With a shuffle buffer the
underlying reader resumes by per-shard replay-and-skip (reservoir
history can't be seeked); the rescale logs that, and the delivered
sequence stays bit-identical.
"""

import logging
import os
import time
import weakref

import numpy as np

from paddle_tpu.monitor.registry import counter as _counter
from paddle_tpu.monitor.registry import gauge as _gauge

__all__ = ["FileDataLoader", "merge_rank_states"]

_log = logging.getLogger("paddle_tpu.dataio")

_m_batches = _counter("dataio_batches_total",
                      "Batches parsed and stacked by FileDataLoader")
_m_records = _counter("data_records_consumed_total",
                      "Records consumed by the training process via "
                      "FileDataLoader (counted at batch delivery, not "
                      "read-ahead)")
_m_native_stateful = _counter(
    "dataio_native_stateful_total",
    "Stateful/data-parallel FileDataLoader streams served by the "
    "deterministic NATIVE loader (vs the Python fallback)")
_m_shard_depth = _gauge(
    "dataio_shard_queue_depth",
    "Records buffered across the native loader's per-shard queues "
    "(read-ahead the merge has not consumed yet)")
_m_h2d_ms = _counter(
    "dataio_h2d_overlap_ms",
    "Milliseconds of host->device feed transfer done in the prefetch "
    "worker thread, i.e. overlapped with the compiled step instead of "
    "paid on its critical path")

STATE_VERSION = 2

_U64 = (1 << 64) - 1


class _ShardRng:
    """splitmix64 over an FNV-1a-mixed (seed, shard, epoch) key — the
    shuffle RNG of the sharded-cursor contract. Deliberately spelled
    out (not ``random.Random``) so the C++ loader implements the exact
    same arithmetic and the two streams are bit-identical."""

    def __init__(self, seed, shard, epoch):
        h = 0xcbf29ce484222325
        for v in (seed, shard, epoch):
            h = ((h ^ (v & _U64)) * 0x100000001b3) & _U64
        self._s = h or 0x9E3779B97F4A7C15

    def next(self):
        self._s = (self._s + 0x9E3779B97F4A7C15) & _U64
        z = self._s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _U64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _U64
        return z ^ (z >> 31)

    def below(self, n):
        return self.next() % n

    def shuffle(self, buf):                 # Fisher-Yates
        for i in range(len(buf) - 1, 0, -1):
            j = self.below(i + 1)
            buf[i], buf[j] = buf[j], buf[i]


def _migrate_v1_state(state):
    """Version-1 cursor (the pre-PR-10 sequential Python reader) ->
    version-2 sharded cursor, where the two record orders provably
    coincide; ``ValueError`` otherwise.

    v1 order was file-sequential (all of file 0, then file 1, ...);
    v2 interleaves per-file shards round-robin. The consumed PREFIX of
    the two streams is the same set only at an epoch boundary (whole
    epochs are the same multiset, and resume only needs the future
    sequence) — or trivially for a single unshuffled file, where both
    orders are plain file order and the byte offset carries over
    (a single SHUFFLED file still refuses: v1 derived its reservoir
    from ``random.Random``, v2 from ``_ShardRng``, so the mid-epoch
    reservoir contents differ)."""
    nfiles = int(state.get("nfiles", 0))
    shards = [{"offset": 0, "epoch_records": 0, "eof": False}
              for _ in range(nfiles)]
    base = {
        "version": STATE_VERSION,
        "epoch": int(state["epoch"]),
        "rr": 0,
        "shards": shards,
        "records_consumed": int(state["records_consumed"]),
        "seed": state.get("seed"),
        "shuffle_buffer": state.get("shuffle_buffer"),
        "nfiles": nfiles,
    }
    if state.get("files") is not None:
        base["files"] = [list(fp) for fp in state["files"]]
    at_epoch_boundary = (int(state.get("epoch_records", 0)) == 0
                         and int(state.get("file_index", 0)) == 0
                         and int(state.get("offset", 0)) == 0)
    if at_epoch_boundary:
        return base
    if nfiles == 1 and not state.get("shuffle_buffer"):
        shards[0]["offset"] = int(state["offset"])
        shards[0]["epoch_records"] = int(state["epoch_records"])
        return base
    raise ValueError(
        f"version-1 data cursor at epoch {state.get('epoch')} + "
        f"{state.get('epoch_records')} record(s) cannot migrate to the "
        f"sharded (version-2) record order mid-epoch: the sequential "
        f"and interleaved streams only coincide at epoch boundaries "
        f"(or for a single unshuffled file) — resume that checkpoint "
        f"on the release that wrote it, or restart the epoch")


class _PyRecordReader:
    """Deterministic, resumable record reader — the single-threaded
    conformance ORACLE for the native loader's sharded-cursor contract
    (see the module docstring for the order definition).

    Iteration order is a pure function of (files, seed,
    shuffle_buffer): shard = file, per-shard reservoir RNG re-derived
    from ``(seed, shard, epoch)``, round-robin merge with an epoch
    barrier. ``state()`` returns the cursor after the last record
    yielded; constructing with ``start_state=`` resumes exactly there —
    per shard by seeking (no shuffle: byte offset) or by replaying the
    epoch's already-emitted records without yielding them (shuffle: the
    reservoir's content is history-dependent, so the skip replay is
    what makes resume bit-identical)."""

    def __init__(self, files, epochs, mode="lines", shuffle_buffer=0,
                 seed=0, start_state=None):
        if mode != "lines":
            raise RuntimeError(
                f"the pure-Python reader only supports mode='lines' "
                f"(got {mode!r}); RecordIO needs the native library")
        self.files = list(files)
        self.epochs = epochs
        self.shuffle_buffer = shuffle_buffer
        self.seed = seed
        # identity of the stream the cursor addresses: a swapped or
        # rewritten file of the same count would make the saved
        # offset/skip-replay land on different records with no error
        self._files_fp = [[os.path.basename(f), os.path.getsize(f)]
                          for f in self.files]
        self._epoch = 0
        self._rr = 0                # next shard in the round robin
        self._consumed = 0          # records yielded since epoch 0
        self._shards = [{"offset": 0, "epoch_records": 0, "eof": False}
                        for _ in self.files]
        if start_state is not None:
            self.set_state(start_state)

    # -- cursor ------------------------------------------------------------
    def state(self):
        return {
            "version": STATE_VERSION,
            "epoch": self._epoch,
            "rr": self._rr,
            "shards": [dict(s) for s in self._shards],
            "records_consumed": self._consumed,
            "seed": self.seed,
            "shuffle_buffer": self.shuffle_buffer,
            "nfiles": len(self.files),
            "files": [list(fp) for fp in self._files_fp],
        }

    def set_state(self, state):
        if not isinstance(state, dict) or \
                state.get("version") not in (1, STATE_VERSION):
            raise ValueError(
                f"unsupported reader state {state!r:.80} (want a dict "
                f"with version={STATE_VERSION})")
        if state.get("version") == 1:
            state = _migrate_v1_state(state)
        for knob in ("seed", "shuffle_buffer"):
            if state.get(knob) != getattr(self, knob):
                raise ValueError(
                    f"reader state was captured with {knob}="
                    f"{state.get(knob)!r} but this reader has {knob}="
                    f"{getattr(self, knob)!r} — resuming would change "
                    f"the record sequence")
        if state.get("nfiles") != len(self.files):
            raise ValueError(
                f"reader state was captured over {state.get('nfiles')} "
                f"file(s) but this reader has {len(self.files)} — the "
                f"saved cursor does not address this file list")
        want_fp = [list(fp) for fp in self._files_fp]
        got_fp = state.get("files")
        if got_fp is not None and got_fp != want_fp:
            changed = [w[0] for w, g in zip(want_fp, got_fp) if w != g]
            raise ValueError(
                f"reader state was captured over different file "
                f"contents (changed: {changed[:3]}) — a swapped or "
                f"rewritten file would silently shift the record "
                f"sequence the cursor addresses")
        shards = state.get("shards")
        if not isinstance(shards, list) or len(shards) != len(self.files):
            raise ValueError(
                f"reader state carries {len(shards or [])} shard "
                f"cursor(s) for {len(self.files)} file(s)")
        self._epoch = int(state["epoch"])
        self._rr = int(state.get("rr", 0))
        self._consumed = int(state["records_consumed"])
        self._shards = [{"offset": int(s["offset"]),
                         "epoch_records": int(s["epoch_records"]),
                         "eof": bool(s.get("eof"))} for s in shards]

    # -- per-shard streams -------------------------------------------------
    def _shard_stream(self, i, epoch, start_offset=0, skip=0):
        """(record, end_offset, emitted_after) for shard i, one epoch.
        ``skip`` replays (without yielding) the first ``skip`` emitted
        records — the shuffle-resume path; with no shuffle the caller
        seeks via ``start_offset`` instead and ``skip`` just offsets
        the emitted counter."""
        B = self.shuffle_buffer
        if B <= 0:
            off = start_offset
            emitted = skip
            with open(self.files[i], "rb") as fh:
                if off:
                    fh.seek(off)
                for line in fh:
                    off += len(line)
                    emitted += 1
                    yield line.rstrip(b"\n"), off, emitted
            return
        rng = _ShardRng(self.seed, i, epoch)
        buf = []
        emitted = 0
        off = 0
        with open(self.files[i], "rb") as fh:
            for line in fh:
                off += len(line)
                rec = line.rstrip(b"\n")
                if len(buf) < B:
                    buf.append(rec)
                    continue
                j = rng.below(len(buf))
                out, buf[j] = buf[j], rec
                emitted += 1
                if emitted > skip:
                    yield out, off, emitted
        rng.shuffle(buf)            # epoch-end reservoir drain
        for out in buf:
            emitted += 1
            if emitted > skip:
                yield out, off, emitted

    def _open_streams(self, fresh):
        streams = []
        for i, sh in enumerate(self._shards):
            if not fresh and sh["eof"]:
                streams.append(iter(()))    # finished current epoch
            elif not fresh and self.shuffle_buffer > 0:
                streams.append(self._shard_stream(
                    i, self._epoch, skip=sh["epoch_records"]))
            elif not fresh:
                streams.append(self._shard_stream(
                    i, self._epoch, start_offset=sh["offset"],
                    skip=sh["epoch_records"]))
            else:
                streams.append(self._shard_stream(i, self._epoch))
        return streams

    # -- deterministic merge -----------------------------------------------
    def __iter__(self):
        S = len(self.files)
        streams = self._open_streams(fresh=False)
        while self.epochs < 0 or self._epoch < self.epochs:
            # round-robin over live shards until every shard ends the
            # epoch (the barrier), then advance the global epoch
            while True:
                emitted = False
                for k in range(S):
                    i = (self._rr + k) % S
                    sh = self._shards[i]
                    if sh["eof"]:
                        continue
                    try:
                        rec, off, em = next(streams[i])
                    except StopIteration:
                        sh["eof"] = True
                        continue
                    sh["offset"], sh["epoch_records"] = off, em
                    self._consumed += 1
                    self._rr = (i + 1) % S
                    yield rec
                    emitted = True
                    break
                if not emitted:
                    break
            self._epoch += 1
            self._rr = 0
            for sh in self._shards:
                sh["offset"] = 0
                sh["epoch_records"] = 0
                sh["eof"] = False
            if self.epochs >= 0 and self._epoch >= self.epochs:
                return
            streams = self._open_streams(fresh=True)


def _py_record_iter(files, epochs, mode, shuffle_buffer=0, seed=0):
    """Fallback reader: same contract as NativeLoader incl. the
    shuffle buffer (single-threaded). Kept as the module's plain-
    iterator face; ``_PyRecordReader`` is the stateful object."""
    return iter(_PyRecordReader(files, epochs, mode,
                                shuffle_buffer=shuffle_buffer,
                                seed=seed))


def merge_rank_states(states):
    """Fold per-rank ``FileDataLoader.state()`` snapshots (taken at
    the same step) into ONE job-level frontier for topology-elastic
    resume.

    Data-parallel ranks are row-slices of one deterministic job-level
    stream, so their cursors MUST agree on every stream field — the
    merge validates that and strips the per-rank identity (``dp`` rank)
    rather than inventing a new position. Raises ``ValueError`` naming
    the diverging fields when they don't: per-rank streams that were
    not slices of one job-level stream have no exact re-partitioning,
    and guessing one would silently drop or double-consume records
    (``io_checkpoint`` turns that into a ``CheckpointTopologyError``).
    The frontier is a valid ``set_state()`` input for a loader at ANY
    world size with the same files/seed/shuffle/global batch."""
    if not states:
        raise ValueError("no rank states to merge")
    stripped, dps = [], []
    for i, s in enumerate(states):
        if not isinstance(s, dict):
            raise ValueError(f"rank {i} data state is not a dict "
                             f"({type(s).__name__})")
        s = dict(s)
        dps.append(s.pop("dp", None))
        stripped.append(s)
    base = stripped[0]
    for i, s in enumerate(stripped[1:], 1):
        if s != base:
            diff = sorted(k for k in set(base) | set(s)
                          if base.get(k) != s.get(k))
            raise ValueError(
                f"rank 0 and rank {i} data cursors diverge on "
                f"{diff} — the per-rank streams were not slices of "
                f"one job-level stream")
    d0 = dps[0]
    for i, d in enumerate(dps[1:], 1):
        for knob in ("world_size", "global_batch"):
            if (d or {}).get(knob) != (d0 or {}).get(knob):
                raise ValueError(
                    f"rank 0 and rank {i} disagree on dp {knob} "
                    f"({(d0 or {}).get(knob)!r} vs "
                    f"{(d or {}).get(knob)!r})")
    frontier = dict(base)
    if d0 is not None:
        # keep the WRITING topology (minus the per-rank identity): the
        # restoring loader uses it to validate the global batch and to
        # log the world-size change
        frontier["dp"] = {"world_size": d0.get("world_size"),
                          "global_batch": d0.get("global_batch")}
    return frontier


class FileDataLoader:
    """Iterate device-ready batches parsed from files.

    parse_fn(record: bytes) -> tuple/np.ndarray sample;
    samples are stacked per-field into numpy batches. ``device_put``
    controls the prefetch worker's device stage: True (default) puts
    each batch on the default device one step ahead of consumption;
    a CALLABLE places the batch itself — pass
    ``Executor.feed_stage(program, feed_names)`` to put batch N+1 on
    the exact shardings the prepared runner consumes (DP/mesh feed
    placement), overlapping the host->device hop with the compiled
    step for batch N (device-side double buffering; the
    ``dataio_h2d_overlap_ms`` counter measures the transfer time moved
    off the critical path); False disables the stage. ``prefetch``
    bounds the read-ahead queue; ``prefetch <= 0`` means UNBOUNDED
    read-ahead (the worker may buffer the whole dataset — only use
    when that fits in host memory).

    ``stateful=True`` enables ``state()``/``set_state()`` for
    exactly-once resume (see the module docstring); the deterministic
    sharded-cursor contract keeps the NATIVE loader's throughput on
    this path (the Python reader is the fallback and the conformance
    oracle). Incompatible with mode='recordio' (the oracle has no
    RecordIO scanner, so a cursor could never be verified).

    ``native=`` pins the reader implementation: None (default) uses
    the native library when available, False forces the Python oracle
    (also via env ``PT_DATAIO_FORCE_PY=1`` — the bench A/B and
    conformance harness knob), True requires native and raises when
    the toolchain is missing.

    ``world_size=W, rank=r`` turns on data-parallel slicing:
    ``batch_size`` becomes the GLOBAL batch, every rank reads the same
    deterministic job-level stream, and rank r keeps rows
    ``[r*B/W, (r+1)*B/W)`` of each global batch. Because the stream is
    rank-count-independent, a checkpointed cursor rescales exactly
    onto a different world size (see ``merge_rank_states``). Requires
    ``batch_size % world_size == 0`` and ``drop_last=True``.
    """

    def __init__(self, files, parse_fn, batch_size, nthreads=2,
                 shuffle_buffer=0, seed=0, epochs=1, mode="lines",
                 drop_last=True, device_put=True, prefetch=2,
                 stateful=False, world_size=None, rank=None,
                 native=None):
        self.files = list(files)
        self.parse_fn = parse_fn
        self.batch_size = batch_size
        self.nthreads = nthreads
        self.shuffle_buffer = shuffle_buffer
        self.seed = seed
        self.epochs = epochs
        self.mode = mode
        self.drop_last = drop_last
        self.device_put = device_put
        self.prefetch = prefetch
        self.stateful = stateful
        self.native = native
        self.world_size = int(world_size) if world_size is not None \
            else None
        self.rank = int(rank) if rank is not None else None
        if self.world_size is not None:
            if self.world_size < 1:
                raise ValueError(f"world_size must be >= 1, got "
                                 f"{world_size!r}")
            if self.rank is None or not 0 <= self.rank < self.world_size:
                raise ValueError(
                    f"rank must be in [0, world_size={self.world_size}),"
                    f" got {rank!r}")
            if batch_size % self.world_size:
                raise ValueError(
                    f"batch_size={batch_size} is the GLOBAL batch and "
                    f"must divide evenly across world_size="
                    f"{self.world_size} — a ragged split would give "
                    f"ranks different record counts per step and break "
                    f"cursor rescaling")
            if not drop_last:
                raise ValueError(
                    "world_size slicing requires drop_last=True: a "
                    "ragged final global batch cannot be sliced into "
                    "equal per-rank shares")
        elif self.rank is not None:
            raise ValueError("rank= given without world_size=")
        if stateful and mode == "recordio":
            raise RuntimeError(
                "stateful=True is incompatible with mode='recordio': "
                "the Python oracle has no RecordIO scanner, so a "
                "resume cursor could never be conformance-checked — "
                "use mode='lines' or a non-stateful loader")
        if self.world_size is not None and mode == "recordio":
            raise RuntimeError(
                "world_size slicing is incompatible with "
                "mode='recordio': hosts without the native library "
                "have no RecordIO scanner, so the job-level stream "
                "could not be reproduced everywhere — use mode='lines'")
        self._pending_state = None      # applied at next __iter__
        self._delivered_state = None    # after the last consumed batch
        self._live_iter = None          # stateful: weakref to the one
        # live iterator. WEAK on purpose: a strong ref would close the
        # (loader -> generator -> loader-closure) cycle, deferring an
        # abandoned iterator's finalization — and its prefetch
        # worker's shutdown — from refcount-immediate to whenever the
        # cyclic GC next runs

    # -- resume cursor -----------------------------------------------------
    def _dp_block(self):
        return {"world_size": self.world_size, "rank": self.rank,
                "global_batch": self.batch_size}

    def state(self):
        """The cursor after the last batch the CONSUMER received (not
        the worker's read-ahead). Save it with a checkpoint; a new
        loader ``set_state()``-ed with it continues the exact record
        sequence. Before any batch is delivered this returns the
        pending (restored) state, or the start-of-stream cursor.
        Under data-parallel slicing the cursor carries a ``dp`` block
        (world_size/rank/global_batch) describing THIS topology — the
        merge/rescale machinery reads it."""
        if not self.stateful:
            raise RuntimeError(
                "state() on a non-stateful FileDataLoader — construct "
                "with stateful=True (exactly-once resume needs the "
                "deterministic reader)")
        if self._delivered_state is not None:
            s = self._delivered_state
        elif self._pending_state is not None:
            s = self._pending_state
        else:
            s = _PyRecordReader(self.files, self.epochs, self.mode,
                                self.shuffle_buffer, self.seed).state()
        if self.world_size is not None:
            s = dict(s, dp=self._dp_block())
        return s

    def set_state(self, state):
        """Resume from a ``state()`` snapshot: takes effect on the next
        ``__iter__`` (create iterators AFTER calling this). Without a
        fresh ``set_state``, each subsequent iterator CONTINUES from
        the last delivered batch — the loader is a stream with a
        cursor, so re-iterating never replays consumed records (an
        exhausted finite stream yields nothing).

        The snapshot may come from a DIFFERENT topology (another
        world_size/rank, or a ``merge_rank_states`` frontier): the
        cursor addresses the shared job-level stream, so it applies
        directly — only the global batch size must match (record→step
        boundaries would shift otherwise). A world-size change is
        logged, including the replay-and-skip cost when a shuffle
        buffer makes the epoch prefix non-seekable. Version-1 cursors
        (pre-sharded-contract checkpoints) migrate where the record
        orders coincide — see ``_migrate_v1_state``."""
        if not self.stateful:
            raise RuntimeError(
                "set_state() on a non-stateful FileDataLoader — "
                "construct with stateful=True")
        state = dict(state)
        dp = state.pop("dp", None)
        if dp is not None:
            gb = dp.get("global_batch")
            if gb is not None and gb != self.batch_size:
                raise ValueError(
                    f"data cursor was captured with global batch "
                    f"{gb} but this loader's is {self.batch_size} — "
                    f"re-partitioning across a changed batch size "
                    f"would shift every step boundary")
        if self.world_size is not None:
            # a cursor without a dp block (saved by a plain stateful
            # loader) carries no global-batch record to compare — but
            # alignment is provable from the position itself: delivery
            # commits whole batches, so a sound resume point must land
            # on a boundary of THIS loader's global batch (dp slicing
            # enforces drop_last, so partial deliveries can't occur)
            rc = int(state.get("records_consumed", 0))
            if rc % self.batch_size:
                raise ValueError(
                    f"data cursor at {rc} consumed record(s) does not "
                    f"land on a global-batch boundary of "
                    f"{self.batch_size} — it was saved by a loader "
                    f"with a different batch size, and resuming would "
                    f"shift every step boundary")
        old_w = (dp.get("world_size") or 1) if dp is not None else 1
        new_w = self.world_size or 1
        if old_w != new_w:
            replay = ""
            epoch_recs = sum(
                int(s.get("epoch_records", 0))
                for s in state.get("shards", [])
            ) if state.get("version") == STATE_VERSION else \
                int(state.get("epoch_records", 0))
            if self.shuffle_buffer and epoch_recs:
                # the reader can't seek into a reservoir-shuffled
                # epoch: resume replays the already-consumed prefix
                # without yielding it — exact, not free
                replay = (f" (shuffled stream: resume replays-and-"
                          f"skips {epoch_recs} "
                          f"record(s) of the current epoch)")
            _log.warning(
                "rescaling data cursor from world_size=%d to "
                "world_size=%d at %d consumed record(s)%s",
                old_w, new_w,
                state.get("records_consumed", 0), replay)
        # validate eagerly (a bad cursor should fail at restore time,
        # not steps later inside the prefetch worker) — the validator
        # also NORMALIZES the snapshot (version-1 migration), so the
        # stored pending state is always a v2 sharded cursor the
        # native loader can restore directly
        validator = _PyRecordReader(self.files, self.epochs, self.mode,
                                    self.shuffle_buffer, self.seed,
                                    start_state=state)
        # a still-live iterator delivering after this call would stomp
        # the snapshot with its own cursor — supersede it now
        self._close_live_iter()
        self._pending_state = validator.state()
        self._delivered_state = None

    def _close_live_iter(self):
        ref, self._live_iter = self._live_iter, None
        it = ref() if ref is not None else None
        if it is not None:
            it.close()

    # -- reading -----------------------------------------------------------
    def _use_native(self):
        """Resolve the reader implementation for THIS stream."""
        if self.native is False or \
                os.environ.get("PT_DATAIO_FORCE_PY") == "1":
            return False
        from paddle_tpu import native
        ok = native.available()
        if self.native is True and not ok:
            raise RuntimeError(
                "FileDataLoader(native=True) but the native library is "
                "unavailable (no C++ toolchain / build failed)")
        return ok

    def _records(self):
        if self.mode not in ("lines", "recordio"):
            raise ValueError(f"mode must be 'lines' or 'recordio', "
                             f"got {self.mode!r}")
        use_native = self._use_native()
        if self.stateful:
            # a later iterator continues from the last DELIVERED batch
            # (falling back to the restored snapshot before anything
            # was delivered): re-seeding from _pending_state would
            # silently replay already-consumed records on the second
            # __iter__ — the exactly-once violation, not a rewind
            start = self._delivered_state \
                if self._delivered_state is not None \
                else self._pending_state
            if use_native:
                # deterministic merge == the Python oracle's order, so
                # exactly-once resume keeps native throughput
                from paddle_tpu import native
                _m_native_stateful.inc()
                return native.NativeLoader(
                    self.files, nthreads=self.nthreads,
                    shuffle_buffer=self.shuffle_buffer, seed=self.seed,
                    epochs=self.epochs, mode=self.mode,
                    start_state=start)
            return _PyRecordReader(self.files, self.epochs, self.mode,
                                   self.shuffle_buffer, self.seed,
                                   start_state=start)
        if self.world_size is not None:
            # dp slicing's core invariant — every rank reads the SAME
            # deterministic job-level stream — holds for BOTH readers
            # under the sharded-cursor contract: ranks slice
            # identically-ordered global batches whichever
            # implementation serves them
            if use_native:
                from paddle_tpu import native
                _m_native_stateful.inc()
                return native.NativeLoader(
                    self.files, nthreads=self.nthreads,
                    shuffle_buffer=self.shuffle_buffer, seed=self.seed,
                    epochs=self.epochs, mode=self.mode)
            return _py_record_iter(self.files, self.epochs, self.mode,
                                   self.shuffle_buffer, self.seed)
        if self.mode == "recordio" and not use_native:
            raise RuntimeError(
                "mode='recordio' needs the native library (no pure-Python "
                "RecordIO scanner); the native build failed or no C++ "
                "toolchain is present")
        if use_native:
            from paddle_tpu import native
            return native.NativeLoader(
                self.files, nthreads=self.nthreads,
                shuffle_buffer=self.shuffle_buffer, seed=self.seed,
                epochs=self.epochs, mode=self.mode)
        # no toolchain: single-threaded Python reader, same contract
        return _py_record_iter(self.files, self.epochs, self.mode,
                               self.shuffle_buffer, self.seed)

    def _slice_rows(self, batch):
        """This rank's contiguous row share of a global batch."""
        b = self.batch_size // self.world_size
        sl = slice(self.rank * b, (self.rank + 1) * b)
        if isinstance(batch, tuple):
            return tuple(f[sl] for f in batch)
        return batch[sl]

    def _batches(self):
        """(batch, n_records, cursor-after-those-records) triples; the
        cursor is None for non-stateful readers. Under data-parallel
        slicing the yielded batch is this rank's rows and n_records
        counts them (the cursor still tracks the GLOBAL stream — it is
        the job-level position every rank shares)."""
        records = self._records()
        snap = records.state if (self.stateful
                                 and hasattr(records, "state")) \
            else (lambda: None)

        def emit(samples):
            _m_batches.inc()
            batch = self._stack(samples)
            if self.world_size is not None:
                return (self._slice_rows(batch),
                        len(samples) // self.world_size, snap())
            return batch, len(samples), snap()

        try:
            pull = getattr(records, "read_records", None)
            if pull is not None:
                # native loader: ONE ctypes crossing per batch (the
                # bulk read), with the cursor snapshot landing exactly
                # on the batch boundary the bulk pull stops at
                depth = getattr(records, "queue_size", None)
                while True:
                    recs = pull(self.batch_size)
                    if depth is not None:
                        _m_shard_depth.set(depth())
                    if not recs:
                        break
                    if len(recs) == self.batch_size:
                        yield emit([self.parse_fn(r) for r in recs])
                        continue
                    if not self.drop_last:
                        yield emit([self.parse_fn(r) for r in recs])
                    break
                return
            buf = []
            for rec in records:
                buf.append(self.parse_fn(rec))
                if len(buf) == self.batch_size:
                    yield emit(buf)
                    buf = []
            if buf and not self.drop_last:
                yield emit(buf)
        finally:
            if hasattr(records, "close"):
                records.close()

    @staticmethod
    def _stack(samples):
        # np.asarray, not np.stack: identical output for equal-shape
        # samples (still an error for ragged ones), but without
        # stack's per-sample expand_dims+concatenate machinery —
        # ~30x cheaper for scalar samples, ~2x for small vectors,
        # which used to dominate the whole ingest pipeline
        if isinstance(samples[0], (tuple, list)):
            return tuple(np.asarray([s[i] for s in samples])
                         for i in range(len(samples[0])))
        return np.asarray(samples)

    def __iter__(self):
        """Async prefetch pipeline: a worker thread parses/batches/
        device-puts ahead of the consumer (buffered_reader.cc's
        double-buffering). The thread/queue machinery is the shared
        background_prefetch helper (static.executor): a parse_fn
        exception re-raises HERE with the worker's traceback intact —
        carrying the failing batch's ordinal for postmortems — and
        abandoning the iterator early (break / close) shuts the worker
        down. The state cursor riding with each batch commits only
        here, at delivery — read-ahead batches the consumer never
        pulled are not "consumed" and resume re-reads them."""
        from paddle_tpu.static.executor import background_prefetch

        # stateful: ONE live cursor. Superseding (closing) any previous
        # iterator before the new reader seeds from _delivered_state
        # makes the one-stream contract enforced, not advisory — two
        # concurrently-live iterators would double-deliver records and
        # let the older one regress the committed cursor
        if self.stateful:
            self._close_live_iter()

        if callable(self.device_put):
            put = self.device_put       # runner-sharding-aware stage
        elif self.device_put:
            import jax
            put = jax.device_put
        else:
            put = None

        def stage(item):
            batch, n, cursor = item
            if put is None:
                return batch, n, cursor
            t0 = time.perf_counter()
            staged = put(batch)
            # transfer time spent HERE runs in the worker thread,
            # overlapped with the consumer's compiled step
            _m_h2d_ms.inc((time.perf_counter() - t0) * 1e3)
            return staged, n, cursor

        inner = background_prefetch(self._batches(), stage,
                                    self.prefetch)

        def deliver():
            try:
                for batch, n, cursor in inner:
                    _m_records.inc(n)
                    if cursor is not None:
                        self._delivered_state = cursor
                    yield batch
            finally:
                inner.close()   # deterministic worker shutdown when
                                # the consumer abandons THIS wrapper
                # NOTE: deliver() must not reference its own generator
                # (e.g. to clear _live_iter) — the closure cell would
                # be a self-cycle keeping an abandoned iterator, and
                # its prefetch worker, alive until a cyclic GC pass.
                # A stale _live_iter weakref is harmless: re-closing a
                # finished generator is a no-op.

        gen = deliver()
        if self.stateful:
            self._live_iter = weakref.ref(gen)
        return gen
